"""The robust SPMD training engine.

One jitted step function replaces the reference's entire per-step distributed
dance (worker gradient push over gRPC/MPI/UDP -> PS-side GAR -> variable
update, SURVEY.md §3.1).  Dataflow per step, for ``n`` logical workers over a
``W``-device ``worker`` mesh axis (k = n/W workers per device):

1.  **Isolated worker gradients** — the batch arrives worker-sharded; each
    device vmaps its k workers' forward/backward.  Gradients are flattened to
    (k, d) with the coherent pytree layout (core/flatten.py).
2.  **Local Byzantine attack / lossy link** — transforms that only read the
    worker's own slot run here, before any collective (honest threat model).
3.  **Reshard worker->dimension** — ``all_to_all`` turns the implicit (n, d)
    gradient matrix into per-device column blocks (n, d/W).  This is the
    engine's key memory move: no device ever holds n gradients, per-device
    footprint stays O(d) (SURVEY.md §7 hard part (b)).
4.  **Omniscient attacks** — coalition attacks needing honest statistics
    (coordinate-wise mean/std) apply blockwise on the gathered rows.
5.  **Distances** — Krum/Bulyan need the (n, n) squared-distance matrix: each
    device computes its block's partial Gram contribution, one O(n²) ``psum``
    completes it (vs the reference's O(n²·d) PS-side loop, op_krum/cpu.cpp).
6.  **Blockwise GAR** — every rule reduces its column block locally
    (selection weights are identical on all devices by construction).
7.  **Gather + update** — ``all_gather`` restores the aggregated (d,) vector;
    the optax update applies identically on every device, keeping parameters
    replicated — the PS's "one canonical copy" without a PS (train_state.py).

Wire cost: one all_to_all (d floats out/in per device) + one O(n²) psum + one
all_gather (d floats) ≈ 2x a ring allreduce — the minimum for robust
aggregation, since the GAR provably needs per-worker gradients, not their sum
(SURVEY.md §2.6).
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import NamedSharding, PartitionSpec as P

from .. import config
from ..core.flatten import FlatMap
from ..core.train_state import TrainState
from ..gars.common import centered_gram_sq_distances
from ..obs import trace
from ..utils import UserException
from ..utils import compat
from .mesh import model_axis, pipe_axis, worker_axis

#: the in-group (within one logical worker's submesh) mesh axes of the
#: leafwise-sharded mode — collectives over these complete replicated-leaf
#: gradients and per-bucket distances; both are size 1 in flat mode
_IN_GROUP_AXES = (pipe_axis, model_axis)


def _is_spec(x):
    return x is None or isinstance(x, P)


def _spec_axis_names(spec):
    names = set()
    for entry in spec or ():
        if entry is None:
            continue
        if isinstance(entry, (tuple, list)):
            names.update(entry)
        else:
            names.add(entry)
    return names


def _replication_axes(spec):
    """In-group mesh axes over which a leaf with this spec is replicated."""
    names = _spec_axis_names(spec)
    return tuple(a for a in _IN_GROUP_AXES if a not in names)


def validate_reputation_args(gar, reputation_decay, quarantine_threshold):
    """Shared validation of the reputation/quarantine knobs (both engines).

    Returns the normalized ``(decay, threshold)`` pair.  Quarantine is
    bounded by the rule's declared budget: at most ``f`` workers are masked
    per step (``quarantine_mask``), so a NaN-excluding rule sized for f
    Byzantine rows never sees more dead rows than it tolerates — which is
    why ``f >= 1`` is required to quarantine at all."""
    decay = None if reputation_decay is None else float(reputation_decay)
    threshold = float(quarantine_threshold)
    if decay is not None and not 0.0 < decay < 1.0:
        raise UserException("reputation_decay must lie in (0, 1), got %r" % reputation_decay)
    if threshold:
        if decay is None:
            raise UserException("quarantine_threshold needs reputation_decay set")
        if not 0.0 < threshold < 1.0:
            raise UserException(
                "quarantine_threshold must lie in (0, 1), got %r" % quarantine_threshold
            )
        if gar.nb_byz_workers < 1:
            raise UserException(
                "Quarantine masks up to f workers per step; declare "
                "--nb-decl-byz-workers >= 1 to use it"
            )
        if not gar.nan_row_tolerant:
            from ..gars import gars as _registry

            tolerant = sorted(
                name for name in _registry.itemize()
                if getattr(_registry.get(name), "nan_row_tolerant", False)
            )
            # ``bucketing``/``hier`` set nan_row_tolerant per-INSTANCE (they
            # inherit their child rules' tolerance), so the class-attribute
            # scan above cannot list them — name them explicitly.
            raise UserException(
                "Quarantine masks rows to NaN, which %s does not cleanly "
                "exclude (pick a NaN-excluding rule: %s; or bucketing/hier "
                "with NaN-tolerant child rules)"
                % (type(gar).__name__, ", ".join(tolerant))
            )
    return decay, threshold


def validate_chaos_args(chaos, attack, lossy_link, nb_workers, nb_real_byz):
    """Shared validation of a ChaosSchedule against the engine's own
    configuration (both engines).  Returns ``chaos`` unchanged."""
    if chaos is None:
        return None
    if attack is not None or lossy_link is not None:
        raise UserException(
            "--chaos subsumes the static --attack/--UDP knobs: encode them as "
            "schedule regimes instead (e.g. '0:attack=empire' / '0:drop=0.3')"
        )
    if chaos.nb_workers != nb_workers:
        raise UserException(
            "ChaosSchedule was built for n=%d workers but the engine has %d"
            % (chaos.nb_workers, nb_workers)
        )
    if chaos.has_attacks or getattr(chaos, "has_forgery", False):
        if nb_real_byz == 0:
            raise UserException(
                "The chaos schedule declares attack/forge/tamper regimes; they "
                "need --nb-real-byz-workers > 0 to have anyone to run them"
            )
        if chaos.nb_real_byz != nb_real_byz:
            # the schedule sized its attacks (e.g. little's z formula) for a
            # different coalition than the engine will gate
            raise UserException(
                "ChaosSchedule was built for %d real Byzantine workers but "
                "the engine declares %d" % (chaos.nb_real_byz, nb_real_byz)
            )
    return chaos


def quarantine_mask(reputation, threshold, nb_byz):
    """(n,) bool: below-threshold AND among the ``nb_byz`` lowest
    reputations — the cap keeps the masked count within the NaN budget the
    rule's (n, f) sizing tolerates (an unbounded mask could exceed it when
    the rank signal rotates across honest stragglers)."""
    from ..gars.common import smallest_k_mask

    return (reputation < threshold) & smallest_k_mask(reputation, nb_byz)


def _partial_pairwise_sq_distances(block):
    """Per-block contribution to the (n, n) squared-distance matrix.

    Direct difference form on the (n, d_block) block would cost O(n²·d_block)
    memory, so the shared centered-Gram helper is used; psum across blocks
    then yields the same convention as the dense tier (NaN anywhere -> NaN
    entry; per-block median centering is a valid translation per block).

    On TPU, large blocks dispatch to the Pallas streaming distance kernel
    (ops/pallas_kernels.py): the Gram form's robust centering pass is a
    per-column median — the same order-statistic cost the Pallas tier
    removes from the coordinate rules (measured r4: krum dist+score at
    d=8.4M, 9.5 ms Pallas vs 398 ms jnp) — while the streamed difference
    form needs no centering because it never cancels.
    """
    block = block.astype(jnp.float32)
    from ..gars.common import use_pallas_coordinate_tier

    if use_pallas_coordinate_tier(block):
        from ..ops import pallas_kernels as pk

        return pk.pairwise_sq_distances(block)
    return centered_gram_sq_distances(block)


class RobustEngine:
    """The ONE sharding-polymorphic robust engine (docs/engine.md).

    Two gradient dataflows behind one constructor, selected by ``sharding``:

    - ``"flat"`` (default on a trivial in-group mesh): one logical worker =
      one vmapped slot on the ``worker`` axis, gradients flattened to (k, d)
      rows, all_to_all reshard to dimension-sharded column blocks, blockwise
      GAR — the module-docstring dataflow.  Granularities ``vector``/``leaf``.
    - ``"sharded"``: one logical worker = a (pipe x model) submesh running a
      pipelined/tensor-parallel replica; robust aggregation runs per
      parameter bucket directly on the *sharded* gradients, the (n, d)
      matrix never materialized.  Granularities ``layer``/``leaf``/``global``.

    Everything that is not the gradient dataflow — knob validation, the
    chaos schedule, reputation/quarantine, worker momentum, the CLEVER
    carry, authenticated submission, the health probe, the flight recorder,
    and the whole step epilogue (``_finalize_step``) — exists ONCE and is
    shared by both bodies.  The two perturbation/submission pipelines stay
    separate on purpose: their PRNG stream layouts differ (flat folds per
    worker over the flattened row; sharded folds per (worker, leaf)), and
    bit-compatibility with existing runs pins both.
    """

    def __init__(self, mesh, gar, nb_workers=None, nb_real_byz=0, attack=None, lossy_link=None,
                 exchange_dtype=None, exchange=None, worker_momentum=None, batch_transform=None,
                 worker_metrics=False, reputation_decay=None, quarantine_threshold=0.0,
                 granularity=None, leaf_bucketing="auto", trace_ops=False, chaos=None,
                 health_probe=True, secure=False, flight=None,
                 l1_regularize=None, l2_regularize=None, sharding=None):
        self.mesh = mesh
        self.gar = gar
        # Mode resolution: explicit ``sharding`` wins; otherwise a mesh with
        # nontrivial in-group (pipe/model) axes means the leafwise-sharded
        # dataflow (a flat engine cannot use those devices at all).
        if sharding is None:
            sharding = (
                "sharded"
                if mesh.shape[pipe_axis] * mesh.shape[model_axis] > 1 else "flat"
            )
        if sharding not in ("flat", "sharded"):
            raise UserException(
                "sharding must be 'flat' or 'sharded' (got %r)" % (sharding,)
            )
        self.sharded = sharding == "sharded"
        if granularity is None:
            granularity = "layer" if self.sharded else "vector"
        if self.sharded:
            if granularity not in ("layer", "leaf", "global"):
                raise UserException(
                    "sharded granularity must be layer, leaf or global (got %r)"
                    % (granularity,)
                )
            if batch_transform is not None:
                raise UserException(
                    "batch_transform is a flat-engine feature (the sharded "
                    "batches flow through the pipeline stages)"
                )
            if trace_ops:
                raise UserException(
                    "trace_ops narrates the flat step body only; use --trace "
                    "for a profiler window on the sharded engine"
                )
        else:
            if granularity not in ("vector", "leaf"):
                raise UserException(
                    "granularity must be vector or leaf (got %r); layer/global "
                    "need the sharded mode (sharding='sharded')" % (granularity,)
                )
            if l1_regularize or l2_regularize:
                raise UserException(
                    "the flat engine takes l1/l2 inside loss_fn (the per-worker "
                    "loss is global there); l1_regularize/l2_regularize are the "
                    "sharded engine's analytic equivalent"
                )
        if nb_workers is None:
            nb_workers = mesh.shape[worker_axis]
        self.nb_workers = int(nb_workers)
        self.nb_real_byz = int(nb_real_byz)
        self.attack = attack
        self.lossy_link = lossy_link
        # Time-varying fault regimes (chaos/schedule.py): the schedule's
        # regime index is computed from the TRACED step counter each step, so
        # attack/loss/straggler knobs switch inside the one compiled program.
        # Chaos SUBSUMES the static whole-run knobs — mixing both would give
        # two transport simulations with colliding PRNG streams.
        self.chaos = validate_chaos_args(chaos, attack, lossy_link, self.nb_workers, self.nb_real_byz)
        # Device-side augmentation: ``batch_transform(worker_batch, key) ->
        # worker_batch`` runs INSIDE the jitted step, per worker, train-only
        # (eval paths never apply it).  Keys are a function of (run seed,
        # step, global worker index) so worker w's augmentation stream is
        # independent of nb_workers/device placement — the same discipline
        # as the host tier (models/preprocessing.py).
        self.batch_transform = batch_transform
        # Per-op terminal narrative (the reference's --trace brackets every
        # loss/gradient/aggregate op with begin/end prints, tools/tf.py:41-58;
        # its graph-level equivalent here is a runtime jax.debug.print after
        # each phase of the step body, value-anchored so the callback sits at
        # the phase boundary in the compiled program).  Debug-cadence only —
        # each device narrates, and the host callback costs real time.
        self.trace_ops = bool(trace_ops)
        # Opt-in per-worker suspicion diagnostics (worker_sq_dist / worker_
        # participation metrics); off by default — the extra O(n·d) pass is
        # a measurable HBM tax at scale.
        self.worker_metrics = bool(worker_metrics)
        # In-step health probe (guardian/probe.py): finite-loss flag, update
        # norm, EMA loss-spike score, per-worker NaN-row flags, nested under
        # metrics["probe"].  On by default — it reuses values the step
        # already computes plus one O(k·d) isfinite pass and an O(n) gather,
        # and adds no dispatches or compiles (tests/test_guardian.py).
        self.health_probe = bool(health_probe)
        # Reputation-gated quarantine: an EMA of a per-step rank signal
        # (1 if the worker's RAW gradient is among the n-f closest to the
        # applied aggregate, else 0); workers whose reputation falls below
        # the threshold have their row masked NaN for that round — the
        # engine treats them exactly like fully-lossy workers, so the rule
        # must absorb NaN rows.  The signal is measured on the raw
        # (pre-quarantine) submissions, so an honest worker whose gradients
        # re-approach the aggregate recovers and is re-admitted.
        self.reputation_decay, self.quarantine_threshold = validate_reputation_args(
            gar, reputation_decay, quarantine_threshold
        )
        # Flat granularity:leaf applies the rule PER PARAMETER LEAF (per-
        # layer selection — the sharded mode's semantics on a plain worker
        # mesh, including n vmapped workers on one chip).  Memory shifts
        # from the dimension-sharded O(d) blocks to one (n, d_leaf) gather
        # at a time, and distance work is replicated per device instead of
        # sharded — the price of letting every layer pick its own honest
        # set.  Sharded granularities were validated above.
        self.granularity = granularity
        if self.sharded:
            if granularity == "global" and (gar.uses_axis or gar.uses_key) and not gar.needs_distances:
                # The global path concatenates DISTANCES across leaves;
                # iterative rules would need their per-iteration row norms
                # accumulated across every leaf instead, which the per-leaf
                # loop cannot do — refuse rather than silently degrade to
                # per-leaf semantics.
                raise UserException(
                    "granularity:global is not supported for %s (whole-vector "
                    "norms across leaves are not implemented); use "
                    "granularity:layer" % type(gar).__name__
                )
            if gar.nb_workers != self.nb_workers:
                raise UserException(
                    "GAR was built for n=%d but the mesh worker axis is %d"
                    % (gar.nb_workers, self.nb_workers)
                )
        # l1/l2 regularization (reference: graph.py:125-139).  The flat
        # engine wraps the per-worker loss; under the sharded shard_map the
        # loss is a LOCAL PARTIAL, so a parameter-norm term in the loss
        # would be counted once per replicating device.  The sharded body
        # instead applies the reg gradient ANALYTICALLY (l1*sign(p) +
        # 2*l2*p, elementwise on each shard) to the psum-completed
        # gradients — exact, shard-local, no double counting — and adds the
        # correctly replication-scaled norm to the reported loss.
        self.l1_regularize = float(l1_regularize) if l1_regularize else None
        self.l2_regularize = float(l2_regularize) if l2_regularize else None
        # Captured by the sharded init_state for put_state (checkpoint
        # restore re-sharding).
        self._state_shardings = None
        # Two numerically-equivalent leaf implementations (identical
        # selections and PRNG keys; values agree to float tolerance —
        # vmapped reductions need not lower bit-exactly), dispatched by backend
        # (measured, BENCHMARKS.md row 6b): stacking same-shaped leaves into
        # one vmapped rule call per distinct size is the TPU-shaped program
        # (O(#shapes) collectives/kernels instead of O(#leaves)), but on
        # XLA:CPU the batched sorts/selects lower WORSE than the plain loop
        # (ResNet-50: 157 vs 93 s/step on the 1-core host).  "auto" picks
        # bucketed on TPU, unrolled elsewhere; True/False force it.
        if leaf_bucketing != "auto":
            if not isinstance(leaf_bucketing, bool):
                # 1/0 would pass a tuple-membership check (bool-int equality)
                # yet miss an `is True` dispatch — normalize strictly instead
                raise UserException(
                    "leaf_bucketing must be 'auto' or a bool (got %r)" % (leaf_bucketing,)
                )
        self.leaf_bucketing = leaf_bucketing
        # History-aware robustness (Karimireddy et al. 2021): with
        # worker_momentum = beta in (0, 1), every worker sends its momentum
        # m_i <- beta*m_i + (1-beta)*g_i instead of the raw gradient, so the
        # GAR aggregates slow-moving honest statistics that a fresh-noise
        # Byzantine strategy cannot track.  Carried worker-sharded.
        self.worker_momentum = None if worker_momentum is None else float(worker_momentum)
        if self.worker_momentum is not None and not 0.0 < self.worker_momentum < 1.0:
            raise UserException("worker_momentum must lie in (0, 1), got %r" % worker_momentum)
        # Wire precision: the all_to_all + all_gather carry ~2d floats per
        # device per step (the dominant wire cost, module docstring); bf16
        # halves it.  Gradients are quantized ONCE before the reshard and all
        # GAR math runs in f32 on the upcast values, so every device still
        # sees bit-identical inputs (replicated-update determinism holds).
        # float32 normalizes to None (no quantization path compiled in).
        dt = jnp.dtype(exchange_dtype) if exchange_dtype else None
        self.exchange_dtype = None if dt == jnp.float32 else dt
        # Generalized wire codec (parallel/compress.py, docs/engine.md "The
        # wire"): ``exchange`` accepts a spec string (int8[:ef] /
        # topk:... / bf16 / f32) or a WireCodec.  bf16/f32 normalize onto
        # the dtype twin above (bit-compatible with existing runs);
        # int8/topk engage the codec in the submission pipeline — encoded
        # after the worker-local attacks, decoded at the aggregation
        # boundary so every GAR sees float32 rows.  Feasibility (masked
        # fixed-point path, sharded mode, topk budget) refuses HERE, which
        # is also the guardian escalation rebuild path — a ladder rung
        # that re-builds the stack re-validates the codec.
        self.codec = None
        if exchange is not None:
            from .compress import parse_exchange_spec

            if self.exchange_dtype is not None:
                raise UserException(
                    "pass either exchange= (the wire codec spec) or "
                    "exchange_dtype=, not both — bf16 is spelled "
                    "exchange='bf16' on the codec surface"
                )
            spec_dtype, self.codec = parse_exchange_spec(exchange)
            if spec_dtype is not None:
                self.exchange_dtype = spec_dtype
        if self.codec is not None:
            if self.sharded:
                raise UserException(
                    "--exchange %s needs the flat engine: the sharded "
                    "dataflow's per-(worker, leaf) submissions would need "
                    "per-leaf codec/error-feedback state, a different "
                    "protocol (bf16/f32 wire dtypes work everywhere)"
                    % self.codec.spec()
                )
            self.codec.validate_for(gar=gar)
        #: the per-worker error-feedback residual rides TrainState.ef
        #: (worker-sharded, serialized — core/train_state.py)
        self.carries_ef = self.codec is not None and self.codec.uses_ef
        # Logical workers are decoupled from worker-axis slots in BOTH
        # modes: k = n/W workers are vmapped per slot (flat: per device;
        # sharded: per (pipe x model) submesh).  ``nb_mesh_workers`` is the
        # historical sharded-mode name for the same axis size.
        self.nb_devices = self.nb_mesh_workers = mesh.shape[worker_axis]
        if self.nb_workers % self.nb_devices != 0:
            raise UserException(
                "nb_workers (%d) must be a multiple of the worker mesh axis (%d)"
                % (self.nb_workers, self.nb_devices)
            )
        self.workers_per_device = self.nb_workers // self.nb_devices
        if self.nb_real_byz > self.nb_workers:
            raise UserException("More real Byzantine workers than workers")
        if attack is not None and self.nb_real_byz == 0:
            raise UserException("An attack needs --nb-real-byz-workers > 0 to have anyone to run it")
        # CLEVER stale infill needs the previously-received gradients carried
        # across steps (mpi_rendezvous_mgr.patch:833-835); stale-mode chaos
        # stragglers reuse the exact same carry (chaos/stragglers.py).
        self.carries_gradients = (lossy_link is not None and lossy_link.clever) or (
            self.chaos is not None and self.chaos.needs_carry
        )
        # Authenticated submission (secure/submit.py): every worker's
        # post-transport row is reduced to a tiny checksum INSIDE the one
        # compiled step (zero added dispatches/recompiles — the compile
        # count is identical with secure on or off, asserted by
        # tests/test_secure.py); rows whose tags cannot verify (chaos
        # forge/tamper) are masked NaN before stacking, and the digests +
        # verdicts ride metrics["secure"] to the host where the real HMAC
        # sign/verify runs one dispatch behind (cli/runner.py).
        self.secure = bool(secure)
        # Flight recorder (obs/flight.py): per-step telemetry lanes written
        # in-scan into a ring carried as a TrainState side buffer, fetched
        # by the host only at summary cadence.  Same compiled program shape
        # discipline as the probe: the ring rides the one executable, so
        # the compile count equals the recorder-off run (tests/
        # test_flight.py asserts).
        self.flight = flight
        if flight is not None:
            flight.validate_for(
                nb_workers=self.nb_workers, probe=self.health_probe,
                worker_metrics=self.worker_metrics,
                chaos=self.chaos is not None, secure=self.secure,
            )
        # jitted slice-concat executables for assemble_batches, per slice count
        self._assemble_cache = {}

    # ------------------------------------------------------------------ #

    def _worker_gradients(self, params, batch_shard, loss_fn):
        """vmap the local k workers' loss/grad; returns ((k,) losses, (k, d) grads, flatmap)."""

        def one(worker_batch):
            loss, grads = jax.value_and_grad(loss_fn)(params, worker_batch)
            return loss, grads

        losses, grads = jax.vmap(one)(batch_shard)
        k = self.workers_per_device
        leaves = jax.tree_util.tree_leaves(grads)
        gvecs = jnp.concatenate([leaf.reshape(k, -1).astype(jnp.float32) for leaf in leaves], axis=1)
        flatmap = FlatMap(jax.tree_util.tree_map(lambda g: g[0], grads))
        return losses, gvecs, flatmap

    def _perturb_local(self, gvecs, key, carry=None, ridx=None, ef=None):
        """Apply local attack + wire codec + lossy link + chaos regime +
        the submission-forgery pipeline to each local worker's own slot.

        Returns (perturbed (k, d), new_carry, secure_info, new_ef) —
        ``new_carry`` is the post-transport gradients, i.e. what "the PS
        received" this step: exactly the stale value a lost packet keeps
        under CLEVER infill, and the value a stale-mode straggler keeps
        re-submitting (a worker late k steps in a row re-sends the same
        gradient k times).  ``secure_info`` (None unless ``secure``)
        carries the per-local-worker submitted/received digests and the
        forge/reject verdicts — what the host-side authenticator signs and
        verifies one dispatch behind (secure/submit.py).  ``ef`` is the
        local (k, d) error-feedback shard when the codec carries it;
        ``new_ef`` the updated residuals (None otherwise).
        """
        from ..secure.submit import FORGE_SCALE, row_digest, tamper_row

        k = self.workers_per_device
        didx = jax.lax.axis_index(worker_axis)
        chaos_forgery = self.chaos is not None and self.chaos.has_forgery
        out = []
        carry_rows = []  # post-transport, PRE-forgery (see carry note below)
        ef_rows = [] if ef is not None else None
        sec = {"digest_sent": [], "digest_recv": [], "forged": [], "rejected": []}
        for j in range(k):
            gidx = didx * k + j
            g = gvecs[j]
            wkey = jax.random.fold_in(key, gidx)
            previous = carry[j] if carry is not None else None
            if self.attack is not None and not self.attack.omniscient:
                forged = self.attack.apply_local(g, jax.random.fold_in(wkey, 1))
                g = jnp.where(gidx < self.nb_real_byz, forged, g)
            if self.chaos is not None and self.chaos.has_local_attacks:
                forged = self.chaos.apply_local_attacks(ridx, g, jax.random.fold_in(wkey, 1))
                g = jnp.where(gidx < self.nb_real_byz, forged, g)
            if self.codec is not None:
                # THE WIRE (parallel/compress.py): the row is encoded here
                # — after the worker-local attacks (an attacker forges what
                # it transmits; its forgery crosses the same lossy wire)
                # and BEFORE the transport faults below, so packet-loss NaN
                # masking lands on the DECODED image (a dropped packet of
                # int8 payload is still a NaN coordinate run —
                # parallel/lossy.py).  From here on, ``g`` is the wire
                # image: what the aggregator's decoder emits.
                if ef is not None:
                    g, new_ef_row = self.codec.ef_roundtrip(g, ef[j])
                    ef_rows.append(new_ef_row)
                else:
                    g = self.codec.roundtrip(g)
            if self.lossy_link is not None:
                g = self.lossy_link.apply(g, jax.random.fold_in(wkey, 2), gidx, previous=previous)
            if self.chaos is not None:
                if self.chaos.has_drop:
                    # chaos loss storms hit EVERY worker (link sized n); the
                    # rate is the regime's traced scalar — no recompilation
                    g = self.chaos.link.apply(
                        g, jax.random.fold_in(wkey, 2), gidx,
                        drop_rate=self.chaos.drop_rate(ridx),
                    )
                if self.chaos.has_stragglers:
                    late = self.chaos.stragglers.is_late(
                        wkey, gidx, self.chaos.straggler_rate(ridx)
                    )
                    g = self.chaos.stragglers.apply(
                        g, late, self.chaos.straggler_stale(ridx), previous=previous
                    )
            # The carry captures the row HERE — post-transport, PRE-forgery
            # (the sharded engine's convention): a stale straggler re-sends
            # the worker's own last submission, not the impostor's noise or
            # the aggregator's NaN rejection (a rejected step must not leak
            # extra NaN rows into later steps' f accounting).
            carry_rows.append(g)
            # Submission forgery pipeline (docs/security.md).  Order matters:
            # an impersonator REPLACES the submission (and will sign it with
            # a key it does not have), the sender-side digest covers what was
            # submitted, tampering corrupts bits AFTER signing, the receiver
            # digests what arrived — and under ``secure`` a row whose tag
            # cannot verify is rejected to NaN before stacking (absorbed by
            # the GARs within the same f budget as a lossy row).  Fold tags
            # 5/6 keep the forge/tamper streams disjoint from attack (1),
            # lossy (2), augment (3) and sampling (4).
            is_forge = is_tamper = None
            if chaos_forgery:
                fkey = jax.random.fold_in(wkey, 5)
                is_forge = (gidx < self.nb_real_byz) & jax.random.bernoulli(
                    fkey, self.chaos.forge_rate(ridx)
                )
                impostor = jax.random.normal(
                    jax.random.fold_in(fkey, 1), g.shape, g.dtype
                ) * jnp.asarray(FORGE_SCALE, g.dtype)
                g = jnp.where(is_forge, impostor, g)
            sent_digest = None
            if self.secure:
                sent_digest = row_digest(g)
                sec["digest_sent"].append(sent_digest)
            if chaos_forgery:
                tkey = jax.random.fold_in(wkey, 6)
                is_tamper = (gidx < self.nb_real_byz) & jax.random.bernoulli(
                    tkey, self.chaos.tamper_rate(ridx)
                )
                g = jnp.where(is_tamper, tamper_row(g, jax.random.fold_in(tkey, 1)), g)
            if self.secure:
                # without in-transit transforms the received bytes ARE the
                # submitted bytes — reuse the checksum instead of paying a
                # second O(d) pass (half the digest tax of the common case)
                sec["digest_recv"].append(
                    row_digest(g) if chaos_forgery else sent_digest
                )
                forged_flag = is_forge if is_forge is not None else jnp.bool_(False)
                rejected = forged_flag
                if is_tamper is not None:
                    rejected = rejected | is_tamper
                sec["forged"].append(forged_flag)
                sec["rejected"].append(rejected)
                g = jnp.where(rejected, jnp.nan, g)
            out.append(g)
        stacked = jnp.stack(out, axis=0)
        carry = jnp.stack(carry_rows, axis=0) if self.carries_gradients else None
        secure_info = None
        if self.secure:
            secure_info = {
                key_: jnp.stack(values) for key_, values in sec.items()
            }
        new_ef = jnp.stack(ef_rows, axis=0) if ef_rows is not None else None
        return stacked, carry, secure_info, new_ef

    def _reshard_to_blocks(self, gvecs, d):
        """(k, d) worker-sharded -> (n, d_block) dimension-sharded column block."""
        W, k = self.nb_devices, self.workers_per_device
        if self.exchange_dtype is not None:
            gvecs = gvecs.astype(self.exchange_dtype)
        blk = -(-d // W)
        padded = jnp.pad(gvecs, ((0, 0), (0, W * blk - d)))
        pieces = padded.reshape(k, W, blk).transpose(1, 0, 2)  # (W, k, blk)
        if W == 1:
            gathered = pieces
        else:
            gathered = jax.lax.all_to_all(pieces, worker_axis, split_axis=0, concat_axis=0, tiled=True)
            gathered = gathered.reshape(W, k, blk)
        return gathered.reshape(self.nb_workers, blk)

    def _prepare_rows(self, rows, attack_key, reputation, ridx=None):
        """The ORDER-SENSITIVE shared front of both aggregation paths:
        omniscient attack -> requantize forged rows -> quarantine mask.

        Returns ``(rows, raw_rows)``: what the rule consumes and the
        post-attack PRE-quarantine rows the reputation signal measures.
        The quarantine mask applies AFTER the omniscient attack so the
        reputation signal sees what attackers actually submitted (masking
        earlier would measure the attacker's honest gradient and never
        suspect it); forged rows are squeezed through the exchange dtype
        because they crossed the same wire as honest ones."""
        forged = False
        if self.attack is not None and self.attack.omniscient:
            byz_mask = jnp.arange(self.nb_workers) < self.nb_real_byz
            rows = self.attack.apply_matrix(rows, byz_mask, attack_key)
            forged = True
        if self.chaos is not None and self.chaos.has_omniscient_attacks:
            byz_mask = jnp.arange(self.nb_workers) < self.nb_real_byz
            rows = self.chaos.apply_omniscient_attacks(ridx, rows, byz_mask, attack_key)
            forged = True
        if forged:
            # forged rows crossed the same quantized wire as honest ones —
            # the one helper owning the precision-loss semantics
            from .compress import wire_roundtrip

            rows = wire_roundtrip(rows, dtype=self.exchange_dtype, codec=self.codec)
        raw_rows = rows
        if self.quarantine_threshold:
            qmask = quarantine_mask(
                reputation, self.quarantine_threshold, self.gar.nb_byz_workers
            )
            rows = jnp.where(qmask[:, None], jnp.nan, rows)
        return rows, raw_rows

    def _aggregate_block(self, block, key, reputation=None, ridx=None):
        """Omniscient attack, quarantine gate, distances (psum), blockwise GAR.

        Returns ``(agg_block, participation, block, raw_block)`` — the (n,)
        worker participation (or None; computed only under
        ``worker_metrics``), the post-quarantine ``block`` the rule actually
        consumed, and the post-attack PRE-quarantine ``raw_block`` the
        reputation signal measures."""
        block, raw_block = self._prepare_rows(block, key, reputation, ridx=ridx)
        dist2 = None
        if self.gar.needs_distances:
            partial = _partial_pairwise_sq_distances(block)
            dist2 = jax.lax.psum(partial, worker_axis) if self.nb_devices > 1 else partial
            dist2 = jnp.maximum(dist2, 0.0)
        axis = worker_axis if self.nb_devices > 1 else None
        # Replicated per-step key for randomized meta-rules (bucketing's
        # permutation); the reserved tag keeps it disjoint from the
        # per-worker attack/lossy streams.
        from ..gars import GAR_KEY_TAG

        gar_key = jax.random.fold_in(key, GAR_KEY_TAG)
        if self.worker_metrics:
            agg, participation = self.gar.aggregate_block_and_participation(
                block, dist2, axis_name=axis, key=gar_key
            )
            return agg, participation, block, raw_block
        agg = self.gar._call_aggregate(block, dist2, axis_name=axis, key=gar_key)
        return agg, None, block, raw_block

    def _aggregate_per_leaf(self, gvecs, flatmap, key, reputation, ridx=None):
        """granularity:leaf dispatch — bucketed on TPU, unrolled elsewhere
        (numerically equivalent; see ``leaf_bucketing`` in __init__)."""
        on_tpu = self.mesh.devices.flat[0].platform == "tpu"  # where THIS mesh runs
        bucketed = (
            self.leaf_bucketing is True
            or (self.leaf_bucketing == "auto" and on_tpu)
        )
        impl = self._aggregate_per_leaf_bucketed if bucketed else self._aggregate_per_leaf_unrolled
        return impl(gvecs, flatmap, key, reputation, ridx=ridx)

    def _aggregate_per_leaf_bucketed(self, gvecs, flatmap, key, reputation, ridx=None):
        """granularity:leaf — gather and reduce each leaf's (n, d_leaf) rows
        independently (per-layer selection), BUCKETED by leaf size.

        Same-sized leaves are stacked into one (L, n, d_leaf) tensor and
        reduced by a single vmapped rule call behind a single all_gather —
        so a ResNet-50 (~160 leaves, ~dozens of distinct shapes) traces
        O(#distinct sizes) collectives and selection graphs instead of
        O(#leaves) (the compile-time/step-latency blowup VERDICT r2 flagged;
        same stacking trick as the sharded engine's layer axis,
        sharded_engine.py).  Per-leaf PRNG keys reproduce the unrolled
        path's exactly (fold_in by ORIGINAL leaf index), so the two paths
        make the same selections and agree with
        ``_aggregate_per_leaf_unrolled`` to float tolerance (vmapped
        reductions are not guaranteed to lower bit-exactly) — asserted by
        tests/test_engine.py.

        Returns ``(agg, participation, wdist, rep_dist)``: the concatenated
        (d,) aggregate (identical on every device), the mean per-leaf
        participation (or None), and the full per-worker squared distances
        to the aggregate over the post-quarantine and raw rows respectively
        (None unless the corresponding feature is on).  No psums needed:
        every device sees complete rows."""
        from ..gars import GAR_KEY_TAG
        from ..gars.common import pairwise_sq_distances

        W = self.nb_devices
        base_key = jax.random.fold_in(key, GAR_KEY_TAG)
        participation_sum = jnp.zeros((self.nb_workers,), jnp.float32)
        participation_count = 0
        wdist = jnp.zeros((self.nb_workers,), jnp.float32) if self.worker_metrics else None
        rep_dist = (
            jnp.zeros((self.nb_workers,), jnp.float32)
            if self.reputation_decay is not None else None
        )

        buckets = {}  # size -> list of (leaf_index, offset), flattening order
        for i, (_, offset, size, _, _) in enumerate(flatmap.slices):
            buckets.setdefault(size, []).append((i, offset))

        concat_parts = []  # per-bucket (L * size,) aggregates
        perm = np.empty((flatmap.size,), np.int32)  # output slot -> concat slot
        pos = 0
        for size, entries in buckets.items():
            idxs = jnp.asarray([i for i, _ in entries], jnp.int32)
            local = jnp.stack(
                [gvecs[:, off:off + size] for _, off in entries], axis=0
            )  # (L, k, size) — static slices, one tensor on the wire
            if self.exchange_dtype is not None:
                local = local.astype(self.exchange_dtype)  # wire precision
            if W > 1:
                gathered = jax.lax.all_gather(local, worker_axis)  # (W, L, k, size)
                rows = gathered.transpose(1, 0, 2, 3).reshape(
                    len(entries), self.nb_workers, size
                )
            else:
                rows = local
            rows = rows.astype(jnp.float32)

            def per_leaf(leaf_rows, leaf_index):
                prep_key = jax.random.fold_in(key, 20_000 + leaf_index)
                leaf_rows, raw_rows = self._prepare_rows(leaf_rows, prep_key, reputation, ridx=ridx)
                dist2 = (
                    jnp.maximum(pairwise_sq_distances(leaf_rows), 0.0)
                    if self.gar.needs_distances else None
                )
                leaf_key = jax.random.fold_in(base_key, leaf_index)
                if self.worker_metrics:
                    agg_leaf, part = self.gar.aggregate_block_and_participation(
                        leaf_rows, dist2, axis_name=None, key=leaf_key
                    )
                else:
                    agg_leaf = self.gar._call_aggregate(
                        leaf_rows, dist2, axis_name=None, key=leaf_key
                    )
                    part = None
                return agg_leaf.astype(jnp.float32), part, leaf_rows, raw_rows

            # (vmapped rule calls: the Pallas auto-tier detects the
            # batching trace centrally and stays on jnp — gars/common.py
            # _is_batched_tracer)
            aggs, parts, prep_rows, raw_rows = jax.vmap(per_leaf)(rows, idxs)
            if parts is not None:
                participation_sum = participation_sum + jnp.sum(parts, axis=0)
                participation_count += len(entries)
            if wdist is not None:
                diff = prep_rows - aggs[:, None, :]
                wdist = wdist + jnp.sum(diff * diff, axis=(0, 2))
            if rep_dist is not None:
                rdiff = raw_rows - aggs[:, None, :]
                rep_dist = rep_dist + jnp.sum(rdiff * rdiff, axis=(0, 2))
            concat_parts.append(aggs.reshape(-1))
            for j, (_, off) in enumerate(entries):
                perm[off:off + size] = np.arange(
                    pos + j * size, pos + (j + 1) * size, dtype=np.int32
                )
            pos += len(entries) * size

        if not concat_parts:
            return jnp.zeros((0,), jnp.float32), None, wdist, rep_dist
        agg = jnp.concatenate(concat_parts)[perm]  # back to flattening order
        participation = (
            participation_sum / participation_count if participation_count else None
        )
        return agg, participation, wdist, rep_dist

    def _aggregate_per_leaf_unrolled(self, gvecs, flatmap, key, reputation, ridx=None):
        """The plain per-leaf loop (one all_gather + one rule call per
        leaf).  Semantically the definition of granularity:leaf — and the
        DEFAULT path off-TPU (``leaf_bucketing="auto"``; measured faster
        than the batched form on XLA:CPU, BENCHMARKS.md row 6b), CLI-
        reachable via ``--leaf-bucketing off`` anywhere."""
        from ..gars import GAR_KEY_TAG
        from ..gars.common import pairwise_sq_distances

        W = self.nb_devices
        base_key = jax.random.fold_in(key, GAR_KEY_TAG)
        agg_parts = []
        participation_sum = jnp.zeros((self.nb_workers,), jnp.float32)
        participation_count = 0
        wdist = jnp.zeros((self.nb_workers,), jnp.float32) if self.worker_metrics else None
        rep_dist = (
            jnp.zeros((self.nb_workers,), jnp.float32)
            if self.reputation_decay is not None else None
        )
        for i, (_, offset, size, _, _) in enumerate(flatmap.slices):
            local = gvecs[:, offset:offset + size]  # static slice
            if self.exchange_dtype is not None:
                local = local.astype(self.exchange_dtype)  # wire precision
            if W > 1:
                rows = jax.lax.all_gather(local, worker_axis).reshape(self.nb_workers, size)
            else:
                rows = local
            rows = rows.astype(jnp.float32)
            rows, raw_rows = self._prepare_rows(
                rows, jax.random.fold_in(key, 20_000 + i), reputation, ridx=ridx
            )
            dist2 = (
                jnp.maximum(pairwise_sq_distances(rows), 0.0)
                if self.gar.needs_distances else None
            )
            leaf_key = jax.random.fold_in(base_key, i)
            if self.worker_metrics:
                agg_leaf, part = self.gar.aggregate_block_and_participation(
                    rows, dist2, axis_name=None, key=leaf_key
                )
                if part is not None:
                    participation_sum = participation_sum + part
                    participation_count += 1
            else:
                agg_leaf = self.gar._call_aggregate(rows, dist2, axis_name=None, key=leaf_key)
            if wdist is not None:
                diff = rows - agg_leaf[None, :]
                wdist = wdist + jnp.sum(diff * diff, axis=1)
            if rep_dist is not None:
                rdiff = raw_rows - agg_leaf.astype(jnp.float32)[None, :]
                rep_dist = rep_dist + jnp.sum(rdiff * rdiff, axis=1)
            agg_parts.append(agg_leaf.astype(jnp.float32))
        agg = jnp.concatenate(agg_parts) if agg_parts else jnp.zeros((0,), jnp.float32)
        participation = (
            participation_sum / participation_count if participation_count else None
        )
        return agg, participation, wdist, rep_dist

    # ------------------------------------------------------------------ #
    # the step epilogue — ONE implementation for both dataflows

    def _finalize_step(self, state, *, params, opt_state, new_carry,
                       new_momentum, new_momentum_steps, total_loss,
                       update_norm, worker_nan, rep_dist, wdist,
                       participation, secure_metrics, ridx, new_ef=None):
        """Everything after the optimizer update, shared by the flat and the
        sharded step bodies (and the bounded-wait aggregator): reputation
        EMA, health probe, the metrics dict, and the flight-recorder write.
        Callers pass values that are already replicated/psum-completed for
        their dataflow; this method adds no collectives."""
        new_reputation = state.reputation
        if self.reputation_decay is not None:
            # Rank signal on the RAW submissions (post-ALL-attacks,
            # pre-quarantine): 1 if among the n-f closest to the applied
            # aggregate AND finite — NaN-infilled lossy rows read +inf
            # -> signal 0 (the finiteness gate stops +inf index-ties
            # from boosting low-index dead workers).
            from ..gars.common import nonfinite_to_inf, smallest_k_mask

            signal = smallest_k_mask(
                nonfinite_to_inf(rep_dist),
                self.nb_workers - self.gar.nb_byz_workers,
            ).astype(jnp.float32) * jnp.isfinite(rep_dist).astype(jnp.float32)
            beta = self.reputation_decay
            new_reputation = beta * state.reputation + (1.0 - beta) * signal
        new_loss_ema = state.loss_ema
        probe_fields = None
        if self.health_probe:
            from ..guardian import probe as health

            probe_fields = health.probe_metrics(
                total_loss, update_norm,
                health.spike_score(total_loss, state.loss_ema), worker_nan,
            )
            new_loss_ema = health.update_loss_ema(state.loss_ema, total_loss)
        new_state = state.replace(
            step=state.step + 1, params=params, opt_state=opt_state,
            carry=new_carry, momentum=new_momentum,
            momentum_steps=new_momentum_steps,
            reputation=new_reputation, loss_ema=new_loss_ema,
            ef=new_ef if self.carries_ef else state.ef,
        )
        metrics = {
            "total_loss": total_loss,
            "grad_norm": update_norm,
        }
        if probe_fields is not None:
            from ..guardian import probe as health

            metrics[health.PROBE_KEY] = probe_fields
        if secure_metrics is not None:
            metrics["secure"] = secure_metrics
        if ridx is not None:
            # replicated scalar (a pure function of the replicated step)
            # — the observability layer's regime column
            metrics["chaos_regime"] = ridx
        if self.worker_metrics:
            # Suspicion diagnostics: squared distance of each worker's
            # gradient to the aggregate (universal), plus the rule's own
            # per-worker participation weight when it selects by worker.
            metrics["worker_sq_dist"] = wdist
            if participation is not None:
                metrics["worker_participation"] = participation
            if self.reputation_decay is not None:
                metrics["worker_reputation"] = new_reputation
                if self.quarantine_threshold:
                    metrics["nb_quarantined"] = jnp.sum(
                        quarantine_mask(
                            state.reputation, self.quarantine_threshold,
                            self.gar.nb_byz_workers,
                        ).astype(jnp.int32)
                    )
        if self.flight is not None:
            # In-scan flight-recorder write (obs/flight.py): each lane
            # stores the exact traced value the metrics dict carries,
            # so ring rows are bit-identical to per-step metrics by
            # construction.
            new_state = new_state.replace(
                flight=self.flight.record(state.flight, state.step, metrics)
            )
        return new_state, metrics

    # ------------------------------------------------------------------ #
    # the flat dataflow

    def _state_spec(self):
        """PartitionSpec prefix tree for TrainState: everything replicated
        except the worker-sharded side buffers (CLEVER carry, momentum)."""
        return TrainState(
            step=P(),
            params=P(),
            opt_state=P(),
            rng=P(),
            carry=P(worker_axis) if self.carries_gradients else None,
            momentum=P(worker_axis) if self.worker_momentum is not None else None,
            momentum_steps=P() if self.worker_momentum is not None else None,
            reputation=P() if self.reputation_decay is not None else None,
            loss_ema=P() if self.health_probe else None,
            flight=P() if self.flight is not None else None,
            ef=P(worker_axis) if self.carries_ef else None,
        )

    def _flat_out_shardings(self):
        """Explicit jit out_shardings for the flat builders: pin the output
        state to the ``_state_spec`` layout.  Without this the compiler
        canonicalizes size-1 mesh axes to replicated specs, so a run with
        a worker-sharded side buffer (momentum, CLEVER carry, the codec's
        error-feedback residual) would see a differently-committed state
        on its SECOND dispatch and retrace once — the same fix the sharded
        builders ship (see ``_sharded_build_step``)."""
        state_shardings = jax.tree.map(
            lambda spec: None if spec is None else NamedSharding(self.mesh, spec),
            self._state_spec(), is_leaf=_is_spec,
        )
        return (state_shardings, NamedSharding(self.mesh, P()))

    def _make_flat_body(self, loss_fn, tx):
        """The per-step SPMD body shared by build_step and build_multi_step."""
        W = self.nb_devices

        def body(state, batch):
            def mark(fmt, **kw):
                # Anchored on the values it prints, so the callback cannot be
                # hoisted across the phase it brackets (XLA preserves the
                # data dependency; pure prints could reorder freely).
                if self.trace_ops:
                    jax.debug.print(
                        "TRACE step {step} dev {dev} " + fmt,
                        step=state.step, dev=jax.lax.axis_index(worker_axis), **kw)

            key = jax.random.fold_in(state.rng, state.step)
            # Active chaos regime for THIS step: a traced array index into
            # the schedule's compiled knob vectors, so regime switches land
            # at exactly their scheduled step with zero recompilation.
            ridx = self.chaos.regime_index(state.step) if self.chaos is not None else None
            if self.batch_transform is not None:
                k = self.workers_per_device
                didx = jax.lax.axis_index(worker_axis)

                def aug_one(worker_batch, j):
                    # fold tag 3: disjoint from the attack (1) / lossy (2)
                    # streams derived from the same (key, global worker) pair
                    wkey = jax.random.fold_in(jax.random.fold_in(key, didx * k + j), 3)
                    return self.batch_transform(worker_batch, wkey)

                batch = jax.vmap(aug_one)(batch, jnp.arange(k))
            losses, gvecs, flatmap = self._worker_gradients(state.params, batch, loss_fn)
            if self.codec is not None:
                # the codec budget is validated at the first trace, which
                # is also every guardian-escalation rebuild
                self.codec.validate_d(gvecs.shape[-1])
            mark("losses+gradients done: local loss sum {l}", l=jnp.sum(losses))
            new_momentum, new_momentum_steps = None, None
            if self.worker_momentum is not None:
                # Honest workers send momenta (computed BEFORE the attack:
                # attackers forge what they transmit, not what honest peers
                # remember).  Bias-corrected like Adam so early steps are not
                # (1-beta)-scaled relative to plain gradients; the correction
                # counts momentum updates, NOT the global step — the buffer
                # re-zeroes on restore and its warmup must restart with it.
                beta = self.worker_momentum
                new_momentum = beta * state.momentum + (1.0 - beta) * gvecs
                new_momentum_steps = state.momentum_steps + 1
                gvecs = new_momentum / (1.0 - beta ** new_momentum_steps.astype(jnp.float32))
            gvecs, new_carry, secure_info, new_ef = self._perturb_local(
                gvecs, key, carry=state.carry, ridx=ridx,
                ef=state.ef if self.carries_ef else None,
            )
            d = gvecs.shape[-1]
            if self.granularity == "leaf":
                agg, participation, wdist, rep_dist = self._aggregate_per_leaf(
                    gvecs, flatmap, key, state.reputation, ridx=ridx
                )
            else:
                block = self._reshard_to_blocks(gvecs, d)
                if self.exchange_dtype is not None:
                    block = block.astype(jnp.float32)  # GAR math always in f32
                agg_block, participation, seen_block, raw_block = self._aggregate_block(
                    block, key, reputation=state.reputation, ridx=ridx
                )
                if self.exchange_dtype is not None:
                    agg_block = agg_block.astype(self.exchange_dtype)  # wire, leg 2
                if W > 1:
                    agg = jax.lax.all_gather(agg_block, worker_axis, axis=0).reshape(-1)[:d]
                else:
                    agg = agg_block[:d]
                agg = agg.astype(jnp.float32)
                wdist = rep_dist = None
                if self.worker_metrics:
                    # distances over what the aggregator actually saw
                    # (post-attack, post-lossy, post-quarantine)
                    diff = seen_block - agg_block[None, :]
                    wdist = jnp.sum(diff * diff, axis=1)
                    if W > 1:
                        wdist = jax.lax.psum(wdist, worker_axis)
                if self.reputation_decay is not None:
                    rdiff = raw_block - agg_block.astype(jnp.float32)[None, :]
                    rep_dist = jnp.sum(rdiff * rdiff, axis=1)
                    if W > 1:
                        rep_dist = jax.lax.psum(rep_dist, worker_axis)
            mark("aggregate done: |agg| {g}", g=jnp.linalg.norm(agg))
            agg_tree = flatmap.inflate(agg)
            updates, opt_state = tx.update(agg_tree, state.opt_state, state.params)
            params = optax.apply_updates(state.params, updates)
            mark("apply done: |p0| {p}",
                 p=jnp.linalg.norm(jax.tree_util.tree_leaves(params)[0]))
            total_loss = jax.lax.psum(jnp.sum(losses), worker_axis) if W > 1 else jnp.sum(losses)
            worker_nan = None
            if self.health_probe:
                # Per-worker NaN-row flags measure the POST-TRANSPORT
                # submissions (what the aggregation actually received:
                # lossy NaN infill, dropped stragglers, inf attacks) —
                # distinct from loss_finite, which measures model health.
                local_bad = jnp.any(~jnp.isfinite(gvecs), axis=1)  # (k,)
                if W > 1:
                    worker_nan = jax.lax.all_gather(local_bad, worker_axis).reshape(
                        self.nb_workers
                    )
                else:
                    worker_nan = local_bad
            secure_metrics = None
            if secure_info is not None:
                # Submission authentication material for the host-side
                # sign/verify (secure/submit.py): per-worker digests of what
                # was submitted vs received, plus the forge/reject verdicts.
                # Gathered worker-major like the probe's NaN flags.
                def gather_workers(local):
                    if W > 1:
                        gathered = jax.lax.all_gather(local, worker_axis)
                        return gathered.reshape((self.nb_workers,) + local.shape[1:])
                    return local

                secure_metrics = {
                    name: gather_workers(value)
                    for name, value in secure_info.items()
                }
            return self._finalize_step(
                state, params=params, opt_state=opt_state, new_carry=new_carry,
                new_momentum=new_momentum, new_momentum_steps=new_momentum_steps,
                total_loss=total_loss, update_norm=jnp.linalg.norm(agg),
                worker_nan=worker_nan, rep_dist=rep_dist, wdist=wdist,
                participation=participation, secure_metrics=secure_metrics,
                ridx=ridx, new_ef=new_ef,
            )

        return body

    def _flat_build_step(self, loss_fn, tx):
        """Build the jitted robust training step.

        Args:
          loss_fn: (params, worker_batch) -> scalar loss.
          tx: optax GradientTransformation.
        Returns:
          step(state, batch) -> (state, metrics) with ``batch`` pytrees of
          leading dimension nb_workers (worker-major), sharded over the mesh.
        """
        body = self._make_flat_body(loss_fn, tx)
        sharded = compat.shard_map(
            body,
            mesh=self.mesh,
            in_specs=(self._state_spec(), P(worker_axis)),
            out_specs=(self._state_spec(), P()),
            check_vma=False,
        )
        # The span wrapper is HOST-side only (obs/trace.py): it never touches
        # the jitted callable, so the compile count is identical with tracing
        # on or off (tests/test_obs.py asserts), and attribute access
        # (``_cache_size``) falls through to the jit.
        return trace.traced(
            "train_step.dispatch",
            jax.jit(sharded, donate_argnums=(0,),
                    out_shardings=self._flat_out_shardings()),
            cat="train",
        )

    def _flat_build_multi_step(self, loss_fn, tx, repeat_steps=None):
        """Build a jitted K-step trainer: one dispatch runs a whole scan.

        Per-step host dispatch dominates wall time for small models (the
        reference pays this as a full PS round-trip per `sess.run`,
        runner.py:562-576); scanning K steps inside one executable removes
        it. Metrics come back per step (leading K).

        Two forms:
        - ``repeat_steps=None``: ``multi(state, batches)`` with every batch
          leaf leading (K, nb_workers, ...) — K distinct batches.
        - ``repeat_steps=K``: ``multi(state, batch)`` reuses one
          device-resident worker-major batch for K steps (no K-fold host
          transfer; what the throughput bench uses).
        """
        step_body = self._make_flat_body(loss_fn, tx)

        if repeat_steps is None:

            def many(state, batches):
                return jax.lax.scan(step_body, state, batches)

            batch_spec = P(None, worker_axis)
        else:

            def many(state, batch):
                return jax.lax.scan(
                    lambda s, _: step_body(s, batch), state, None, length=int(repeat_steps)
                )

            batch_spec = P(worker_axis)

        sharded = compat.shard_map(
            many,
            mesh=self.mesh,
            in_specs=(self._state_spec(), batch_spec),
            out_specs=(self._state_spec(), P()),
            check_vma=False,
        )
        return trace.traced(
            "train_multi_step.dispatch",
            jax.jit(sharded, donate_argnums=(0,),
                    out_shardings=self._flat_out_shardings()),
            cat="train",
        )

    def build_sampled_multi_step(self, loss_fn, tx, repeat_steps, batch_size):
        """K-step trainer drawing FRESH per-worker batches ON DEVICE each
        step from a device-resident dataset.

        Rationale: on a tunneled TPU the host->device input path is the
        measured bound — config 2 streams at ~2.0 steps/s while the same
        program with the batch already resident runs at ~26 steps/s
        (bench_mini, round 4).  The reference streams each worker's batches
        through a local queue-runner pipeline every step (graph.py:251-254
        places each worker's input ops on that task's CPU; the pipeline
        itself is the experiment's DatasetDataProvider + tf.train.batch +
        prefetch_queue stack, experiments/cnnet.py:127-141); the
        TPU-native equivalent is to transfer the dataset ONCE (CIFAR-10
        train is ~0.6 GB in f32 — a few percent of HBM) and gather each
        worker's sampled rows in-graph, so every step still trains on a
        fresh i.i.d.-with-replacement draw (the same stream semantics as
        ``WorkerBatchIterator``, datasets.py:318-325) but no step pays the
        tunnel.

        Returns ``multi(state, data) -> (state, metrics)`` where ``data`` is
        the dataset pytree (e.g. ``{"image": x_train, "label": y_train}``),
        placed replicated via :meth:`replicate`.  Worker w's step-s draw is
        a pure function of ``(state.rng, s, w)`` — independent of the mesh
        layout, reproducible across restores, and disjoint (fold tag 4) from
        the attack (1) / lossy (2) / augment (3) streams derived from the
        same key.  Device-side augmentation (``batch_transform``) composes
        unchanged: it runs inside the step body on the sampled batch.
        """
        step_body = self._make_flat_body(loss_fn, tx)
        k = self.workers_per_device
        nb_steps = int(repeat_steps)
        batch_size = int(batch_size)

        def many(state, data):
            nb_examples = jax.tree_util.tree_leaves(data)[0].shape[0]

            def sampled_body(s, _):
                key = jax.random.fold_in(s.rng, s.step)
                didx = jax.lax.axis_index(worker_axis)

                def draw(j):
                    # fold tag 4: the data-sampling stream, disjoint from
                    # attack (1) / lossy (2) / augment (3)
                    wkey = jax.random.fold_in(
                        jax.random.fold_in(key, didx * k + j), 4
                    )
                    idx = jax.random.randint(wkey, (batch_size,), 0, nb_examples)
                    return jax.tree_util.tree_map(lambda a: a[idx], data)

                batch = jax.vmap(draw)(jnp.arange(k))
                return step_body(s, batch)

            return jax.lax.scan(sampled_body, state, None, length=nb_steps)

        sharded = compat.shard_map(
            many,
            mesh=self.mesh,
            in_specs=(self._state_spec(), P()),
            out_specs=(self._state_spec(), P()),
            check_vma=False,
        )
        return trace.traced(
            "train_sampled_multi_step.dispatch",
            jax.jit(sharded, donate_argnums=(0,),
                    out_shardings=self._flat_out_shardings()),
            cat="train",
        )

    def _flat_build_gar_probe(self, d, seed=0):
        """Jitted GAR-only executable at the engine's exact (n, d) and
        sharding — the measurement instrument behind the runner's
        ``gar_seconds_total`` / ``gar.aggregate`` telemetry.

        Returns ``probe(step)``: one full aggregation (psum-completed
        distances + the rule's blockwise reduction — the same path the
        compiled train step runs in phase 5/6 of the module docstring) over
        a persistent synthetic device-resident row matrix.  Attacks, lossy
        links and quarantine are deliberately excluded: the probe times the
        RULE at the run's real (n, d), not the adversity simulation.  The
        caller times ``jax.block_until_ready(probe(step))``; ``step`` folds
        into the rule key so randomized meta-rules (bucketing/hier) redraw
        like they do in training."""
        from ..gars import GAR_KEY_TAG

        W = self.nb_devices
        blk = -(-int(d) // W)
        # Generate the synthetic rows ON DEVICE under jit with an explicit
        # output sharding: GSPMD shards the generation itself, so the host
        # never materializes the (n, d) matrix (n x the model footprint at
        # the large n the probe exists to measure).
        make_rows = jax.jit(
            lambda k: jax.random.normal(k, (self.nb_workers, W * blk), jnp.float32),
            out_shardings=jax.sharding.NamedSharding(self.mesh, P(None, worker_axis)),
        )
        rows = make_rows(jax.random.PRNGKey(seed))

        def body(block, key):
            dist2 = None
            if self.gar.needs_distances:
                partial = _partial_pairwise_sq_distances(block)
                dist2 = jax.lax.psum(partial, worker_axis) if W > 1 else partial
                dist2 = jnp.maximum(dist2, 0.0)
            axis = worker_axis if W > 1 else None
            gar_key = jax.random.fold_in(key, GAR_KEY_TAG)
            return self.gar._call_aggregate(block, dist2, axis_name=axis, key=gar_key)

        sharded = compat.shard_map(
            body, mesh=self.mesh,
            in_specs=(P(None, worker_axis), P()),
            out_specs=P(worker_axis),
            check_vma=False,
        )
        fn = jax.jit(sharded)
        base = jax.random.PRNGKey(seed)

        def probe(step=0):
            return fn(rows, jax.random.fold_in(base, step))

        return probe

    def build_eval_sums(self, metric_fn):
        """Build the jitted evaluation step returning (sum, count) accumulators.

        Exact full-split metrics need sums accumulated across *all* eval
        batches before dividing (the reference evaluates the whole test set in
        one graph pass, experiments/mnist.py:136-148; here the host loop
        accumulates per-batch device sums instead).

        Args:
          metric_fn: (params, worker_batch) -> dict name -> (sum, count).
        Returns:
          eval_step(state, batch) -> dict name -> (sum, count) over the batch.
        """
        W = self.nb_devices

        def body(state, batch):
            sums = jax.vmap(lambda b: metric_fn(state.params, b))(batch)
            folded = jax.tree_util.tree_map(lambda x: jnp.sum(x, axis=0), sums)
            if W > 1:
                folded = jax.lax.psum(folded, worker_axis)
            return folded

        sharded = compat.shard_map(
            body,
            mesh=self.mesh,
            in_specs=(self._state_spec(), P(worker_axis)),
            out_specs=P(),
            check_vma=False,
        )
        return trace.traced("eval_step.dispatch", jax.jit(sharded), cat="eval")

    def _flat_build_eval(self, metric_fn):
        """Like ``build_eval_sums`` but divides, returning per-batch means."""
        eval_sums = self.build_eval_sums(metric_fn)

        def means(state, batch):
            folded = eval_sums(state, batch)
            return {name: total / jnp.maximum(count, 1) for name, (total, count) in folded.items()}

        return means

    # ------------------------------------------------------------------ #

    def shard_batch(self, batch):
        """Device_put a worker-major batch pytree with the worker sharding."""
        spec = jax.sharding.NamedSharding(self.mesh, P(worker_axis))
        return jax.device_put(batch, spec)

    def shard_batches(self, batches):
        """Device_put a (K, nb_workers, ...) batch stack for build_multi_step.

        The step axis is unsharded, so this also places a chunk SLICE
        ((k_i, nb_workers, ...) for any k_i) — the input pipeline
        (models/datasets.py ChunkPipeline) issues one such transfer per
        slice and re-joins them with :meth:`assemble_batches`."""
        spec = jax.sharding.NamedSharding(self.mesh, P(None, worker_axis))
        return jax.device_put(batches, spec)

    def assemble_batches(self, parts):
        """Concatenate step-axis chunk slices (each ``shard_batches``-placed)
        into the one (K, nb_workers, ...) device chunk ``build_multi_step``
        consumes.  Jitted (cached per slice count), so after the first chunk
        this is a single device-side executable whose output is a FRESH
        buffer — the input pipeline's host ping-pong buffers are safe to
        reuse once it has run, even if a backend aliased a ``device_put``."""
        fn = self._assemble_cache.get(len(parts))
        if fn is None:
            fn = jax.jit(lambda *xs: jax.tree_util.tree_map(
                lambda *leaves: jnp.concatenate(leaves, axis=0), *xs))
            self._assemble_cache[len(parts)] = fn
        return fn(*parts)

    def replicate(self, tree):
        """Device_put a pytree fully replicated over the mesh."""
        spec = jax.sharding.NamedSharding(self.mesh, P())
        return jax.device_put(tree, spec)

    def _worker_sharded(self, array_or_none, d=None):
        """Device_put (or create zeroed) a (nb_workers, d) worker-sharded buffer."""
        spec = jax.sharding.NamedSharding(self.mesh, P(worker_axis))
        if array_or_none is not None:
            return jax.device_put(array_or_none, spec)
        return jax.jit(lambda: jnp.zeros((self.nb_workers, d), jnp.float32), out_shardings=spec)()

    def _flat_put_state(self, state):
        """Device_put a TrainState with the engine's state sharding — fully
        replicated except the worker-sharded side buffers (restore path)."""
        carry, momentum, ef = state.carry, state.momentum, state.ef
        placed = self.replicate(state.replace(carry=None, momentum=None, ef=None))
        if carry is not None:
            carry = self._worker_sharded(carry)
        if momentum is not None:
            momentum = self._worker_sharded(momentum)
        if ef is not None:
            ef = self._worker_sharded(ef)
        return placed.replace(carry=carry, momentum=momentum, ef=ef)

    def _flat_init_state(self, params, tx, seed=0):
        """Create a replicated TrainState, plus zeroed worker-sharded side
        buffers when enabled: the CLEVER carry (packets lost before any
        gradient was received read as zero contributions, like the
        reference's freshly-allocated reassembly buffer) and the per-worker
        momentum."""
        state = self.replicate(TrainState.create(params, tx, rng=jax.random.PRNGKey(seed)))
        d = sum(leaf.size for leaf in jax.tree_util.tree_leaves(params))
        if self.carries_gradients:
            state = state.replace(carry=self._worker_sharded(None, d))
        if self.worker_momentum is not None:
            state = state.replace(
                momentum=self._worker_sharded(None, d),
                momentum_steps=self.replicate(jnp.zeros((), jnp.int32)),
            )
        if self.codec is not None:
            # the codec budget is validated as soon as d is known — which
            # includes every guardian-escalation rebuild
            self.codec.validate_d(d)
        if self.carries_ef:
            # fresh codec state: zero residuals (restore overwrites them —
            # the EF buffer is serialized, unlike carry/momentum)
            state = state.replace(ef=self._worker_sharded(None, d))
        if self.reputation_decay is not None:
            # everyone starts trusted; quarantine only after evidence accrues
            state = state.replace(
                reputation=self.replicate(jnp.ones((self.nb_workers,), jnp.float32))
            )
        if self.health_probe:
            from ..guardian.probe import EMA_UNSET

            state = state.replace(
                loss_ema=self.replicate(jnp.float32(EMA_UNSET))
            )
        if self.flight is not None:
            # empty ring, every slot tagged invalid (step -1)
            state = state.replace(
                flight=self.replicate(self.flight.init_buffers())
            )
        return state

    # ------------------------------------------------------------------ #
    # the leafwise-sharded dataflow (logical worker = (pipe x model) submesh)

    def _sharded_init_state(self, init_fn, specs, tx, seed=0):
        """Create the sharded TrainState.

        Args:
          init_fn: key -> global parameter pytree (e.g. transformer.init_params).
          specs:   matching pytree of PartitionSpecs (transformer.param_specs).
          tx:      optax GradientTransformation.
        """
        shardings = jax.tree.map(lambda s: NamedSharding(self.mesh, s), specs, is_leaf=_is_spec)
        params = jax.jit(init_fn, out_shardings=shardings)(jax.random.PRNGKey(seed))
        rep = NamedSharding(self.mesh, P())
        # Optimizer state must come out with EXPLICIT NamedShardings: optax
        # buffers that mirror the params (adam's mu/nu, momentum's trace —
        # they share the params' treedef) take the params' layouts, every
        # other allocation (schedule counts etc.) replicates.  Relying on
        # ambient-mesh propagation instead is version-fragile: on older JAX
        # there is no ambient mesh and jit commits fresh outputs to a single
        # device, which the spec-deriving build_step cannot consume.
        opt_shapes = jax.eval_shape(tx.init, params)
        params_treedef = jax.tree_util.tree_structure(params)
        param_shardings = jax.tree.map(lambda p: p.sharding, params)

        def params_like(node):
            try:
                return jax.tree_util.tree_structure(node) == params_treedef
            except TypeError:
                return False

        if params_treedef.num_leaves == 1:
            # a single-leaf treedef would "match" every leaf, so identify
            # the params-mirroring buffers by shape/dtype identity instead
            only = jax.tree_util.tree_leaves(params)[0]
            opt_shardings = jax.tree.map(
                lambda s: only.sharding
                if (s.shape, s.dtype) == (only.shape, only.dtype) else rep,
                opt_shapes,
            )
        else:
            opt_shardings = jax.tree.map(
                lambda node: param_shardings if params_like(node) else rep,
                opt_shapes, is_leaf=params_like,
            )
        with compat.set_mesh(self.mesh):  # new-JAX path also wants the mesh ambient
            opt_state = jax.jit(tx.init, out_shardings=opt_shardings)(params)

        def per_worker_zeros():
            m_shardings = jax.tree.map(
                lambda s: NamedSharding(self.mesh, P(worker_axis, *tuple(s))),
                specs, is_leaf=_is_spec,
            )
            return jax.jit(
                lambda: jax.tree.map(
                    lambda p: jnp.zeros((self.nb_workers,) + p.shape, jnp.float32), params
                ),
                out_shardings=m_shardings,
            )()

        momentum = momentum_steps = carry = reputation = loss_ema = None
        flight = None
        if self.worker_momentum is not None:
            momentum = per_worker_zeros()
            momentum_steps = jax.device_put(jnp.zeros((), jnp.int32), rep)
        if self.carries_gradients:
            carry = per_worker_zeros()
        if self.reputation_decay is not None:
            reputation = jax.device_put(jnp.ones((self.nb_workers,), jnp.float32), rep)
        if self.health_probe:
            from ..guardian.probe import EMA_UNSET

            loss_ema = jax.device_put(jnp.float32(EMA_UNSET), rep)
        if self.flight is not None:
            # empty replicated ring, every slot tagged invalid (step -1)
            flight = jax.device_put(self.flight.init_buffers(), rep)
        state = TrainState(
            step=jax.device_put(jnp.zeros((), jnp.int32), rep),
            params=params,
            opt_state=opt_state,
            rng=jax.device_put(jax.random.PRNGKey(seed), rep),
            carry=carry,
            momentum=momentum,
            momentum_steps=momentum_steps,
            reputation=reputation,
            loss_ema=loss_ema,
            flight=flight,
        )
        # Remember the layout for put_state (checkpoint restore re-sharding).
        self._state_shardings = jax.tree.map(lambda a: a.sharding, state)
        return state

    def _sharded_put_state(self, state):
        """Re-shard a (possibly host-resident) state onto this mesh with the
        layout ``init_state`` established — the checkpoint-restore path
        (cli/runner.py) round-trips state through the host and needs the
        sharded placement back.  Leaves that are already live device arrays
        with the right sharding pass through unchanged."""
        if self._state_shardings is None:
            raise RuntimeError("put_state needs init_state to have run first")
        return jax.tree.map(jax.device_put, state, self._state_shardings)

    def _perturb(self, g, spec, key, widx, previous=None, ridx=None, late=None):
        """Worker-local attack + lossy link + chaos regime on this worker's
        own shard (the sharded twin of ``_perturb_local``'s head; kept
        separate because the PRNG stream is keyed per (worker, leaf) here).

        Returns (perturbed leaf, post-transport leaf) — the latter is what
        "the receiver saw", the stale value a lost packet keeps under CLEVER
        and a stale-mode straggler keeps re-submitting.  ``late`` is the
        worker's per-STEP lateness flag (drawn once in the body, shared by
        every leaf: a late worker misses the deadline for its whole
        gradient).
        """
        flat = g.reshape(-1)
        prev_flat = previous.reshape(-1) if previous is not None else None
        if self.attack is not None and not self.attack.omniscient:
            forged = self.attack.apply_local(flat, jax.random.fold_in(key, 1))
            flat = jnp.where(widx < self.nb_real_byz, forged, flat)
        if self.chaos is not None and self.chaos.has_local_attacks:
            forged = self.chaos.apply_local_attacks(ridx, flat, jax.random.fold_in(key, 1))
            flat = jnp.where(widx < self.nb_real_byz, forged, flat)
        if self.lossy_link is not None:
            flat = self.lossy_link.apply(flat, jax.random.fold_in(key, 2), widx, previous=prev_flat)
        if self.chaos is not None:
            if self.chaos.has_drop:
                flat = self.chaos.link.apply(
                    flat, jax.random.fold_in(key, 2), widx,
                    drop_rate=self.chaos.drop_rate(ridx),
                )
            if late is not None:
                flat = self.chaos.stragglers.apply(
                    flat, late, self.chaos.straggler_stale(ridx), previous=prev_flat
                )
        out = flat.reshape(g.shape)
        return out, out

    def _submission_pipeline(self, g_leaves, key, gidx, ridx):
        """The submission-forgery pipeline on sharded leaves (the tail of
        the flat ``_perturb_local``, re-expressed per leaf): chaos ``forge``
        replaces every leaf of a coalition worker with impostor noise,
        sender digests accumulate over all leaf shards, ``tamper`` flips a
        bit after signing, receiver digests follow, and under ``secure`` a
        rejected worker's every leaf reads NaN.

        Returns ``(g_leaves, secure_local)`` — ``secure_local`` (None unless
        ``secure``) holds the per-LOCAL-worker digests (lane sums over this
        device's shards; the body psum-completes them within the worker
        group) and the forge/reject verdicts.
        """
        from ..secure.submit import (
            DIGEST_LANES,
            FORGE_SCALE,
            row_digest,
            tamper_row,
        )

        chaos_forgery = self.chaos is not None and self.chaos.has_forgery
        if not (self.secure or chaos_forgery):
            return g_leaves, None
        k = self.workers_per_device
        out_leaves = [[] for _ in g_leaves]
        sent = jnp.zeros((k, DIGEST_LANES), jnp.uint32)
        recv = jnp.zeros((k, DIGEST_LANES), jnp.uint32)
        forged_flags, rejected_flags = [], []
        for j in range(k):
            widx = gidx * k + j
            # the 32_000+ offset namespace keeps these per-worker streams
            # disjoint from the per-(worker, leaf) perturbation parents and
            # the 30_000+ straggler draws (see the body's key discipline)
            wkey = jax.random.fold_in(key, 32_000 + widx)
            is_forge = is_tamper = None
            if chaos_forgery:
                fkey = jax.random.fold_in(wkey, 5)
                is_forge = (widx < self.nb_real_byz) & jax.random.bernoulli(
                    fkey, self.chaos.forge_rate(ridx)
                )
                tkey = jax.random.fold_in(wkey, 6)
                is_tamper = (widx < self.nb_real_byz) & jax.random.bernoulli(
                    tkey, self.chaos.tamper_rate(ridx)
                )
            forged_flag = is_forge if is_forge is not None else jnp.bool_(False)
            rejected = forged_flag
            if is_tamper is not None:
                rejected = rejected | is_tamper
            sent_j = jnp.zeros((DIGEST_LANES,), jnp.uint32)
            recv_j = jnp.zeros((DIGEST_LANES,), jnp.uint32)
            for i, g in enumerate(g_leaves):
                flat = g[j].reshape(-1).astype(jnp.float32)
                if is_forge is not None:
                    impostor = jax.random.normal(
                        jax.random.fold_in(jax.random.fold_in(fkey, 1), i),
                        flat.shape, flat.dtype,
                    ) * jnp.float32(FORGE_SCALE)
                    flat = jnp.where(is_forge, impostor, flat)
                leaf_digest = None
                if self.secure:
                    # per-leaf salt: leaves must not alias in the checksum
                    leaf_digest = row_digest(flat, salt=i * 0x9E3779B1)
                    sent_j = sent_j + leaf_digest
                if is_tamper is not None and i == 0:
                    # one bit flipped in transit (the first leaf's shard)
                    flat = jnp.where(
                        is_tamper, tamper_row(flat, jax.random.fold_in(tkey, 1)), flat
                    )
                if self.secure:
                    # no in-transit transform on this leaf -> received bytes
                    # are the submitted bytes, reuse the checksum
                    if chaos_forgery and i == 0:
                        leaf_digest = row_digest(flat, salt=i * 0x9E3779B1)
                    recv_j = recv_j + leaf_digest
                    flat = jnp.where(rejected, jnp.nan, flat)
                out_leaves[i].append(flat.reshape(g[j].shape).astype(g.dtype))
            sent = sent.at[j].set(sent_j)
            recv = recv.at[j].set(recv_j)
            forged_flags.append(forged_flag)
            rejected_flags.append(rejected)
        g_leaves = [jnp.stack(rows) for rows in out_leaves]
        if not self.secure:
            return g_leaves, None
        return g_leaves, {
            "digest_sent": sent,
            "digest_recv": recv,
            "forged": jnp.stack(forged_flags),
            "rejected": jnp.stack(rejected_flags),
        }

    def _leaf_buckets(self, g, spec):
        """Reshape a locally worker-stacked (k, ...) leaf to (k, n_buckets,
        d_bucket) rows-to-be."""
        k = g.shape[0]
        if self.granularity == "layer" and spec is not None and len(spec) >= 2 and spec[0] == pipe_axis:
            # Stage-stacked leaf (local stage dim 1, then the scanned layer
            # dim): one bucket per layer.
            return g.reshape(k, g.shape[1] * g.shape[2], -1)
        return g.reshape(k, 1, -1)

    def _gather_rows(self, buckets):
        """(k, Lb, d) local buckets -> (Lb, n, d) per-worker rows via one
        all_gather over the worker axis (worker-major: global worker index
        is group * k + local slot, the same layout the flat dataflow uses)."""
        if self.exchange_dtype is not None:
            buckets = buckets.astype(self.exchange_dtype)
        rows = jax.lax.all_gather(buckets, worker_axis)  # (W, k, Lb, d)
        if self.exchange_dtype is not None:
            rows = rows.astype(jnp.float32)
        rows = rows.reshape((self.nb_workers,) + rows.shape[2:])  # (n, Lb, d)
        return jnp.swapaxes(rows, 0, 1)

    def _apply_omniscient(self, rows, key, ridx=None):
        byz_mask = jnp.arange(self.nb_workers) < self.nb_real_byz
        forged = False
        if self.attack is not None and self.attack.omniscient:
            rows = jax.vmap(lambda m: self.attack.apply_matrix(m, byz_mask, key))(rows)
            forged = True
        if self.chaos is not None and self.chaos.has_omniscient_attacks:
            rows = jax.vmap(
                lambda m: self.chaos.apply_omniscient_attacks(ridx, m, byz_mask, key)
            )(rows)
            forged = True
        if forged:
            # forged rows crossed the same quantized wire as honest ones
            # (sharded mode refuses codecs, so this is the dtype twin —
            # elementwise, shape-agnostic over the bucket stack)
            from .compress import wire_roundtrip

            rows = wire_roundtrip(rows, dtype=self.exchange_dtype)
        return rows

    def _bucket_distances(self, rows, spec):
        """(Lb, n, n) squared distances for this leaf's buckets (exact)."""
        partial = jax.vmap(centered_gram_sq_distances)(rows.astype(jnp.float32))
        if model_axis in _spec_axis_names(spec):
            partial = jax.lax.psum(partial, model_axis)
        return jnp.maximum(partial, 0.0)

    def _replication_scale(self, spec):
        scale = 1.0
        for a in _replication_axes(spec):
            scale /= self.mesh.shape[a]
        return scale

    def _make_sharded_body(self, loss_fn, tx, state_specs):
        """The single-step shard_map body of the leafwise-sharded dataflow,
        shared by its ``build_step`` and ``build_multi_step`` forms."""
        param_specs = state_specs.params
        gar = self.gar
        k = self.workers_per_device

        def body(state, batch):
            key = jax.random.fold_in(state.rng, state.step)
            gidx = jax.lax.axis_index(worker_axis)  # worker-GROUP index
            # Active chaos regime + per-STEP worker lateness (one draw per
            # logical worker, shared by all its leaves).  The lateness key
            # lives in the 30_000+ offset namespace — fold_in(key, widx) is
            # the PARENT of every per-leaf stream (fold i, then tags 1/2),
            # so folding the straggler tag onto it directly would collide
            # with leaf index 5's stream (same convention as the 10_000+i /
            # 20_000+i offsets the engine uses elsewhere).
            ridx = None
            lates = [None] * k
            if self.chaos is not None:
                ridx = self.chaos.regime_index(state.step)
                if self.chaos.has_stragglers:
                    lates = [
                        self.chaos.stragglers.is_late(
                            jax.random.fold_in(key, 30_000 + gidx * k + j),
                            gidx * k + j,
                            self.chaos.straggler_rate(ridx),
                        )
                        for j in range(k)
                    ]
            if k == 1:
                # one logical worker per submesh: the historical (and
                # bit-proven) unvmapped path — keep it byte-for-byte
                local = jax.tree.map(lambda x: x[0], batch)  # strip block dim
                loss, grads = jax.value_and_grad(loss_fn)(state.params, local)
                losses = loss[None]
                grads = jax.tree.map(lambda g: g[None], grads)
            else:
                # k logical workers per submesh (the large-n regime): vmap
                # the per-worker loss/grad — every leaf leads with k
                losses, grads = jax.vmap(
                    lambda b: jax.value_and_grad(loss_fn)(state.params, b)
                )(batch)

            g_leaves, treedef = jax.tree_util.tree_flatten(grads)
            s_leaves = treedef.flatten_up_to(param_specs)

            # (2) complete replicated-leaf grads within the worker group
            g_leaves = [
                jax.lax.psum(g, _replication_axes(s)) if _replication_axes(s) else g
                for g, s in zip(g_leaves, s_leaves)
            ]
            # (2a) l1/l2 regularization, analytically on the completed grads
            # (see __init__): part of every worker's HONEST gradient, so it
            # lands before momentum and before the Byzantine perturbation —
            # the flat dataflow's in-loss placement, same math.
            l1, l2 = self.l1_regularize, self.l2_regularize
            if l1 or l2:
                p_leaves = jax.tree_util.tree_leaves(state.params)
                reg = jnp.float32(0.0)
                for i, (p, s) in enumerate(zip(p_leaves, s_leaves)):
                    p32 = p.astype(jnp.float32)
                    delta = jnp.zeros_like(p32)
                    if l1:
                        delta = delta + l1 * jnp.sign(p32)
                        reg = reg + l1 * jnp.sum(jnp.abs(p32)) * self._replication_scale(s)
                    if l2:
                        delta = delta + 2.0 * l2 * p32
                        reg = reg + l2 * jnp.sum(p32 * p32) * self._replication_scale(s)
                    g_leaves[i] = g_leaves[i] + delta.astype(g_leaves[i].dtype)
                # scaled per-leaf partials psum exactly like the data loss:
                # the in-group psum in `metrics` then counts the norm once
                # (every logical worker's loss carries the reg term, the flat
                # dataflow's per-worker in-loss placement)
                losses = losses + reg
            # (2b) honest worker momentum (pre-attack, like the flat body):
            # send bias-corrected momenta, carry the uncorrected buffer
            new_momentum, new_momentum_steps = state.momentum, state.momentum_steps
            if self.worker_momentum is not None:
                beta = self.worker_momentum
                # momentum buffers are worker-sharded: local block (k, ...)
                m_leaves, _ = jax.tree_util.tree_flatten(state.momentum)
                new_momentum_steps = state.momentum_steps + 1
                corr = 1.0 - beta ** new_momentum_steps.astype(jnp.float32)
                m_new = [beta * m + (1.0 - beta) * g for m, g in zip(m_leaves, g_leaves)]
                g_leaves = [m / corr for m in m_new]
                new_momentum = jax.tree_util.tree_unflatten(treedef, m_new)
            # (3) per-worker perturbation of each logical worker's own shards
            # (skipped entirely when no adversity is configured — at k
            # workers per submesh the k-fold loop would otherwise pay trace
            # size for an identity transform)
            carry_leaves = None
            if self.carries_gradients:
                carry_leaves = jax.tree_util.tree_leaves(state.carry)  # (k, ...)
            new_carry = state.carry
            if (self.attack is not None or self.lossy_link is not None
                    or self.chaos is not None):
                post_leaves = []
                for i, (g, s) in enumerate(zip(g_leaves, s_leaves)):
                    outs, posts = [], []
                    for j in range(k):
                        widx = gidx * k + j
                        out, post = self._perturb(
                            g[j], s,
                            jax.random.fold_in(jax.random.fold_in(key, widx), i),
                            widx,
                            previous=(
                                carry_leaves[i][j]
                                if carry_leaves is not None else None
                            ),
                            ridx=ridx, late=lates[j],
                        )
                        outs.append(out)
                        posts.append(post)
                    g_leaves[i] = jnp.stack(outs)
                    post_leaves.append(jnp.stack(posts))
                if self.carries_gradients:
                    new_carry = jax.tree_util.tree_unflatten(treedef, post_leaves)

            # (3b) submission forgery + authentication digests (secure/):
            # impersonated/tampered submissions, sender/receiver checksums
            # over every leaf shard, reject-to-NaN under ``secure``
            g_leaves, secure_local = self._submission_pipeline(
                g_leaves, key, gidx, ridx
            )

            # (4/5) per-bucket robust aggregation over the worker axis
            all_rows = []
            for i, (g, s) in enumerate(zip(g_leaves, s_leaves)):
                rows = self._gather_rows(self._leaf_buckets(g, s))
                rows = self._apply_omniscient(rows, jax.random.fold_in(key, 10_000 + i), ridx=ridx)
                all_rows.append(rows)

            # Quarantine BEFORE any distance computation (incl. the global
            # path below): masked rows must read +inf-distant to selection
            # rules, never finite-distant-but-NaN-valued.  raw rows are kept
            # for the reputation signal.
            raw_all_rows = all_rows
            if self.quarantine_threshold:
                qmask = quarantine_mask(
                    state.reputation, self.quarantine_threshold, gar.nb_byz_workers
                )
                all_rows = [
                    jnp.where(qmask[None, :, None], jnp.nan, rows) for rows in all_rows
                ]

            global_dist2 = None
            if self.granularity == "global" and gar.needs_distances:
                acc = jnp.zeros((self.nb_workers, self.nb_workers), jnp.float32)
                for rows, s in zip(all_rows, s_leaves):
                    partial = centered_gram_sq_distances(
                        rows.reshape(self.nb_workers, -1).astype(jnp.float32)
                    )
                    acc = acc + partial * self._replication_scale(s)
                global_dist2 = jnp.maximum(jax.lax.psum(acc, _IN_GROUP_AXES), 0.0)

            agg_leaves = []
            # Suspicion accumulators (worker_metrics): whole-model per-worker
            # squared distance to the aggregate — per-leaf partials scaled by
            # the replication factor exactly like grad_norm's, psum-completed
            # below — and the mean per-bucket participation.  Participation
            # values are identical on every in-group device EXCEPT along the
            # pipe axis of stage-stacked leaves (distinct buckets), so each
            # contribution is scaled by 1/(replicating axes' size) and the
            # in-group psum then counts every distinct bucket exactly once.
            wdist = jnp.zeros((self.nb_workers,), jnp.float32)
            part_sum = jnp.zeros((self.nb_workers,), jnp.float32)
            part_count = 0.0  # global distinct-bucket count (static)
            rep_dist = jnp.zeros((self.nb_workers,), jnp.float32)
            # (vmapped rule calls below: the Pallas auto-tier detects the
            # batching trace centrally and stays on jnp — gars/common.py
            # _is_batched_tracer)
            for rows, raw_rows, g, s in zip(all_rows, raw_all_rows, g_leaves, s_leaves):
                participation = None
                if gar.needs_distances:
                    if global_dist2 is not None:
                        dist2 = jnp.broadcast_to(global_dist2, rows.shape[:1] + global_dist2.shape)
                    else:
                        dist2 = self._bucket_distances(rows, s)
                    if self.worker_metrics:
                        # One pass: the memoized selection graph serves both
                        # the aggregate and the participation (two separate
                        # vmaps would trace it twice per leaf).
                        agg, participation = jax.vmap(
                            gar.aggregate_block_and_participation
                        )(rows, dist2)
                    else:
                        agg = jax.vmap(gar.aggregate_block)(rows, dist2)
                elif gar.uses_axis or gar.uses_key:
                    # Iterative rules' row norms complete over the model axis
                    # when this leaf's dimensions are sharded across it —
                    # exactly _bucket_distances' discipline — so every shard
                    # derives identical weights and the result matches dense.
                    # Randomized meta-rules get the replicated step key (one
                    # permutation per step, same on every device and leaf).
                    axis = model_axis if model_axis in _spec_axis_names(s) else None
                    from ..gars import GAR_KEY_TAG

                    gkey = jax.random.fold_in(key, GAR_KEY_TAG)
                    if self.worker_metrics:
                        agg, participation = jax.vmap(
                            lambda r, axis=axis: gar.aggregate_block_and_participation(
                                r, None, axis_name=axis, key=gkey
                            )
                        )(rows)
                    else:
                        agg = jax.vmap(
                            lambda r, axis=axis: gar._call_aggregate(
                                r, None, axis_name=axis, key=gkey)
                        )(rows)
                else:
                    agg = jax.vmap(lambda r: gar.aggregate_block(r, None))(rows)
                if self.reputation_decay is not None:
                    rdiff = raw_rows.astype(jnp.float32) - agg.astype(jnp.float32)[:, None, :]
                    rep_dist = rep_dist + jnp.sum(rdiff * rdiff, axis=(0, 2)) * self._replication_scale(s)
                if self.worker_metrics:
                    diff = rows.astype(jnp.float32) - agg.astype(jnp.float32)[:, None, :]
                    wdist = wdist + jnp.sum(diff * diff, axis=(0, 2)) * self._replication_scale(s)
                    if participation is not None:
                        stacked = (
                            self.granularity == "layer" and s is not None
                            and len(s) >= 2 and s[0] == pipe_axis
                        )
                        rep = (model_axis,) + (() if stacked else (pipe_axis,))
                        pscale = 1.0
                        for a in rep:
                            pscale /= self.mesh.shape[a]
                        part_sum = part_sum + jnp.sum(participation, axis=0) * pscale
                        part_count += participation.shape[0] * (
                            self.mesh.shape[pipe_axis] if stacked else 1
                        )
                # one aggregate per PARAMETER: strip the local worker
                # stacking dim from the layout target
                agg_leaves.append(agg.reshape(g.shape[1:]).astype(g.dtype))
            agg_tree = jax.tree_util.tree_unflatten(treedef, agg_leaves)

            # (6) local optax update — layouts already match the parameters
            updates, opt_state = tx.update(agg_tree, state.opt_state, state.params)
            params = optax.apply_updates(state.params, updates)

            sq = jnp.float32(0.0)
            for agg, s in zip(agg_leaves, s_leaves):
                sq = sq + jnp.sum(jnp.square(agg.astype(jnp.float32))) * self._replication_scale(s)
            grad_norm = jnp.sqrt(jax.lax.psum(sq, _IN_GROUP_AXES))

            # loss is a local partial: sum the local workers, then the worker
            # group's devices, then groups
            total_loss = jax.lax.psum(jnp.sum(losses), _IN_GROUP_AXES + (worker_axis,))
            worker_nan = None
            if self.health_probe:
                # Per-worker NaN-row flags over the POST-TRANSPORT shards:
                # count this worker's non-finite coordinates locally,
                # complete over the worker group, flag, gather workers.
                bad = jnp.zeros((k,), jnp.int32)
                for g in g_leaves:
                    bad = bad + jnp.sum(
                        (~jnp.isfinite(g)).astype(jnp.int32),
                        axis=tuple(range(1, g.ndim)),
                    )
                bad = jax.lax.psum(bad, _IN_GROUP_AXES)
                worker_nan = jax.lax.all_gather(bad > 0, worker_axis).reshape(
                    self.nb_workers
                )
            secure_metrics = None
            if secure_local is not None:
                # complete each worker's lane sums over its in-group shards
                # (uint32 psum wraps mod 2^32 — the checksum's own domain),
                # then gather worker-major like the probe's NaN flags
                def complete(local, summed):
                    value = (
                        jax.lax.psum(local, _IN_GROUP_AXES) if summed else local
                    )
                    gathered = jax.lax.all_gather(value, worker_axis)
                    return gathered.reshape((self.nb_workers,) + value.shape[1:])

                secure_metrics = {
                    "digest_sent": complete(secure_local["digest_sent"], True),
                    "digest_recv": complete(secure_local["digest_recv"], True),
                    "forged": complete(secure_local["forged"], False),
                    "rejected": complete(secure_local["rejected"], False),
                }
            return self._finalize_step(
                state, params=params, opt_state=opt_state, new_carry=new_carry,
                new_momentum=new_momentum, new_momentum_steps=new_momentum_steps,
                total_loss=total_loss, update_norm=grad_norm,
                worker_nan=worker_nan,
                rep_dist=(
                    jax.lax.psum(rep_dist, _IN_GROUP_AXES)
                    if self.reputation_decay is not None else None
                ),
                wdist=(
                    jax.lax.psum(wdist, _IN_GROUP_AXES)
                    if self.worker_metrics else None
                ),
                participation=(
                    jax.lax.psum(part_sum, _IN_GROUP_AXES) / part_count
                    if part_count else None
                ),
                secure_metrics=secure_metrics, ridx=ridx,
            )

        return body

    def _sharded_build_step(self, loss_fn, tx, state):
        state_specs = jax.tree.map(lambda a: a.sharding.spec, state)
        body = self._make_sharded_body(loss_fn, tx, state_specs)
        sharded = compat.shard_map(
            body,
            mesh=self.mesh,
            in_specs=(state_specs, P(worker_axis)),
            out_specs=(state_specs, P()),
            check_vma=False,
        )
        # Host-side span wrapper only (obs/trace.py): the jit underneath is
        # untouched — zero added compiles, ``_cache_size`` falls through.
        # EXPLICIT out_shardings pin the output state to the init_state
        # layout: without them the compiler canonicalizes size-1 mesh axes
        # to replicated specs, so the SECOND step call would see differently
        # committed inputs and retrace (the zero-steady-state-recompile bar,
        # tests/test_gar_scaling.py).
        out_shardings = (
            jax.tree.map(lambda a: a.sharding, state),
            NamedSharding(self.mesh, P()),
        )
        return trace.traced(
            "train_step.dispatch",
            jax.jit(sharded, donate_argnums=(0,), out_shardings=out_shardings),
            cat="train",
        )

    def _sharded_build_multi_step(self, loss_fn, tx, state, repeat_steps=None):
        state_specs = jax.tree.map(lambda a: a.sharding.spec, state)
        body = self._make_sharded_body(loss_fn, tx, state_specs)

        if repeat_steps is None:

            def many(state, batches):
                return jax.lax.scan(body, state, batches)

            batch_spec = P(None, worker_axis)
        else:

            def many(state, batch):
                return jax.lax.scan(
                    lambda s, _: body(s, batch), state, None, length=int(repeat_steps)
                )

            batch_spec = P(worker_axis)

        sharded = compat.shard_map(
            many,
            mesh=self.mesh,
            in_specs=(state_specs, batch_spec),
            out_specs=(state_specs, P()),
            check_vma=False,
        )
        # Same out_shardings discipline as build_step: keep the output state
        # committed exactly like init_state's, or call 2 retraces.
        out_shardings = (
            jax.tree.map(lambda a: a.sharding, state),
            NamedSharding(self.mesh, P()),
        )
        return trace.traced(
            "train_multi_step.dispatch",
            jax.jit(sharded, donate_argnums=(0,), out_shardings=out_shardings),
            cat="train",
        )

    def _sharded_build_gar_probe(self, d, seed=0):
        """The sharded twin of the flat GAR probe (the measurement
        instrument behind ``gar_seconds_total`` / the ``gar.aggregate``
        span).

        The engine proper reduces per leaf/bucket; the probe measures ONE
        rule application over the whole-model (n, d) row matrix on a single
        replica — exact for ``granularity=global`` (one selection over the
        flattened vector) and an upper bound for layer/leaf granularity
        (the same arithmetic split across buckets).  Attacks/quarantine are
        excluded: the probe times the rule, not the adversity simulation."""
        from ..gars import GAR_KEY_TAG

        # Column-shard the synthetic rows over the worker axis (the flat
        # probe's layout): a replicated (n, d) matrix at whole-model d and
        # large n would cost n x the model footprint PER DEVICE — the
        # sharded mode's whole reason to exist is that that doesn't fit.
        # The body is plain jit, so GSPMD partitions the distance Gram and
        # the rule's columnwise work along d automatically.  d is padded to
        # the worker-axis multiple (sharding a dim requires divisibility;
        # model_dim is an arbitrary parameter count), and the rows are
        # generated ON DEVICE under jit with an explicit output sharding so
        # the host never materializes the (n, d) matrix.
        W = self.nb_mesh_workers
        blk = -(-int(d) // W)
        make_rows = jax.jit(
            lambda k: jax.random.normal(k, (self.nb_workers, W * blk), jnp.float32),
            out_shardings=NamedSharding(self.mesh, P(None, worker_axis)),
        )
        rows = make_rows(jax.random.PRNGKey(seed))
        gar = self.gar

        def body(rows, key):
            dist2 = None
            if gar.needs_distances:
                # jnp-tier Gram distances (same as _bucket_distances): the
                # common pairwise_sq_distances auto-dispatches to a Pallas
                # kernel on TPU, which GSPMD cannot partition over the
                # column-sharded rows
                dist2 = jnp.maximum(centered_gram_sq_distances(rows), 0.0)
            gar_key = jax.random.fold_in(key, GAR_KEY_TAG)
            return gar._call_aggregate(rows, dist2, axis_name=None, key=gar_key)

        fn = jax.jit(body)
        base = jax.random.PRNGKey(seed)

        def probe(step=0):
            return fn(rows, jax.random.fold_in(base, step))

        return probe

    def _sharded_build_eval(self, loss_fn, state):
        """Jitted eval: mean of the sharded loss over the worker axis.

        Built once from ``state``'s layout (like ``build_step``) so repeated
        cadenced evals hit the jit cache instead of recompiling.
        """
        specs = jax.tree.map(lambda a: a.sharding.spec, state)
        k = self.workers_per_device

        def body(state, batch):
            if k == 1:
                local = jax.tree.map(lambda x: x[0], batch)
                total = loss_fn(state.params, local)  # local partial
            else:
                total = jnp.sum(
                    jax.vmap(lambda b: loss_fn(state.params, b))(batch)
                )
            return jax.lax.psum(total, _IN_GROUP_AXES + (worker_axis,)) / self.nb_workers

        sharded = compat.shard_map(
            body,
            mesh=self.mesh,
            in_specs=(specs, P(worker_axis)),
            out_specs=P(),
            check_vma=False,
        )
        return trace.traced("eval_step.dispatch", jax.jit(sharded), cat="eval")

    # ------------------------------------------------------------------ #
    # the public, mode-polymorphic surface

    def init_state(self, *args, seed=0):
        """Create the TrainState for this engine's mode.

        - flat:    ``init_state(params, tx, seed=0)``
        - sharded: ``init_state(init_fn, specs, tx, seed=0)``
        """
        if self.sharded:
            if len(args) != 3:
                raise UserException(
                    "sharded init_state wants (init_fn, specs, tx); got %d "
                    "positional argument(s)" % len(args)
                )
            return self._sharded_init_state(*args, seed=seed)
        if len(args) != 2:
            raise UserException(
                "flat init_state wants (params, tx); got %d positional "
                "argument(s)" % len(args)
            )
        return self._flat_init_state(*args, seed=seed)

    def put_state(self, state):
        """Device_put a TrainState with this engine's state layout (the
        checkpoint-restore path)."""
        if self.sharded:
            return self._sharded_put_state(state)
        return self._flat_put_state(state)

    def build_step(self, loss_fn, tx, state=None):
        """Build the jitted robust training step.

        The sharded mode derives its in/out shardings from ``state`` (the
        TrainState from ``init_state``) and therefore requires it; the flat
        mode's layout is static and ``state`` is accepted and ignored, so
        callers can pass it uniformly."""
        if self.sharded:
            if state is None:
                raise UserException(
                    "the sharded build_step derives its shardings from the "
                    "TrainState; pass state=init_state(...)"
                )
            return self._sharded_build_step(loss_fn, tx, state)
        return self._flat_build_step(loss_fn, tx)

    def build_multi_step(self, loss_fn, tx, state=None, repeat_steps=None):
        """Build the jitted K-step scanned trainer (same ``state`` contract
        as :meth:`build_step`; ``repeat_steps`` reuses one resident batch)."""
        if self.sharded:
            if state is None:
                raise UserException(
                    "the sharded build_multi_step derives its shardings from "
                    "the TrainState; pass state=init_state(...)"
                )
            return self._sharded_build_multi_step(
                loss_fn, tx, state, repeat_steps=repeat_steps
            )
        return self._flat_build_multi_step(loss_fn, tx, repeat_steps=repeat_steps)

    def build_eval(self, fn, state=None):
        """flat: ``build_eval(metric_fn)`` -> per-batch means;
        sharded: ``build_eval(loss_fn, state)`` -> mean sharded loss."""
        if self.sharded:
            if state is None:
                raise UserException(
                    "the sharded build_eval derives its shardings from the "
                    "TrainState; pass state=init_state(...)"
                )
            return self._sharded_build_eval(fn, state)
        return self._flat_build_eval(fn)

    def build_gar_probe(self, d, seed=0):
        """Jitted GAR-only executable at the engine's exact (n, d) — see the
        mode-specific docstrings."""
        if self.sharded:
            return self._sharded_build_gar_probe(d, seed=seed)
        return self._flat_build_gar_probe(d, seed=seed)


    # ------------------------------------------------------------------ #
    # bounded-wait protocol hooks (parallel/bounded.py, docs/engine.md):
    # the fused SPMD step splits into per-worker submission executables the
    # host dispatches asynchronously, plus one aggregate+update executable
    # that absorbs workers missing the deadline as NaN rows — the chaos
    # straggler model as the ACTUAL protocol, not a simulation.

    def _check_bounded_wait_supported(self, allow_submesh=False):
        if self.sharded:
            in_group = self.mesh.shape[pipe_axis] * self.mesh.shape[model_axis]
            if in_group != 1 and not allow_submesh:
                raise UserException(
                    "build_group_grad needs trivial in-group axes "
                    "(--mesh W,1,1): a (pipe x model) submesh submission is "
                    "one collective program whose members cannot time out "
                    "independently — per-SUBMESH collective timeouts are "
                    "build_submesh_grad's protocol (docs/engine.md, "
                    "'v3: submesh deadlines')"
                )
            if self.granularity != "global":
                raise UserException(
                    "sharded bounded-wait aggregates the whole flattened "
                    "gradient; use granularity global (the sharded spelling "
                    "of the flat mode's vector)"
                )
            if self.worker_momentum is not None:
                raise UserException(
                    "sharded bounded-wait does not carry worker momentum: "
                    "the sharded TrainState.momentum is a per-leaf pytree, "
                    "not the flat (n, d) buffer the submission body indexes "
                    "— run the flat engine for momentum + bounded-wait"
                )
        elif self.granularity != "vector":
            raise UserException(
                "bounded-wait aggregates the whole flattened gradient "
                "(granularity vector); per-leaf selection is not supported"
            )
        if self.lossy_link is not None or self.chaos is not None:
            raise UserException(
                "bounded-wait replaces the simulated transport: drop --UDP/"
                "--chaos in-graph regimes (straggler regimes move to the "
                "host straggler model, parallel/bounded.py)"
            )

    def _bounded_submission_body(self, loss_fn):
        """The shared per-worker submission body of both bounded-wait
        builders: gradient -> worker momentum -> local attack -> wire
        encode -> digest, returning a dict with keys ``loss``, ``row``
        and (configured) ``momentum`` / ``ef`` / ``digest``.

        ``momentum`` / ``ef`` in the argument list are the WHOLE (n, d)
        buffers from ``TrainState`` (dynamically indexed by the traced
        worker index, so steady state never recompiles); the returned
        entries are the worker's updated (d,) rows, which the bounded
        aggregate writes back only for workers whose submission ARRIVED —
        a timed-out worker's momentum (and error-feedback residual) never
        updated, exactly as its gradient never shipped.  The submitted row
        is the bias-corrected momentum (Karimireddy et al. 2021),
        corrected by the GLOBAL update count: a straggler that missed
        rounds sends a slightly over-corrected momentum rather than
        forcing a per-worker count into the compiled signature.

        The wire: under a codec (parallel/compress.py) ``row`` is the
        ENCODED payload pytree — what actually crosses the host boundary,
        so the (n, d) f32 stack never does — and the digest covers the
        wire IMAGE (the exact f32 rows the aggregation-side decoder
        emits, a deterministic function of the encoded bytes: tampering
        the payload moves the image and therefore the digest).  On the
        dtype twin the digest keeps its historical convention (post-
        attack, pre-quantization — the fused ``_perturb_local``'s)."""
        from ..secure.submit import row_digest

        beta = self.worker_momentum

        def body(params, worker_batch, rng, step, widx, momentum,
                 momentum_steps, ef):
            key = jax.random.fold_in(rng, step)
            if self.batch_transform is not None:
                # fold tag 3: the augmentation stream (same as the fused body)
                wkey = jax.random.fold_in(jax.random.fold_in(key, widx), 3)
                worker_batch = self.batch_transform(worker_batch, wkey)
            loss, grads = jax.value_and_grad(loss_fn)(params, worker_batch)
            leaves = jax.tree_util.tree_leaves(grads)
            row = jnp.concatenate(
                [leaf.reshape(-1).astype(jnp.float32) for leaf in leaves]
            )
            out = {"loss": loss}
            if beta is not None:
                new_m = beta * momentum[widx] + (1.0 - beta) * row
                out["momentum"] = new_m
                correction = 1.0 - beta ** (
                    jnp.asarray(momentum_steps, jnp.float32) + 1.0
                )
                row = new_m / correction
            if self.attack is not None and not self.attack.omniscient:
                wkey = jax.random.fold_in(key, widx)
                forged = self.attack.apply_local(row, jax.random.fold_in(wkey, 1))
                row = jnp.where(widx < self.nb_real_byz, forged, row)
            if self.codec is not None:
                if ef is not None:
                    payload, image, new_ef = self.codec.ef_encode(row, ef[widx])
                    out["ef"] = new_ef
                else:
                    payload = self.codec.encode(row)
                    image = self.codec.decode(payload, row.shape[-1])
                if self.secure:
                    out["digest"] = row_digest(image)
                out["row"] = payload
                return out
            if self.secure:
                out["digest"] = row_digest(row)
            if self.exchange_dtype is not None:
                row = row.astype(self.exchange_dtype)
            out["row"] = row
            return out

        return body

    def build_worker_grad(self, loss_fn):
        """One jitted per-worker submission executable: ``grad_fn(params,
        worker_batch, rng, step, widx[, momentum, momentum_steps]) ->
        {loss, row[, momentum][, digest]}`` (the momentum operands appear
        iff ``worker_momentum`` is set; see ``_bounded_submission_body``).

        Compiled ONCE and dispatched n times per step (worker index and
        step are traced operands, so steady state never recompiles).  The
        row is what the worker "sends": flattened f32, worker momentum
        applied, local attack applied to coalition workers with the fused
        body's exact key discipline (fold worker, then tag 1), digest-
        summarized under ``secure``, wire-quantized when
        ``exchange_dtype`` is set — or the ENCODED codec payload when a
        wire codec is configured (``momentum`` and the error-feedback
        ``ef`` buffer append to the operand list in that order, each iff
        configured)."""
        self._check_bounded_wait_supported()
        body = self._bounded_submission_body(loss_fn)
        with_momentum = self.worker_momentum is not None
        with_ef = self.carries_ef

        def grad_fn(params, worker_batch, rng, step, widx, *extra):
            momentum = momentum_steps = ef = None
            i = 0
            if with_momentum:
                momentum, momentum_steps = extra[0], extra[1]
                i = 2
            if with_ef:
                ef = extra[i]
            return body(params, worker_batch, rng, step, widx, momentum,
                        momentum_steps, ef)

        return trace.traced(
            "worker_grad.dispatch", jax.jit(grad_fn), cat="train"
        )

    def build_group_grad(self, loss_fn):
        """The sharded-mode submission executable: one jitted program per
        WORKER-AXIS SUBMESH, computing its k = n/W logical workers vmapped —
        ``group_fn(params, group_batch, rng, step, gidx[, momentum,
        momentum_steps]) -> {loss: (k,), row: (k, d)[, momentum: (k, d)]
        [, digest: (k, 4)]}``.

        The group index is a traced operand like the flat mode's worker
        index (one executable, dispatched W times per round, zero steady-
        state recompiles); global worker indices are ``gidx * k + j``, so
        attack coalitions and PRNG streams address workers exactly as the
        flat submission path does.  Requires trivial in-group axes (the
        submesh is a single device — ``_check_bounded_wait_supported``):
        the group's submission then completes independently of its peers,
        which is what a per-group deadline needs."""
        self._check_bounded_wait_supported()
        body = self._bounded_submission_body(loss_fn)
        k = self.workers_per_device

        def group_body(params, group_batch, rng, step, gidx, momentum,
                       momentum_steps):
            def one(j, worker_batch):
                # codec exchange is flat-engine-only (__init__), so the
                # group body never sees an ef operand
                return body(params, worker_batch, rng, step, gidx * k + j,
                            momentum, momentum_steps, None)

            return jax.vmap(one)(jnp.arange(k), group_batch)

        if self.worker_momentum is not None:
            def group_fn(params, group_batch, rng, step, gidx, momentum,
                         momentum_steps):
                return group_body(params, group_batch, rng, step, gidx,
                                  momentum, momentum_steps)
        else:
            def group_fn(params, group_batch, rng, step, gidx):
                return group_body(params, group_batch, rng, step, gidx,
                                  None, None)

        return trace.traced(
            "group_grad.dispatch", jax.jit(group_fn), cat="train"
        )

    def build_submesh_grad(self, loss_fn):
        """The bounded-wait v3 submission executable for NONTRIVIAL
        (pipe x model) submeshes: one jitted program per WORKER-AXIS
        SUBMESH whose pipe/model collectives are INTERNAL to the program
        — ``submesh_fn(params, group_batch, rng, step, gidx) ->
        {loss: (k,), row: (k, d)[, digest: (k, 4)]}``.

        Where ``build_group_grad`` requires the submesh to be a single
        device, this builder embraces the collectives: the params stay
        committed to their (pipe, model) shardings, GSPMD partitions the
        per-worker gradient across the submesh's in-group devices, and
        the OUTPUTS are pinned replicated (``out_shardings``) so the
        host-side stack of W independent submissions commits one layout
        every round.  Each of the W dispatches is then one self-contained
        collective program: its in-group members finish or miss the
        deadline TOGETHER, so a submesh that misses the window forfeits
        its k = n/W logical rows as a unit into the same declared-f
        budget (parallel/bounded.py, ``submesh_timeout``).  The group
        index is a traced operand — one compiled signature, W dispatches
        per round, zero steady-state recompiles.  Momentum stays refused
        sharded and the codec exchange stays flat-engine-only, so the
        body never sees those operands."""
        self._check_bounded_wait_supported(allow_submesh=True)
        if not self.sharded:
            raise UserException(
                "build_submesh_grad is the sharded-mode submission builder "
                "(per-submesh collective programs); the flat engine "
                "dispatches build_worker_grad"
            )
        body = self._bounded_submission_body(loss_fn)
        k = self.workers_per_device

        def submesh_fn(params, group_batch, rng, step, gidx):
            def one(j, worker_batch):
                # momentum is refused sharded and the codec exchange is
                # flat-engine-only, so the body sees neither operand
                return body(params, worker_batch, rng, step, gidx * k + j,
                            None, None, None)

            return jax.vmap(one)(jnp.arange(k), group_batch)

        jitted = jax.jit(
            submesh_fn, out_shardings=NamedSharding(self.mesh, P())
        )
        return trace.traced("submesh_grad.dispatch", jitted, cat="train")

    def build_bounded_aggregate(self, tx, params_template, rows_form="wire",
                                stale_reweight=False):
        """The aggregator side of the bounded-wait protocol: ``agg(state,
        rows, losses, arrived, stale, extras) -> (state, metrics)``, jitted
        once (``params_template`` fixes the flatten/inflate layout).

        ``rows`` is the (n, ...) submission buffer in one of two forms
        (fixed at build time — one compiled signature per step):

        - ``rows_form="wire"``: what crossed the wire — (n, d) rows in
          the exchange dtype, or the stacked ENCODED payload pytree under
          a codec, decoded HERE so the GAR (and everything downstream)
          sees float32 rows;
        - ``rows_form="decoded"``: already-decoded float32 (n, d) rows —
          the incremental as-rows-land mode (parallel/bounded.py folds
          each submission into the buffer the instant it arrives, so the
          barrier only pays the aggregation).

        Fresh rows where ``arrived``, CLEVER carry rows where ``stale``
        (the host's stale infill, parallel/bounded.py), garbage elsewhere
        — masked to NaN in-graph AFTER decoding.  A row that is neither
        fresh nor stale is a NaN drop INSIDE the same declared-f budget
        as Byzantine rows, and a STALE row spends that budget too
        (timeouts + stale + attacks <= f for the rule's guarantee to hold
        — docs/engine.md, "f-accounting": the carry may hold a Byzantine
        worker's attack row).  Deadline verdicts land in
        ``metrics["straggler_timeout"]`` / ``metrics["stale_infill"]``;
        missed workers are excluded from the loss sum (the aggregator
        only averages what it received).  ``extras`` carries the
        configured optional operands: ``momentum`` / ``ef`` (the (n, d)
        updated rows, written back only where ``arrived`` — a timed-out
        worker's momentum and error-feedback residual never updated) and
        ``digests`` (the (n, 4) submission digests the host authenticator
        signs/verifies one dispatch behind, secure/submit.py).
        Omniscient attacks, quarantine, reputation, the health probe and
        the flight recorder ride the same shared code paths as the fused
        step (``_prepare_rows`` / ``_finalize_step``).

        ``stale_reweight=True`` is the v3 age-reweighted stale correction
        (the unbiased-estimator framing of arXiv:2505.23523): a stale
        carry row of age a is scaled by the traced coefficient
        c(a) = 1/(1 + a) — ``extras["stale_age"]`` carries the host's
        (n,) age vector — instead of re-entering at full weight.  The
        discount composes with the codec as two traced scalars (decode
        first, then reweight; parallel/compress.py), and it does NOT
        relax the f-accounting: a reweighted stale row still SPENDS the
        declared-f budget (the carry may hold a Byzantine worker's
        attack row — damping it is not dropping it)."""
        self._check_bounded_wait_supported(allow_submesh=True)
        if rows_form not in ("wire", "decoded"):
            raise UserException(
                "rows_form must be 'wire' or 'decoded' (got %r)" % (rows_form,)
            )
        from ..gars import GAR_KEY_TAG
        from ..gars.common import pairwise_sq_distances

        from .compress import wire_roundtrip

        # the flattening layout, for inflating the aggregate back to a tree
        flatmap = FlatMap(params_template)
        d = flatmap.size
        if self.codec is not None:
            self.codec.validate_d(d)

        def agg_fn(state, rows, losses, arrived, stale, extras):
            key = jax.random.fold_in(state.rng, state.step)
            if rows_form == "wire" and self.codec is not None:
                # decode at the aggregation boundary: every GAR sees f32
                rows = self.codec.decode_rows(rows, d)
            else:
                rows = rows.astype(jnp.float32)
            # deadline verdict first: a worker that neither arrived nor
            # carries a live stale row IS a NaN row — the exact convention
            # of a fully-lossy link, absorbed by the rule
            valid = arrived | stale
            rows = jnp.where(valid[:, None], rows, jnp.nan)
            if rows_form == "wire" and self.codec is None:
                # the dtype twin's wire image (no-op on the f32 wire; the
                # codec/decoded forms already ARE the wire image)
                rows = wire_roundtrip(rows, dtype=self.exchange_dtype)
            reweight_coeff = None
            if stale_reweight:
                # v3 age reweighting: damp each stale carry row by
                # c(a) = 1/(1+a) — traced, so steady state never
                # recompiles as ages tick.  Applied AFTER decode and the
                # wire image (the coefficient scales what the rule sees,
                # not what crossed the wire) and BEFORE _prepare_rows
                # (reputation/quarantine judge the damped row, exactly
                # what enters the aggregate).
                ages = extras["stale_age"].astype(jnp.float32)
                reweight_coeff = jnp.where(stale, 1.0 / (1.0 + ages), 1.0)
                rows = rows * reweight_coeff[:, None]
            rows, raw_rows = self._prepare_rows(rows, key, state.reputation)
            dist2 = None
            if self.gar.needs_distances:
                dist2 = jnp.maximum(pairwise_sq_distances(rows), 0.0)
            gar_key = jax.random.fold_in(key, GAR_KEY_TAG)
            participation = None
            if self.worker_metrics:
                agg, participation = self.gar.aggregate_block_and_participation(
                    rows, dist2, axis_name=None, key=gar_key
                )
            else:
                agg = self.gar._call_aggregate(
                    rows, dist2, axis_name=None, key=gar_key
                )
            agg = agg.astype(jnp.float32)
            agg_tree = flatmap.inflate(agg)
            updates, opt_state = tx.update(agg_tree, state.opt_state, state.params)
            params = optax.apply_updates(state.params, updates)
            # the aggregator can only sum the losses it RECEIVED; a late
            # worker's loss never arrived (its row is the NaN infill)
            total_loss = jnp.sum(jnp.where(arrived, losses, 0.0))
            wdist = rep_dist = None
            if self.worker_metrics:
                diff = rows - agg[None, :]
                wdist = jnp.sum(diff * diff, axis=1)
            if self.reputation_decay is not None:
                rdiff = raw_rows - agg[None, :]
                rep_dist = jnp.sum(rdiff * rdiff, axis=1)
            worker_nan = None
            if self.health_probe:
                worker_nan = jnp.any(~jnp.isfinite(rows), axis=1)
            new_momentum = new_momentum_steps = None
            if self.worker_momentum is not None:
                # write back only the rows whose submission ARRIVED: a
                # timed-out worker's momentum update never completed (its
                # thread's result was discarded with the round).  Emitted
                # replicated, like every other plain-jit output here; the
                # host step re-places init_state's worker-sharded buffer
                # ONCE so round 0's input layout matches every later
                # round's (parallel/bounded.py — else both executables
                # would recompile at round 1)
                new_momentum = jnp.where(
                    arrived[:, None], extras["momentum"], state.momentum
                )
                new_momentum_steps = state.momentum_steps + 1
            new_ef = None
            if self.carries_ef:
                # same convention as momentum: a timed-out worker's
                # error-feedback residual never updated (its submission —
                # and the quantization error it absorbed — never shipped)
                new_ef = jnp.where(arrived[:, None], extras["ef"], state.ef)
            secure_metrics = None
            if self.secure:
                # sent == received by construction on this path (no
                # in-transit transform between the submission executable
                # and the host's stack); the host authenticator still
                # signs and verifies one dispatch behind, and a digest
                # mismatch there would name a real corruption
                nobody = jnp.zeros((self.nb_workers,), bool)
                secure_metrics = {
                    "digest_sent": extras["digests"],
                    "digest_recv": extras["digests"],
                    "forged": nobody,
                    "rejected": nobody,
                }
            new_state, metrics = self._finalize_step(
                state, params=params, opt_state=opt_state, new_carry=None,
                new_momentum=new_momentum,
                new_momentum_steps=new_momentum_steps,
                total_loss=total_loss, update_norm=jnp.linalg.norm(agg),
                worker_nan=worker_nan, rep_dist=rep_dist, wdist=wdist,
                participation=participation, secure_metrics=secure_metrics,
                ridx=None, new_ef=new_ef,
            )
            # deadline evidence AFTER the epilogue: the flight recorder's
            # lane set predates the protocol; forensics/registry consume
            # these from the metrics dict on the host.  ``nb_timeouts`` is
            # the round's f-budget spend: NaN drops AND stale infills both
            # count (the guardian's over-budget escalation input).
            metrics["straggler_timeout"] = ~arrived
            metrics["stale_infill"] = stale
            metrics["nb_timeouts"] = jnp.sum((~arrived).astype(jnp.int32))
            metrics["nb_stale"] = jnp.sum(stale.astype(jnp.int32))
            if reweight_coeff is not None:
                metrics["stale_reweight_coeff"] = reweight_coeff
            return new_state, metrics

        jitted = jax.jit(agg_fn, donate_argnums=(0,))
        return trace.traced("bounded_aggregate.dispatch", jitted, cat="train")

    def build_incremental_fold(self, d):
        """The incremental-aggregation fold (parallel/bounded.py): write ONE
        worker's decoded submission into the aggregate-side (n, d) float32
        buffer the instant it lands, instead of stacking everything at the
        round barrier.  ``fold(buffer, wire_row, widx) -> buffer`` — the
        buffer is donated (an in-place row write), the worker index is a
        traced operand, and the decode runs here, overlapped with the
        submissions still outstanding — so the barrier-side aggregate
        consumes already-decoded rows (``rows_form="decoded"``).  Returns
        ``(fold, fresh)`` where ``fresh()`` allocates the round's zeroed
        buffer (content under never-written slots is irrelevant: the
        aggregate masks non-arrived, non-stale slots to NaN)."""
        self._check_bounded_wait_supported(allow_submesh=True)
        codec, dt = self.codec, self.exchange_dtype
        if codec is not None:
            codec.validate_d(d)
        n = self.nb_workers

        del dt  # the dtype twin's row arrives ALREADY in its wire dtype

        def fold(buffer, wire_row, widx):
            if codec is not None:
                row = codec.decode(wire_row, d)
            else:
                row = wire_row.astype(jnp.float32)
            return buffer.at[widx].set(row)

        # the fresh buffer commits REPLICATED like every fold output (the
        # submission payloads carry the mesh's replicated NamedSharding),
        # so the first fold of every round hits the same trace as the rest
        fresh = jax.jit(
            lambda: jnp.zeros((n, d), jnp.float32),
            out_shardings=NamedSharding(self.mesh, P()),
        )
        jitted = jax.jit(fold, donate_argnums=(0,))
        return trace.traced("bounded_fold.dispatch", jitted, cat="train"), fresh


class ShardedRobustEngine(RobustEngine):
    """Thin compatibility shim: ``RobustEngine(..., sharding="sharded")``
    under the historical name/signature.  New code should construct
    :class:`RobustEngine` directly."""

    def __init__(self, mesh, gar, nb_real_byz=0, attack=None, lossy_link=None,
                 granularity="layer", exchange_dtype=None, worker_momentum=None,
                 worker_metrics=False, reputation_decay=None,
                 quarantine_threshold=0.0, l1_regularize=None,
                 l2_regularize=None, chaos=None, health_probe=True,
                 nb_workers=None, secure=False, flight=None):
        super().__init__(
            mesh, gar, nb_workers=nb_workers, nb_real_byz=nb_real_byz,
            attack=attack, lossy_link=lossy_link, granularity=granularity,
            exchange_dtype=exchange_dtype, worker_momentum=worker_momentum,
            worker_metrics=worker_metrics, reputation_decay=reputation_decay,
            quarantine_threshold=quarantine_threshold,
            l1_regularize=l1_regularize, l2_regularize=l2_regularize,
            chaos=chaos, health_probe=health_probe, secure=secure,
            flight=flight, sharding="sharded",
        )

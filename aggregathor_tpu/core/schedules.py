"""Learning-rate schedule registry.

Same three schedules as the reference's LR registry (reference:
graph.py:51-57): ``fixed``, ``polynomial``, ``exponential``, built from typed
``key:value`` args with the defaults of config.py.  Implemented as optax
schedules (step -> rate), evaluated inside the jitted train step.
"""

import optax

from .. import config
from ..utils import ClassRegister, parse_keyval

schedules = ClassRegister("learning-rate schedule")


def _fixed(args):
    kv = parse_keyval(args, {"initial-rate": config.default_learning_rate})
    return optax.constant_schedule(kv["initial-rate"])


def _polynomial(args):
    kv = parse_keyval(
        args,
        {
            "initial-rate": config.default_learning_rate,
            "end-rate": config.default_end_learning_rate,
            "decay-step": config.default_decay_step,
            "power": 1.0,
        },
    )
    return optax.polynomial_schedule(
        init_value=kv["initial-rate"],
        end_value=kv["end-rate"],
        power=kv["power"],
        transition_steps=kv["decay-step"],
    )


def _exponential(args):
    kv = parse_keyval(
        args,
        {
            "initial-rate": config.default_learning_rate,
            "decay-step": config.default_decay_step,
            "decay-rate": config.default_decay_rate,
        },
    )
    return optax.exponential_decay(
        init_value=kv["initial-rate"],
        transition_steps=kv["decay-step"],
        decay_rate=kv["decay-rate"],
    )


schedules.register("fixed", _fixed)
schedules.register("polynomial", _polynomial)
schedules.register("exponential", _exponential)


def build_schedule(name, args=None):
    """Build an optax schedule from its registered name and key:value args."""
    return schedules.get(name)(args or [])

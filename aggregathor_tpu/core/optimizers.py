"""Optimizer registry.

Same five optimizers as the reference's registry (reference: graph.py:58-66):
``sgd``, ``adam``, ``adadelta``, ``adagrad``, ``rmsprop``, with their tunables
exposed as ``key:value`` args.  Each factory takes the learning-rate schedule
(the aggregated gradient is applied once to one canonical parameter copy, so a
single optax transform replaces the reference's PS-resident optimizer).
"""

import optax

from ..utils import ClassRegister, parse_keyval

optimizers = ClassRegister("optimizer")


def _sgd(schedule, args):
    kv = parse_keyval(args, {"momentum": 0.0, "nesterov": False})
    momentum = kv["momentum"] if kv["momentum"] > 0.0 else None
    return optax.sgd(schedule, momentum=momentum, nesterov=kv["nesterov"])


def _adam(schedule, args):
    kv = parse_keyval(args, {"beta1": 0.9, "beta2": 0.999, "epsilon": 1e-8})
    return optax.adam(schedule, b1=kv["beta1"], b2=kv["beta2"], eps=kv["epsilon"])


def _adadelta(schedule, args):
    kv = parse_keyval(args, {"rho": 0.95, "epsilon": 1e-8})
    return optax.adadelta(schedule, rho=kv["rho"], eps=kv["epsilon"])


def _adagrad(schedule, args):
    kv = parse_keyval(args, {"initial-accumulator": 0.1, "epsilon": 1e-7})
    return optax.adagrad(schedule, initial_accumulator_value=kv["initial-accumulator"], eps=kv["epsilon"])


def _rmsprop(schedule, args):
    kv = parse_keyval(args, {"decay": 0.9, "momentum": 0.0, "epsilon": 1e-10})
    return optax.rmsprop(schedule, decay=kv["decay"], momentum=kv["momentum"], eps=kv["epsilon"])


optimizers.register("sgd", _sgd)
optimizers.register("adam", _adam)
optimizers.register("adadelta", _adadelta)
optimizers.register("adagrad", _adagrad)
optimizers.register("rmsprop", _rmsprop)


def build_optimizer(name, schedule, args=None):
    """Build an optax GradientTransformation from a registered name, schedule and key:value args."""
    return optimizers.get(name)(schedule, args or [])

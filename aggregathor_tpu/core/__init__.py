"""Training core: flatten machinery, schedules, optimizers, train state, step builder.

Replaces the reference's graph-construction layer (reference: graph.py) with
functional JAX equivalents: pytree ravel instead of per-variable concat
(graph.py:144-199), optax instead of tf.train optimizers (graph.py:58-66),
and a pure jitted step function instead of a replicated tf.Graph.
"""

from .flatten import FlatMap, flatten, inflate  # noqa: F401
from .schedules import schedules, build_schedule  # noqa: F401
from .optimizers import optimizers, build_optimizer  # noqa: F401
from .train_state import TrainState  # noqa: F401

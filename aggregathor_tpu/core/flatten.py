"""Gradient flatten/inflate machinery.

The GARs operate on 1-D gradient vectors: the reference concatenates every
per-variable gradient into one flat tensor with a shared variable->offset
"flatmap" so coordinates align across workers (reference: graph.py:144-199).
In JAX the gradient is a pytree; ``jax.flatten_util.ravel_pytree`` gives the
same coherent flattening for free (identical tree structure on every worker
=> identical coordinate layout).  ``FlatMap`` additionally records per-leaf
offsets/shapes, which powers per-layer GAR application (bounding the (n, d)
matrices for LLM-scale models, see SURVEY.md §5) and diagnostics.
"""

import jax
import jax.numpy as jnp
import numpy as np


class FlatMap:
    """Records the leaf layout of a flattened pytree (reference: graph.py:144-168).

    Attributes:
      treedef:  the pytree structure.
      slices:   list of (path, offset, size, shape, dtype) per leaf, in
                flattening order.
      size:     total number of coordinates d.
    """

    def __init__(self, tree):
        leaves_with_paths = jax.tree_util.tree_leaves_with_path(tree)
        self.treedef = jax.tree_util.tree_structure(tree)
        self.slices = []
        offset = 0
        for path, leaf in leaves_with_paths:
            size = int(np.prod(np.shape(leaf))) if np.ndim(leaf) else 1
            self.slices.append(
                (jax.tree_util.keystr(path), offset, size, np.shape(leaf), np.result_type(leaf))
            )
            offset += size
        self.size = offset

    def inflate(self, flat):
        """Slice a 1-D vector back into the recorded pytree shapes (reference: graph.py:182-199)."""
        leaves = []
        for _, offset, size, shape, dtype in self.slices:
            leaves.append(jax.lax.dynamic_slice(flat, (offset,), (size,)).reshape(shape).astype(dtype))
        return jax.tree_util.tree_unflatten(self.treedef, leaves)


def flatten(tree, dtype=jnp.float32):
    """Flatten a pytree of arrays into one 1-D vector.

    Returns (vector, flatmap); ``flatmap.inflate`` restores the structure.
    The vector is cast to ``dtype`` (GARs aggregate in float32 regardless of
    compute dtype, matching the reference's float/double kernels).
    """
    flatmap = FlatMap(tree)
    leaves = jax.tree_util.tree_leaves(tree)
    vector = jnp.concatenate([jnp.ravel(leaf).astype(dtype) for leaf in leaves]) if leaves else jnp.zeros((0,), dtype)
    return vector, flatmap


def inflate(flat, flatmap):
    """Module-level alias of ``FlatMap.inflate`` (reference: graph.py:182-199)."""
    return flatmap.inflate(flat)

"""Replicated train state.

The reference keeps one canonical parameter copy on the parameter server
(reference: graph.py:97-120).  The SPMD equivalent is a *replicated* pytree:
every device holds identical params/optimizer state, and determinism of the
aggregated gradient (all_gather + identical GAR computation) keeps the copies
bit-identical — the PS semantics without a PS.
"""

import flax.serialization
import flax.struct
import jax
import jax.numpy as jnp
import optax  # noqa: F401  (type provider for opt_state pytrees)


@flax.struct.dataclass
class TrainState:
    """Pure-pytree training state: parameters, optimizer state, step counter, PRNG key.

    ``carry`` is the optional per-worker previously-received gradient matrix,
    global shape (nb_workers, d), used by the CLEVER stale-value infill of the
    lossy link (reference: mpi_rendezvous_mgr.patch:833-835 — the PS's
    reassembly buffer keeps last step's bytes where packets are lost).  Unlike
    every other field it is *worker-sharded*, never replicated: each device
    carries only its own workers' rows.
    """

    step: jax.Array
    params: object
    opt_state: object
    rng: jax.Array
    carry: object = None

    @classmethod
    def create(cls, params, tx, rng=None, carry=None):
        return cls(
            step=jnp.zeros((), jnp.int32),
            params=params,
            opt_state=tx.init(params),
            rng=rng if rng is not None else jax.random.PRNGKey(0),
            carry=carry,
        )


_SERIALIZED_FIELDS = ("step", "params", "opt_state", "rng")


def _to_state_dict(state):
    # ``carry`` never reaches checkpoints: it is a transport buffer, not model
    # state — writing it would cost (n, d) host bytes per snapshot and break
    # restore of snapshots taken before the field existed.  A restarted run
    # re-zeroes it, like the reference's freshly-allocated reassembly buffer.
    return {f: flax.serialization.to_state_dict(getattr(state, f)) for f in _SERIALIZED_FIELDS}


def _from_state_dict(target, state_dict):
    restored = {
        f: flax.serialization.from_state_dict(getattr(target, f), state_dict[f], name=f)
        for f in _SERIALIZED_FIELDS
    }
    return target.replace(**restored)


flax.serialization.register_serialization_state(
    TrainState, _to_state_dict, _from_state_dict, override=True
)

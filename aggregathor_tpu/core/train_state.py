"""Replicated train state.

The reference keeps one canonical parameter copy on the parameter server
(reference: graph.py:97-120).  The SPMD equivalent is a *replicated* pytree:
every device holds identical params/optimizer state, and determinism of the
aggregated gradient (all_gather + identical GAR computation) keeps the copies
bit-identical — the PS semantics without a PS.
"""

import flax.struct
import jax
import jax.numpy as jnp
import optax  # noqa: F401  (type provider for opt_state pytrees)


@flax.struct.dataclass
class TrainState:
    """Pure-pytree training state: parameters, optimizer state, step counter, PRNG key."""

    step: jax.Array
    params: object
    opt_state: object
    rng: jax.Array

    @classmethod
    def create(cls, params, tx, rng=None):
        return cls(
            step=jnp.zeros((), jnp.int32),
            params=params,
            opt_state=tx.init(params),
            rng=rng if rng is not None else jax.random.PRNGKey(0),
        )

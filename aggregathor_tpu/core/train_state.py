"""Replicated train state.

The reference keeps one canonical parameter copy on the parameter server
(reference: graph.py:97-120).  The SPMD equivalent is a *replicated* pytree:
every device holds identical params/optimizer state, and determinism of the
aggregated gradient (all_gather + identical GAR computation) keeps the copies
bit-identical — the PS semantics without a PS.
"""

import flax.serialization
import flax.struct
import jax
import jax.numpy as jnp
import optax  # noqa: F401  (type provider for opt_state pytrees)


@flax.struct.dataclass
class TrainState:
    """Pure-pytree training state: parameters, optimizer state, step counter, PRNG key.

    Two optional (nb_workers, d) per-worker matrices ride along, both
    *worker-sharded* (each device holds only its own workers' rows, never
    replicated) and both excluded from checkpoints:

    - ``carry``: the previously-received gradients, used by the CLEVER
      stale-value infill of the lossy link (reference:
      mpi_rendezvous_mgr.patch:833-835 — the PS's reassembly buffer keeps
      last step's bytes where packets are lost);
    - ``momentum``: per-worker momentum for history-aware robust aggregation
      (Karimireddy et al. 2021): workers send momenta instead of raw
      gradients, so a Byzantine worker cannot re-inject fresh noise each
      step (time-coupled attacks average out in honest momenta).

    ``momentum_steps`` counts momentum updates separately from ``step``: the
    buffer re-zeroes on restore (never serialized), so its bias correction
    must restart too — correcting by the global step would attenuate the
    first post-restore sends by up to (1 - beta).
    """

    step: jax.Array
    params: object
    opt_state: object
    rng: jax.Array
    carry: object = None
    momentum: object = None
    momentum_steps: object = None
    #: (nb_workers,) replicated reputation EMA for the quarantine mechanism
    #: (parallel/engine.py); a side buffer like carry/momentum — never
    #: serialized, re-warms from 1.0 after restore
    reputation: object = None
    #: replicated scalar EMA of |loss| for the guardian health probe
    #: (guardian/probe.py); never serialized — re-warms from the sentinel
    #: after restore so a rollback never judges recovery against a
    #: poisoned reference
    loss_ema: object = None
    #: replicated flight-recorder ring buffers (obs/flight.py): a dict of
    #: fixed-size per-step telemetry lanes written in-scan by the step
    #: body.  A side buffer like carry/momentum — never serialized; a
    #: restore or rollback re-initializes an empty ring (stale rows from
    #: an abandoned timeline must not masquerade as fresh evidence)
    flight: object = None
    #: (nb_workers, d) per-worker error-feedback residuals of the
    #: compressed wire codec (parallel/compress.py): worker w transmits
    #: C(g + ef[w]) and carries the quantization residual forward.
    #: Worker-sharded like carry/momentum but — unlike them — SERIALIZED
    #: (conditionally, below): a residual is accumulated signal, and
    #: zeroing it on restore would silently re-bias the first post-restore
    #: submissions.  Checkpoint/restore/rollback round-trips preserve it
    #: bit-exactly (tests/test_compress.py); EF runs are single-process
    #: (the runner refuses multi-host EF), so the device_get is addressable
    ef: object = None

    @classmethod
    def create(cls, params, tx, rng=None, carry=None, momentum=None):
        return cls(
            step=jnp.zeros((), jnp.int32),
            params=params,
            opt_state=tx.init(params),
            rng=rng if rng is not None else jax.random.PRNGKey(0),
            carry=carry,
            momentum=momentum,
        )


_SERIALIZED_FIELDS = ("step", "params", "opt_state", "rng")


def _to_state_dict(state):
    # The worker-sharded side buffers (carry, momentum) never reach
    # checkpoints: writing them would cost (n, d) host bytes per snapshot
    # and break restore of snapshots taken before the fields existed.  A
    # restarted run re-zeroes them (for CLEVER, exactly the reference's
    # freshly-allocated reassembly buffer; for momentum, a short re-warmup).
    # The error-feedback residual is the exception (see the field doc):
    # serialized CONDITIONALLY, so snapshots of EF-less runs keep their
    # historical layout and pre-EF snapshots restore into EF runs (the
    # target's zeroed buffer stands in, exactly a fresh codec's state).
    out = {f: flax.serialization.to_state_dict(getattr(state, f)) for f in _SERIALIZED_FIELDS}
    if state.ef is not None:
        out["ef"] = flax.serialization.to_state_dict(state.ef)
    return out


def _from_state_dict(target, state_dict):
    restored = {
        f: flax.serialization.from_state_dict(getattr(target, f), state_dict[f], name=f)
        for f in _SERIALIZED_FIELDS
    }
    if target.ef is not None and "ef" in state_dict:
        restored["ef"] = flax.serialization.from_state_dict(
            target.ef, state_dict["ef"], name="ef"
        )
    return target.replace(**restored)


flax.serialization.register_serialization_state(
    TrainState, _to_state_dict, _from_state_dict, override=True
)

"""Submission-integrity layer: the train -> sign -> serve chain of custody.

The reference's defining systems contribution beyond the GARs was its
hardened transport: every worker->PS tensor push is signed and verified
before reassembly, and transport failures degrade into values the rules
already absorb (mpi_rendezvous_mgr.patch:585-627, SURVEY L1).  This package
is that layer for the SPMD engines, in three pieces (docs/security.md):

- ``submit``   per-(worker, step) HMAC authentication of gradient
  submissions: in-graph row digests, host-side sign/verify around the
  jitted step (zero added recompiles), reject-and-name through the
  forensics ledger;
- ``masking``  optional Bonawitz-style pairwise additive masking, cancelled
  EXACTLY (mod 2^64) inside bucket/hier group means so individual rows stay
  hidden while group means are unchanged;
- ``custody``  signed lineage manifests beside every checkpoint, verified
  by the training auto-restore, the guardian rollback and the serving
  restore paths — closing the train -> sign -> serve chain.
"""

from .custody import ChainOfCustody, manifest_path  # noqa: F401
from .masking import GroupMasking, enable_masking, masked_group_mean  # noqa: F401
from .submit import (  # noqa: F401
    DIGEST_LANES,
    SubmissionAuthenticator,
    digest_to_bytes,
    row_digest,
    tamper_row,
)

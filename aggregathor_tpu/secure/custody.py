"""Chain of custody: signed lineage manifests beside every checkpoint.

The HMAC tag (``obs/checkpoint.py``) proves a snapshot's BYTES are intact;
it says nothing about where they came from.  The custody manifest carries
the lineage — run id, step, GAR spec, experiment + data digest, and the
submission **tag chain** head (``secure/submit.py``) covering every
verified gradient that flowed into the state — and is itself HMAC-signed
under a dedicated ``b"custody"`` key family from the session secret.

Writers: the training run (``Checkpoints(custody=...)`` writes a manifest
in the same atomic dance as the ``.tag`` sidecar).  Verifiers: the training
auto-restore, the guardian rollback restore, and ``serve/``'s replica
loading — the full train -> sign -> serve chain.  Verification is
fail-closed: a missing manifest refuses the restore unless the caller
explicitly opted out (``allow_unsigned=True`` — serve's ``--allow-unsigned``
flag), because an attacker with file access could otherwise simply delete
the manifest.

Schema ``aggregathor.secure.custody.v1``::

    {"schema": ..., "run_id": ..., "step": N, "experiment": ...,
     "gar": "<spec>", "data_digest": "<sha256 hex of the experiment's
     training arrays, or of the config identity when the data is not
     host-addressable>", "snapshot_digest": "<sha256 hex of the on-disk
     snapshot bytes (post-encryption: digest-then-sign what disk holds)>",
     "tag_chain": {"head": hex, "steps": N, "nb_workers": n} | null,
     "created_at": ..., "signature": "<HMAC-SHA256 hex over the canonical
     JSON of every other field, step-bound>"}
"""

import hashlib
import json
import os
import time

from ..parallel.auth import GradientAuthenticator
from ..utils import UserException, warning

SCHEMA = "aggregathor.secure.custody.v1"


def manifest_path(ckpt_path):
    """The lineage manifest sitting beside a snapshot file."""
    return str(ckpt_path) + ".manifest.json"


def data_digest_for(experiment, fallback_identity):
    """SHA-256 over the experiment's host-addressable training arrays
    (leaves in sorted key order), or over the config identity string when
    the data never materializes on host (streaming corpora, host
    transforms).  The digest pins WHICH data trained the snapshot."""
    import numpy as np

    arrays = None
    try:
        arrays = experiment.train_arrays()
    except Exception:
        arrays = None
    digest = hashlib.sha256()
    if arrays is not None:
        import jax

        leaves, treedef = jax.tree_util.tree_flatten(arrays)
        digest.update(repr(treedef).encode())
        for leaf in leaves:
            host = np.ascontiguousarray(np.asarray(leaf))
            digest.update(str(host.dtype).encode() + repr(host.shape).encode())
            digest.update(host.tobytes())
    else:
        digest.update(b"config-identity:" + str(fallback_identity).encode())
    return digest.hexdigest()


class ChainOfCustody:
    """Writes and verifies signed lineage manifests.

    One instance serves both roles: the trainer constructs it with the run's
    lineage fields and hands it to ``Checkpoints(custody=...)``; a verifier
    (serve, or a restoring trainer) needs only the session secret (and its
    ``allow_unsigned`` policy).  ``submission`` is the optional
    :class:`~aggregathor_tpu.secure.submit.SubmissionAuthenticator` whose
    live tag chain each manifest snapshots.
    """

    def __init__(self, session_secret, run_id=None, experiment=None,
                 gar_spec=None, data_digest=None, submission=None,
                 allow_unsigned=False):
        self.auth = GradientAuthenticator(session_secret, 1, context=b"custody")
        self.run_id = run_id
        self.experiment = experiment
        self.gar_spec = gar_spec  # updated by the runner on guardian escalation
        self.data_digest = data_digest
        self.submission = submission
        self.allow_unsigned = bool(allow_unsigned)
        #: verification tallies (serve's /healthz custody_verified reads them)
        self.verified = 0
        self.unsigned = 0
        self.last_manifest = None

    # ------------------------------------------------------------------ #
    # write side

    def lineage(self, step):
        """Snapshot the mutable lineage state for ``step`` — called on the
        SAVE caller's thread, so a background checkpoint writer signs the
        chain head as of the save, not of some later step."""
        return {
            "schema": SCHEMA,
            "run_id": self.run_id,
            "step": int(step),
            "experiment": self.experiment,
            "gar": self.gar_spec,
            "data_digest": self.data_digest,
            "tag_chain": (
                self.submission.chain() if self.submission is not None else None
            ),
            "created_at": time.time(),
        }

    @staticmethod
    def _canonical(payload):
        return json.dumps(payload, sort_keys=True, separators=(",", ":")).encode()

    def write(self, ckpt_path, step, data, payload=None):
        """Write the signed manifest beside ``ckpt_path``.  ``data`` is the
        snapshot's final on-disk bytes (post-encryption: the digest covers
        exactly what a verifier will read back).  Atomic like the snapshot
        itself."""
        payload = dict(payload if payload is not None else self.lineage(step))
        payload["snapshot_digest"] = hashlib.sha256(bytes(data)).hexdigest()
        signature = self.auth.sign(0, int(step), self._canonical(payload))
        payload["signature"] = signature.hex()
        path = manifest_path(ckpt_path)
        tmp = path + ".tmp"
        with open(tmp, "w") as fd:
            json.dump(payload, fd, sort_keys=True, indent=1)
            fd.write("\n")
        os.replace(tmp, path)
        return path

    # ------------------------------------------------------------------ #
    # verify side

    def verify(self, ckpt_path, step, data):
        """Verify provenance of a snapshot about to be loaded.

        Fail-closed ``UserException`` on a missing manifest (unless
        ``allow_unsigned``), a bad signature, a step mismatch, or snapshot
        bytes that do not match the signed digest.  Returns True when the
        chain verified, False when an unsigned snapshot was explicitly
        allowed through.
        """
        path = manifest_path(ckpt_path)
        try:
            with open(path) as fd:
                doc = json.load(fd)
        except OSError:
            if self.allow_unsigned:
                warning(
                    "Checkpoint %r has NO custody manifest — loading it "
                    "anyway (--allow-unsigned): provenance is unverified"
                    % (str(ckpt_path),)
                )
                self.unsigned += 1
                return False
            raise UserException(
                "Checkpoint %r has no custody manifest: it was saved without "
                "--secure (or the manifest was deleted). Refusing to load an "
                "unsigned checkpoint; pass --allow-unsigned to opt out, or "
                "re-save it from a --secure run" % (str(ckpt_path),)
            )
        if not isinstance(doc, dict) or doc.get("schema") != SCHEMA:
            raise UserException(
                "Custody manifest %r is not a %s document" % (path, SCHEMA)
            )
        signature = doc.pop("signature", "")
        try:
            tag = bytes.fromhex(signature)
        except ValueError:
            tag = b""
        if not self.auth.verify(0, int(step), self._canonical(doc), tag):
            raise UserException(
                "Custody manifest %r failed signature verification: forged, "
                "tampered, or a --session-secret mismatch; treat the "
                "checkpoint as untrusted" % (path,)
            )
        if int(doc.get("step", -1)) != int(step):
            raise UserException(
                "Custody manifest %r signs step %r but snapshot step %d was "
                "restored — a manifest copied between snapshots"
                % (path, doc.get("step"), int(step))
            )
        actual = hashlib.sha256(bytes(data)).hexdigest()
        if actual != doc.get("snapshot_digest"):
            raise UserException(
                "Checkpoint %r does not match its signed custody manifest "
                "(snapshot digest mismatch): the snapshot was swapped or "
                "corrupted after signing" % (str(ckpt_path),)
            )
        self.verified += 1
        self.last_manifest = dict(doc)
        return True

    @property
    def all_verified(self):
        """True iff every restore so far verified (and at least one did)."""
        return self.verified > 0 and self.unsigned == 0

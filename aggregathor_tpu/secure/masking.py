"""Bucket-level pairwise additive masking (Bonawitz et al. 2017 style).

Secure aggregation hides individual submissions from the aggregator by
having workers add pairwise masks that cancel in the SUM.  Robust rules
break that story — they need per-row structure, not just the sum — so
masking here composes with the meta-GARs instead (NET-SA, arXiv:2501.01187:
secure aggregation as an architecture concern): masks are exchanged only
*within* a bucket (``bucketing``) or hier group whose inner reduction is a
mean, and cancel inside that group mean.  The aggregator's selection rule
then operates on group means exactly as before, while any individual row it
could inspect is one-time-padded.  The privacy unit is the group: what
leaks per group is its mean (s-anonymity in the Bonawitz sense), which is
precisely the quantity the meta-GAR consumes anyway.

**Exact cancellation.**  Additive masks in float arithmetic cannot cancel
bitwise (float addition is not associative), so — like every real secure-
aggregation protocol — the masked mean runs in modular integer arithmetic:
each coordinate is encoded as a signed 64-bit fixed-point value (32
fraction bits, emulated as two uint32 limbs so no x64 mode is needed),
member ``j`` of a group of ``s`` adds the chain mask ``m_j - m_{(j+1) mod
s}`` (each ``m`` a fresh uniform draw mod 2^64 — a one-time pad per
coordinate, shared by the adjacent pair), and the group sum is taken mod
2^64 where the masks cancel EXACTLY.  The decoded mean is therefore
bit-identical between a masked run and the same run with masks disabled
(``GroupMasking(enabled=False)`` — the "unmasked" baseline with the same
deterministic arithmetic; a plain ``jnp.mean`` differs in low bits because
float summation rounds differently, which is exactly why the masked path
needs its own arithmetic).  Encoding quantizes at 2^-32 absolute — orders
of magnitude below float32 noise at gradient scale; coordinates beyond
+/-2^31 wrap into garbage, which the OUTER rule treats as one more outlier
group.

**Drop-out semantics.**  A worker whose row drops mid-step (lossy NaN, dead
straggler, rejected forgery) leaves its pairwise masks uncancelled — the
real protocol cannot unmask that group sum without a recovery round, so the
whole group's mean reads NaN here and the NaN-tolerant outer rule absorbs
it: one dropped worker costs its group, composing with the ragged-bucket
machinery (the padded bucket was already always-NaN).

**Key flow.**  Pairwise mask seeds derive from the session secret
(:meth:`GroupMasking.from_secret` — material the aggregator role would not
hold in a real deployment) folded with a per-step salt drawn from the
replicated step key, so masks redraw every step and follow the bucketing
permutation (all parties can compute the permutation: its key is the
replicated step key, the Bonawitz key-agreement round collapsed by the
simulation).  Under a sharded ``axis_name`` the device's axis index folds
in too, so column blocks on different devices never reuse pad material.
"""

import hashlib

from ..utils import UserException

#: fold tag deriving the mask stream from the rule's per-step key — disjoint
#: from bucketing's permutation (raw key), inner (fold 1) and outer (fold 2)
MASK_KEY_TAG = 7

#: fixed-point fraction bits of the masked-mean integer domain
FRACTION_BITS = 32


class GroupMasking:
    """Masking configuration carried by a mean-inner meta-GAR instance.

    ``enabled=False`` keeps the exact fixed-point group-mean arithmetic but
    adds no masks — the bit-identity baseline the tests and the smoke
    compare a masked run against ("unmasked run", same deterministic path).
    """

    def __init__(self, base_key, enabled=True):
        self.base_key = base_key
        self.enabled = bool(enabled)

    @classmethod
    def from_secret(cls, session_secret, enabled=True):
        """Derive the pairwise-mask key material from the session secret
        (domain-separated from every HMAC family)."""
        import jax

        seed = int.from_bytes(
            hashlib.sha256(b"pairwise-mask:" + bytes(session_secret)).digest()[:4],
            "little",
        )
        return cls(jax.random.PRNGKey(seed), enabled=enabled)


# --------------------------------------------------------------------- #
# two-limb (uint32 hi/lo) arithmetic mod 2^64 — exact, no x64 mode needed


def _neg64(hi, lo):
    import jax.numpy as jnp

    nlo = (~lo) + jnp.uint32(1)
    nhi = (~hi) + (nlo == 0).astype(jnp.uint32)
    return nhi, nlo


def _add64(ah, al, bh, bl):
    import jax.numpy as jnp

    lo = al + bl
    carry = (lo < al).astype(jnp.uint32)
    return ah + bh + carry, lo


def _sub64(ah, al, bh, bl):
    nh, nl = _neg64(bh, bl)
    return _add64(ah, al, nh, nl)


def _encode64(x):
    """float32 -> signed 64-bit fixed point (FRACTION_BITS), two uint32
    limbs.  Exact integer/fraction split (Sterbenz: ``x - floor(x)`` is
    exact in IEEE); the fraction truncates to the 2^-32 grid.  Inputs must
    be finite (callers zero non-finite values and flag the row)."""
    import jax.numpy as jnp

    x = x.astype(jnp.float32)
    ax = jnp.abs(x)
    hi_f = jnp.floor(ax)
    frac = ax - hi_f
    hi = hi_f.astype(jnp.uint32)
    lo = (frac * jnp.float32(2.0 ** 32)).astype(jnp.uint32)
    nhi, nlo = _neg64(hi, lo)
    neg = x < 0
    return jnp.where(neg, nhi, hi), jnp.where(neg, nlo, lo)


def _decode64(hi, lo):
    """Signed 64-bit fixed point -> float32 (one deterministic rounding)."""
    import jax.numpy as jnp

    neg = hi >= jnp.uint32(0x80000000)
    mh, ml = _neg64(hi, lo)
    mag_hi = jnp.where(neg, mh, hi)
    mag_lo = jnp.where(neg, ml, lo)
    mag = (
        mag_hi.astype(jnp.float32) * jnp.float32(2.0 ** 32)
        + mag_lo.astype(jnp.float32)
    )
    return jnp.where(neg, -mag, mag) * jnp.float32(2.0 ** -FRACTION_BITS)


# --------------------------------------------------------------------- #


def masked_group_mean(grouped, key, masking, axis_name=None):
    """(G, s, d) grouped rows -> (G, d) float32 group means with pairwise
    masks cancelled exactly (mod 2^64); any non-finite row NaNs its whole
    group (the uncancelled-mask story, module docstring).

    ``key`` is the rule's replicated per-step PRNG key (required: masks must
    redraw every step); ``axis_name`` folds the device's axis index into the
    pad stream under sharded execution.
    """
    import jax
    import jax.numpy as jnp

    if key is None:
        raise UserException(
            "bucket-level masking needs the per-step PRNG key (both engines "
            "pass it; the keyless dense/oracle tier cannot run masked)"
        )
    nb_groups, group_size, dim = grouped.shape
    x = grouped.astype(jnp.float32)
    group_ok = jnp.all(jnp.isfinite(x), axis=(1, 2))
    hi, lo = _encode64(jnp.where(jnp.isfinite(x), x, 0.0))
    if masking.enabled:
        salt = jax.random.bits(
            jax.random.fold_in(key, MASK_KEY_TAG), (), jnp.uint32
        )
        pad_key = jax.random.fold_in(masking.base_key, salt)
        if axis_name is not None:
            pad_key = jax.random.fold_in(pad_key, jax.lax.axis_index(axis_name))
        mask_hi = jax.random.bits(
            jax.random.fold_in(pad_key, 0), (nb_groups, group_size, dim), jnp.uint32
        )
        mask_lo = jax.random.bits(
            jax.random.fold_in(pad_key, 1), (nb_groups, group_size, dim), jnp.uint32
        )
        # chain topology: member j holds pad m_j with its successor — adds
        # m_j, subtracts m_{(j+1) mod s}; the per-group telescoping sum is
        # ZERO mod 2^64 by construction, any single row is one-time-padded
        rh, rl = _sub64(
            mask_hi, mask_lo,
            jnp.roll(mask_hi, -1, axis=1), jnp.roll(mask_lo, -1, axis=1),
        )
        hi, lo = _add64(hi, lo, rh, rl)
    acc_hi = jnp.zeros((nb_groups, dim), jnp.uint32)
    acc_lo = jnp.zeros((nb_groups, dim), jnp.uint32)
    for member in range(group_size):  # static, small s
        acc_hi, acc_lo = _add64(acc_hi, acc_lo, hi[:, member], lo[:, member])
    mean = _decode64(acc_hi, acc_lo) / jnp.float32(group_size)
    return jnp.where(group_ok[:, None], mean, jnp.nan)


def enable_masking(gar, masking):
    """Attach ``masking`` to a meta-GAR instance, validating at parse time
    that the spec CAN cancel masks: the group reduction must be a mean.

    Accepted: ``bucketing`` (its bucket reduction IS a mean, any inner rule
    over the bucket means) and ``hier`` with ``inner=average``.  Everything
    else is rejected here — before any compilation — because a non-mean
    group reduction would see one-time-padded garbage rows.  Group size
    must be >= 2 (a group of one hides nothing).  Returns ``gar``.
    """
    from ..gars.average import AverageGAR
    from ..gars.bucketing import BucketingGAR
    from ..gars.hierarchical import HierarchicalGAR

    if isinstance(gar, BucketingGAR):
        if gar.s < 2:
            raise UserException(
                "masking over buckets of s=%d hides nothing (each row IS its "
                "bucket mean); use s >= 2" % gar.s
            )
    elif isinstance(gar, HierarchicalGAR):
        if not isinstance(gar.inner, AverageGAR):
            raise UserException(
                "bucket-level masking cancels only inside a MEAN group "
                "reduction: hier needs inner=average (got inner=%s); "
                "bucketing works with any inner rule (its buckets are means)"
                % type(gar.inner).__name__
            )
        if gar.g < 2:
            raise UserException(
                "masking over hier groups of g=%d hides nothing; use g >= 2"
                % gar.g
            )
    else:
        raise UserException(
            "bucket-level masking needs a mean-inner meta-GAR spec — "
            "'bucketing:s=...,inner=...' or 'hier:g=...,inner=average,"
            "outer=...' — got %s" % type(gar).__name__
        )
    gar.masking = masking
    return gar

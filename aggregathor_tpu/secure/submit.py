"""Authenticated gradient submission (the per-step layer of ``secure/``).

The reference signs every worker->PS tensor push with a per-worker key and
the PS verifies before reassembly (mpi_rendezvous_mgr.patch:585-627); a
failed signature drops the push, which the NaN-row conventions absorb.  The
TPU-native mapping splits that protocol across the host/device boundary:

- **In graph** (both engines): each worker's flattened post-transport row is
  reduced to a tiny position-sensitive checksum (:func:`row_digest`, a few
  multiply-shift lanes over the float32 bit patterns — one O(d) pass per
  worker, part of the ONE compiled step, zero added dispatches or
  recompiles).  Rows whose tags cannot verify (``forge``: the submitter
  never held the session secret; ``tamper``: bytes flipped after signing)
  are masked NaN *before stacking*, so the GARs absorb the rejection within
  the same f budget as a lossy row — and the digests, the coalition mask
  and the rejection verdict ride the step metrics to the host.

- **On host** (:class:`SubmissionAuthenticator`, driven by the runner one
  dispatch behind, exactly like the forensics feed): each worker's digest
  bytes are HMAC-tagged under its per-(worker, step) key derived from the
  session secret (``parallel/auth.py`` ``derive_worker_key`` — one
  derivation pass at construction, ``sign_many``/``verify_many`` over the
  whole stack per step), every tag is verified, failures are counted and
  handed to the forensics ledger as named ``forgery`` evidence
  (reject-and-name, never a silent drop), and the verified tags extend a
  rolling **tag chain** whose head the custody manifest signs — the
  train->sign->serve lineage (``secure/custody.py``).

What the HMAC buys — and does not — is spelled out in docs/security.md: it
stops impersonation and in-flight tampering; it does NOT stop a Byzantine
worker that signs its own poison honestly (that is the GARs' job).
"""

import hashlib
import struct
import time

import numpy as np

from ..obs import events
from ..parallel.auth import GradientAuthenticator

#: uint32 checksum lanes per row digest (16 bytes of tag material)
DIGEST_LANES = 4

#: per-lane odd multipliers of the multiply-shift family (position-weighted
#: modular sums: permuting or editing coordinates moves every lane)
_LANE_MULT = (0x85EBCA6B, 0xC2B2AE35, 0x27D4EB2F, 0x9E3779B1)
_LANE_ADD = (0x165667B1, 0x5BD1E995, 0x2545F491, 0x61C88647)

#: what a forger without the session secret signs with — ANY key material
#: other than the real secret behaves identically (the tag cannot verify)
FORGER_SECRET = b"forger-without-the-session-secret"

#: scale of a forged (impersonated) submission's noise content — what an
#: UNDEFENDED run accepts into aggregation when the chaos ``forge`` regime
#: fires without ``--secure``
FORGE_SCALE = 8.0


def row_digest(row, salt=0):
    """(d,) float32 row -> (DIGEST_LANES,) uint32 checksum, in graph.

    Position-weighted modular sums over the row's float32 bit patterns:
    lane L = sum_c bits(row[c]) * (A_L * (c + salt) + B_L)  mod 2^32.
    Cheap (one fused pass), deterministic, order- and value-sensitive — the
    simulation's stand-in for hashing the row bytes the reference's
    transport signs.  NOT a cryptographic hash: collision resistance comes
    from the HMAC over the digest, unforgeability from the per-worker key
    (an attacker without the key gains nothing from digest collisions it
    cannot sign).  ``salt`` offsets the position stream (the sharded engine
    folds a per-leaf constant so leaves do not alias).
    """
    import jax
    import jax.numpy as jnp

    bits = jax.lax.bitcast_convert_type(row.astype(jnp.float32), jnp.uint32)
    idx = jnp.arange(bits.shape[-1], dtype=jnp.uint32) + jnp.uint32(
        int(salt) & 0xFFFFFFFF
    )
    lanes = [
        jnp.sum(bits * (idx * jnp.uint32(mult) + jnp.uint32(add)),
                dtype=jnp.uint32)
        for mult, add in zip(_LANE_MULT, _LANE_ADD)
    ]
    return jnp.stack(lanes)


def tamper_row(row, key):
    """In-transit bit corruption (the chaos ``tamper`` mode): flip the
    lowest EXPONENT bit of one PRNG-chosen coordinate — the value doubles
    or halves, a corruption subtle enough to slip under distance-outlier
    thresholds (exactly the class statistical robustness cannot see and
    cryptographic integrity catches)."""
    import jax
    import jax.numpy as jnp

    bits = jax.lax.bitcast_convert_type(row.astype(jnp.float32), jnp.uint32)
    coord = jax.random.randint(key, (), 0, bits.shape[-1])
    flipped = bits.at[coord].set(bits[coord] ^ jnp.uint32(1 << 23))
    return jax.lax.bitcast_convert_type(flipped, jnp.float32)


def digest_to_bytes(digest):
    """One host-side digest row ((DIGEST_LANES,) uint32) -> the 16 bytes the
    HMAC signs (little-endian, fixed layout on every platform)."""
    return np.ascontiguousarray(np.asarray(digest, dtype="<u4")).tobytes()


class SubmissionAuthenticator:
    """Host-side sign/verify of per-step submission digests.

    One instance per run (the aggregator role): per-worker keys derive once
    from the session secret under the ``b"submit"`` context (disjoint from
    the checkpoint/handshake/custody families), and each completed step's
    (n, DIGEST_LANES) digest stacks are signed and verified through the
    vectorized ``sign_many``/``verify_many`` fast path.

    The **forge simulation**: workers flagged in ``forged`` sign under
    :data:`FORGER_SECRET`-derived keys — the behavior of an impersonator
    that never held the session secret — so their tags cannot verify.  A
    *tampered* submission signs under the real key but over the pre-tamper
    digest, so verification against the received digest fails identically.

    Every verified step extends ``chain()``: head' = SHA-256(head || step ||
    tags || verdicts), the tag chain the custody manifest signs.

    Cost is measured, not presumed: ``secure_sign_seconds_total`` /
    ``secure_verify_seconds_total`` accumulate the wall time, and
    ``secure_forgeries_total{worker=...}`` names every rejected submission
    on the PR-4 metrics registry.
    """

    def __init__(self, session_secret, nb_workers, registry=None):
        self.nb_workers = int(nb_workers)
        self.auth = GradientAuthenticator(
            session_secret, self.nb_workers, context=b"submit"
        )
        self._forger = GradientAuthenticator(
            FORGER_SECRET, self.nb_workers, context=b"submit"
        )
        self._chain = hashlib.sha256(b"aggregathor-tag-chain-v1").digest()
        self._chain_steps = 0
        self._c_sign = self._c_verify = None
        self._c_submissions = self._c_forgeries = None
        if registry is not None:
            self._c_sign = registry.counter(
                "secure_sign_seconds_total",
                "Cumulative submission-tag signing wall time",
            )
            self._c_verify = registry.counter(
                "secure_verify_seconds_total",
                "Cumulative submission-tag verification wall time",
            )
            self._c_submissions = registry.counter(
                "secure_submissions_total", "Worker submissions processed"
            )
            self._c_forgeries = registry.counter(
                "secure_forgeries_total",
                "Submissions whose tag failed verification",
                labelnames=("worker",),
            )

    # ------------------------------------------------------------------ #

    def sign_step(self, step, sent_digests, forged=None):
        """Tag one step's (n, DIGEST_LANES) submitted digests.

        ``forged`` is an optional (n,) bool mask of workers signing WITHOUT
        the session secret (the chaos ``forge`` coalition).  Returns the
        (n, 32) uint8 tag stack.
        """
        sent = np.ascontiguousarray(np.asarray(sent_digests, dtype="<u4"))
        if sent.shape[0] != self.nb_workers:
            raise ValueError(
                "sign_step got %d digest rows for %d workers"
                % (sent.shape[0], self.nb_workers)
            )
        begin = time.perf_counter()
        tags = self.auth.sign_many(step, sent)
        if forged is not None:
            for worker in np.nonzero(np.asarray(forged).astype(bool))[0]:
                tags[worker] = np.frombuffer(
                    self._forger.sign(
                        int(worker), step, digest_to_bytes(sent[worker])
                    ),
                    np.uint8,
                )
        elapsed = time.perf_counter() - begin
        if self._c_sign is not None:
            self._c_sign.inc(elapsed)
            self._c_submissions.inc(self.nb_workers)
        return tags

    def verify_step(self, step, recv_digests, tags):
        """Verify one step's tags against the RECEIVED digests.

        Returns the (n,) bool verdict (True = tag verifies) and extends the
        tag chain.  Failures land on ``secure_forgeries_total``.
        """
        recv = np.ascontiguousarray(np.asarray(recv_digests, dtype="<u4"))
        begin = time.perf_counter()
        ok = self.auth.verify_many(step, recv, tags)
        elapsed = time.perf_counter() - begin
        rejected = np.nonzero(~ok)[0]
        if self._c_verify is not None:
            self._c_verify.inc(elapsed)
            for worker in rejected:
                self._c_forgeries.labels(worker=str(int(worker))).inc()
        if rejected.size:
            # journal (obs/events.py): a failed tag is a DECISION — the row
            # was rejected inside the f budget and the worker named
            events.emit("forgery_verdict", step=step,
                        workers=[int(w) for w in rejected],
                        nb_rejected=int(rejected.size))
        self._chain = hashlib.sha256(
            self._chain + struct.pack("<q", int(step))
            + np.ascontiguousarray(tags).tobytes() + ok.tobytes()
        ).digest()
        self._chain_steps += 1
        return ok

    def process_step(self, step, sent_digests, recv_digests, forged=None):
        """Sign-then-verify one completed step (the runner's per-step feed).
        Returns the (n,) bool verdict."""
        tags = self.sign_step(step, sent_digests, forged=forged)
        return self.verify_step(step, recv_digests, tags)

    def chain(self):
        """The current tag-chain lineage (what the custody manifest signs)."""
        return {
            "head": self._chain.hex(),
            "steps": self._chain_steps,
            "nb_workers": self.nb_workers,
        }

"""Declarative aggregation-tree specification (the ``tree:`` grammar).

The parameter-server star has one trusted aggregator and one GAR call; a
tree replaces it with L levels of *untrusted* sub-aggregators (CodedReduce,
arXiv:1902.01981; efficient meta-aggregation, arXiv:2405.14759).  The spec
is declarative and validated ENTIRELY at parse time — the same discipline
as every ``(n, f)`` feasibility check in ``gars/``: a tree that cannot
honor its Byzantine budget is rejected before a step ever runs.

Grammar (the ``tree:`` GAR spec, also accepted by ``--topology``)::

    tree:g=16x4,rules=median>trimmed-mean>krum,link=int8,redundancy=2,agg-f=1x0

- ``g``          ``x``-separated per-level group sizes: level 1 reduces n
                 workers in groups of 16 to n/16 summaries, level 2 reduces
                 those in groups of 4, ... — each size must divide the rows
                 entering its level;
- ``rules``      ``>``-separated rule specs, one per level PLUS the root
                 (``len(g) + 1`` entries); nested composite specs use the
                 parenthesized form (``bucketing(s=2,inner=krum)``) so their
                 commas stay attached, exactly like ``hier``/``bucketing``;
- ``link``       the wire codec of every inter-level link
                 (``f32``/``bf16``/``int8``/``topk(...)`` —
                 parallel/compress.py; error feedback is refused: a link
                 residual would need per-sub-aggregator state the tree does
                 not carry);
- ``redundancy`` r >= 1: each level-l group's summary is computed by r
                 units — its primary and r-1 *sibling* sub-aggregators at
                 the same level (circular assignment).  Honest shadows
                 compute the identical summary from the identical child
                 rows, so a straggling or forging primary is RECONSTRUCTED
                 for free; with r=1 it is excluded (NaN row) and spends the
                 level's budget;
- ``agg-f``      ``x``-separated per-level Byzantine *sub-aggregator*
                 budgets: how many level-l units may be corrupt parents.

**f-accounting through the levels.**  Rows entering level 1 carry the
declared worker budget ``b_1 = f``.  A level is a *partition* of its input
rows, so ``b_l`` corrupted rows contaminate at most ``min(b_l, m_l)`` of
its ``m_l`` output rows — a Byzantine *parent* corrupts at most ONE outer
row — and ``agg_f_l`` Byzantine sub-aggregators add their own::

    b_{l+1} = min(b_l, m_l) + agg_f_l        (must stay < m_l)

Each level's rule is best-effort damage control within a group
(``inner_f = min(b_l, g_l - 1)``, the ``hier`` convention); the breakdown
property is carried by the levels ABOVE: the root rule is instantiated
with ``(m_L, b_root)`` so its own feasibility check (krum's ``n >= f + 3``,
bulyan's ``n >= 4f + 3``, ...) runs here, at parse time.
"""

import numpy as np

from ..utils import UserException

#: spec defaults of the ``tree`` meta-rule (string-typed so the ``x``/``>``
#: grammars stay un-coerced; parse_keyval passes them through verbatim)
TREE_ARG_DEFAULTS = {
    "g": "4",
    "rules": "median>krum",
    "link": "f32",
    "redundancy": 1,
    "agg-f": "0",
}


def _split_top(text, sep):
    """Split on ``sep`` at paren depth 0 only — nested rule specs keep
    their separators (the ``_split_args`` discipline of gars/__init__.py)."""
    parts, depth, cur = [], 0, []
    for ch in text:
        if ch == "(":
            depth += 1
        elif ch == ")":
            depth -= 1
        if ch == sep and depth == 0:
            parts.append("".join(cur))
            cur = []
        else:
            cur.append(ch)
    parts.append("".join(cur))
    return [p.strip() for p in parts if p.strip()]


def _normalize_rule_spec(spec):
    """``bucketing(s=2,inner=krum)`` and ``bucketing:s=2,inner=krum`` are
    the same spec; gars.parse_spec accepts both — pass through verbatim."""
    return spec.strip()


class TreeSpec:
    """One parsed + validated aggregation tree.

    Attributes (all fixed at parse time):

    - ``nb_workers`` / ``f``: the leaf plane's (n, declared-f);
    - ``group_sizes``: [g_1..g_L];
    - ``nb_units``: [m_1..m_L] units (groups) per level — m_L rows enter
      the root;
    - ``rule_specs`` / ``rules``: the L instantiated per-level rules
      (level l's rule runs over (g_l, inner_f_l));
    - ``root_spec`` / ``root_rule``: the rule over the m_L top rows,
      instantiated with the COMPOSED budget b_root;
    - ``row_budgets``: [b_1..b_{L+1}] — b_1 = f, b_{L+1} = b_root;
    - ``agg_fs``: per-level Byzantine sub-aggregator budgets;
    - ``redundancy``: shadows-per-group count r;
    - ``link_dtype`` / ``link_codec``: the inter-level wire
      (parallel/compress.py conventions: at most one non-None).
    """

    def __init__(self, nb_workers, nb_byz_workers, args):
        from .. import gars
        from ..parallel.compress import parse_exchange_spec

        self.nb_workers = int(nb_workers)
        self.f = int(nb_byz_workers)
        if self.f < 0:
            raise UserException("tree: negative declared Byzantine count")
        if self.f >= self.nb_workers:
            raise UserException(
                "tree: f=%d >= n=%d leaves no honest worker"
                % (self.f, self.nb_workers)
            )

        # ---- per-level group sizes --------------------------------------
        g_text = str(args["g"])
        try:
            self.group_sizes = [int(g) for g in g_text.split("x") if g.strip()]
        except ValueError:
            raise UserException(
                "tree: g=%r wants x-separated integers (e.g. g=16x4)" % g_text
            )
        if not self.group_sizes:
            raise UserException("tree: g=%r declares no levels" % g_text)
        if any(g < 2 for g in self.group_sizes):
            raise UserException(
                "tree: every group size must be >= 2 (got g=%s) — a "
                "1-group level aggregates nothing" % g_text
            )

        # ---- per-level + root rule specs --------------------------------
        rule_specs = [_normalize_rule_spec(s)
                      for s in _split_top(str(args["rules"]), ">")]
        if len(rule_specs) != len(self.group_sizes) + 1:
            raise UserException(
                "tree: g=%s declares %d level(s), so rules wants %d "
                ">-separated entries (one per level plus the root), got %d "
                "(%r)" % (g_text, len(self.group_sizes),
                          len(self.group_sizes) + 1, len(rule_specs),
                          str(args["rules"]))
            )
        self.rule_specs = rule_specs[:-1]
        self.root_spec = rule_specs[-1]

        # ---- the f-composition recurrence (module docstring) ------------
        self.nb_units = []
        self.rules = []
        self.inner_fs = []
        rows = self.nb_workers
        budget = self.f
        self.row_budgets = [budget]
        agg_text = str(args["agg-f"])
        try:
            agg_fs = [int(a) for a in agg_text.split("x") if a.strip()]
        except ValueError:
            raise UserException(
                "tree: agg-f=%r wants x-separated integers (e.g. agg-f=1x0)"
                % agg_text
            )
        if len(agg_fs) == 1:
            agg_fs = agg_fs * len(self.group_sizes)
        if len(agg_fs) != len(self.group_sizes):
            raise UserException(
                "tree: agg-f=%r wants one entry per level (%d), got %d"
                % (agg_text, len(self.group_sizes), len(agg_fs))
            )
        if any(a < 0 for a in agg_fs):
            raise UserException("tree: agg-f entries must be >= 0")
        self.agg_fs = agg_fs
        for level, (g, spec, agg_f) in enumerate(
                zip(self.group_sizes, self.rule_specs, agg_fs), start=1):
            if rows % g != 0:
                raise UserException(
                    "tree: level %d group size g=%d does not divide its %d "
                    "input rows (g=%s over n=%d)"
                    % (level, g, rows, g_text, self.nb_workers)
                )
            units = rows // g
            # within-group damage control: a group may hold up to
            # min(budget, g) corrupted rows; clamp to what any rule admits
            inner_f = min(budget, g - 1)
            self.rules.append(gars.instantiate(spec, g, inner_f))
            self.inner_fs.append(inner_f)
            # a partition: budget corrupted rows contaminate <= min(budget,
            # units) summaries (a Byzantine parent corrupts at most ONE
            # outer row), plus this level's Byzantine sub-aggregators
            budget = min(budget, units) + agg_f
            if budget >= units:
                raise UserException(
                    "tree: the composed Byzantine budget after level %d is "
                    "%d of %d rows (worker f=%d through the partition, plus "
                    "agg-f=%d sub-aggregators) — no rule can tolerate a "
                    "corrupt majority-or-all; widen the groups or lower "
                    "agg-f" % (level, budget, units, self.f, agg_f)
                )
            self.nb_units.append(units)
            self.row_budgets.append(budget)
            rows = units
        # the root rule's OWN feasibility check runs here, at parse time,
        # against the composed budget (krum's n >= f + 3 and friends)
        self.root_rule = gars.instantiate(self.root_spec, rows, budget)

        # ---- redundancy --------------------------------------------------
        self.redundancy = int(args["redundancy"])
        if self.redundancy < 1:
            raise UserException("tree: redundancy must be >= 1")
        if self.redundancy > min(self.nb_units):
            raise UserException(
                "tree: redundancy=%d exceeds the smallest level width %d — "
                "shadows are SIBLING sub-aggregators, a level cannot host "
                "more copies than it has units"
                % (self.redundancy, min(self.nb_units))
            )

        # ---- the inter-level wire ---------------------------------------
        self.link_spec = str(args["link"]).replace("(", ":").replace(")", "")
        self.link_dtype, self.link_codec = parse_exchange_spec(self.link_spec)
        if self.link_codec is not None and self.link_codec.uses_ef:
            raise UserException(
                "tree: link=%s declares error feedback, but an inter-level "
                "link carries no residual state (there is no per-sub-"
                "aggregator TrainState row to persist it in) — drop ef"
                % self.link_spec
            )

    # ------------------------------------------------------------------ #
    # shape helpers

    @property
    def nb_levels(self):
        return len(self.group_sizes)

    def leaf_span(self, level, unit):
        """Leaf workers under unit ``unit`` of level ``level`` (1-based
        level), as a ``range`` — the mask a whole-subtree exclusion clears."""
        width = int(np.prod(self.group_sizes[:level]))
        return range(unit * width, (unit + 1) * width)

    def shadows(self, level, unit):
        """Sibling units holding shadow copies of ``unit``'s groups at
        ``level`` (circular assignment, r-1 of them)."""
        m = self.nb_units[level - 1]
        return [(unit + k) % m for k in range(1, self.redundancy)]

    def unit_index(self, level, unit):
        """Flat index of (level, unit) across all levels — the per-unit
        key slot of the custody authenticator."""
        return int(sum(self.nb_units[:level - 1]) + unit)

    @property
    def total_units(self):
        return int(sum(self.nb_units))

    def validate_fault_target(self, level, unit):
        """Loudly reject a chaos ``corrupt-agg``/``straggle-agg`` target
        outside this tree."""
        if not 1 <= level <= self.nb_levels:
            raise UserException(
                "topology fault targets level %d but the tree has %d "
                "level(s)" % (level, self.nb_levels)
            )
        if not 0 <= unit < self.nb_units[level - 1]:
            raise UserException(
                "topology fault targets unit %d.%d but level %d has %d "
                "unit(s)" % (level, unit, level, self.nb_units[level - 1])
            )

    # ------------------------------------------------------------------ #
    # wire accounting (static, like parallel/compress.bytes_per_row)

    def link_bytes_per_row(self, d):
        from ..parallel.compress import bytes_per_row

        return bytes_per_row(d, dtype=self.link_dtype, codec=self.link_codec)

    def link_bytes_per_round(self, d):
        """Bytes every inter-level link ships per round: each level's m_l
        summaries cross one link (the root's input is the last link)."""
        return int(sum(self.nb_units)) * self.link_bytes_per_row(d)

    def link_ratio(self, d):
        """Inter-level compression ratio vs an uncompressed f32 link."""
        from ..parallel.compress import bytes_per_row

        return (bytes_per_row(d) * 1.0) / self.link_bytes_per_row(d)

    def describe(self):
        return ("tree: n=%d f=%d g=%s rules=%s root=%s budgets=%s "
                "agg-f=%s redundancy=%d link=%s" % (
                    self.nb_workers, self.f,
                    "x".join(str(g) for g in self.group_sizes),
                    ">".join(self.rule_specs), self.root_spec,
                    self.row_budgets,
                    "x".join(str(a) for a in self.agg_fs),
                    self.redundancy, self.link_spec))


def parse_topology_spec(spec, nb_workers, nb_byz_workers):
    """``--topology tree:...`` -> a validated :class:`TreeSpec`.  The spec
    shares the GAR grammar; the name must be ``tree`` (the one registered
    topology-aware meta-rule)."""
    from .. import gars
    from ..utils import parse_keyval

    name, args = gars.parse_spec(spec)
    if name != "tree":
        raise UserException(
            "--topology wants a tree: spec (got %r); the star topology is "
            "the default — just drop the flag" % (spec,)
        )
    kv = parse_keyval(args, TREE_ARG_DEFAULTS, strict=True)
    return TreeSpec(nb_workers, nb_byz_workers, kv)

"""The host plane of the aggregation tree: per-level bounded wait,
chained custody, redundant reconstruction.

``gars/tree.py`` is the tree's NUMERICS — one fused in-graph function.
This module is the tree's PROTOCOL: the per-round decisions a real
deployment of untrusted sub-aggregators has to make, driven one host step
per round from ``parallel/bounded.py``:

- **Per-level bounded wait.**  Each level is its own round with its own
  :class:`~aggregathor_tpu.parallel.deadline.DeadlineController`: a unit's
  arrival is the max of its children's effective arrivals (a child that
  missed ITS window was resolved at window close) plus the level's
  measured aggregation time plus any injected stall (chaos
  ``straggle-agg``).  A unit past its level window times out AS A UNIT —
  the whole subtree is one row to its parent.
- **Redundant reconstruction** (CodedReduce, arXiv:1902.01981).  With
  ``redundancy=r`` each group is computed by its primary and ``r - 1``
  circularly-assigned sibling units; honest shadows compute the identical
  summary from the identical child rows (the tree is deterministic), so a
  faulted primary is served by its first live verified shadow — the
  aggregate is unchanged and no budget is spent.  With no live shadow the
  subtree is EXCLUDED: its leaf workers' ``arrived``/``stale`` flags are
  cleared, the in-graph NaN conventions propagate one NaN row to the
  parent level, and the declared per-level budget (``agg-f``) is spent.
- **Chained custody.**  Every unit HMAC-signs the digest of the wire
  image it emitted (per-(level, unit) keys under the ``b"topology"``
  context — disjoint from the worker ``b"submit"`` family); the root
  verifies every tag and folds them into a rolling chain head
  (``SHA-256(head || step || level || tags || verdicts)``).  A failed tag
  NAMES the (level, unit) node — ``topology_corruption_verdict`` in the
  journal, ``note_subaggregator`` in forensics — and the node is
  reconstructed or excluded like a timeout.  What the chaos
  ``corrupt-agg`` fault models is an IMPERSONATED/custody-violating
  sub-aggregator (it signs without the session secret, the detectable
  crime); a sub-aggregator that signs its own poison honestly is the
  ``agg-f`` budget's job, enforced by the levels above it
  (topology/spec.py's composition arithmetic, probed in the benchmark's
  breakdown cells).

Everything here is synthetic-clock testable: :meth:`TreeAggregator.
resolve_round` is the pure decision core (arrivals in, verdicts out — no
devices, no sleeps, no wall clock), and the chaos stalls are arithmetic
on the arrival vectors, never ``time.sleep``.
"""

import hashlib
import struct
import time

import numpy as np

from ..obs import events
from ..parallel.auth import GradientAuthenticator
from ..parallel.deadline import DeadlineController
from ..secure.submit import FORGER_SECRET, digest_to_bytes
from ..utils import UserException

#: what an unsecured tree signs with — custody needs SOME key material so
#: the chain head is well-defined; forgery DETECTION additionally needs
#: the operator's --session-secret (the forger's keys must differ)
DEFAULT_TOPOLOGY_SECRET = b"aggregathor-topology-default-secret"


class TreeAggregator:
    """Per-round tree protocol driver (one per run, survives guardian
    Overrides rebuilds exactly like the deadline controller).

    Args:
      spec: a validated :class:`~aggregathor_tpu.topology.spec.TreeSpec`.
      registry: optional ``MetricsRegistry`` — per-level timing, timeout/
        reconstruction/corruption counters, bytes-on-wire, link ratio.
      session_secret: custody key material; ``None`` falls back to
        :data:`DEFAULT_TOPOLOGY_SECRET` (chain still well-defined, but an
        impersonator could derive the same keys — pass ``--session-secret``
        for real forgery detection, docs/security.md).
      deadline: initial per-level bounded-wait window (seconds); ``None``
        disables level deadlines (only injected stalls and custody
        verdicts fault a unit).
      deadline_opts: dict of DeadlineController knobs (percentile, floor,
        ceiling, ema) shared by every level's controller.

    Post-construction attachments (the runner's wiring order):
    ``ledger`` (ForensicsLedger, attached after its construction) and
    ``schedule`` (ChaosSchedule, queried per round for ``corrupt-agg``/
    ``straggle-agg`` targets).
    """

    def __init__(self, spec, registry=None, session_secret=None,
                 deadline=None, deadline_opts=None):
        self.spec = spec
        self.ledger = None
        self.schedule = None
        self.deadline = deadline
        secret = session_secret or DEFAULT_TOPOLOGY_SECRET
        self.auth = GradientAuthenticator(
            secret, spec.total_units, context=b"topology"
        )
        self._forger = GradientAuthenticator(
            FORGER_SECRET, spec.total_units, context=b"topology"
        )
        self._chain = hashlib.sha256(b"aggregathor-topology-chain-v1").digest()
        self._chain_steps = 0
        self.controllers = None
        if deadline is not None:
            opts = dict(deadline_opts or {})
            self.controllers = [
                DeadlineController(deadline, **opts)
                for _ in range(spec.nb_levels)
            ]
        # bound by the BoundedWaitStep that drives this tree (bind())
        self._d = None
        self._codec = None
        self._level_fns = None
        self._warm = False
        self.rounds_total = 0
        self._c_seconds = self._c_timeouts = self._c_reconstructions = None
        self._c_corruptions = self._c_exclusions = self._c_bytes = None
        self._c_rounds = self._g_ratio = None
        if registry is not None:
            self._c_seconds = registry.counter(
                "topology_level_seconds_total",
                "Cumulative per-level sub-aggregation wall time",
                labelnames=("level",),
            )
            self._c_timeouts = registry.counter(
                "topology_level_timeouts_total",
                "Sub-aggregator units that missed their level window",
                labelnames=("level",),
            )
            self._c_reconstructions = registry.counter(
                "topology_reconstructions_total",
                "Faulted units served by a redundant sibling shadow",
                labelnames=("level",),
            )
            self._c_corruptions = registry.counter(
                "topology_corruptions_total",
                "Units whose custody tag failed chain verification",
                labelnames=("level",),
            )
            self._c_exclusions = registry.counter(
                "topology_exclusions_total",
                "Faulted units with no live shadow — whole subtree "
                "excluded (NaN row, budget spent)",
                labelnames=("level",),
            )
            self._c_bytes = registry.counter(
                "topology_bytes_on_wire_total",
                "Bytes shipped on the inter-level links (all redundant "
                "copies counted)",
                labelnames=("level",),
            )
            self._c_rounds = registry.counter(
                "topology_rounds_total", "Tree aggregation rounds processed"
            )
            self._g_ratio = registry.gauge(
                "topology_link_compression_ratio",
                "Inter-level link compression ratio vs the f32 wire",
            )

    # ------------------------------------------------------------------ #
    # binding (BoundedWaitStep construction time)

    def bind(self, nb_workers, d, codec=None):
        """Late-bind the leaf plane: the flattened row width, the WORKER
        exchange codec (the leaf links' wire — the tree's own inter-level
        wire is ``spec.link_*``).  Called once by the driving
        BoundedWaitStep; the per-level jitted emission functions build
        here and compile on first use (one executable each, counted by
        :meth:`cache_size` for the zero-recompile assertions)."""
        if nb_workers != self.spec.nb_workers:
            raise UserException(
                "topology tree is sized for n=%d but the engine runs n=%d"
                % (self.spec.nb_workers, nb_workers)
            )
        self._d = int(d)
        self._codec = codec
        if self.spec.link_codec is not None:
            self.spec.link_codec.validate_d(self._d)
        if self._g_ratio is not None:
            self._g_ratio.set(self.spec.link_ratio(self._d))
        self._level_fns = [
            self._make_level_fn(level) for level in range(self.spec.nb_levels)
        ]

    def _make_level_fn(self, level):
        """Level ``level`` (0-based) emission: child rows in, (summaries,
        per-unit digests) out — the custody plane recomputes what each
        sub-aggregator ships so there is a concrete wire image to sign.
        Level 0 additionally decodes the leaf wire and applies the
        ``arrived|stale`` NaN mask, so the chain signs EXACTLY what the
        in-graph aggregate consumes."""
        import jax
        import jax.numpy as jnp

        from ..gars.common import centered_gram_sq_distances
        from ..secure.submit import row_digest

        spec = self.spec
        rule = spec.rules[level]
        g = spec.group_sizes[level]
        m = spec.nb_units[level]
        codec = self._codec
        d = self._d

        def fn(rows, valid, key):
            if level == 0:
                if codec is not None:
                    rows = codec.decode_rows(rows, d)
                else:
                    rows = rows.astype(jnp.float32)
                rows = jnp.where(valid[:, None], rows, jnp.nan)
            grouped = rows.reshape(m, g, rows.shape[-1])
            dist2 = None
            if rule.needs_distances:
                partial = jax.vmap(centered_gram_sq_distances)(
                    grouped.astype(jnp.float32)
                )
                dist2 = jnp.maximum(partial, 0.0)
            base = jax.random.fold_in(key, level + 1)
            keys = jax.vmap(lambda i: jax.random.fold_in(base, i))(
                jnp.arange(m)
            )

            def one(block, d2, k):
                return rule._call_aggregate(block, d2, axis_name=None, key=k)

            in_axes = (0, 0 if dist2 is not None else None, 0)
            summaries = jax.vmap(one, in_axes=in_axes)(grouped, dist2, keys)
            # the inter-level wire: ship what the next level aggregates
            if spec.link_codec is not None:
                summaries = spec.link_codec.roundtrip_rows(summaries)
            elif spec.link_dtype is not None:
                summaries = summaries.astype(spec.link_dtype).astype(
                    jnp.float32
                )
            digests = jax.vmap(row_digest)(summaries)
            return summaries, digests

        return jax.jit(fn)

    # ------------------------------------------------------------------ #
    # the pure decision core (synthetic-clock tests drive this directly)

    def resolve_round(self, step, child_arrivals, compute_seconds,
                      corrupt_units=(), straggle_units=(), windows=None):
        """One round's per-level verdicts — pure arithmetic, no devices.

        The clock is ABSOLUTE (zero = the leaf round's open).  Level l's
        round opens when level l-1's round closes (a bounded-wait round
        closes at its last effective arrival — early when everyone made
        it, the window when someone did not), and level l's window judges
        arrivals RELATIVE to that open: a unit whose children all arrived
        early is ready before its round even opens (relative arrival 0 —
        the pipelining a tree buys), while a unit resolved by exclusion
        at level l-1 charges exactly that level's window, never its
        parent's (no spurious timeout cascade up the root path).

        Args:
          step: the training step (stamped on ledger notes by the caller).
          child_arrivals: (n,) FINITE effective leaf arrivals (the caller
            caps censored leaf timeouts at the leaf window — those rows
            were already resolved by the leaf protocol).
          compute_seconds: per-level measured aggregation seconds.
          corrupt_units: iterable of (level, unit) whose custody tag
            FAILED verification (1-based level).
          straggle_units: iterable of (level, unit) with an injected
            stall — the unit's arrival becomes +inf (a stall is
            ARITHMETIC here, never a sleep).
          windows: per-level window seconds (None entries disable that
            level's deadline); defaults to the live controller windows.

        Returns a list of per-level verdict dicts: ``{level, window,
        arrivals, timed_out, corrupt, reconstructed: {unit: shadow},
        excluded: [unit, ...]}`` — ``arrivals`` are the round-RELATIVE
        per-unit arrivals the level's controller observes.  ``excluded``
        units' leaf spans are what :meth:`process_round` clears from
        ``arrived``/``stale``.
        """
        spec = self.spec
        if windows is None:
            if self.controllers is not None:
                windows = [c.window for c in self.controllers]
            else:
                windows = [None] * spec.nb_levels
        corrupt_units = set((int(l), int(u)) for l, u in corrupt_units)
        straggle_units = set((int(l), int(u)) for l, u in straggle_units)
        arrivals = np.asarray(child_arrivals, np.float64).reshape(-1)
        close = float(arrivals.max()) if arrivals.size else 0.0
        verdicts = []
        for index in range(spec.nb_levels):
            level = index + 1
            g = spec.group_sizes[index]
            m = spec.nb_units[index]
            window = windows[index]
            # absolute availability: a unit starts when its last child
            # lands, takes the level's measured compute, plus any injected
            # stall (a stall is arithmetic, never a sleep)
            avail = (
                arrivals.reshape(m, g).max(axis=1)
                + float(compute_seconds[index])
            )
            for (l, u) in straggle_units:
                if l == level:
                    avail[u] = np.inf
            finite = np.isfinite(avail)
            # round-relative arrival: this level's round opens at the
            # previous close; a unit done before then arrives at 0
            relative = np.maximum(avail - close, 0.0)
            if window is None:
                timed_out = ~finite
            else:
                timed_out = ~finite | (relative > window)
            corrupt = np.zeros((m,), bool)
            for (l, u) in corrupt_units:
                if l == level:
                    corrupt[u] = True
            faulted = timed_out | corrupt
            # resolution: first live verified shadow serves, else exclude.
            # Shadow liveness is judged against the full fault set — a
            # shadow that is itself faulted this round cannot serve.
            reconstructed = {}
            excluded = []
            for unit in np.nonzero(faulted)[0]:
                shadow = next(
                    (s for s in spec.shadows(level, int(unit))
                     if not faulted[s]),
                    None,
                )
                if shadow is not None:
                    reconstructed[int(unit)] = int(shadow)
                else:
                    excluded.append(int(unit))
            # this level's absolute close: its last effective arrival —
            # a clean unit at its own availability (capped at the window
            # close), a reconstructed unit at its shadow's, an excluded
            # unit at the full window (the level waited it out)
            if window is not None:
                cap = close + float(window)
            elif finite.any():
                cap = float(avail[finite].max())
            else:
                cap = close + float(compute_seconds[index])
            effective = np.minimum(np.where(finite, avail, cap), cap)
            for unit, shadow in reconstructed.items():
                effective[unit] = effective[shadow]
            for unit in excluded:
                effective[unit] = cap
            verdicts.append({
                "level": level,
                "window": window,
                "arrivals": relative,
                "timed_out": timed_out,
                "corrupt": corrupt,
                "reconstructed": reconstructed,
                "excluded": excluded,
            })
            arrivals = effective
            close = float(effective.max()) if effective.size else close
        return verdicts

    # ------------------------------------------------------------------ #
    # the per-round protocol (driven by parallel/bounded.py)

    def process_round(self, step, arrived, stale, arrival_seconds, rows_in,
                      leaf_window=None):
        """One completed leaf round through the tree: emissions + custody
        + per-level bounded wait + reconstruction/exclusion.  Returns the
        updated ``(arrived, stale)`` masks (excluded subtrees cleared —
        the in-graph aggregate NaN-masks them like any other drop).
        """
        import jax

        if self._level_fns is None:
            raise UserException(
                "TreeAggregator.process_round before bind() — the driving "
                "BoundedWaitStep binds the leaf plane at construction"
            )
        spec = self.spec
        arrived = np.asarray(arrived).astype(bool).copy()
        stale = np.asarray(stale).astype(bool).copy()
        valid = arrived | stale

        regime = None
        if self.schedule is not None:
            regime = self.schedule.regimes[self.schedule.regime_at(step)]
        corrupt_targets = tuple(getattr(regime, "agg_corrupt", ()) or ())
        straggle_targets = tuple(getattr(regime, "agg_straggle", ()) or ())

        # ---- emissions: recompute each level's wire images + digests ----
        import jax.numpy as jnp

        key = jax.random.PRNGKey(int(step))
        valid_dev = jnp.asarray(valid)
        compute_seconds = []
        level_digests = []
        rows = rows_in
        for index, fn in enumerate(self._level_fns):
            begin = time.perf_counter()
            rows, digests = fn(rows, valid_dev, key)
            digests = np.asarray(jax.device_get(digests))
            elapsed = time.perf_counter() - begin
            compute_seconds.append(elapsed)
            level_digests.append(digests)
            if self._c_seconds is not None:
                self._c_seconds.labels(level=str(index + 1)).inc(elapsed)

        # ---- custody: sign every unit's wire image, verify the chain ----
        corrupt_units = []
        for index, digests in enumerate(level_digests):
            level = index + 1
            tags = []
            verdicts = []
            for unit in range(spec.nb_units[index]):
                idx = spec.unit_index(level, unit)
                payload = digest_to_bytes(digests[unit])
                if (level, unit) in set(corrupt_targets):
                    # the chaos fault: this unit signs WITHOUT the session
                    # secret (impersonation / custody violation)
                    tag = self._forger.sign(idx, int(step), payload)
                else:
                    tag = self.auth.sign(idx, int(step), payload)
                ok = self.auth.verify(idx, int(step), payload, tag)
                tags.append(tag)
                verdicts.append(ok)
                if not ok:
                    corrupt_units.append((level, unit))
                    if self._c_corruptions is not None:
                        self._c_corruptions.labels(level=str(level)).inc()
            self._chain = hashlib.sha256(
                self._chain + struct.pack("<qq", int(step), level)
                + b"".join(tags)
                + np.asarray(verdicts, bool).tobytes()
            ).digest()
        self._chain_steps += 1

        # ---- per-level bounded wait over the SYNTHETIC+measured clock ---
        if leaf_window is not None:
            cap = float(leaf_window)
        elif np.isfinite(arrival_seconds).any():
            cap = float(np.asarray(arrival_seconds)[
                np.isfinite(arrival_seconds)].max())
        else:
            cap = 0.0
        leaf_arrivals = np.where(
            np.isfinite(arrival_seconds), arrival_seconds, cap
        )
        warm = self._warm
        self._warm = True
        windows = None
        if not warm or self.controllers is None:
            # the first processed round compiles the emission executables;
            # charging XLA against the level windows would fault every
            # unit of round 0 (the leaf protocol gates its deadline the
            # same way) — injected stalls still resolve (inf beats any
            # window, including none)
            windows = [None] * spec.nb_levels
        verdicts = self.resolve_round(
            step, leaf_arrivals, compute_seconds,
            corrupt_units=corrupt_units, straggle_units=straggle_targets,
            windows=windows,
        )

        # ---- apply + account -------------------------------------------
        for verdict in verdicts:
            level = verdict["level"]
            index = level - 1
            if self.controllers is not None and warm:
                censored = np.where(
                    verdict["timed_out"], np.inf, verdict["arrivals"]
                )
                self.controllers[index].observe_round(censored, step=step)
            if self._c_bytes is not None:
                self._c_bytes.labels(level=str(level)).inc(
                    spec.nb_units[index] * spec.redundancy
                    * spec.link_bytes_per_row(self._d)
                )
            # per-unit conviction records of THIS level round: the
            # reconstruction event cites the convicting timeout/forgery
            # event as its cause (the causal plane — same journal, so the
            # reference's instance stays None)
            convictions = {}
            for unit in np.nonzero(verdict["timed_out"])[0]:
                unit = int(unit)
                excluded = unit in verdict["excluded"]
                if self._c_timeouts is not None:
                    self._c_timeouts.labels(level=str(level)).inc()
                convictions[unit] = events.emit(
                    "topology_level_timeout", step=int(step), level=level,
                    unit=unit,
                    window=None if verdict["window"] is None
                    else float(verdict["window"]),
                    excluded=excluded, cause=None,
                )
                if self.ledger is not None:
                    self.ledger.note_subaggregator(
                        step, level, unit, "timeout",
                        {"excluded": excluded},
                    )
            for unit in np.nonzero(verdict["corrupt"])[0]:
                unit = int(unit)
                excluded = unit in verdict["excluded"]
                convictions[unit] = events.emit(
                    "topology_corruption_verdict", step=int(step),
                    level=level, unit=unit, excluded=excluded, cause=None,
                )
                if self.ledger is not None:
                    self.ledger.note_subaggregator(
                        step, level, unit, "forgery",
                        {"excluded": excluded},
                    )
            for unit, shadow in verdict["reconstructed"].items():
                trigger = (
                    "forgery" if verdict["corrupt"][unit] else "timeout"
                )
                if self._c_reconstructions is not None:
                    self._c_reconstructions.labels(level=str(level)).inc()
                conviction = convictions.get(int(unit))
                events.emit(
                    "topology_reconstruction", step=int(step), level=level,
                    unit=int(unit), shadow=int(shadow), trigger=trigger,
                    cause=(events.cause_of(conviction)
                           if conviction is not None else None),
                )
                if self.ledger is not None:
                    self.ledger.note_subaggregator(
                        step, level, unit, "reconstructed",
                        {"shadow": int(shadow), "cause": trigger},
                    )
            for unit in verdict["excluded"]:
                if self._c_exclusions is not None:
                    self._c_exclusions.labels(level=str(level)).inc()
                span = spec.leaf_span(level, unit)
                arrived[span.start:span.stop] = False
                stale[span.start:span.stop] = False
        self.rounds_total += 1
        if self._c_rounds is not None:
            self._c_rounds.inc()
        return arrived, stale

    # ------------------------------------------------------------------ #

    def chain(self):
        """The custody-chain lineage (the topology twin of
        ``SubmissionAuthenticator.chain()``)."""
        return {
            "head": self._chain.hex(),
            "steps": self._chain_steps,
            "nb_units": self.spec.total_units,
        }

    def cache_size(self):
        """Max compile count over the per-level emission executables —
        the zero-recompile surface (steady state reads 1, like every
        other executable the CompileWatch sums over)."""
        if not self._level_fns:
            return 0
        return max(fn._cache_size() for fn in self._level_fns)

"""Byzantine-tolerant tree-aggregation topologies (beyond the PS star).

Two planes, one declarative spec:

- :mod:`~aggregathor_tpu.topology.spec` — the ``tree:`` grammar and its
  parse-time f-composition arithmetic (``TreeSpec``);
- :mod:`~aggregathor_tpu.gars.tree` — the in-graph numerics (``tree`` in
  the GAR registry: L-level aggregation + inter-level wire codec);
- :mod:`~aggregathor_tpu.topology.tree` — the host protocol
  (``TreeAggregator``: per-level bounded wait, chained custody, redundant
  reconstruction), driven per round by ``parallel/bounded.py``.

Long-form semantics: docs/topology.md.
"""

from .spec import TreeSpec, parse_topology_spec  # noqa: F401
from .tree import TreeAggregator  # noqa: F401

"""Pallas TPU kernels for the GAR hot path.

The framework's counterpart of the reference's C++/CUDA custom ops
(native/op_krum/cpu.cpp:53-122, native/op_bulyan/cpu.cpp:52-188,
aggregators/deprecated_native/native.cpp:678-747).  Two hot shapes:

- **Pairwise squared distances** of the (n, d) gradient matrix — O(n²·d),
  streamed over column blocks so the whole matrix never sits in VMEM.  Two
  kernels: an exact difference-form (VPU, reference-faithful accumulation
  order per block) and an MXU Gram-form (``|a|² + |b|² − 2ab`` per block,
  per-block median-centered against catastrophic cancellation — the same
  math the sharded engine psums, parallel/engine.py).
- **Coordinate-wise selection** (median / averaged-median, Bulyan phase 3) —
  the reference's per-coordinate ``nth_element`` (native.cpp:678-747) is
  control flow, which doesn't vectorize on TPU; here selection is
  reformulated as *rank computation*: ``rank(i) = #{j : key_j < key_i}``
  (ties to the lower index) is n fused VPU compare-accumulate passes over
  the whole block, and "the median" is a masked sum over rows — no sort, no
  gather, O(n²) vector ops per coordinate slab (SURVEY.md §7 hard part (a)).

NaN conventions are identical to the jnp tier and the numpy oracle: a
non-finite value keys as +inf (sorts last); ties break by lower worker
index; a selected non-finite value is returned *as-is* (the original
NaN/inf poisons that coordinate, same identity in every tier).

Tile alignment (Mosaic lowers f32 in (8, 128) sublane x lane tiles): the
host wrappers pad the worker dim to a multiple of 8 and the coordinate
kernels write full (8, blk) output tiles — no sub-tile block shapes reach
the compiler.  Worker padding is provably neutral: a padded row is all-NaN,
keys +inf at the highest indices, and its rank is exactly n (every real row
precedes it), strictly above every selection threshold (n//2 < n, beta <=
n); ``average_nan_columns`` ignores non-finite rows by construction, and
the distance wrappers slice padded rows/columns off before returning.

All kernels auto-fall back to interpreter mode off-TPU, so the same code
path is exercised by the CPU test suite.
"""

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _interpret():
    return jax.default_backend() != "tpu"


def _pad_axis(x, axis, multiple, value=0.0):
    size = x.shape[axis]
    pad = (-size) % multiple
    if pad == 0:
        return x
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, pad)
    return jnp.pad(x, widths, constant_values=value)


def _clamp_block(blk, d):
    blk = max(128, min(1024, (blk // 128) * 128))
    return min(blk, max(128, ((d + 127) // 128) * 128))


#: Worker-row tile of the distance kernels: above this many (padded) rows
#: the row axis is tiled so n=128..512 lowers without holding the whole
#: (n, d_block) slab pair — per grid cell only two (ROW_TILE, blk) input
#: tiles and one (ROW_TILE, ROW_TILE) output tile live in VMEM.
ROW_TILE = 128


def _pick_block_diff(tile, d, vmem_budget=1 << 22):
    """Diff-form distance block: the tile·tile·blk difference tensor sets
    the size (``tile`` is the ROW TILE, not n — row tiling keeps the
    budget independent of the worker count)."""
    return _clamp_block(vmem_budget // max(tile * tile * 4, 1), d)


def _pick_block_coord(n, d, vmem_budget=1 << 21):
    """Coordinate-kernel block: footprint is O(n·blk) (value slab + rank
    temporaries, ~8 live (n, blk) f32 buffers).  The budget is HALF the
    distance kernels' — the coordinate kernels cannot tile the row axis
    (every rank needs all n comparators), so large n must come out of the
    column block instead: at n=512 this picks blk=128, ~2 MB of live slab,
    which lowers without spilling where the old budget's blk=256 doubled it."""
    return _clamp_block(vmem_budget // max(n * 4 * 8, 1), d)


# --------------------------------------------------------------------------- #
# Rank machinery (shared by the coordinate-wise kernels)

#: Worker count above which ``_ranks`` switches from the statically-unrolled
#: compare+accumulate loop to a ``fori_loop``: at n=512 the unrolled form
#: emits 512 fused passes into the kernel body — a compile-time blowup —
#: while the rolled loop compiles one pass.  The unrolled tier stays the
#: default at small n (the silicon-proven path, scripts/pallas_tpu_check.py).
RANK_UNROLL_MAX = 64


def _ranks(key, n):
    """rank[i, :] = #{j : key_j < key_i, ties to lower j}, per coordinate.

    n VPU passes of compare+accumulate over the (n, blk) slab; memory stays
    O(n·blk).  Statically unrolled up to ``RANK_UNROLL_MAX`` comparators,
    a ``fori_loop`` with a dynamic row slice beyond (identical selections:
    the loop body is the same compare+accumulate either way).
    """
    row = jax.lax.broadcasted_iota(jnp.int32, key.shape, 0)
    if n <= RANK_UNROLL_MAX:
        ranks = jnp.zeros(key.shape, jnp.int32)
        for j in range(n):
            kj = key[j, :][None, :]
            ranks = ranks + jnp.where((kj < key) | ((kj == key) & (j < row)), 1, 0)
        return ranks

    def body(j, ranks):
        kj = jax.lax.dynamic_slice_in_dim(key, j, 1, axis=0)  # (1, blk)
        return ranks + jnp.where((kj < key) | ((kj == key) & (j < row)), 1, 0)

    return jax.lax.fori_loop(0, n, body, jnp.zeros(key.shape, jnp.int32))


def _select_rank(x, ranks, r):
    """Per coordinate, the value whose rank equals r (masked sum over rows)."""
    return jnp.sum(jnp.where(ranks == r, x, 0.0), axis=0)


def _inf_key(x):
    return jnp.where(jnp.isfinite(x), x, jnp.inf)


# --------------------------------------------------------------------------- #
# Coordinate-wise selection kernels

def _store_row(out_ref, row):
    # Full-tile store: writing all 8 sublanes of the (8, blk) output block
    # keeps the store aligned (no masked sub-tile write); the wrapper reads
    # row 0.
    out_ref[:] = jnp.broadcast_to(row[None, :], out_ref.shape)


def _median_kernel(n, x_ref, out_ref):
    x = x_ref[:]
    _store_row(out_ref, _select_rank(x, _ranks(_inf_key(x), n), n // 2))


def _averaged_median_kernel(n, beta, x_ref, out_ref):
    x = x_ref[:]
    med = _select_rank(x, _ranks(_inf_key(x), n), n // 2)
    dev_ranks = _ranks(_inf_key(jnp.abs(x - med[None, :])), n)
    chosen = jnp.where(dev_ranks < beta, x, 0.0)
    _store_row(out_ref, jnp.sum(chosen, axis=0) / float(beta))


def _trimmed_mean_kernel(n, trim, keep, x_ref, out_ref):
    # Mean of the CLEANED (+inf-mapped) values at ranks [trim, trim+keep):
    # an inf in the kept band poisons the sum -> NaN surfaced, matching
    # gars/trimmed_mean.trimmed_mean_columns.  Padded rows rank exactly n
    # (every real row outranks or index-ties below them), never selected.
    x = x_ref[:]
    key = _inf_key(x)
    ranks = _ranks(key, n)
    sel = jnp.where((ranks >= trim) & (ranks < trim + keep), key, 0.0)
    mean = jnp.sum(sel, axis=0) / float(keep)
    _store_row(out_ref, jnp.where(jnp.isfinite(mean), mean, jnp.nan))


def _coordinate_call(kernel, x, block_d=None):
    """Run a (n, blk) -> row coordinate kernel over column blocks.

    Rank thresholds inside ``kernel`` use the REAL n; the slab rows are
    padded to the f32 sublane multiple with NaN (neutral, module docstring).
    """
    n, d = x.shape
    rows = n + (-n) % 8  # the slab the kernel actually holds is padded
    blk = block_d or _pick_block_coord(rows, d)
    xp = _pad_axis(x.astype(jnp.float32), 1, blk)
    xp = _pad_axis(xp, 0, 8, jnp.nan)
    grid = xp.shape[1] // blk
    out = pl.pallas_call(
        kernel,
        grid=(grid,),
        in_specs=[pl.BlockSpec((rows, blk), lambda i: (0, i), memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec((8, blk), lambda i: (0, i), memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((8, xp.shape[1]), jnp.float32),
        interpret=_interpret(),
    )(xp)
    return out[0, :d]


def coordinate_median(x, block_d=None):
    """(d,) upper median per column of an (n, d) matrix, non-finite last."""
    n = x.shape[0]
    return _coordinate_call(functools.partial(_median_kernel, n), x, block_d)


def coordinate_averaged_median(x, beta, block_d=None):
    """(d,) per-column mean of the ``beta`` values closest to the median."""
    n = x.shape[0]
    return _coordinate_call(
        functools.partial(_averaged_median_kernel, n, int(beta)), x, block_d
    )


def coordinate_trimmed_mean(x, trim, keep, block_d=None):
    """(d,) per-column mean of the values at sorted ranks [trim, trim+keep)
    with non-finite mapped to +inf; NaN where the kept band is poisoned."""
    n = x.shape[0]
    return _coordinate_call(
        functools.partial(_trimmed_mean_kernel, n, int(trim), int(keep)), x, block_d
    )


def average_nan_columns(x, block_d=None):
    """(d,) finite-only column mean (all-non-finite column -> 0)."""

    def kernel(x_ref, out_ref):
        v = x_ref[:]
        finite = jnp.isfinite(v)  # NaN-padded rows count for nothing
        total = jnp.sum(jnp.where(finite, v, 0.0), axis=0)
        count = jnp.sum(finite.astype(jnp.float32), axis=0)
        _store_row(out_ref, jnp.where(count > 0, total / jnp.maximum(count, 1.0), 0.0))

    return _coordinate_call(kernel, x, block_d)


# --------------------------------------------------------------------------- #
# Pairwise squared distances, tiled over row pairs and streamed over column
# blocks.  The grid is (row tile i, row tile j, column block k) with k
# innermost, so each (i, j) output tile stays resident in VMEM while its
# column blocks accumulate — per grid cell only two (T, blk) input tiles and
# one (T, T) output tile are live, which is what lets n=128..512 lower
# without spilling (a single-tile grid reproduces the old full-slab kernels
# bit-for-bit: same per-block accumulation order).

def _dist_diff_kernel(xa_ref, xb_ref, out_ref):
    @pl.when(pl.program_id(2) == 0)
    def _():
        out_ref[:] = jnp.zeros_like(out_ref)

    xa = xa_ref[:].astype(jnp.float32)
    xb = xb_ref[:].astype(jnp.float32)
    diff = xa[:, None, :] - xb[None, :, :]
    out_ref[:] += jnp.sum(diff * diff, axis=-1)


def _dist_gram_kernel(xa_ref, xb_ref, out_ref):
    # Input is pre-centered by the NaN-ignoring coordinate median (see
    # pairwise_sq_distances): |a|²+|b|²−2ab stays conditioned, NaN rows
    # poison only their own rows/columns, and the kernel is pure MXU work.
    @pl.when(pl.program_id(2) == 0)
    def _():
        out_ref[:] = jnp.zeros_like(out_ref)

    xa = xa_ref[:].astype(jnp.float32)
    xb = xb_ref[:].astype(jnp.float32)
    sqa = jnp.sum(xa * xa, axis=-1, keepdims=True)  # (T, 1)
    sqb = jnp.sum(xb * xb, axis=-1, keepdims=True)  # (T, 1)
    gram = jax.lax.dot_general(
        xa, xb, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    )
    out_ref[:] += sqa + jnp.transpose(sqb) - 2.0 * gram


def pairwise_sq_distances(x, block_d=None, use_mxu=None, row_tile=None):
    """(n, n) all-pairs squared L2 distances of the rows of (n, d).

    ``use_mxu=None`` picks the difference-form (exact) when the per-block
    tile²·blk intermediate is cheap and the Gram-form (one MXU matmul per
    tile pair) otherwise.  NaN rows yield NaN entries (callers map to +inf),
    matching the jnp tier.  Rows are processed in ``row_tile``-sized tiles
    (default: one tile up to ROW_TILE rows, ROW_TILE beyond) so the VMEM
    footprint is independent of the worker count.
    """
    n, d = x.shape
    rows = n + (-n) % 8  # sublane-padded row count
    tile = row_tile or (rows if rows <= ROW_TILE else ROW_TILE)
    tile = max(8, tile + (-tile) % 8)
    if use_mxu is None:
        use_mxu = n > 64
    x = x.astype(jnp.float32)
    if use_mxu:
        kernel = _dist_gram_kernel
        blk = block_d or _pick_block_coord(tile, d)
        # Robust centering outside the kernel (distances are translation-
        # invariant, one global center suffices): NaN-ignoring coordinate
        # median, same scheme as gars/common.py centered_gram_sq_distances.
        center = jnp.nan_to_num(jnp.nanmedian(jnp.where(jnp.isfinite(x), x, jnp.nan), axis=0))
        x = x - center[None, :]
    else:
        kernel = _dist_diff_kernel
        blk = block_d or _pick_block_diff(tile, d)
    xp = _pad_axis(x, 1, blk)
    # Row-pad the worker dim to the tile multiple with zero rows; every
    # real-pair entry is computed rowwise-independently, so padded rows only
    # affect their own (sliced-off) rows/columns.
    xp = _pad_axis(xp, 0, tile, 0.0)
    rows_p = xp.shape[0]
    nt = rows_p // tile
    grid = (nt, nt, xp.shape[1] // blk)
    out = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((tile, blk), lambda i, j, k: (i, k), memory_space=pltpu.VMEM),
            pl.BlockSpec((tile, blk), lambda i, j, k: (j, k), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((tile, tile), lambda i, j, k: (i, j), memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((rows_p, rows_p), jnp.float32),
        interpret=_interpret(),
    )(xp, xp)
    out = out[:n, :n]
    # Column padding contributes zero to every distance.  The Gram form can
    # go slightly negative from cancellation — clamp it (NaN passes through
    # jnp.maximum); downstream scoring masks the diagonal itself.
    return jnp.maximum(out, 0.0) if use_mxu else out

// Native host GAR kernels — the framework's C++ tier.
//
// Parallel (threadpool.hpp) implementations of every Gradient Aggregation
// Rule the framework ships, semantically identical to the numpy oracle
// (aggregathor_tpu/gars/oracle.py), which itself mirrors the reference's CPU
// kernels (aggregators/deprecated_native/native.cpp:637-1041,
// native/op_krum/cpu.cpp:53-122, native/op_bulyan/cpu.cpp:52-188).
// Conventions shared across rules:
//   - non-finite values order LAST (key = +inf) in every coordinate-wise
//     selection (reference native.cpp:691-697);
//   - ties break by lowest original index (stable ordering, matching
//     numpy's stable argsort used by the oracle);
//   - accumulation is double precision regardless of input dtype.
// Exported as a C ABI (..._f32 / ..._f64 per rule) consumed via ctypes by
// aggregathor_tpu/ops/native/__init__.py.

#include <algorithm>
#include <cmath>
#include <cstdint>
#include <limits>
#include <numeric>
#include <vector>

#include "threadpool.hpp"

namespace {

using std::int64_t;

constexpr double kInf = std::numeric_limits<double>::infinity();

// Ordering key: non-finite values compare as +inf (and so sort last).
inline double Key(double v) { return std::isfinite(v) ? v : kInf; }

// Indices 0..n-1 stably ordered by ascending Key(values[i]).
inline void StableOrder(const double* values, int64_t n,
                        std::vector<int64_t>& order) {
  order.resize(n);
  std::iota(order.begin(), order.end(), int64_t{0});
  std::stable_sort(order.begin(), order.end(), [&](int64_t a, int64_t b) {
    return Key(values[a]) < Key(values[b]);
  });
}

// Upper median of a column: element at rank n/2 of the non-finite-last
// stable order (oracle _nonfinite_last_sorted + [n // 2]).
inline double ColumnMedian(const double* col, int64_t n,
                           std::vector<int64_t>& scratch) {
  StableOrder(col, n, scratch);
  return col[scratch[n / 2]];
}

// Mean of the beta values closest to the column's median (ties by index).
inline double ColumnAveragedMedian(const double* col, int64_t n, int64_t beta,
                                   std::vector<double>& dev,
                                   std::vector<int64_t>& scratch) {
  const double med = ColumnMedian(col, n, scratch);
  dev.resize(n);
  for (int64_t i = 0; i < n; ++i) {
    const double a = std::fabs(col[i] - med);
    dev[i] = std::isfinite(a) ? a : kInf;
  }
  StableOrder(dev.data(), n, scratch);
  double sum = 0.0;
  for (int64_t k = 0; k < beta; ++k) sum += col[scratch[k]];
  return sum / static_cast<double>(beta);
}

// ---------------------------------------------------------------------------
// Rule implementations, templated on the I/O scalar type.

template <typename T>
void Average(const T* grads, int64_t n, int64_t d, T* out) {
  agtpu::ParallelFor(0, d, [&](int64_t lo, int64_t hi) {
    for (int64_t x = lo; x < hi; ++x) {
      double sum = 0.0;
      for (int64_t i = 0; i < n; ++i) sum += static_cast<double>(grads[i * d + x]);
      out[x] = static_cast<T>(sum / static_cast<double>(n));
    }
  });
}

template <typename T>
void AverageNaN(const T* grads, int64_t n, int64_t d, T* out) {
  agtpu::ParallelFor(0, d, [&](int64_t lo, int64_t hi) {
    for (int64_t x = lo; x < hi; ++x) {
      double sum = 0.0;
      int64_t count = 0;
      for (int64_t i = 0; i < n; ++i) {
        const double v = static_cast<double>(grads[i * d + x]);
        if (std::isfinite(v)) {
          sum += v;
          ++count;
        }
      }
      out[x] = static_cast<T>(count > 0 ? sum / static_cast<double>(count) : 0.0);
    }
  });
}

template <typename T>
void Median(const T* grads, int64_t n, int64_t d, T* out) {
  agtpu::ParallelFor(0, d, [&](int64_t lo, int64_t hi) {
    std::vector<double> col(n);
    std::vector<int64_t> scratch;
    for (int64_t x = lo; x < hi; ++x) {
      for (int64_t i = 0; i < n; ++i) col[i] = static_cast<double>(grads[i * d + x]);
      out[x] = static_cast<T>(ColumnMedian(col.data(), n, scratch));
    }
  });
}

template <typename T>
void AveragedMedian(const T* grads, int64_t n, int64_t d, int64_t f, T* out) {
  const int64_t beta = n - f;
  agtpu::ParallelFor(0, d, [&](int64_t lo, int64_t hi) {
    std::vector<double> col(n), dev;
    std::vector<int64_t> scratch;
    for (int64_t x = lo; x < hi; ++x) {
      for (int64_t i = 0; i < n; ++i) col[i] = static_cast<double>(grads[i * d + x]);
      out[x] = static_cast<T>(ColumnAveragedMedian(col.data(), n, beta, dev, scratch));
    }
  });
}

// All-pairs squared L2 distances; a non-finite distance becomes +inf
// (oracle _pairwise_sq_distances).  Parallel over the i<j upper triangle
// rows; symmetric fill, zero diagonal.
template <typename T>
void PairwiseSqDist(const T* grads, int64_t n, int64_t d, double* out) {
  agtpu::ParallelFor(0, n, [&](int64_t lo, int64_t hi) {
    for (int64_t i = lo; i < hi; ++i) {
      out[i * n + i] = 0.0;
      for (int64_t j = i + 1; j < n; ++j) {
        double acc = 0.0;
        const T* a = grads + i * d;
        const T* b = grads + j * d;
        for (int64_t x = 0; x < d; ++x) {
          const double delta = static_cast<double>(a[x]) - static_cast<double>(b[x]);
          acc += delta * delta;
        }
        if (std::isnan(acc)) acc = kInf;
        out[i * n + j] = acc;
        out[j * n + i] = acc;
      }
    }
  });
}

// Multi-Krum scores: score(i) = sum of i's (n - f - 2) smallest distances to
// the other gradients, ascending-order summation like the oracle.
inline void KrumScores(const double* dist, int64_t n, int64_t f,
                       std::vector<double>& scores) {
  const int64_t k = n - f - 2;
  scores.resize(n);
  agtpu::ParallelFor(0, n, [&](int64_t lo, int64_t hi) {
    std::vector<double> row;
    row.reserve(n - 1);
    for (int64_t i = lo; i < hi; ++i) {
      row.clear();
      for (int64_t j = 0; j < n; ++j)
        if (j != i) row.push_back(dist[i * n + j]);
      std::sort(row.begin(), row.end(),
                [](double a, double b) { return Key(a) < Key(b); });
      double s = 0.0;
      for (int64_t t = 0; t < k; ++t) s += row[t];
      scores[i] = s;
    }
  });
}

// Mean of the rows listed in sel[0..m) over every coordinate, in parallel
// over coordinate slices.
template <typename T>
void MeanOfRows(const T* grads, int64_t d, const int64_t* sel, int64_t m,
                double* out) {
  agtpu::ParallelFor(0, d, [&](int64_t lo, int64_t hi) {
    for (int64_t x = lo; x < hi; ++x) {
      double sum = 0.0;
      for (int64_t k = 0; k < m; ++k) sum += static_cast<double>(grads[sel[k] * d + x]);
      out[x] = sum / static_cast<double>(m);
    }
  });
}

template <typename T>
void Krum(const T* grads, int64_t n, int64_t d, int64_t f, int64_t m, T* out) {
  std::vector<double> dist(n * n);
  PairwiseSqDist(grads, n, d, dist.data());
  std::vector<double> scores;
  KrumScores(dist.data(), n, f, scores);
  std::vector<int64_t> order;
  StableOrder(scores.data(), n, order);
  std::vector<double> mean(d);
  MeanOfRows(grads, d, order.data(), m, mean.data());
  agtpu::ParallelFor(0, d, [&](int64_t lo, int64_t hi) {
    for (int64_t x = lo; x < hi; ++x) out[x] = static_cast<T>(mean[x]);
  });
}

// Bulyan: iterative Multi-Krum selection with row-pruned incremental
// rescoring, then coordinate-wise averaged-median over the t winners
// (oracle bulyan(), mirroring op_bulyan/cpu.cpp:52-188).
template <typename T>
void Bulyan(const T* grads, int64_t n, int64_t d, int64_t f, T* out) {
  const int64_t m = n - f - 2;
  const int64_t t = n - 2 * f - 2;
  const int64_t b = t - 2 * f;
  const int64_t in_score = n - f - 2;

  std::vector<double> dist(n * n);
  PairwiseSqDist(grads, n, d, dist.data());
  for (int64_t i = 0; i < n; ++i) dist[i * n + i] = kInf;

  // Row-wise pruning: keep each row's in_score smallest entries; a kept
  // non-finite entry is stored as +inf; everything else is 0 so the later
  // column subtraction is a plain vector op.
  std::vector<double> pruned(n * n, 0.0);
  std::vector<double> scores(n);
  agtpu::ParallelFor(0, n, [&](int64_t lo, int64_t hi) {
    std::vector<int64_t> order;
    for (int64_t i = lo; i < hi; ++i) {
      StableOrder(dist.data() + i * n, n, order);
      double s = 0.0;
      for (int64_t k = 0; k < in_score; ++k) {
        const int64_t j = order[k];
        const double v = dist[i * n + j];
        pruned[i * n + j] = std::isfinite(v) ? v : kInf;
        s += pruned[i * n + j];
      }
      scores[i] = s;
    }
  });

  // Sequential selection loop (t rounds); each round's row-mean is parallel
  // over coordinates.  inf - inf = NaN in the rescoring is intentional: the
  // ordering key maps it back to +inf, exactly like the oracle.
  std::vector<double> selections(t * d);
  std::vector<double> live = scores;
  std::vector<int64_t> order;
  for (int64_t k = 0; k < t; ++k) {
    StableOrder(live.data(), n, order);
    MeanOfRows(grads, d, order.data(), m - k, selections.data() + k * d);
    if (k + 1 < t) {
      const int64_t best = order[0];
      for (int64_t i = 0; i < n; ++i) live[i] -= pruned[i * n + best];
      live[best] = kInf;
    }
  }

  agtpu::ParallelFor(0, d, [&](int64_t lo, int64_t hi) {
    std::vector<double> col(t), dev;
    std::vector<int64_t> scratch;
    for (int64_t x = lo; x < hi; ++x) {
      for (int64_t k = 0; k < t; ++k) col[k] = selections[k * d + x];
      out[x] = static_cast<T>(ColumnAveragedMedian(col.data(), t, b, dev, scratch));
    }
  });
}

}  // namespace

// ---------------------------------------------------------------------------
// C ABI.  int64 sizes throughout; matrices are row-major contiguous.

extern "C" {

int64_t agtpu_num_threads(void) {
  return static_cast<int64_t>(agtpu::ThreadPool::Global().size());
}

#define AGTPU_EXPORT_RULE(T, SUFFIX)                                          \
  void agtpu_average_##SUFFIX(const T* g, int64_t n, int64_t d, T* out) {     \
    Average(g, n, d, out);                                                    \
  }                                                                           \
  void agtpu_average_nan_##SUFFIX(const T* g, int64_t n, int64_t d, T* out) { \
    AverageNaN(g, n, d, out);                                                 \
  }                                                                           \
  void agtpu_median_##SUFFIX(const T* g, int64_t n, int64_t d, T* out) {      \
    Median(g, n, d, out);                                                     \
  }                                                                           \
  void agtpu_averaged_median_##SUFFIX(const T* g, int64_t n, int64_t d,       \
                                      int64_t f, T* out) {                    \
    AveragedMedian(g, n, d, f, out);                                          \
  }                                                                           \
  void agtpu_pairwise_sqdist_##SUFFIX(const T* g, int64_t n, int64_t d,       \
                                      double* out) {                          \
    PairwiseSqDist(g, n, d, out);                                             \
  }                                                                           \
  void agtpu_krum_##SUFFIX(const T* g, int64_t n, int64_t d, int64_t f,       \
                           int64_t m, T* out) {                               \
    Krum(g, n, d, f, m, out);                                                 \
  }                                                                           \
  void agtpu_bulyan_##SUFFIX(const T* g, int64_t n, int64_t d, int64_t f,     \
                             T* out) {                                        \
    Bulyan(g, n, d, f, out);                                                  \
  }

AGTPU_EXPORT_RULE(float, f32)
AGTPU_EXPORT_RULE(double, f64)

#undef AGTPU_EXPORT_RULE

}  // extern "C"

// Host thread pool + parallel_for for the native GAR kernels.
//
// Fresh C++17 design standing in for the reference's global pool
// (native/so_threadpool/threadpool.cpp, threadpool.hpp:219-239): a lazily
// created process-wide pool of hardware_concurrency() workers draining a
// condition-variable task queue, and a blocking range splitter that chunks
// [begin, end) into ~4x-oversubscribed cache-friendly slices.  Lifetime of
// each parallel_for's shared state is owned by a shared_ptr captured in the
// task closures, so there is no completion race by construction.
#pragma once

#include <condition_variable>
#include <cstdint>
#include <cstdlib>
#include <deque>
#include <functional>
#include <memory>
#include <mutex>
#include <thread>
#include <vector>

namespace agtpu {

class ThreadPool {
 public:
  explicit ThreadPool(std::size_t nthreads) {
    if (nthreads < 1) nthreads = 1;
    workers_.reserve(nthreads);
    for (std::size_t i = 0; i < nthreads; ++i) {
      workers_.emplace_back([this] { WorkerLoop(); });
    }
  }

  ~ThreadPool() {
    {
      std::lock_guard<std::mutex> lock(mu_);
      stop_ = true;
    }
    cv_.notify_all();
    for (auto& w : workers_) w.join();
  }

  ThreadPool(const ThreadPool&) = delete;
  ThreadPool& operator=(const ThreadPool&) = delete;

  std::size_t size() const { return workers_.size(); }

  void Submit(std::function<void()> task) {
    {
      std::lock_guard<std::mutex> lock(mu_);
      queue_.push_back(std::move(task));
    }
    cv_.notify_one();
  }

  // Process-wide pool; AGTPU_NUM_THREADS overrides the worker count.
  static ThreadPool& Global() {
    static ThreadPool pool(DefaultThreads());
    return pool;
  }

 private:
  static std::size_t DefaultThreads() {
    if (const char* env = std::getenv("AGTPU_NUM_THREADS")) {
      long v = std::strtol(env, nullptr, 10);
      if (v > 0) return static_cast<std::size_t>(v);
    }
    std::size_t hw = std::thread::hardware_concurrency();
    return hw > 0 ? hw : 1;
  }

  void WorkerLoop() {
    for (;;) {
      std::function<void()> task;
      {
        std::unique_lock<std::mutex> lock(mu_);
        cv_.wait(lock, [this] { return stop_ || !queue_.empty(); });
        if (stop_ && queue_.empty()) return;
        task = std::move(queue_.front());
        queue_.pop_front();
      }
      task();
    }
  }

  std::mutex mu_;
  std::condition_variable cv_;
  std::deque<std::function<void()>> queue_;
  bool stop_ = false;
  std::vector<std::thread> workers_;
};

// Run body(lo, hi) over disjoint slices covering [begin, end), blocking until
// every slice completed.  Serial when the range or the pool is trivial.
template <typename Body>
void ParallelFor(std::int64_t begin, std::int64_t end, const Body& body) {
  const std::int64_t n = end - begin;
  if (n <= 0) return;
  ThreadPool& pool = ThreadPool::Global();
  const std::int64_t max_chunks =
      static_cast<std::int64_t>(pool.size()) * 4;
  const std::int64_t nchunks = n < max_chunks ? n : max_chunks;
  if (pool.size() <= 1 || nchunks <= 1) {
    body(begin, end);
    return;
  }

  struct Sync {
    std::mutex mu;
    std::condition_variable done;
    std::int64_t pending;
  };
  auto sync = std::make_shared<Sync>();
  sync->pending = nchunks;

  const std::int64_t chunk = (n + nchunks - 1) / nchunks;
  for (std::int64_t c = 0; c < nchunks; ++c) {
    const std::int64_t lo = begin + c * chunk;
    const std::int64_t hi = lo + chunk < end ? lo + chunk : end;
    pool.Submit([sync, lo, hi, &body] {
      body(lo, hi);
      std::lock_guard<std::mutex> lock(sync->mu);
      if (--sync->pending == 0) sync->done.notify_all();
    });
  }
  std::unique_lock<std::mutex> lock(sync->mu);
  sync->done.wait(lock, [&] { return sync->pending == 0; });
}

}  // namespace agtpu

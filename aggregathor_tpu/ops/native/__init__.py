"""Auto-built C++ host GAR library, loaded via ctypes.

The framework's counterpart of the reference's self-compiling native layer:
sources in this directory are compiled into one shared library on first
import, with an mtime-based incremental rebuild (reference:
native/__init__.py:190-206, aggregators/deprecated_native/__init__.py:43-68).
The toolchain is plain ``c++ -std=c++17 -O3`` — no TF/TPU headers, because
this tier is pure host code: the accelerator path is jnp/Pallas, and this
library serves host-side aggregation, large-scale oracles, and CPU-only
deployments.

Public API (all take/return numpy arrays, float32 or float64, row-major):
  ``average(g)  average_nan(g)  median(g)  averaged_median(g, f)``
  ``pairwise_sq_distances(g)  krum(g, f, m=None)  bulyan(g, f)``
plus ``available()`` / ``load()`` / ``build(force=...)`` and
``num_threads()``.  Set ``AGTPU_NATIVE_CXX`` to override the compiler and
``AGTPU_NUM_THREADS`` to bound the pool.
"""

import ctypes
import os
import subprocess
import tempfile

import numpy as np

_DIR = os.path.dirname(os.path.abspath(__file__))
_SOURCES = ("kernels.cpp", "auth.cpp", "io.cpp", "threadpool.hpp")
_COMPILE_UNITS = ("kernels.cpp", "auth.cpp", "io.cpp")
_LIBNAME = "libagtpu_host.so"

_lib = None
_load_error = None


def _lib_path():
    return os.path.join(_DIR, _LIBNAME)


def _must_rebuild():
    """True when the library is absent or older than any source (mtime check)."""
    target = _lib_path()
    if not os.path.exists(target):
        return True
    built = os.path.getmtime(target)
    return any(os.path.getmtime(os.path.join(_DIR, src)) > built for src in _SOURCES)


def build(force=False):
    """Compile the shared library if stale; returns its path.

    Atomic: compiles to a temp file in the same directory, then renames —
    concurrent importers either see the old or the new complete library.
    """
    target = _lib_path()
    if not force and not _must_rebuild():
        return target
    compiler = os.environ.get("AGTPU_NATIVE_CXX", "c++")
    fd, tmp = tempfile.mkstemp(suffix=".so", prefix=".build-", dir=_DIR)
    os.close(fd)
    cmd = [
        compiler, "-std=c++17", "-O3", "-fPIC", "-shared", "-pthread",
        "-Wall", "-Wextra",
        *[os.path.join(_DIR, unit) for unit in _COMPILE_UNITS],
        "-o", tmp,
    ]
    try:
        proc = subprocess.run(cmd, capture_output=True, text=True)
        if proc.returncode != 0:
            raise RuntimeError(
                "native build failed (%s):\n%s" % (" ".join(cmd), proc.stderr.strip())
            )
        os.replace(tmp, target)
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)
    return target


def _declare(lib):
    """Attach ctypes signatures for every exported symbol."""
    i64 = ctypes.c_int64
    f32p = ctypes.POINTER(ctypes.c_float)
    f64p = ctypes.POINTER(ctypes.c_double)
    lib.agtpu_num_threads.restype = i64
    lib.agtpu_num_threads.argtypes = []
    for suffix, ptr in (("f32", f32p), ("f64", f64p)):
        for name, extra in (
            ("average", ()),
            ("average_nan", ()),
            ("median", ()),
            ("averaged_median", (i64,)),
            ("krum", (i64, i64)),
            ("bulyan", (i64,)),
        ):
            fn = getattr(lib, "agtpu_%s_%s" % (name, suffix))
            fn.restype = None
            fn.argtypes = [ptr, i64, i64] + list(extra) + [ptr]
        fn = getattr(lib, "agtpu_pairwise_sqdist_%s" % suffix)
        fn.restype = None
        fn.argtypes = [ptr, i64, i64, f64p]
    u8p = ctypes.POINTER(ctypes.c_uint8)
    size_t = ctypes.c_size_t
    i64p = ctypes.POINTER(i64)
    lib.agtpu_crc32c.restype = ctypes.c_uint32
    lib.agtpu_crc32c.argtypes = [u8p, size_t]
    lib.agtpu_tfrecord_index.restype = i64
    lib.agtpu_tfrecord_index.argtypes = [u8p, i64, i64p, i64p, i64, ctypes.c_int]
    lib.agtpu_sha256.restype = None
    lib.agtpu_sha256.argtypes = [u8p, size_t, u8p]
    lib.agtpu_hmac_sha256.restype = None
    lib.agtpu_hmac_sha256.argtypes = [u8p, size_t, u8p, size_t, u8p]
    lib.agtpu_hmac_verify.restype = ctypes.c_int
    lib.agtpu_hmac_verify.argtypes = [u8p, size_t, u8p, size_t, u8p]


def load():
    """Build if needed and load the library (cached); raises on failure."""
    global _lib, _load_error
    if _lib is not None:
        return _lib
    if _load_error is not None:
        raise _load_error
    try:
        lib = ctypes.CDLL(build())
        _declare(lib)
    except Exception as exc:  # compiler missing, unsupported platform, ...
        _load_error = RuntimeError("native GAR library unavailable: %s" % exc)
        raise _load_error from exc
    _lib = lib
    return lib


def available():
    """True when the native library builds and loads on this host."""
    try:
        load()
        return True
    except Exception:
        return False


def num_threads():
    return int(load().agtpu_num_threads())


# --------------------------------------------------------------------------- #
# numpy wrappers

def _prepare(grads):
    """Contiguous 2-D float32/float64 view + (suffix, ctype) dispatch info."""
    g = np.asarray(grads)
    if g.ndim != 2:
        raise ValueError("expected an (n, d) gradient matrix, got shape %r" % (g.shape,))
    if g.dtype == np.float32:
        suffix, ctype = "f32", ctypes.c_float
    else:
        g = g.astype(np.float64, copy=False)
        suffix, ctype = "f64", ctypes.c_double
    return np.ascontiguousarray(g), suffix, ctype


def _ptr(arr, ctype):
    return arr.ctypes.data_as(ctypes.POINTER(ctype))


def _rowwise(name, grads, *extra):
    lib = load()
    g, suffix, ctype = _prepare(grads)
    n, d = g.shape
    out = np.empty(d, dtype=g.dtype)
    fn = getattr(lib, "agtpu_%s_%s" % (name, suffix))
    fn(_ptr(g, ctype), n, d, *[ctypes.c_int64(int(e)) for e in extra], _ptr(out, ctype))
    return out


def average(grads):
    return _rowwise("average", grads)


def average_nan(grads):
    return _rowwise("average_nan", grads)


def median(grads):
    return _rowwise("median", grads)


def averaged_median(grads, f):
    return _rowwise("averaged_median", grads, f)


def krum(grads, f, m=None):
    n = np.asarray(grads).shape[0]
    if m is None:
        m = n - int(f) - 2
    if not 1 <= int(m) <= n:
        raise ValueError("krum selection size m=%d out of range [1, n=%d] (f=%d)" % (m, n, f))
    return _rowwise("krum", grads, f, m)


def bulyan(grads, f):
    return _rowwise("bulyan", grads, f)


def pairwise_sq_distances(grads):
    """(n, n) float64 all-pairs squared distances (non-finite -> +inf)."""
    lib = load()
    g, suffix, ctype = _prepare(grads)
    n, d = g.shape
    out = np.empty((n, n), dtype=np.float64)
    fn = getattr(lib, "agtpu_pairwise_sqdist_%s" % suffix)
    fn(_ptr(g, ctype), n, d, _ptr(out, ctypes.c_double))
    return out


# --------------------------------------------------------------------------- #
# TFRecord IO (io.cpp; the fast path behind models/tfrecord.py)

def crc32c(data):
    """CRC32C (Castagnoli) of bytes/uint8 array — the TFRecord checksum."""
    lib = load()
    _, ptr, length = _u8(data)
    return int(lib.agtpu_crc32c(ptr, length))


def tfrecord_index(buf, verify=True):
    """Index a whole TFRecord shard held in ``buf`` (bytes/mmap/uint8 array).

    Returns (offsets, lengths) int64 arrays — payload i is
    ``buf[offsets[i]:offsets[i]+lengths[i]]``.  With ``verify`` all framing
    CRCs are checked (payloads in parallel on the thread pool).  Raises
    ValueError at the first corrupt byte offset.
    """
    lib = load()
    arr, ptr, length = _u8(buf)
    # every record is >= 16 bytes of framing
    cap = max(1, length // 16 + 1)
    offsets = np.empty(cap, dtype=np.int64)
    lengths = np.empty(cap, dtype=np.int64)
    i64p = ctypes.POINTER(ctypes.c_int64)
    count = int(lib.agtpu_tfrecord_index(
        ptr, length,
        offsets.ctypes.data_as(i64p), lengths.ctypes.data_as(i64p),
        cap, 1 if verify else 0,
    ))
    if count < 0:
        raise ValueError("corrupt TFRecord framing at byte %d" % (-count - 1))
    # copies: slicing views would pin the file-sized scratch allocation
    return offsets[:count].copy(), lengths[:count].copy()


# --------------------------------------------------------------------------- #
# host authentication (auth.cpp; see parallel/auth.py for the policy layer)

def _u8(buf):
    arr = np.frombuffer(buf, dtype=np.uint8) if not isinstance(buf, np.ndarray) else buf
    arr = np.ascontiguousarray(arr, dtype=np.uint8).ravel()
    return arr, arr.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)), arr.size


def sha256(data):
    """32-byte SHA-256 digest of ``data`` (bytes or uint8 array)."""
    lib = load()
    _, dptr, dlen = _u8(data)
    out = np.empty(32, dtype=np.uint8)
    lib.agtpu_sha256(dptr, dlen, out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)))
    return out.tobytes()


def hmac_sha256(key, data):
    """32-byte HMAC-SHA256 tag of ``data`` under ``key``."""
    lib = load()
    _, kptr, klen = _u8(key)
    _, dptr, dlen = _u8(data)
    out = np.empty(32, dtype=np.uint8)
    lib.agtpu_hmac_sha256(kptr, klen, dptr, dlen, out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)))
    return out.tobytes()


def hmac_verify(key, data, tag):
    """Constant-time verification of a 32-byte tag."""
    if len(tag) != 32:
        return False
    lib = load()
    _, kptr, klen = _u8(key)
    _, dptr, dlen = _u8(data)
    _, tptr, _tlen = _u8(tag)
    return bool(lib.agtpu_hmac_verify(kptr, klen, dptr, dlen, tptr))

// Host-side gradient authentication: SHA-256 + HMAC-SHA256 (RFC 6234/2104).
//
// The reference authenticates worker->PS tensor pushes with libsodium ed25519
// signatures inside the patched UDP rendezvous
// (tf_patches/patches/mpi_rendezvous_mgr.patch:585-627, verification at
// 777-781, 1057-1064). In the TPU-native design the on-chip path (ICI/DCN
// collectives) is trusted hardware, so authentication moves to the host
// boundary: multi-host coordination RPCs and checkpoint blobs are tagged with
// HMAC-SHA256 under per-worker shared keys — symmetric instead of asymmetric
// because the single controller already holds every worker's identity (there
// is no third-party verification need). Off the hot path by design, exactly
// like the reference's signatures (they ride the metadata side channel).
//
// SHA-256 implemented directly from the FIPS 180-4 specification.

#include <cstdint>
#include <cstring>

namespace {

struct Sha256 {
    uint32_t state[8];
    uint64_t length;     // total bytes absorbed
    uint8_t buffer[64];
    size_t fill;

    static constexpr uint32_t K[64] = {
        0x428a2f98u, 0x71374491u, 0xb5c0fbcfu, 0xe9b5dba5u, 0x3956c25bu, 0x59f111f1u,
        0x923f82a4u, 0xab1c5ed5u, 0xd807aa98u, 0x12835b01u, 0x243185beu, 0x550c7dc3u,
        0x72be5d74u, 0x80deb1feu, 0x9bdc06a7u, 0xc19bf174u, 0xe49b69c1u, 0xefbe4786u,
        0x0fc19dc6u, 0x240ca1ccu, 0x2de92c6fu, 0x4a7484aau, 0x5cb0a9dcu, 0x76f988dau,
        0x983e5152u, 0xa831c66du, 0xb00327c8u, 0xbf597fc7u, 0xc6e00bf3u, 0xd5a79147u,
        0x06ca6351u, 0x14292967u, 0x27b70a85u, 0x2e1b2138u, 0x4d2c6dfcu, 0x53380d13u,
        0x650a7354u, 0x766a0abbu, 0x81c2c92eu, 0x92722c85u, 0xa2bfe8a1u, 0xa81a664bu,
        0xc24b8b70u, 0xc76c51a3u, 0xd192e819u, 0xd6990624u, 0xf40e3585u, 0x106aa070u,
        0x19a4c116u, 0x1e376c08u, 0x2748774cu, 0x34b0bcb5u, 0x391c0cb3u, 0x4ed8aa4au,
        0x5b9cca4fu, 0x682e6ff3u, 0x748f82eeu, 0x78a5636fu, 0x84c87814u, 0x8cc70208u,
        0x90befffau, 0xa4506cebu, 0xbef9a3f7u, 0xc67178f2u,
    };

    void init() {
        static constexpr uint32_t iv[8] = {
            0x6a09e667u, 0xbb67ae85u, 0x3c6ef372u, 0xa54ff53au,
            0x510e527fu, 0x9b05688cu, 0x1f83d9abu, 0x5be0cd19u,
        };
        std::memcpy(state, iv, sizeof(iv));
        length = 0;
        fill = 0;
    }

    static uint32_t rotr(uint32_t x, unsigned n) { return (x >> n) | (x << (32 - n)); }

    void compress(uint8_t const* block) {
        uint32_t w[64];
        for (int i = 0; i < 16; ++i) {
            w[i] = (uint32_t(block[4 * i]) << 24) | (uint32_t(block[4 * i + 1]) << 16) |
                   (uint32_t(block[4 * i + 2]) << 8) | uint32_t(block[4 * i + 3]);
        }
        for (int i = 16; i < 64; ++i) {
            uint32_t const s0 = rotr(w[i - 15], 7) ^ rotr(w[i - 15], 18) ^ (w[i - 15] >> 3);
            uint32_t const s1 = rotr(w[i - 2], 17) ^ rotr(w[i - 2], 19) ^ (w[i - 2] >> 10);
            w[i] = w[i - 16] + s0 + w[i - 7] + s1;
        }
        uint32_t a = state[0], b = state[1], c = state[2], d = state[3];
        uint32_t e = state[4], f = state[5], g = state[6], h = state[7];
        for (int i = 0; i < 64; ++i) {
            uint32_t const s1 = rotr(e, 6) ^ rotr(e, 11) ^ rotr(e, 25);
            uint32_t const ch = (e & f) ^ (~e & g);
            uint32_t const t1 = h + s1 + ch + K[i] + w[i];
            uint32_t const s0 = rotr(a, 2) ^ rotr(a, 13) ^ rotr(a, 22);
            uint32_t const maj = (a & b) ^ (a & c) ^ (b & c);
            uint32_t const t2 = s0 + maj;
            h = g; g = f; f = e; e = d + t1;
            d = c; c = b; b = a; a = t1 + t2;
        }
        state[0] += a; state[1] += b; state[2] += c; state[3] += d;
        state[4] += e; state[5] += f; state[6] += g; state[7] += h;
    }

    void update(uint8_t const* data, size_t len) {
        length += len;
        while (len > 0) {
            size_t const take = len < (64 - fill) ? len : (64 - fill);
            std::memcpy(buffer + fill, data, take);
            fill += take;
            data += take;
            len -= take;
            if (fill == 64) {
                compress(buffer);
                fill = 0;
            }
        }
    }

    void final(uint8_t out[32]) {
        uint64_t const bits = length * 8;
        uint8_t const pad = 0x80;
        update(&pad, 1);
        uint8_t const zero = 0x00;
        while (fill != 56) update(&zero, 1);
        uint8_t len_be[8];
        for (int i = 0; i < 8; ++i) len_be[i] = uint8_t(bits >> (56 - 8 * i));
        update(len_be, 8);
        for (int i = 0; i < 8; ++i) {
            out[4 * i] = uint8_t(state[i] >> 24);
            out[4 * i + 1] = uint8_t(state[i] >> 16);
            out[4 * i + 2] = uint8_t(state[i] >> 8);
            out[4 * i + 3] = uint8_t(state[i]);
        }
    }
};

constexpr uint32_t Sha256::K[64];

void hmac_sha256(uint8_t const* key, size_t keylen, uint8_t const* data, size_t len,
                 uint8_t out[32]) {
    uint8_t kblock[64] = {0};
    if (keylen > 64) {
        Sha256 kh;
        kh.init();
        kh.update(key, keylen);
        kh.final(kblock);  // first 32 bytes; rest stay zero
    } else {
        std::memcpy(kblock, key, keylen);
    }
    uint8_t ipad[64], opad[64];
    for (int i = 0; i < 64; ++i) {
        ipad[i] = kblock[i] ^ 0x36;
        opad[i] = kblock[i] ^ 0x5c;
    }
    uint8_t inner[32];
    Sha256 h;
    h.init();
    h.update(ipad, 64);
    h.update(data, len);
    h.final(inner);
    h.init();
    h.update(opad, 64);
    h.update(inner, 32);
    h.final(out);
}

}  // namespace

extern "C" {

void agtpu_sha256(uint8_t const* data, size_t len, uint8_t* out32) {
    Sha256 h;
    h.init();
    h.update(data, len);
    h.final(out32);
}

void agtpu_hmac_sha256(uint8_t const* key, size_t keylen, uint8_t const* data, size_t len,
                       uint8_t* out32) {
    hmac_sha256(key, keylen, data, len, out32);
}

// Constant-time tag comparison: 1 = match, 0 = mismatch.
int agtpu_hmac_verify(uint8_t const* key, size_t keylen, uint8_t const* data, size_t len,
                      uint8_t const* tag32) {
    uint8_t expect[32];
    hmac_sha256(key, keylen, data, len, expect);
    unsigned diff = 0;
    for (int i = 0; i < 32; ++i) diff |= unsigned(expect[i] ^ tag32[i]);
    return diff == 0 ? 1 : 0;
}

}  // extern "C"

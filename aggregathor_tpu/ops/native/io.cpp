// Native TFRecord framing: CRC32C and record indexing/verification.
//
// The reference's input path runs multi-threaded fetchers over TFRecord
// shards inside the TF runtime (reference: experiments/cnnet.py:115-146,
// nb-fetcher-threads / nb-batcher-threads); this framework's equivalent is a
// host-native scanner: slice-by-8 CRC32C (Castagnoli, the TFRecord checksum)
// plus a framing walker that indexes every record in a memory-mapped shard
// and verifies all checksums in parallel on the shared thread pool.  The
// Python tier (models/tfrecord.py) falls back to its pure-Python
// implementation when this library cannot build.

#include <cstdint>
#include <cstring>

#include "threadpool.hpp"

namespace {

// Slice-by-8 CRC32C tables, built once at first use.
struct Crc32cTables {
  std::uint32_t t[8][256];
  Crc32cTables() {
    const std::uint32_t poly = 0x82F63B78u;  // reflected Castagnoli
    for (std::uint32_t i = 0; i < 256; ++i) {
      std::uint32_t crc = i;
      for (int k = 0; k < 8; ++k) crc = (crc >> 1) ^ ((crc & 1) ? poly : 0);
      t[0][i] = crc;
    }
    for (int s = 1; s < 8; ++s) {
      for (std::uint32_t i = 0; i < 256; ++i) {
        t[s][i] = (t[s - 1][i] >> 8) ^ t[0][t[s - 1][i] & 0xFF];
      }
    }
  }
};

const Crc32cTables& Tables() {
  static Crc32cTables tables;
  return tables;
}

std::uint32_t Crc32c(const std::uint8_t* data, std::size_t len) {
  const auto& tb = Tables();
  std::uint32_t crc = 0xFFFFFFFFu;
  while (len >= 8) {
    std::uint64_t word;
    std::memcpy(&word, data, 8);  // little-endian hosts (x86/ARM/TPU-host)
    word ^= crc;
    crc = tb.t[7][word & 0xFF] ^ tb.t[6][(word >> 8) & 0xFF] ^
          tb.t[5][(word >> 16) & 0xFF] ^ tb.t[4][(word >> 24) & 0xFF] ^
          tb.t[3][(word >> 32) & 0xFF] ^ tb.t[2][(word >> 40) & 0xFF] ^
          tb.t[1][(word >> 48) & 0xFF] ^ tb.t[0][(word >> 56) & 0xFF];
    data += 8;
    len -= 8;
  }
  while (len--) crc = tb.t[0][(crc ^ *data++) & 0xFF] ^ (crc >> 8);
  return crc ^ 0xFFFFFFFFu;
}

std::uint32_t MaskedCrc(const std::uint8_t* data, std::size_t len) {
  const std::uint32_t crc = Crc32c(data, len);
  return ((crc >> 15) | (crc << 17)) + 0xA282EAD8u;
}

std::uint32_t LoadU32(const std::uint8_t* p) {
  std::uint32_t v;
  std::memcpy(&v, p, 4);
  return v;
}

std::uint64_t LoadU64(const std::uint8_t* p) {
  std::uint64_t v;
  std::memcpy(&v, p, 8);
  return v;
}

}  // namespace

extern "C" {

std::uint32_t agtpu_crc32c(const std::uint8_t* data, std::size_t len) {
  return Crc32c(data, len);
}

// Walk the TFRecord framing of `buf` (a whole mapped shard), writing each
// record's payload offset/length into `offsets`/`lengths` (capacity
// `max_records`).  When `verify` is nonzero, every length and payload CRC is
// checked — payload checks run in parallel on the shared pool.  Returns the
// record count, or -(1 + byte_offset) at the first framing/CRC error.
std::int64_t agtpu_tfrecord_index(const std::uint8_t* buf, std::int64_t len,
                                  std::int64_t* offsets, std::int64_t* lengths,
                                  std::int64_t max_records, int verify) {
  std::int64_t count = 0;
  std::int64_t pos = 0;
  while (pos < len) {
    if (pos + 12 > len || count >= max_records) return -(1 + pos);
    const std::uint64_t rec_len = LoadU64(buf + pos);
    if (verify && MaskedCrc(buf + pos, 8) != LoadU32(buf + pos + 8)) {
      return -(1 + pos);
    }
    const std::int64_t payload = pos + 12;
    // Unsigned bounds check: rec_len is untrusted 64-bit input, and casting
    // a huge value to int64 would overflow the naive `payload + rec_len + 4
    // > len` comparison (UB) and walk out of the buffer.
    const std::uint64_t remaining = static_cast<std::uint64_t>(len - payload);
    if (remaining < 4 || rec_len > remaining - 4) return -(1 + pos);
    offsets[count] = payload;
    lengths[count] = static_cast<std::int64_t>(rec_len);
    ++count;
    pos = payload + static_cast<std::int64_t>(rec_len) + 4;
  }
  if (verify && count > 0) {
    // Payload CRCs dominate the scan cost (the whole file is hashed once);
    // verify records in parallel, recording the first failing offset.
    std::int64_t bad = -1;
    std::mutex mu;
    agtpu::ParallelFor(0, count, [&](std::int64_t lo, std::int64_t hi) {
      for (std::int64_t i = lo; i < hi; ++i) {
        const std::uint8_t* payload = buf + offsets[i];
        const std::uint32_t want = LoadU32(payload + lengths[i]);
        if (MaskedCrc(payload, static_cast<std::size_t>(lengths[i])) != want) {
          std::lock_guard<std::mutex> lock(mu);
          if (bad < 0 || offsets[i] < bad) bad = offsets[i];
        }
      }
    });
    if (bad >= 0) return -(1 + bad);
  }
  return count;
}

}  // extern "C"

"""Kernel tiers below the jnp/XLA default.

- ``ops.native`` — C++17 host library (threadpool + GAR kernels) loaded via
  ctypes; the framework's equivalent of the reference's native op layer
  (native/__init__.py, aggregators/deprecated_native/) for host-side
  aggregation, oracles at scale, and environments without an accelerator.
- ``ops.pallas_kernels`` — hand-written Pallas TPU kernels for the GAR hot
  path (pairwise distances, coordinate-wise selection), replacing the
  reference's CUDA/custom-op tier (native/op_krum, native/op_bulyan).
"""

"""Data-poisoning MNIST experiment.

Parity with the reference's ``mnistAttack`` (experiments/mnistAttack.py:51-92,
138-140): the *training* stream is malformed — severity 1 multiplies inputs
by -100; severity 2 multiplies by -1e12 and applies independent random
permutations to inputs and labels (destroying their correspondence).  The
reference hardwires severity 2 in ``losses``; here severity is a key:value
arg defaulting to 2.  Evaluation data stays clean, so accuracy measures what
the poisoned workers did to the model.
"""

import numpy as np

from ..utils import parse_keyval
from . import register
from .datasets import WorkerBatchIterator, load_digits8x8
from .mnist import MNISTExperiment


class MNISTAttackExperiment(MNISTExperiment):
    def __init__(self, args):
        super().__init__(args)
        self.severity = parse_keyval(args, {"severity": 2})["severity"]

    def _poison(self, images, labels):
        if self.severity <= 1:
            return images * np.float32(-100.0), labels
        flat_img = images.reshape(-1, *images.shape[2:])
        flat_lab = labels.reshape(-1)
        rng = np.random.default_rng(int(flat_lab.sum()) % (2**31))
        img_perm = rng.permutation(flat_img.shape[0])
        lab_perm = rng.permutation(flat_lab.shape[0])
        poisoned = (flat_img[img_perm] * np.float32(-1e12)).reshape(images.shape)
        shuffled = flat_lab[lab_perm].reshape(labels.shape)
        return poisoned, shuffled

    def make_train_iterator(self, nb_workers, seed=0):
        from .preprocessing import stateless

        # the poison is a pure function of its inputs (severity-2's rng is
        # keyed off the batch's own labels), so resume fast-forward may
        # skip it: only the index streams need advancing
        return WorkerBatchIterator(
            self.dataset.x_train, self.dataset.y_train, nb_workers, self.batch_size,
            seed=seed, transform=stateless(lambda bx, by: self._poison(bx, by)),
        )

    def train_arrays(self):
        # the poisoning is a HOST batch transform — a plain device-side row
        # gather would silently train on clean data
        return None


register("mnistAttack", MNISTAttackExperiment)


class DigitsAttackExperiment(MNISTAttackExperiment):
    """The same data-poisoning stream over REAL data (sklearn digits):
    clean-eval accuracy after training on a severity-2 poisoned cluster
    collapses to chance on a real corpus, not just on the synthetic
    stand-in — the reference's mnistAttack failure-mode demonstration
    (experiments/mnistAttack.py:51-92) with a real measurement."""

    sample_shape = (8, 8, 1)
    load_dataset = staticmethod(load_digits8x8)


register("digitsAttack", DigitsAttackExperiment)

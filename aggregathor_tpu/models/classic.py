"""Classic small nets: LeNet, CifarNet, AlexNet v2, OverFeat.

Capability parity with the reference's slim nets_factory entries ``lenet``,
``cifarnet``, ``alexnet_v2``, ``overfeat``
(external/slim/nets/nets_factory.py:39-60) — the small-image workhorses of
the slim zoo, written fresh as flax modules (same conventions as resnet.py:
NHWC, mixed precision via ``dtype``, float32 logits).
"""

import flax.linen as nn
import jax.numpy as jnp

from .common import resize_min


class LeNet(nn.Module):
    """LeNet-5-style: 2x (conv + maxpool) -> 1024 dense -> logits."""

    classes: int = 10
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        d = self.dtype
        x = x.astype(d)
        x = nn.relu(nn.Conv(32, (5, 5), padding="SAME", dtype=d, name="conv1")(x))
        x = nn.max_pool(x, (2, 2), (2, 2))
        x = nn.relu(nn.Conv(64, (5, 5), padding="SAME", dtype=d, name="conv2")(x))
        x = nn.max_pool(x, (2, 2), (2, 2))
        x = x.reshape((x.shape[0], -1))
        x = nn.relu(nn.Dense(1024, dtype=d, name="fc3")(x))
        return nn.Dense(self.classes, dtype=jnp.float32, name="logits")(x)


class CifarNet(nn.Module):
    """slim cifarnet shape: 2x (conv5x5-64 + pool + norm) -> 384 -> 192 -> logits."""

    classes: int = 10
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        d = self.dtype
        x = x.astype(d)
        x = nn.relu(nn.Conv(64, (5, 5), padding="SAME", dtype=d, name="conv1")(x))
        x = nn.max_pool(x, (3, 3), (2, 2), padding="SAME")
        x = nn.LayerNorm(dtype=d, name="norm1")(x)
        x = nn.relu(nn.Conv(64, (5, 5), padding="SAME", dtype=d, name="conv2")(x))
        x = nn.LayerNorm(dtype=d, name="norm2")(x)
        x = nn.max_pool(x, (3, 3), (2, 2), padding="SAME")
        x = x.reshape((x.shape[0], -1))
        x = nn.relu(nn.Dense(384, dtype=d, name="fc3")(x))
        x = nn.relu(nn.Dense(192, dtype=d, name="fc4")(x))
        return nn.Dense(self.classes, dtype=jnp.float32, name="logits")(x)


class AlexNetV2(nn.Module):
    """slim alexnet_v2: 5 convs + 2 fully-connected-as-conv heads."""

    classes: int = 1000
    dense_units: int = 4096
    dtype: jnp.dtype = jnp.float32
    min_size: int = 64

    @nn.compact
    def __call__(self, x):
        d = self.dtype
        x = resize_min(x, self.min_size).astype(d)
        x = nn.relu(nn.Conv(64, (11, 11), (4, 4), padding="SAME", dtype=d, name="conv1")(x))
        x = nn.max_pool(x, (3, 3), (2, 2), padding="SAME")
        x = nn.relu(nn.Conv(192, (5, 5), padding="SAME", dtype=d, name="conv2")(x))
        x = nn.max_pool(x, (3, 3), (2, 2), padding="SAME")
        x = nn.relu(nn.Conv(384, (3, 3), padding="SAME", dtype=d, name="conv3")(x))
        x = nn.relu(nn.Conv(384, (3, 3), padding="SAME", dtype=d, name="conv4")(x))
        x = nn.relu(nn.Conv(256, (3, 3), padding="SAME", dtype=d, name="conv5")(x))
        x = nn.max_pool(x, (3, 3), (2, 2), padding="SAME")
        x = jnp.mean(x, axis=(1, 2))  # spatial pool replaces the 6x6 VALID fc
        x = nn.relu(nn.Dense(self.dense_units, dtype=d, name="fc6")(x))
        x = nn.relu(nn.Dense(self.dense_units, dtype=d, name="fc7")(x))
        return nn.Dense(self.classes, dtype=jnp.float32, name="logits")(x.astype(jnp.float32))


class OverFeat(nn.Module):
    """slim overfeat: 5 convs (11x11/4 stem, wide 1024 tail) + 2 dense heads."""

    classes: int = 1000
    dense_units: int = 3072
    dtype: jnp.dtype = jnp.float32
    min_size: int = 64

    @nn.compact
    def __call__(self, x):
        d = self.dtype
        x = resize_min(x, self.min_size).astype(d)
        x = nn.relu(nn.Conv(64, (11, 11), (4, 4), padding="SAME", dtype=d, name="conv1")(x))
        x = nn.max_pool(x, (2, 2), (2, 2), padding="SAME")
        x = nn.relu(nn.Conv(256, (5, 5), padding="SAME", dtype=d, name="conv2")(x))
        x = nn.max_pool(x, (2, 2), (2, 2), padding="SAME")
        x = nn.relu(nn.Conv(512, (3, 3), padding="SAME", dtype=d, name="conv3")(x))
        x = nn.relu(nn.Conv(1024, (3, 3), padding="SAME", dtype=d, name="conv4")(x))
        x = nn.relu(nn.Conv(1024, (3, 3), padding="SAME", dtype=d, name="conv5")(x))
        x = nn.max_pool(x, (2, 2), (2, 2), padding="SAME")
        x = jnp.mean(x, axis=(1, 2))  # spatial pool replaces the 6x6 VALID fc
        x = nn.relu(nn.Dense(self.dense_units, dtype=d, name="fc6")(x))
        x = nn.relu(nn.Dense(self.dense_units + 1024, dtype=d, name="fc7")(x))
        return nn.Dense(self.classes, dtype=jnp.float32, name="logits")(x.astype(jnp.float32))

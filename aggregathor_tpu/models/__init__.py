"""Experiments: model + dataset plugins.

An experiment bundles a model family with its input pipeline and evaluation
metrics, mirroring the reference's ``_Experiment`` contract —
``__init__(args)``, per-worker ``losses``, ``accuracy`` returning a dict of
name -> value (reference: experiments/__init__.py:40-71) — re-expressed
functionally for JAX:

- ``init(rng)``                  -> parameter pytree (one canonical copy;
                                    sharing across workers is automatic since
                                    SPMD replicates params, the equivalent of
                                    the reference's AUTO_REUSE variable scopes,
                                    experiments/mnist.py:83-104)
- ``loss(params, batch)``        -> scalar (per-worker; vmapped by the engine)
- ``metrics(params, batch)``     -> dict name -> (sum, count) accumulators
- ``make_train_iterator(...)``   -> infinite worker-major batch iterator
- ``make_eval_iterator(...)``    -> finite epoch over the held-out split

Experiments self-register by name at import time (reference:
experiments/__init__.py:76-85).
"""

from ..utils import ClassRegister, import_directory

experiments = ClassRegister("experiment")


def register(name, cls):
    return experiments.register(name, cls)


def itemize():
    return experiments.itemize()


def get(name):
    """The experiment class registered under ``name`` (not instantiated)."""
    return experiments.get(name)


def instantiate(name, args=None):
    """Build the experiment registered under ``name`` from key:value args."""
    return experiments.get(name)(args or [])


class Experiment:
    """Base experiment (see module docstring for the contract)."""

    #: True if the experiment publishes the sharded-engine hooks the CLI's
    #: ``--mesh`` path needs: ``sharded_init(n_stages) -> (key -> params)``,
    #: ``sharded_specs() -> PartitionSpec pytree``, and
    #: ``sharded_loss(n_stages, microbatches) -> shard_map local-partial
    #: loss``.  See models/transformer.py for the reference implementation.
    supports_sharded = False

    def __init__(self, args):
        self.args = args

    def init(self, rng):
        raise NotImplementedError

    def loss(self, params, batch):
        raise NotImplementedError

    def metrics(self, params, batch):
        raise NotImplementedError

    def predict_logits(self, params, x):
        """The inference apply path: ``(params, (B, *sample_shape)) -> (B,
        classes)`` logits.  This is the single hook ``serve/engine.py`` jits —
        the training-only heads (aux logits, label smoothing, weight decay)
        never enter the serving graph.  Default: the bare ``model.apply``,
        which is the logits path for every bundled experiment family (mnist/
        digits MLPs, cnnet, the zoo); experiments whose apply signature
        differs override this.
        """
        model = getattr(self, "model", None)
        if model is None:
            raise NotImplementedError(
                "Experiment %r keeps no .model; override predict_logits()"
                % type(self).__name__
            )
        return model.apply(params, x)

    def make_train_iterator(self, nb_workers, seed=0):
        raise NotImplementedError

    def make_eval_iterator(self, nb_workers):
        raise NotImplementedError

    def device_transform(self):
        """Optional jnp train-batch transform run INSIDE the jitted step.

        Experiments that support ``augment:device`` return the in-step
        augmentation here (models/preprocessing.py ``device_transform``) and
        leave their host iterator transform-free; the engine applies it per
        worker with (seed, step, worker)-keyed randomness.  Default: the
        in-step tier of ``self.preprocessing`` when the experiment opted
        into ``augment:device`` (the cnnet/zoo convention: ``self.augment``
        is ``"host"`` or ``"device"``); none otherwise.
        """
        if getattr(self, "augment", "host") != "device":
            return None
        from .preprocessing import device_transform

        return device_transform(self.preprocessing)

    def train_arrays(self):
        """Optional array-backed training corpus for DEVICE-SIDE sampling.

        Returns the full training split as a batch-structured pytree (same
        keys as ``make_train_iterator``'s batches, leading axis = examples)
        when — and only when — a uniform in-graph row gather reproduces the
        iterator's stream semantics: i.i.d.-with-replacement draws and NO
        host-side transform (poisoning, host augmentation, windowing).
        ``None`` (the default) keeps the experiment on the streaming path.

        Consumers: ``RobustEngine.build_sampled_multi_step`` and the CLI's
        ``--input-source device`` — on a tunneled TPU the per-step
        host->device transfer bounds training (measured r4: config 2 streams
        at 2.0 steps/s vs 26 resident), and a dataset transferred once
        removes it.

        Default: the ``self.dataset`` train split for experiments whose
        host input path is a plain gather — augmentation moved in-step
        (``augment:device``) or a host tier that is the identity
        (``preprocessing:none``/``lenet``); None otherwise (a stateful host
        transform — augmentation streams, poisoning — must see every batch).
        """
        augment = getattr(self, "augment", None)
        if augment == "device":
            eligible = True
        elif augment == "host":
            from .preprocessing import PREPROCESSING, none_preprocessing

            eligible = (
                PREPROCESSING.get(getattr(self, "preprocessing", None))
                is none_preprocessing
            )
        else:
            eligible = False
        if not eligible:
            return None
        dataset = getattr(self, "dataset", None)
        if dataset is None:
            return None
        return {"image": dataset.x_train, "label": dataset.y_train}

    def route_augmentation_to_device(self):
        """Move a host-tier augmentation to its in-step device twin
        (models/preprocessing.py ``DEVICE_PREPROCESSING``), making the host
        input path a plain gather so DEVICE-RESIDENT sampling
        (``train_arrays`` + ``RobustEngine.build_sampled_multi_step``) can
        serve augmented training too.  Returns True when the experiment now
        augments in-step (or already did); False when it has no
        re-routable augmentation machinery — a stateful non-augmentation
        transform (poisoning, streaming corpus) stays host-bound and
        ``train_arrays`` keeps returning None.  Note the augmentation
        STREAM changes (numpy per-worker generators -> in-step
        (seed, step, worker) keys) — same distribution, different draws,
        exactly like the device sampling it enables."""
        if getattr(self, "augment", None) == "device":
            return True
        name = getattr(self, "preprocessing", None)
        if getattr(self, "augment", None) != "host" or name is None:
            return False
        from .preprocessing import DEVICE_PREPROCESSING

        if name not in DEVICE_PREPROCESSING:
            return False
        self.augment = "device"
        return True


import_directory(__name__, __path__, skip=("datasets",))

"""Minimal TFRecord + tf.Example codec for the reference's CIFAR-10 layout.

The reference reads CIFAR-10 from TF-Slim TFRecord shards on local disk
(reference: experiments/cnnet.py:115-146, expecting the layout written by
slim's ``download_and_convert_cifar10.py``: ``cifar10_train.tfrecord`` /
``cifar10_test.tfrecord``, each record a ``tf.Example`` with PNG-encoded
``image/encoded``, ``image/format`` and ``image/class/label`` features).
This module reads — and, for fixtures/conversion, writes — that exact
on-disk format without TensorFlow:

- TFRecord framing: ``uint64 length | masked crc32c(length) | payload |
  masked crc32c(payload)`` with the Castagnoli CRC and TF's rotation mask.
- tf.Example: a hand-rolled protobuf wire-format walker for the fixed
  3-level shape Example > Features(map<string, Feature>) >
  bytes_list/float_list/int64_list.  No generated code, no proto dep.
- PNG: PIL (baked into the environment) for decode/encode.

``scripts/convert_cifar10.py`` uses this to turn the reference's TFRecord
shards into the ``cifar10.npz`` the loaders prefer; ``datasets.load_cifar10``
also falls back to reading the shards directly.
"""

import os
import struct

import numpy as np

from ..utils import UserException

# ---------------------------------------------------------------- crc32c --

_CRC_TABLE = []


def _crc_table():
    if not _CRC_TABLE:
        poly = 0x82F63B78  # Castagnoli, reflected
        for i in range(256):
            crc = i
            for _ in range(8):
                crc = (crc >> 1) ^ poly if crc & 1 else crc >> 1
            _CRC_TABLE.append(crc)
    return _CRC_TABLE


def crc32c(data):
    table = _crc_table()
    crc = 0xFFFFFFFF
    for byte in data:
        crc = table[(crc ^ byte) & 0xFF] ^ (crc >> 8)
    return crc ^ 0xFFFFFFFF


def _masked_crc(data):
    crc = crc32c(data)
    return ((crc >> 15) | (crc << 17)) + 0xA282EAD8 & 0xFFFFFFFF


# ------------------------------------------------------- TFRecord framing --


def iter_tfrecords(path):
    """Yield the payload bytes of every record in a TFRecord file.

    Uses the native scanner when the C++ library is available (slice-by-8
    CRC32C + parallel payload verification over a memory-mapped shard,
    ops/native/io.cpp — the counterpart of the reference's multi-threaded
    fetchers); otherwise the pure-Python walker below.
    """
    from ..ops import native

    use_native = False
    try:
        use_native = native.available()
    except Exception:
        pass
    if use_native:
        import mmap

        with open(path, "rb") as fd:
            if os.fstat(fd.fileno()).st_size == 0:
                return
            buf = mmap.mmap(fd.fileno(), 0, access=mmap.ACCESS_READ)
        try:
            # Lifetime care: every numpy view over the mmap must be dropped
            # before close() or it raises BufferError — including views
            # pinned by exception tracebacks, so the ValueError is fully
            # handled (its frames released) before a fresh error is raised.
            view = np.frombuffer(buf, dtype=np.uint8)
            error = None
            try:
                offsets, lengths = native.tfrecord_index(view)
            except ValueError as exc:
                error = "%s in %r" % (exc, path)
            finally:
                del view
            if error is not None:
                raise UserException(error)
            for offset, length in zip(offsets, lengths):
                yield bytes(buf[offset:offset + length])
        finally:
            buf.close()
        return
    with open(path, "rb") as fd:
        while True:
            header = fd.read(12)
            if not header:
                return
            if len(header) != 12:
                raise UserException("Truncated TFRecord header in %r" % path)
            (length,), (length_crc,) = struct.unpack("<Q", header[:8]), struct.unpack("<I", header[8:])
            if _masked_crc(header[:8]) != length_crc:
                raise UserException("Corrupt TFRecord length CRC in %r" % path)
            payload = fd.read(length)
            (payload_crc,) = struct.unpack("<I", fd.read(4))
            if len(payload) != length or _masked_crc(payload) != payload_crc:
                raise UserException("Corrupt TFRecord payload in %r" % path)
            yield payload


def write_tfrecords(path, payloads):
    """Write an iterable of payload bytes as a TFRecord file."""
    with open(path, "wb") as fd:
        for payload in payloads:
            header = struct.pack("<Q", len(payload))
            fd.write(header)
            fd.write(struct.pack("<I", _masked_crc(header)))
            fd.write(payload)
            fd.write(struct.pack("<I", _masked_crc(payload)))


# ------------------------------------------------- protobuf wire walking --


def _read_varint(buf, pos):
    result = shift = 0
    while True:
        byte = buf[pos]
        pos += 1
        result |= (byte & 0x7F) << shift
        if not byte & 0x80:
            return result, pos
        shift += 7


def _write_varint(value):
    out = bytearray()
    while True:
        byte = value & 0x7F
        value >>= 7
        if value:
            out.append(byte | 0x80)
        else:
            out.append(byte)
            return bytes(out)


def _iter_fields(buf):
    """Yield (field_number, wire_type, value) over a protobuf message.

    Length-delimited fields (wire type 2) yield their raw bytes; varints
    (type 0) the int; 64/32-bit (types 1/5) the raw 8/4 bytes.
    """
    pos = 0
    while pos < len(buf):
        key, pos = _read_varint(buf, pos)
        field, wire = key >> 3, key & 7
        if wire == 0:
            value, pos = _read_varint(buf, pos)
        elif wire == 1:
            value, pos = buf[pos:pos + 8], pos + 8
        elif wire == 2:
            length, pos = _read_varint(buf, pos)
            value, pos = buf[pos:pos + length], pos + length
        elif wire == 5:
            value, pos = buf[pos:pos + 4], pos + 4
        else:
            raise UserException("Unsupported protobuf wire type %d" % wire)
        yield field, wire, value


def parse_example(buf):
    """Parse a serialized tf.Example into {name: list-of-values}.

    bytes_list values come back as ``bytes``, int64_list as ``int``,
    float_list as ``float``.
    """
    features = {}
    for field, _, value in _iter_fields(buf):  # Example
        if field != 1:  # Example.features
            continue
        for ffield, _, entry in _iter_fields(value):  # Features
            if ffield != 1:  # Features.feature (map entry)
                continue
            name, feature = None, b""
            for mfield, _, mvalue in _iter_fields(entry):  # MapEntry
                if mfield == 1:
                    name = mvalue.decode("utf-8")
                elif mfield == 2:
                    feature = mvalue
            values = []
            for kfield, _, kvalue in _iter_fields(feature):  # Feature oneof
                for _, wire, item in _iter_fields(kvalue):
                    if kfield == 1:  # BytesList
                        values.append(item)
                    elif kfield == 2:  # FloatList (packed or not)
                        if wire == 2:
                            values.extend(struct.unpack("<%df" % (len(item) // 4), item))
                        else:
                            values.append(struct.unpack("<f", item)[0])
                    elif kfield == 3:  # Int64List (packed or not)
                        if wire == 2:
                            pos = 0
                            while pos < len(item):
                                v, pos = _read_varint(item, pos)
                                values.append(v)
                        else:
                            values.append(item)
            if name is not None:
                features[name] = values
    return features


def _delimited(field, payload):
    return _write_varint(field << 3 | 2) + _write_varint(len(payload)) + payload


def build_example(features):
    """Serialize {name: bytes | int | list-of-ints} as a tf.Example."""
    entries = b""
    for name, value in sorted(features.items()):
        if isinstance(value, bytes):
            feature = _delimited(1, _delimited(1, value))  # BytesList
        else:
            items = value if isinstance(value, (list, tuple)) else [value]
            packed = b"".join(_write_varint(int(v)) for v in items)
            feature = _delimited(3, _delimited(1, packed))  # Int64List (packed)
        entry = _delimited(1, name.encode("utf-8")) + _delimited(2, feature)
        entries += _delimited(1, entry)
    return _delimited(1, entries)  # Example.features


# ----------------------------------------------------------- PNG via PIL --


def png_decode(data):
    """PNG bytes -> (h, w, 3) uint8 array."""
    import io

    from PIL import Image

    with Image.open(io.BytesIO(data)) as img:
        return np.asarray(img.convert("RGB"), dtype=np.uint8)


def png_encode(array):
    """(h, w, 3) uint8 array -> PNG bytes."""
    import io

    from PIL import Image

    out = io.BytesIO()
    Image.fromarray(np.asarray(array, dtype=np.uint8)).save(out, format="PNG")
    return out.getvalue()


# ------------------------------------------------------- CIFAR-10 layout --

#: shard names written by slim's download_and_convert_cifar10.py
CIFAR10_SHARDS = {"train": "cifar10_train.tfrecord", "test": "cifar10_test.tfrecord"}


def read_cifar10_split(directory, split):
    """Read one slim CIFAR-10 shard -> (images uint8 (n, 32, 32, 3), labels int32)."""
    path = os.path.join(directory, CIFAR10_SHARDS[split])
    images, labels = [], []
    for payload in iter_tfrecords(path):
        example = parse_example(payload)
        encoded = example["image/encoded"][0]
        fmt = example.get("image/format", [b"png"])[0]
        if fmt not in (b"png", b"PNG"):
            raise UserException("Expected png-encoded CIFAR-10, got %r" % fmt)
        images.append(png_decode(encoded))
        labels.append(int(example["image/class/label"][0]))
    return np.stack(images), np.asarray(labels, dtype=np.int32)


def write_cifar10_split(directory, split, images, labels):
    """Write images/labels in the exact slim shard layout (fixtures, tests)."""
    os.makedirs(directory, exist_ok=True)
    path = os.path.join(directory, CIFAR10_SHARDS[split])

    def payloads():
        for image, label in zip(images, labels):
            yield build_example({
                "image/encoded": png_encode(image),
                "image/format": b"png",
                "image/class/label": int(label),
                "image/height": int(image.shape[0]),
                "image/width": int(image.shape[1]),
            })

    write_tfrecords(path, payloads())
    return path


def has_cifar10_tfrecords(directory):
    return all(
        os.path.isfile(os.path.join(directory, name)) for name in CIFAR10_SHARDS.values()
    )


# ------------------------------------------------------- ImageNet layout --
#
# The reference trains slims models on TFRecord ImageNet built by slim's
# build_imagenet_data.py (experiments/slims.py:98-111): sharded files named
# ``train-00000-of-01024`` / ``validation-00000-of-00128`` (no extension),
# each example carrying a JPEG under ``image/encoded`` and a 1-based label
# (0 = background, hence the reference's ``--labels-offset`` knob) under
# ``image/class/label``.  Decode is PIL (TF-free), like the PNG codec above.

import re as _re

_IMAGENET_SHARD = {"train": _re.compile(r"^train-\d{5}-of-\d{5}$"),
                   "validation": _re.compile(r"^validation-\d{5}-of-\d{5}$")}


def jpeg_decode(data, image_size=None):
    """JPEG bytes -> (h, w, 3) uint8; optionally resized to a square."""
    import io

    from PIL import Image

    with Image.open(io.BytesIO(data)) as img:
        img = img.convert("RGB")
        if image_size is not None and img.size != (image_size, image_size):
            img = img.resize((image_size, image_size), Image.BILINEAR)
        return np.asarray(img, dtype=np.uint8)


def jpeg_encode(array, quality=90):
    """(h, w, 3) uint8 -> JPEG bytes (fixture writer)."""
    import io

    from PIL import Image

    out = io.BytesIO()
    Image.fromarray(np.asarray(array, dtype=np.uint8)).save(out, format="JPEG", quality=quality)
    return out.getvalue()


def imagenet_shards(directory, split):
    """Sorted shard paths of one split under the slim naming convention."""
    pattern = _IMAGENET_SHARD[split]
    try:
        names = sorted(n for n in os.listdir(directory) if pattern.match(n))
    except OSError:
        return []
    return [os.path.join(directory, n) for n in names]


def has_imagenet_tfrecords(directory):
    return bool(imagenet_shards(directory, "train")) and bool(
        imagenet_shards(directory, "validation")
    )


def read_imagenet_split(directory, split, image_size, limit=None):
    """Stream slim ImageNet shards -> (uint8 (n, s, s, 3), int32 labels).

    ``limit`` caps the example count (full ImageNet does not fit host RAM as
    a dense array; the capped subset is REAL data — decoded, resized — and
    the loader states the cap).  Shards are consumed in name order so the
    subset is deterministic."""
    images, labels = [], []
    for path in imagenet_shards(directory, split):
        for payload in iter_tfrecords(path):
            example = parse_example(payload)
            fmt = example.get("image/format", [b"JPEG"])[0]
            encoded = example["image/encoded"][0]
            if fmt in (b"png", b"PNG"):
                image = png_decode(encoded)
                if image.shape[:2] != (image_size, image_size):
                    image = jpeg_decode(png_encode(image), image_size)  # resize path
            else:
                image = jpeg_decode(encoded, image_size)
            images.append(image)
            labels.append(int(example["image/class/label"][0]))
            if limit is not None and len(images) >= limit:
                return np.stack(images), np.asarray(labels, dtype=np.int32)
    if not images:
        raise UserException(
            "No %s examples under %r (expected slim-layout shards like "
            "train-00000-of-01024)" % (split, directory)
        )
    return np.stack(images), np.asarray(labels, dtype=np.int32)


def write_imagenet_split(directory, split, images, labels, nb_shards=2):
    """Write slim-layout ImageNet shards (fixtures, tests)."""
    os.makedirs(directory, exist_ok=True)
    chunks = np.array_split(np.arange(len(images)), nb_shards)
    paths = []
    for shard_index, chunk in enumerate(chunks):
        path = os.path.join(
            directory, "%s-%05d-of-%05d" % (split, shard_index, nb_shards)
        )

        def payloads(chunk=chunk):
            for i in chunk:
                yield build_example({
                    "image/encoded": jpeg_encode(images[i]),
                    "image/format": b"JPEG",
                    "image/class/label": int(labels[i]),
                    "image/height": int(images[i].shape[0]),
                    "image/width": int(images[i].shape[1]),
                })

        write_tfrecords(path, payloads())
        paths.append(path)
    return paths

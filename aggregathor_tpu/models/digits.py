"""Digits MLP experiment: REAL data on a zero-egress box.

Same shape as the mnist experiment (reference: experiments/mnist.py:83-148 —
one hidden ReLU layer, sparse softmax cross-entropy, full-test-set top-1
accuracy), but backed by the REAL UCI hand-written digits set bundled inside
scikit-learn (1797 8x8 images; see datasets.load_digits8x8).  This is the
repo's real-data accuracy anchor: every other vision experiment on this box
trains a synthetic stand-in, so committed accuracy numbers (convergence,
robustness-under-attack) that must mean something against the literature run
here.  An MLP of this shape reaches ~96% test accuracy on the 80/20 split
under Multi-Krum (97% under plain averaging, docs/robustness.md); the
loss/metrics/iterator machinery is inherited from MNISTExperiment — only the
corpus and the input shape differ.
"""

from . import register
from .datasets import load_digits8x8
from .mnist import MNISTExperiment


class DigitsExperiment(MNISTExperiment):
    sample_shape = (8, 8, 1)
    load_dataset = staticmethod(load_digits8x8)


register("digits", DigitsExperiment)

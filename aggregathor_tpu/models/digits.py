"""Digits MLP experiment: REAL data on a zero-egress box.

Same shape as the mnist experiment (reference: experiments/mnist.py:83-148 —
one hidden ReLU layer, sparse softmax cross-entropy, full-test-set top-1
accuracy), but backed by the REAL UCI hand-written digits set bundled inside
scikit-learn (1797 8x8 images; see datasets.load_digits8x8).  This is the
repo's real-data accuracy anchor: every other vision experiment on this box
trains a synthetic stand-in, so committed accuracy numbers (convergence,
robustness-under-attack) that must mean something against the literature run
here.  An MLP of this shape reaches ~96% test accuracy on the 80/20 split
under Multi-Krum (97% under plain averaging, docs/robustness.md); the
loss/metrics/iterator machinery is inherited from MNISTExperiment — only the
corpus and the input shape differ.
"""

from . import register
from .datasets import load_digits8x8, load_digits_upscaled
from .mnist import MNISTExperiment


class DigitsExperiment(MNISTExperiment):
    sample_shape = (8, 8, 1)
    load_dataset = staticmethod(load_digits8x8)


class DigitsConvExperiment(DigitsExperiment):
    """The reference's flagship conv topology on REAL data.

    The reference's headline experiment is cnnet on CIFAR-10
    (experiments/cnnet.py:115-146); real CIFAR bytes are unobtainable on
    this box, so the SAME conv stack (models/cnnet.CNNet: 2x conv5x5-64 +
    3x3/2 max-pools, dense 384/192 — experiments/cnnet.py:137-146) trains
    on the real digits corpus upscaled to 32x32 — the conv-scale
    real-data accuracy anchor (docs/robustness.md)."""

    sample_shape = (32, 32, 1)
    load_dataset = staticmethod(load_digits_upscaled)

    def __init__(self, args):
        super().__init__(args)
        from .cnnet import CNNet

        self.model = CNNet(classes=self.dataset.nb_classes)


register("digits", DigitsExperiment)
register("digits-conv", DigitsConvExperiment)

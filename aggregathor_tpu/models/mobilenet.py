"""MobileNet v1 + v2 families, TPU-first.

Capability parity with the reference's slim nets_factory entries
``mobilenet_v1`` / ``mobilenet_v1_075`` / ``mobilenet_v1_050`` /
``mobilenet_v1_025`` and ``mobilenet_v2`` / ``mobilenet_v2_140`` /
``mobilenet_v2_035`` (external/slim/nets/nets_factory.py:39-60) — written
fresh as flax modules with the same design stance as resnet.py (GroupNorm
instead of BatchNorm, NHWC, mixed-precision via ``dtype``).

Depthwise separable convolutions map to ``nn.Conv`` with
``feature_group_count=channels`` — XLA lowers these to the TPU's native
depthwise convolution path.
"""

import flax.linen as nn
import jax
import jax.numpy as jnp

from .common import group_norm as _norm, resize_min


class SeparableBlock(nn.Module):
    """3x3 depthwise + 1x1 pointwise, each with norm + ReLU."""

    features: int
    stride: int = 1
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        channels = x.shape[-1]
        y = nn.Conv(
            channels,
            (3, 3),
            (self.stride, self.stride),
            padding="SAME",
            feature_group_count=channels,
            use_bias=False,
            dtype=self.dtype,
            name="depthwise",
        )(x)
        y = nn.relu(_norm(y, "dw_norm", self.dtype))
        y = nn.Conv(self.features, (1, 1), use_bias=False, dtype=self.dtype, name="pointwise")(y)
        return nn.relu(_norm(y, "pw_norm", self.dtype))


# (filters, stride) after the stem conv — the standard v1 body
_V1_BODY = [
    (64, 1),
    (128, 2),
    (128, 1),
    (256, 2),
    (256, 1),
    (512, 2),
    (512, 1),
    (512, 1),
    (512, 1),
    (512, 1),
    (512, 1),
    (1024, 2),
    (1024, 1),
]

MOBILENET_MULTIPLIERS = {
    "mobilenet_v1": 1.0,
    "mobilenet_v1_075": 0.75,
    "mobilenet_v1_050": 0.5,
    "mobilenet_v1_025": 0.25,
}


class MobileNetV1(nn.Module):
    """MobileNet v1 classifier with a width (depth) multiplier."""

    classes: int = 1000
    multiplier: float = 1.0
    dtype: jnp.dtype = jnp.float32
    min_size: int = 64

    @nn.compact
    def __call__(self, x):
        d = self.dtype
        x = resize_min(x, self.min_size).astype(d)

        def width(f):
            return max(8, int(f * self.multiplier))

        x = nn.Conv(width(32), (3, 3), (2, 2), padding="SAME", use_bias=False, dtype=d, name="stem")(x)
        x = nn.relu(_norm(x, "stem_norm", d))
        for i, (filters, stride) in enumerate(_V1_BODY):
            x = SeparableBlock(width(filters), stride, dtype=d, name="sep_%d" % i)(x)
        x = jnp.mean(x, axis=(1, 2)).astype(jnp.float32)  # global average pool
        return nn.Dense(self.classes, dtype=jnp.float32, name="logits")(x)


class InvertedResidual(nn.Module):
    """v2 bottleneck: 1x1 expand -> 3x3 depthwise -> 1x1 linear project,
    residual when stride 1 and channels match.  ReLU6 as in the paper."""

    features: int
    stride: int = 1
    expand: int = 6
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        d = self.dtype
        channels = x.shape[-1]
        y = x
        hidden = channels * self.expand
        if self.expand != 1:
            y = nn.Conv(hidden, (1, 1), use_bias=False, dtype=d, name="expand")(y)
            y = jax.nn.relu6(_norm(y, "expand_norm", d))
        y = nn.Conv(hidden, (3, 3), (self.stride, self.stride), padding="SAME",
                    feature_group_count=hidden, use_bias=False, dtype=d, name="depthwise")(y)
        y = jax.nn.relu6(_norm(y, "dw_norm", d))
        y = nn.Conv(self.features, (1, 1), use_bias=False, dtype=d, name="project")(y)
        y = _norm(y, "project_norm", d)  # linear bottleneck: no activation
        if self.stride == 1 and channels == self.features:
            y = x + y
        return y


# (expansion t, channels c, repeats n, first stride s) — the v2 paper body
_V2_BODY = [
    (1, 16, 1, 1),
    (6, 24, 2, 2),
    (6, 32, 3, 2),
    (6, 64, 4, 2),
    (6, 96, 3, 1),
    (6, 160, 3, 2),
    (6, 320, 1, 1),
]

MOBILENET_V2_MULTIPLIERS = {
    "mobilenet_v2": 1.0,
    "mobilenet_v2_140": 1.4,
    "mobilenet_v2_035": 0.35,
}


class MobileNetV2(nn.Module):
    """MobileNet v2 classifier with a width multiplier.

    As in the paper/slim, the width multiplier scales every layer except the
    final 1280-channel head, which only scales *up* (multiplier > 1).
    """

    classes: int = 1000
    multiplier: float = 1.0
    dtype: jnp.dtype = jnp.float32
    min_size: int = 64

    @nn.compact
    def __call__(self, x):
        d = self.dtype
        x = resize_min(x, self.min_size).astype(d)

        def width(f):
            # slim's make_divisible: round to /8, never below 90% of the target
            v = f * self.multiplier
            new = max(8, int(v + 4) // 8 * 8)
            return new + 8 if new < 0.9 * v else new

        x = nn.Conv(width(32), (3, 3), (2, 2), padding="SAME", use_bias=False, dtype=d, name="stem")(x)
        x = jax.nn.relu6(_norm(x, "stem_norm", d))
        i = 0
        for expand, channels, repeats, stride in _V2_BODY:
            for r in range(repeats):
                x = InvertedResidual(width(channels), stride if r == 0 else 1, expand,
                                     dtype=d, name="block_%d" % i)(x)
                i += 1
        head = width(1280) if self.multiplier > 1.0 else 1280
        x = nn.Conv(head, (1, 1), use_bias=False, dtype=d, name="head")(x)
        x = jax.nn.relu6(_norm(x, "head_norm", d))
        x = jnp.mean(x, axis=(1, 2)).astype(jnp.float32)
        return nn.Dense(self.classes, dtype=jnp.float32, name="logits")(x)

"""MobileNet v1 family (depth multipliers 1.0 / 0.75 / 0.5 / 0.25), TPU-first.

Capability parity with the reference's slim nets_factory entries
``mobilenet_v1`` / ``mobilenet_v1_075`` / ``mobilenet_v1_050`` /
``mobilenet_v1_025`` (external/slim/nets/nets_factory.py:39-60) — written
fresh as flax modules with the same design stance as resnet.py (GroupNorm
instead of BatchNorm, NHWC, mixed-precision via ``dtype``).

Depthwise separable convolutions map to ``nn.Conv`` with
``feature_group_count=channels`` — XLA lowers these to the TPU's native
depthwise convolution path.
"""

import flax.linen as nn
import jax.numpy as jnp

from .common import group_norm as _norm, resize_min


class SeparableBlock(nn.Module):
    """3x3 depthwise + 1x1 pointwise, each with norm + ReLU."""

    features: int
    stride: int = 1
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        channels = x.shape[-1]
        y = nn.Conv(
            channels,
            (3, 3),
            (self.stride, self.stride),
            padding="SAME",
            feature_group_count=channels,
            use_bias=False,
            dtype=self.dtype,
            name="depthwise",
        )(x)
        y = nn.relu(_norm(y, "dw_norm", self.dtype))
        y = nn.Conv(self.features, (1, 1), use_bias=False, dtype=self.dtype, name="pointwise")(y)
        return nn.relu(_norm(y, "pw_norm", self.dtype))


# (filters, stride) after the stem conv — the standard v1 body
_V1_BODY = [
    (64, 1),
    (128, 2),
    (128, 1),
    (256, 2),
    (256, 1),
    (512, 2),
    (512, 1),
    (512, 1),
    (512, 1),
    (512, 1),
    (512, 1),
    (1024, 2),
    (1024, 1),
]

MOBILENET_MULTIPLIERS = {
    "mobilenet_v1": 1.0,
    "mobilenet_v1_075": 0.75,
    "mobilenet_v1_050": 0.5,
    "mobilenet_v1_025": 0.25,
}


class MobileNetV1(nn.Module):
    """MobileNet v1 classifier with a width (depth) multiplier."""

    classes: int = 1000
    multiplier: float = 1.0
    dtype: jnp.dtype = jnp.float32
    min_size: int = 64

    @nn.compact
    def __call__(self, x):
        d = self.dtype
        x = resize_min(x, self.min_size).astype(d)

        def width(f):
            return max(8, int(f * self.multiplier))

        x = nn.Conv(width(32), (3, 3), (2, 2), padding="SAME", use_bias=False, dtype=d, name="stem")(x)
        x = nn.relu(_norm(x, "stem_norm", d))
        for i, (filters, stride) in enumerate(_V1_BODY):
            x = SeparableBlock(width(filters), stride, dtype=d, name="sep_%d" % i)(x)
        x = jnp.mean(x, axis=(1, 2)).astype(jnp.float32)  # global average pool
        return nn.Dense(self.classes, dtype=jnp.float32, name="logits")(x)

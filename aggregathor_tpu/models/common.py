"""Shared building blocks for the image-model families."""

import flax.linen as nn
import jax
import jax.numpy as jnp

from ..utils import UserException

#: the compute dtypes experiments accept (params always stay float32)
COMPUTE_DTYPES = {"float32": jnp.float32, "bfloat16": jnp.bfloat16}


def check_dtype(name):
    """Validate a ``dtype:`` experiment arg at construction time (fail fast
    with a clean UserException instead of a numpy TypeError mid-build, and
    never silently coerce — ``dtype:bf16`` or ``dtype:int32`` must not
    quietly train in float32 or truncate images to zeros)."""
    if name not in COMPUTE_DTYPES:
        raise UserException(
            "Unknown dtype %r (accepted: %s)" % (name, ", ".join(sorted(COMPUTE_DTYPES)))
        )
    return COMPUTE_DTYPES[name]


def group_norm(x, name, dtype):
    """GroupNorm with the largest group count <= 32 that divides the channels.

    Stateless BatchNorm replacement — see models/resnet.py's docstring for why
    the Byzantine-DP setting rules out mutable batch statistics.
    """
    groups = min(32, x.shape[-1])
    while x.shape[-1] % groups:
        groups -= 1
    return nn.GroupNorm(num_groups=groups, dtype=dtype, name=name)(x)


def resize_min(x, min_size):
    """Bilinearly upsample NHWC images below ``min_size`` (e.g. CIFAR 32x32
    into an ImageNet-shaped stem), instead of failing like slim's
    VALID-padded stems do on small inputs."""
    if x.shape[1] < min_size or x.shape[2] < min_size:
        x = jax.image.resize(x, (x.shape[0], min_size, min_size, x.shape[3]), "bilinear")
    return x

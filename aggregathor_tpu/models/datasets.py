"""Input pipelines: real data when present, deterministic synthetic otherwise.

The reference pulls MNIST through keras' downloader
(experiments/mnist.py:51-81) and CIFAR-10 from TF-Slim TFRecords on local
disk (experiments/cnnet.py:115-146).  This environment has zero egress, so
each loader first looks for a local ``.npz`` file (search order: the
``AGGREGATHOR_DATA`` env dir, ``~/.aggregathor/data``, ``./data``) and
otherwise *derives a deterministic synthetic stand-in*: class-conditional
Gaussian images whose per-class means are fixed random templates.  The
synthetic sets are honestly learnable (a linear model separates them), which
is exactly what the convergence smoke tests need, and every consumer is told
which flavour it got via ``.synthetic``.

File formats accepted: ``mnist.npz`` with x_train/y_train/x_test/y_test (the
keras layout), ``cifar10.npz`` with the same keys.

All pipelines are numpy-side (host) and hand worker-major device batches to
the engine; on TPU the transfer is one host->device copy per step, the
equivalent of the reference's dataset-on-task-CPU placement (graph.py:248-252).
"""

import os

import numpy as np

from ..utils import UserException, can_access, info, warning


def _data_dirs():
    dirs = []
    env = os.environ.get("AGGREGATHOR_DATA")
    if env:
        dirs.append(env)
    dirs.append(os.path.expanduser("~/.aggregathor/data"))
    dirs.append(os.path.join(os.getcwd(), "data"))
    return dirs


def _find_npz(basename, subdirs=None):
    """Probe <data>/<basename> plus <data>/<subdir>/<basename> for each
    candidate subdir (default: the basename's stem — where the CIFAR-10
    TFRecord fallback writes its cache; ImageNet passes 'imagenet' since its
    cache name carries size/cap suffixes the shard directory does not)."""
    stem = basename.split(".")[0]
    subdirs = (stem,) if subdirs is None else tuple(subdirs)
    for dirname in _data_dirs():
        for path in [os.path.join(dirname, basename)] + [
            os.path.join(dirname, sub, basename) for sub in subdirs
        ]:
            if os.path.isfile(path):
                return path
    return None


class ArrayDataset:
    """An in-memory labeled dataset split into train/test."""

    def __init__(self, x_train, y_train, x_test, y_test, nb_classes, synthetic):
        self.x_train = x_train
        self.y_train = y_train
        self.x_test = x_test
        self.y_test = y_test
        self.nb_classes = nb_classes
        self.synthetic = synthetic


def _synthetic_classification(name, shape, nb_classes, nb_train, nb_test, seed, separation=2.0):
    """Class-conditional Gaussians around fixed random unit templates."""
    rng = np.random.default_rng(seed)
    templates = rng.normal(size=(nb_classes,) + shape).astype(np.float32)
    templates /= np.linalg.norm(templates.reshape(nb_classes, -1), axis=1).reshape((-1,) + (1,) * len(shape))

    def make(count, split_seed):
        r = np.random.default_rng(split_seed)
        labels = r.integers(0, nb_classes, size=count)
        noise = r.normal(size=(count,) + shape).astype(np.float32)
        images = separation * templates[labels] + noise
        return images.astype(np.float32), labels.astype(np.int32)

    x_train, y_train = make(nb_train, seed + 1)
    x_test, y_test = make(nb_test, seed + 2)
    warning(
        "Dataset %r not found on disk; using a deterministic synthetic stand-in "
        "(drop an %s.npz under $AGGREGATHOR_DATA to use real data)" % (name, name)
    )
    return ArrayDataset(x_train, y_train, x_test, y_test, nb_classes, synthetic=True)


def _head_size(requested, y_train, y_test, name):
    """Class count for the model head: covers BOTH the requested class count
    and every label actually observed (train AND test).  Sizing from the
    train subset's max alone would let take_along_axis clamp out-of-range
    labels into silently wrong nll/accuracy (ADVICE r3); one shared helper so
    the decode path and the npz-cache path can never disagree about the head."""
    # train-only caches / limit_test=0 yield empty splits: np.max over a
    # zero-size array has no identity, so only non-empty splits vote
    seen = max(
        [int(np.max(y)) + 1 for y in (y_train, y_test) if np.size(y)] or [1]
    )
    if requested and seen < requested:
        warning(
            "%s labels only cover %d of the requested %d classes; keeping the "
            "%d-way head (subset accuracy is not full-dataset accuracy)"
            % (name, seen, requested, requested)
        )
    return max(int(requested or 0), seen)


def _load_npz(path, shape, scale, nb_classes=None):
    import zipfile

    try:
        data = np.load(path)
    except (OSError, ValueError, zipfile.BadZipFile) as exc:
        # A clear startup message instead of a mid-pipeline traceback, like
        # the reference's up-front dir validation (tools/access.py); covers
        # unreadable files AND corrupt/truncated archives.
        raise UserException("Cannot load dataset %r: %s" % (path, exc))
    def prep(x):
        x = x.astype(np.float32) / scale
        return x.reshape((x.shape[0],) + shape)
    info("Loaded dataset from %s" % path)
    y_train = data["y_train"].astype(np.int32).ravel()
    y_test = data["y_test"].astype(np.int32).ravel()
    return ArrayDataset(
        prep(data["x_train"]), y_train, prep(data["x_test"]), y_test,
        nb_classes=_head_size(nb_classes, y_train, y_test, os.path.basename(path)),
        synthetic=False,
    )


def load_mnist():
    """28x28x1 digits in [0, 1]; real file or synthetic stand-in."""
    path = _find_npz("mnist.npz")
    if path:
        return _load_npz(path, (28, 28, 1), 255.0, nb_classes=10)
    return _synthetic_classification("mnist", (28, 28, 1), 10, nb_train=8192, nb_test=2048, seed=7)


def load_digits8x8(train_fraction=0.8, seed=11):
    """REAL handwritten digits: the UCI ML hand-written digits set (1797
    8x8 grayscale images, 10 classes) bundled INSIDE scikit-learn — the one
    real vision dataset reachable on a zero-egress box.

    Same role as the reference's real-MNIST path (experiments/mnist.py:51-81
    downloads via keras): a genuine accuracy target instead of a synthetic
    stand-in.  Deterministic seeded shuffle then an 80/20 split; pixels are
    0..16 ints, normalized to [0, 1].  Resolution order: a digits.npz under
    $AGGREGATHOR_DATA (so the _synthetic_classification recovery hint is a
    live path), then sklearn, then the synthetic stand-in (flagged via
    ``.synthetic``), mirroring the 1797-image corpus at the same split.
    """
    path = _find_npz("digits.npz")
    if path:
        return _load_npz(path, (8, 8, 1), 16.0, nb_classes=10)
    nb_train = int(1797 * train_fraction)
    try:
        from sklearn.datasets import load_digits as _sk_load_digits
    except ImportError:
        return _synthetic_classification(
            "digits", (8, 8, 1), 10, nb_train=nb_train, nb_test=1797 - nb_train,
            seed=seed)
    bunch = _sk_load_digits()
    images = (bunch.images.astype(np.float32) / 16.0).reshape(-1, 8, 8, 1)
    labels = bunch.target.astype(np.int32)
    order = np.random.default_rng(seed).permutation(len(labels))
    images, labels = images[order], labels[order]
    split = int(len(labels) * train_fraction)
    info("Loaded REAL sklearn digits: %d train / %d test" % (split, len(labels) - split))
    return ArrayDataset(
        images[:split], labels[:split], images[split:], labels[split:],
        nb_classes=10, synthetic=False,
    )


def load_digits_upscaled(size=32, train_fraction=0.8, seed=11):
    """The REAL digits corpus upscaled to ``size``x``size`` (nearest-
    neighbor, integer factor) — conv-topology input on real data.

    Purpose (VERDICT r4 task 3): the reference's flagship experiment is a
    conv net on real CIFAR-10 (experiments/cnnet.py:115-146), but the real
    CIFAR bytes are unobtainable on this zero-egress box (the reference's
    own dataset symlinks dangle — docs/robustness.md "Why not real
    CIFAR-10").  Nearest-neighbor upscaling adds no information, so
    accuracies here measure the conv stack on genuine handwriting, not an
    interpolation artifact."""
    base = load_digits8x8(train_fraction=train_fraction, seed=seed)
    if size % 8:
        raise ValueError("size must be a multiple of 8 (got %d)" % size)
    k = size // 8

    def up(x):
        return np.repeat(np.repeat(x, k, axis=1), k, axis=2)

    return ArrayDataset(
        up(base.x_train), base.y_train, up(base.x_test), base.y_test,
        nb_classes=base.nb_classes, synthetic=base.synthetic,
    )


def _find_cifar10_tfrecords():
    from .tfrecord import has_cifar10_tfrecords

    for dirname in _data_dirs():
        for candidate in (dirname, os.path.join(dirname, "cifar10")):
            if has_cifar10_tfrecords(candidate):
                if not can_access(candidate, read=True):
                    warning("CIFAR-10 shards at %r are not readable; skipping" % candidate)
                    continue
                return candidate
    return None


def load_cifar10():
    """32x32x3 images in [0, 1]; real data (npz, or the reference's slim
    TFRecord shards — experiments/cnnet.py:115-146) or synthetic stand-in."""
    path = _find_npz("cifar10.npz")
    if path:
        return _load_npz(path, (32, 32, 3), 255.0, nb_classes=10)
    tfr_dir = _find_cifar10_tfrecords()
    if tfr_dir:
        from .tfrecord import read_cifar10_split

        x_train, y_train = read_cifar10_split(tfr_dir, "train")
        x_test, y_test = read_cifar10_split(tfr_dir, "test")
        info("Loaded CIFAR-10 TFRecord shards from %s" % tfr_dir)
        # Parsing 60k PNG records through the pure-Python codec costs minutes;
        # cache as the preferred npz so the next run short-circuits above.
        cache = os.path.join(tfr_dir, "cifar10.npz")
        try:
            np.savez_compressed(cache, x_train=x_train, y_train=y_train,
                                x_test=x_test, y_test=y_test)
            info("Cached npz at %s" % cache)
        except OSError:
            pass  # read-only data dir: pay the parse each run
        return ArrayDataset(
            x_train.astype(np.float32) / 255.0, y_train,
            x_test.astype(np.float32) / 255.0, y_test,
            # CIFAR-10 is 10 classes by definition; _head_size guards against
            # a truncated shard set whose subset misses the top labels
            nb_classes=_head_size(10, y_train, y_test, "CIFAR-10"),
            synthetic=False,
        )
    return _synthetic_classification("cifar10", (32, 32, 3), 10, nb_train=8192, nb_test=2048, seed=11)


def load_imagenet_standin(image_size=224, nb_classes=1000):
    """Synthetic ImageNet-shaped data (the slims experiments' scale axis).

    Sized for throughput benchmarking, not accuracy: 512 train images at
    224x224x3 float32 is ~300 MB of host RAM; the model only ever sees
    sampled batches so epoch coverage is irrelevant here.
    """
    return _synthetic_classification(
        "imagenet%d" % image_size, (image_size, image_size, 3), nb_classes,
        nb_train=512, nb_test=128, seed=13,
    )


def _find_imagenet_tfrecords():
    from .tfrecord import has_imagenet_tfrecords

    for dirname in _data_dirs():
        for candidate in (dirname, os.path.join(dirname, "imagenet")):
            if has_imagenet_tfrecords(candidate):
                if not can_access(candidate, read=True):
                    warning("ImageNet shards at %r are not readable; skipping" % candidate)
                    continue
                return candidate
    return None


def load_imagenet(image_size=224, nb_classes=1000, limit_train=4096, limit_test=1024):
    """REAL slim-layout TFRecord ImageNet when shards are on disk
    (reference: experiments/slims.py:98-111 + experiments/datasets/imagenet),
    decoded with PIL and resized to ``image_size``; otherwise the synthetic
    stand-in with its loud warning.

    Full ImageNet does not fit host RAM as a dense array, so the real path
    loads a DETERMINISTIC CAPPED SUBSET (first ``limit_train``/``limit_test``
    examples in shard order) — real pixels for throughput benchmarking and
    smoke accuracy, stated in the log line.  The decoded subset is cached as
    an npz next to the other dataset caches so subsequent runs skip the
    JPEG decode."""
    # The cache key encodes the caps too: a smoke run's tiny cache must not
    # silently satisfy a later request for the full benchmark subset.
    cache_name = "imagenet%d-t%d-v%d.npz" % (image_size, limit_train, limit_test)
    path = _find_npz(cache_name, subdirs=("imagenet",))
    if path:
        return _load_npz(path, (image_size, image_size, 3), 255.0, nb_classes=nb_classes)
    tfr_dir = _find_imagenet_tfrecords()
    if tfr_dir:
        from .tfrecord import read_imagenet_split

        x_train, y_train = read_imagenet_split(tfr_dir, "train", image_size, limit=limit_train)
        x_test, y_test = read_imagenet_split(tfr_dir, "validation", image_size, limit=limit_test)
        info(
            "Loaded ImageNet TFRecord shards from %s (capped subset: %d train / "
            "%d validation examples at %dx%d)"
            % (tfr_dir, len(x_train), len(x_test), image_size, image_size)
        )
        cache = os.path.join(tfr_dir, cache_name)
        try:
            np.savez_compressed(cache, x_train=x_train, y_train=y_train,
                                x_test=x_test, y_test=y_test)
            info("Cached npz at %s" % cache)
        except OSError:
            pass  # read-only data dir: pay the decode each run
        # slim ImageNet labels are 1-based with 0 = background (1001 classes
        # for the full set; the reference's --labels-offset knob exists for
        # models that drop background).  The capped subset may not contain
        # the top label ids — _head_size covers both the requested count and
        # every observed label (train AND validation).
        return ArrayDataset(
            x_train.astype(np.float32) / 255.0, y_train,
            x_test.astype(np.float32) / 255.0, y_test,
            nb_classes=_head_size(nb_classes, y_train, y_test, "ImageNet subset"),
            synthetic=False,
        )
    return load_imagenet_standin(image_size, nb_classes)


class WorkerBatchIterator:
    """Infinite iterator of worker-major batches [n_workers, batch, ...].

    Each worker draws its own i.i.d. sample stream (the reference gives each
    task its own dataset pipeline, graph.py:224-233); a per-worker seed keeps
    streams independent and runs reproducible.
    """

    def __init__(self, x, y, nb_workers, batch_size, seed=0, transform=None):
        self.x, self.y = x, y
        self.nb_workers = nb_workers
        self.batch_size = batch_size
        # one stream per worker: worker w's sample sequence is a function of
        # (seed, w) only, independent of nb_workers or other workers
        self.rngs = [np.random.default_rng([seed, w]) for w in range(nb_workers)]
        self.transform = transform

    def __iter__(self):
        return self

    def __next__(self):
        idx = np.stack([rng.integers(0, self.x.shape[0], size=self.batch_size) for rng in self.rngs])
        flat = idx.reshape(-1)
        bx = self.x[flat].reshape((self.nb_workers, self.batch_size) + self.x.shape[1:])
        by = self.y[flat].reshape(self.nb_workers, self.batch_size)
        if self.transform is not None:
            bx, by = self.transform(bx, by)
        return {"image": bx, "label": by}

    def skip(self, k):
        """Advance every worker's sample stream by ``k`` batches without
        gathering data — the resume fast-forward (cli/runner.py): after
        restoring step S, the stream must sit exactly where an
        uninterrupted run's would, so the resumed trajectory is
        bit-identical.  Stateful host transforms (preprocessing.py per-worker
        augmentation streams) must advance in lockstep, so with a transform
        the full draw path is kept."""
        k = int(k)
        if self.transform is not None:
            for _ in range(k):
                next(self)
            return
        for _ in range(k):
            for rng in self.rngs:
                rng.integers(0, self.x.shape[0], size=self.batch_size)

    def next_many(self, k):
        """K batches in one call: a (k, nb_workers, batch, ...) stack.

        Sample streams are identical to k successive ``next()`` calls (each
        batch's indices are drawn per worker in the same order); the speedup
        is doing ONE gather into a contiguous stack instead of k gathers plus
        an ``np.stack`` re-copy — at CIFAR bench scale (k=20, n=8, b=128)
        that re-copy alone cost seconds per chunk.  With a host ``transform``
        the per-batch path is kept (host augmentation is per-batch seeded);
        the fast path serves device-side augmentation (preprocessing.py
        ``device_transform``), where the host's only job is the gather.
        """
        if self.transform is not None:
            batches = [next(self) for _ in range(k)]
            return {
                name: np.stack([b[name] for b in batches]) for name in batches[0]
            }
        # (k, n, b) index block, worker streams drawn batch-major like next()
        idx = np.empty((k, self.nb_workers, self.batch_size), dtype=np.int64)
        for step in range(k):
            for w, rng in enumerate(self.rngs):
                idx[step, w] = rng.integers(0, self.x.shape[0], size=self.batch_size)
        flat = idx.reshape(-1)
        bx = self.x[flat].reshape((k, self.nb_workers, self.batch_size) + self.x.shape[1:])
        by = self.y[flat].reshape(k, self.nb_workers, self.batch_size)
        return {"image": bx, "label": by}


def eval_batches(x, y, nb_workers, batch_size):
    """Finite worker-major pass over an eval split (pads by wrapping)."""
    per_step = nb_workers * batch_size
    total = x.shape[0]
    for start in range(0, total, per_step):
        idx = np.arange(start, start + per_step) % total
        # mark wrapped duplicates so metric counts stay exact
        valid = (np.arange(start, start + per_step) < total)
        bx = x[idx].reshape((nb_workers, batch_size) + x.shape[1:])
        by = y[idx].reshape(nb_workers, batch_size)
        yield {"image": bx, "label": by, "valid": valid.reshape(nb_workers, batch_size)}


class _PrefetchError:
    def __init__(self, exc):
        self.exc = exc


class DevicePrefetcher:
    """Background-thread input prefetch: overlaps host-side batch assembly
    and host->device transfer with device compute.

    The reference hides its input path behind TF queue runners with
    fetcher/batcher threads and a prefetch queue (experiments/cnnet.py:115-146);
    the JAX equivalent is this double buffer: a daemon thread pulls host
    batches from ``iterator``, applies ``put`` (e.g. ``engine.shard_batch`` —
    ``jax.device_put`` is thread-safe and asynchronous), and keeps up to
    ``depth`` device-resident batches ready for the training loop.
    """

    def __init__(self, iterator, put, depth=2):
        import queue
        import threading

        self._queue = queue.Queue(maxsize=max(1, int(depth)))
        self._iterator = iterator
        self._put = put
        self._stop = threading.Event()
        self._terminal = None  # remembered end-of-stream / producer error
        self._thread = threading.Thread(target=self._run, daemon=True, name="prefetch")
        self._thread.start()

    def _run(self):
        try:
            for batch in self._iterator:
                if self._stop.is_set():
                    return
                device_batch = self._put(batch)
                if self._stop.is_set():
                    return
                self._queue.put(device_batch)
            self._queue.put(_PrefetchError(StopIteration()))
        except BaseException as exc:  # surfaced on the consumer side
            self._queue.put(_PrefetchError(exc))

    def __iter__(self):
        return self

    def __next__(self):
        if self._terminal is not None:  # iterator protocol: stay terminal
            raise self._terminal
        item = self._queue.get()
        if isinstance(item, _PrefetchError):
            self._terminal = item.exc
            raise item.exc
        return item

    def close(self):
        """Stop and join the worker; no batch stays pinned afterwards.

        The drain loop keeps the queue unblocked while the producer winds
        down (it may complete one last ``put``), then the join makes the
        shutdown terminal — no in-flight ``device_put`` can race a
        subsequent run's setup.
        """
        import queue
        import time

        self._stop.set()
        self._terminal = StopIteration()
        # bounded: a producer stuck inside the wrapped iterator cannot be
        # interrupted — it is a daemon thread and dies with the process
        deadline = time.monotonic() + 5.0
        while self._thread.is_alive() and time.monotonic() < deadline:
            try:
                while True:
                    self._queue.get_nowait()
            except queue.Empty:
                pass
            self._thread.join(timeout=0.1)
        try:
            while True:
                self._queue.get_nowait()
        except queue.Empty:
            pass

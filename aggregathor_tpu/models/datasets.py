"""Input pipelines: real data when present, deterministic synthetic otherwise.

The reference pulls MNIST through keras' downloader
(experiments/mnist.py:51-81) and CIFAR-10 from TF-Slim TFRecords on local
disk (experiments/cnnet.py:115-146).  This environment has zero egress, so
each loader first looks for a local ``.npz`` file (search order: the
``AGGREGATHOR_DATA`` env dir, ``~/.aggregathor/data``, ``./data``) and
otherwise *derives a deterministic synthetic stand-in*: class-conditional
Gaussian images whose per-class means are fixed random templates.  The
synthetic sets are honestly learnable (a linear model separates them), which
is exactly what the convergence smoke tests need, and every consumer is told
which flavour it got via ``.synthetic``.

File formats accepted: ``mnist.npz`` with x_train/y_train/x_test/y_test (the
keras layout), ``cifar10.npz`` with the same keys.

All pipelines are numpy-side (host) and hand worker-major device batches to
the engine; on TPU the transfer is one host->device copy per step, the
equivalent of the reference's dataset-on-task-CPU placement (graph.py:248-252).
"""

import os
import threading

import numpy as np

from ..utils import UserException, can_access, info, warning

# --------------------------------------------------------------------- #
# Sharded host gather: the ~250 MB-per-chunk fancy-index gather of
# ``WorkerBatchIterator.next_many`` split into contiguous row ranges
# written concurrently via ``np.take(..., out=...)``.  The reference hid
# this work behind TF queue-runner fetcher/batcher thread pools
# (experiments/cnnet.py:115-146); this is the numpy-side equivalent, and
# with ``out=`` there is also no fresh ~250 MB allocation per chunk.

#: rows below this skip the pool entirely (thread dispatch costs more than
#: the copy it would parallelize)
_GATHER_POOL_MIN_ROWS = 4096

_gather_pool = None
_gather_pool_lock = threading.Lock()


def gather_threads():
    """Worker count for the sharded gather pool: ``AGGREGATHOR_GATHER_THREADS``
    or min(4, cpu_count).  0/1 disables the pool (single-shot gather)."""
    env = os.environ.get("AGGREGATHOR_GATHER_THREADS")
    if env is not None:
        try:
            return max(0, int(env))
        except ValueError:
            raise UserException(
                "AGGREGATHOR_GATHER_THREADS must be an integer (got %r)" % env
            )
    return min(4, os.cpu_count() or 1)


def _pool():
    global _gather_pool
    if _gather_pool is None:
        with _gather_pool_lock:
            if _gather_pool is None:
                from concurrent.futures import ThreadPoolExecutor

                _gather_pool = ThreadPoolExecutor(
                    max_workers=gather_threads(), thread_name_prefix="gather"
                )
    return _gather_pool


def sharded_take(src, indices, out):
    """``out[:] = src[indices]`` with the row copies sharded over the gather
    pool.  Bit-identical to the fancy index by construction (``np.take``
    writes the same rows; shards are disjoint contiguous ranges of ``out``).
    Falls back to one single-shot ``np.take`` for small gathers or when the
    pool is disabled."""
    nb = gather_threads()
    rows = indices.shape[0]
    if nb <= 1 or rows < _GATHER_POOL_MIN_ROWS:
        np.take(src, indices, axis=0, out=out)
        return out
    bounds = np.linspace(0, rows, nb + 1).astype(np.int64)
    futures = [
        _pool().submit(np.take, src, indices[lo:hi], 0, out[lo:hi])
        for lo, hi in zip(bounds[:-1], bounds[1:]) if hi > lo
    ]
    for future in futures:
        future.result()  # re-raises a shard's failure
    return out


def supports_buffered_next_many(iterator):
    """True when ``iterator.next_many`` accepts the ``out=`` buffer the
    ChunkPipeline's ping-pong gather needs.  Plugin iterators that copied
    the pre-pipeline ``next_many(k)`` signature stay on the legacy
    whole-chunk prefetch path instead of crashing in the producer."""
    next_many = getattr(iterator, "next_many", None)
    if next_many is None:
        return False
    import inspect

    try:
        return "out" in inspect.signature(next_many).parameters
    except (TypeError, ValueError):
        return False


def transform_is_stateless(transform):
    """True when ``transform`` declared itself stateless (``.stateless``):
    its output depends only on its inputs — it draws no RNG and keeps no
    call-count state — so skipping batches never needs to invoke it and
    batches may be produced out of order (models/preprocessing.py marks the
    identity tier; custom transforms opt in via ``stateless(fn)``)."""
    return transform is None or bool(getattr(transform, "stateless", False))


def _data_dirs():
    dirs = []
    env = os.environ.get("AGGREGATHOR_DATA")
    if env:
        dirs.append(env)
    dirs.append(os.path.expanduser("~/.aggregathor/data"))
    dirs.append(os.path.join(os.getcwd(), "data"))
    return dirs


def _find_npz(basename, subdirs=None):
    """Probe <data>/<basename> plus <data>/<subdir>/<basename> for each
    candidate subdir (default: the basename's stem — where the CIFAR-10
    TFRecord fallback writes its cache; ImageNet passes 'imagenet' since its
    cache name carries size/cap suffixes the shard directory does not)."""
    stem = basename.split(".")[0]
    subdirs = (stem,) if subdirs is None else tuple(subdirs)
    for dirname in _data_dirs():
        for path in [os.path.join(dirname, basename)] + [
            os.path.join(dirname, sub, basename) for sub in subdirs
        ]:
            if os.path.isfile(path):
                return path
    return None


class ArrayDataset:
    """An in-memory labeled dataset split into train/test."""

    def __init__(self, x_train, y_train, x_test, y_test, nb_classes, synthetic):
        self.x_train = x_train
        self.y_train = y_train
        self.x_test = x_test
        self.y_test = y_test
        self.nb_classes = nb_classes
        self.synthetic = synthetic


def _synthetic_classification(name, shape, nb_classes, nb_train, nb_test, seed, separation=2.0):
    """Class-conditional Gaussians around fixed random unit templates."""
    rng = np.random.default_rng(seed)
    templates = rng.normal(size=(nb_classes,) + shape).astype(np.float32)
    templates /= np.linalg.norm(templates.reshape(nb_classes, -1), axis=1).reshape((-1,) + (1,) * len(shape))

    def make(count, split_seed):
        r = np.random.default_rng(split_seed)
        labels = r.integers(0, nb_classes, size=count)
        noise = r.normal(size=(count,) + shape).astype(np.float32)
        images = separation * templates[labels] + noise
        return images.astype(np.float32), labels.astype(np.int32)

    x_train, y_train = make(nb_train, seed + 1)
    x_test, y_test = make(nb_test, seed + 2)
    warning(
        "Dataset %r not found on disk; using a deterministic synthetic stand-in "
        "(drop an %s.npz under $AGGREGATHOR_DATA to use real data)" % (name, name)
    )
    return ArrayDataset(x_train, y_train, x_test, y_test, nb_classes, synthetic=True)


def _head_size(requested, y_train, y_test, name):
    """Class count for the model head: covers BOTH the requested class count
    and every label actually observed (train AND test).  Sizing from the
    train subset's max alone would let take_along_axis clamp out-of-range
    labels into silently wrong nll/accuracy (ADVICE r3); one shared helper so
    the decode path and the npz-cache path can never disagree about the head."""
    # train-only caches / limit_test=0 yield empty splits: np.max over a
    # zero-size array has no identity, so only non-empty splits vote
    seen = max(
        [int(np.max(y)) + 1 for y in (y_train, y_test) if np.size(y)] or [1]
    )
    if requested and seen < requested:
        warning(
            "%s labels only cover %d of the requested %d classes; keeping the "
            "%d-way head (subset accuracy is not full-dataset accuracy)"
            % (name, seen, requested, requested)
        )
    return max(int(requested or 0), seen)


def _load_npz(path, shape, scale, nb_classes=None):
    import zipfile

    try:
        data = np.load(path)
    except (OSError, ValueError, zipfile.BadZipFile) as exc:
        # A clear startup message instead of a mid-pipeline traceback, like
        # the reference's up-front dir validation (tools/access.py); covers
        # unreadable files AND corrupt/truncated archives.
        raise UserException("Cannot load dataset %r: %s" % (path, exc))
    def prep(x):
        x = x.astype(np.float32) / scale
        return x.reshape((x.shape[0],) + shape)
    info("Loaded dataset from %s" % path)
    y_train = data["y_train"].astype(np.int32).ravel()
    y_test = data["y_test"].astype(np.int32).ravel()
    return ArrayDataset(
        prep(data["x_train"]), y_train, prep(data["x_test"]), y_test,
        nb_classes=_head_size(nb_classes, y_train, y_test, os.path.basename(path)),
        synthetic=False,
    )


def load_mnist():
    """28x28x1 digits in [0, 1]; real file or synthetic stand-in."""
    path = _find_npz("mnist.npz")
    if path:
        return _load_npz(path, (28, 28, 1), 255.0, nb_classes=10)
    return _synthetic_classification("mnist", (28, 28, 1), 10, nb_train=8192, nb_test=2048, seed=7)


def load_digits8x8(train_fraction=0.8, seed=11):
    """REAL handwritten digits: the UCI ML hand-written digits set (1797
    8x8 grayscale images, 10 classes) bundled INSIDE scikit-learn — the one
    real vision dataset reachable on a zero-egress box.

    Same role as the reference's real-MNIST path (experiments/mnist.py:51-81
    downloads via keras): a genuine accuracy target instead of a synthetic
    stand-in.  Deterministic seeded shuffle then an 80/20 split; pixels are
    0..16 ints, normalized to [0, 1].  Resolution order: a digits.npz under
    $AGGREGATHOR_DATA (so the _synthetic_classification recovery hint is a
    live path), then sklearn, then the synthetic stand-in (flagged via
    ``.synthetic``), mirroring the 1797-image corpus at the same split.
    """
    path = _find_npz("digits.npz")
    if path:
        return _load_npz(path, (8, 8, 1), 16.0, nb_classes=10)
    nb_train = int(1797 * train_fraction)
    try:
        from sklearn.datasets import load_digits as _sk_load_digits
    except ImportError:
        return _synthetic_classification(
            "digits", (8, 8, 1), 10, nb_train=nb_train, nb_test=1797 - nb_train,
            seed=seed)
    bunch = _sk_load_digits()
    images = (bunch.images.astype(np.float32) / 16.0).reshape(-1, 8, 8, 1)
    labels = bunch.target.astype(np.int32)
    order = np.random.default_rng(seed).permutation(len(labels))
    images, labels = images[order], labels[order]
    split = int(len(labels) * train_fraction)
    info("Loaded REAL sklearn digits: %d train / %d test" % (split, len(labels) - split))
    return ArrayDataset(
        images[:split], labels[:split], images[split:], labels[split:],
        nb_classes=10, synthetic=False,
    )


def load_digits_upscaled(size=32, train_fraction=0.8, seed=11):
    """The REAL digits corpus upscaled to ``size``x``size`` (nearest-
    neighbor, integer factor) — conv-topology input on real data.

    Purpose (VERDICT r4 task 3): the reference's flagship experiment is a
    conv net on real CIFAR-10 (experiments/cnnet.py:115-146), but the real
    CIFAR bytes are unobtainable on this zero-egress box (the reference's
    own dataset symlinks dangle — docs/robustness.md "Why not real
    CIFAR-10").  Nearest-neighbor upscaling adds no information, so
    accuracies here measure the conv stack on genuine handwriting, not an
    interpolation artifact."""
    base = load_digits8x8(train_fraction=train_fraction, seed=seed)
    if size % 8:
        raise ValueError("size must be a multiple of 8 (got %d)" % size)
    k = size // 8

    def up(x):
        return np.repeat(np.repeat(x, k, axis=1), k, axis=2)

    return ArrayDataset(
        up(base.x_train), base.y_train, up(base.x_test), base.y_test,
        nb_classes=base.nb_classes, synthetic=base.synthetic,
    )


def _find_cifar10_tfrecords():
    from .tfrecord import has_cifar10_tfrecords

    for dirname in _data_dirs():
        for candidate in (dirname, os.path.join(dirname, "cifar10")):
            if has_cifar10_tfrecords(candidate):
                if not can_access(candidate, read=True):
                    warning("CIFAR-10 shards at %r are not readable; skipping" % candidate)
                    continue
                return candidate
    return None


def load_cifar10():
    """32x32x3 images in [0, 1]; real data (npz, or the reference's slim
    TFRecord shards — experiments/cnnet.py:115-146) or synthetic stand-in."""
    path = _find_npz("cifar10.npz")
    if path:
        return _load_npz(path, (32, 32, 3), 255.0, nb_classes=10)
    tfr_dir = _find_cifar10_tfrecords()
    if tfr_dir:
        from .tfrecord import read_cifar10_split

        x_train, y_train = read_cifar10_split(tfr_dir, "train")
        x_test, y_test = read_cifar10_split(tfr_dir, "test")
        info("Loaded CIFAR-10 TFRecord shards from %s" % tfr_dir)
        # Parsing 60k PNG records through the pure-Python codec costs minutes;
        # cache as the preferred npz so the next run short-circuits above.
        cache = os.path.join(tfr_dir, "cifar10.npz")
        try:
            np.savez_compressed(cache, x_train=x_train, y_train=y_train,
                                x_test=x_test, y_test=y_test)
            info("Cached npz at %s" % cache)
        except OSError:
            pass  # read-only data dir: pay the parse each run
        return ArrayDataset(
            x_train.astype(np.float32) / 255.0, y_train,
            x_test.astype(np.float32) / 255.0, y_test,
            # CIFAR-10 is 10 classes by definition; _head_size guards against
            # a truncated shard set whose subset misses the top labels
            nb_classes=_head_size(10, y_train, y_test, "CIFAR-10"),
            synthetic=False,
        )
    return _synthetic_classification("cifar10", (32, 32, 3), 10, nb_train=8192, nb_test=2048, seed=11)


def load_imagenet_standin(image_size=224, nb_classes=1000):
    """Synthetic ImageNet-shaped data (the slims experiments' scale axis).

    Sized for throughput benchmarking, not accuracy: 512 train images at
    224x224x3 float32 is ~300 MB of host RAM; the model only ever sees
    sampled batches so epoch coverage is irrelevant here.
    """
    return _synthetic_classification(
        "imagenet%d" % image_size, (image_size, image_size, 3), nb_classes,
        nb_train=512, nb_test=128, seed=13,
    )


def _find_imagenet_tfrecords():
    from .tfrecord import has_imagenet_tfrecords

    for dirname in _data_dirs():
        for candidate in (dirname, os.path.join(dirname, "imagenet")):
            if has_imagenet_tfrecords(candidate):
                if not can_access(candidate, read=True):
                    warning("ImageNet shards at %r are not readable; skipping" % candidate)
                    continue
                return candidate
    return None


def load_imagenet(image_size=224, nb_classes=1000, limit_train=4096, limit_test=1024):
    """REAL slim-layout TFRecord ImageNet when shards are on disk
    (reference: experiments/slims.py:98-111 + experiments/datasets/imagenet),
    decoded with PIL and resized to ``image_size``; otherwise the synthetic
    stand-in with its loud warning.

    Full ImageNet does not fit host RAM as a dense array, so the real path
    loads a DETERMINISTIC CAPPED SUBSET (first ``limit_train``/``limit_test``
    examples in shard order) — real pixels for throughput benchmarking and
    smoke accuracy, stated in the log line.  The decoded subset is cached as
    an npz next to the other dataset caches so subsequent runs skip the
    JPEG decode."""
    # The cache key encodes the caps too: a smoke run's tiny cache must not
    # silently satisfy a later request for the full benchmark subset.
    cache_name = "imagenet%d-t%d-v%d.npz" % (image_size, limit_train, limit_test)
    path = _find_npz(cache_name, subdirs=("imagenet",))
    if path:
        return _load_npz(path, (image_size, image_size, 3), 255.0, nb_classes=nb_classes)
    tfr_dir = _find_imagenet_tfrecords()
    if tfr_dir:
        from .tfrecord import read_imagenet_split

        x_train, y_train = read_imagenet_split(tfr_dir, "train", image_size, limit=limit_train)
        x_test, y_test = read_imagenet_split(tfr_dir, "validation", image_size, limit=limit_test)
        info(
            "Loaded ImageNet TFRecord shards from %s (capped subset: %d train / "
            "%d validation examples at %dx%d)"
            % (tfr_dir, len(x_train), len(x_test), image_size, image_size)
        )
        cache = os.path.join(tfr_dir, cache_name)
        try:
            np.savez_compressed(cache, x_train=x_train, y_train=y_train,
                                x_test=x_test, y_test=y_test)
            info("Cached npz at %s" % cache)
        except OSError:
            pass  # read-only data dir: pay the decode each run
        # slim ImageNet labels are 1-based with 0 = background (1001 classes
        # for the full set; the reference's --labels-offset knob exists for
        # models that drop background).  The capped subset may not contain
        # the top label ids — _head_size covers both the requested count and
        # every observed label (train AND validation).
        return ArrayDataset(
            x_train.astype(np.float32) / 255.0, y_train,
            x_test.astype(np.float32) / 255.0, y_test,
            nb_classes=_head_size(nb_classes, y_train, y_test, "ImageNet subset"),
            synthetic=False,
        )
    return load_imagenet_standin(image_size, nb_classes)


class WorkerBatchIterator:
    """Infinite iterator of worker-major batches [n_workers, batch, ...].

    Each worker draws its own i.i.d. sample stream (the reference gives each
    task its own dataset pipeline, graph.py:224-233); a per-worker seed keeps
    streams independent and runs reproducible.
    """

    def __init__(self, x, y, nb_workers, batch_size, seed=0, transform=None):
        self.x, self.y = x, y
        self.nb_workers = nb_workers
        self.batch_size = batch_size
        # one stream per worker: worker w's sample sequence is a function of
        # (seed, w) only, independent of nb_workers or other workers
        self.rngs = [np.random.default_rng([seed, w]) for w in range(nb_workers)]
        self.transform = transform

    def __iter__(self):
        return self

    def _draw_indices(self, k):
        """The (k, nb_workers, batch) index block: worker streams drawn
        batch-major exactly like ``__next__`` — every consumer of a block
        shares this one definition, so sharded/sequential gathers and
        ``skip`` can never disagree about the sample streams."""
        idx = np.empty((k, self.nb_workers, self.batch_size), dtype=np.int64)
        for step in range(k):
            for w, rng in enumerate(self.rngs):
                idx[step, w] = rng.integers(0, self.x.shape[0], size=self.batch_size)
        return idx

    def __next__(self):
        idx = self._draw_indices(1)[0]
        flat = idx.reshape(-1)
        bx = self.x[flat].reshape((self.nb_workers, self.batch_size) + self.x.shape[1:])
        by = self.y[flat].reshape(self.nb_workers, self.batch_size)
        if self.transform is not None:
            bx, by = self.transform(bx, by)
        return {"image": bx, "label": by}

    def skip(self, k):
        """Advance every worker's sample stream by ``k`` batches without
        gathering data — the resume fast-forward (cli/runner.py): after
        restoring step S, the stream must sit exactly where an
        uninterrupted run's would, so the resumed trajectory is
        bit-identical.  Stateful host transforms (preprocessing.py per-worker
        augmentation streams) must advance in lockstep, so those keep the
        full draw path; stateless transforms (``transform_is_stateless``)
        consume no per-batch randomness, so only the index streams advance —
        resuming after a long run costs index draws, not gathers."""
        k = int(k)
        if not transform_is_stateless(self.transform):
            for _ in range(k):
                next(self)
            return
        for _ in range(k):
            for rng in self.rngs:
                rng.integers(0, self.x.shape[0], size=self.batch_size)

    def alloc_chunk(self, k):
        """A preallocated (k, nb_workers, batch, ...) chunk for
        ``next_many(k, out=...)`` — the ping-pong buffers of the input
        pipeline are two of these."""
        k = int(k)
        return {
            "image": np.empty(
                (k, self.nb_workers, self.batch_size) + self.x.shape[1:], self.x.dtype
            ),
            "label": np.empty((k, self.nb_workers, self.batch_size), self.y.dtype),
        }

    def next_many(self, k, out=None):
        """K batches in one call: a (k, nb_workers, batch, ...) stack.

        Sample streams are identical to k successive ``next()`` calls (each
        batch's indices are drawn per worker in the same order; asserted by
        tests/test_input_pipeline.py).  The gather is sharded over a small
        thread pool via ``np.take(..., out=...)`` (``sharded_take``), and
        with ``out`` (an ``alloc_chunk(k)`` buffer) it re-fills the caller's
        buffer instead of allocating ~chunk-size afresh — the zero-re-copy
        half of the input pipeline (ChunkPipeline alternates two such
        buffers).  Without ``out`` a fresh chunk is allocated (still one
        sharded gather, no ``np.stack`` re-copy).

        A STATEFUL host ``transform`` (per-worker augmentation streams,
        poisoning) must see every batch in order, so that path keeps the
        per-batch draws; stateless transforms run on the gathered stack.
        """
        if not transform_is_stateless(self.transform):
            batches = [next(self) for _ in range(k)]
            stack = {
                name: np.stack([b[name] for b in batches]) for name in batches[0]
            }
            if out is not None:
                for name, value in stack.items():
                    out[name][...] = value
                return out
            return stack
        idx = self._draw_indices(k)
        flat = idx.reshape(-1)
        if out is None:
            out = self.alloc_chunk(k)
        sharded_take(self.x, flat, out["image"].reshape((-1,) + self.x.shape[1:]))
        sharded_take(self.y, flat, out["label"].reshape(-1))
        if self.transform is not None:
            # stateless: per-slice application == sequential application
            for step in range(k):
                img, lab = out["image"][step], out["label"][step]
                bx, by = self.transform(img, lab)
                if bx is not img:
                    img[...] = bx
                if by is not lab:
                    lab[...] = by
        return out


def eval_batches(x, y, nb_workers, batch_size):
    """Finite worker-major pass over an eval split (pads by wrapping)."""
    per_step = nb_workers * batch_size
    total = x.shape[0]
    for start in range(0, total, per_step):
        idx = np.arange(start, start + per_step) % total
        # mark wrapped duplicates so metric counts stay exact
        valid = (np.arange(start, start + per_step) < total)
        bx = x[idx].reshape((nb_workers, batch_size) + x.shape[1:])
        by = y[idx].reshape(nb_workers, batch_size)
        yield {"image": bx, "label": by, "valid": valid.reshape(nb_workers, batch_size)}


class _PrefetchError:
    def __init__(self, exc):
        self.exc = exc


class DevicePrefetcher:
    """Background-thread input prefetch: overlaps host-side batch assembly
    and host->device transfer with device compute.

    The reference hides its input path behind TF queue runners with
    fetcher/batcher threads and a prefetch queue (experiments/cnnet.py:115-146);
    the JAX equivalent is this double buffer: a daemon thread pulls host
    batches from ``iterator``, applies ``put`` (e.g. ``engine.shard_batch`` —
    ``jax.device_put`` is thread-safe and asynchronous), and keeps up to
    ``depth`` device-resident batches ready for the training loop.
    """

    def __init__(self, iterator, put, depth=2):
        import queue
        import threading

        self._queue = queue.Queue(maxsize=max(1, int(depth)))
        self._iterator = iterator
        self._put = put
        self._stop = threading.Event()
        self._terminal = None  # remembered end-of-stream / producer error
        self._thread = threading.Thread(target=self._run, daemon=True, name="prefetch")
        self._thread.start()

    def _run(self):
        try:
            for batch in self._iterator:
                if self._stop.is_set():
                    return
                device_batch = self._put(batch)
                if self._stop.is_set():
                    return
                self._queue.put(device_batch)
            self._queue.put(_PrefetchError(StopIteration()))
        except BaseException as exc:  # surfaced on the consumer side
            self._queue.put(_PrefetchError(exc))

    def __iter__(self):
        return self

    def __next__(self):
        if self._terminal is not None:  # iterator protocol: stay terminal
            raise self._terminal
        item = self._queue.get()
        if isinstance(item, _PrefetchError):
            self._terminal = item.exc
            raise item.exc
        return item

    def close(self):
        """Stop and join the worker; no batch stays pinned afterwards.

        The drain loop keeps the queue unblocked while the producer winds
        down (it may complete one last ``put``), then the join makes the
        shutdown terminal — no in-flight ``device_put`` can race a
        subsequent run's setup.
        """
        import queue
        import time

        self._stop.set()
        self._terminal = StopIteration()
        # bounded: a producer stuck inside the wrapped iterator cannot be
        # interrupted — it is a daemon thread and dies with the process
        deadline = time.monotonic() + 5.0
        while self._thread.is_alive() and time.monotonic() < deadline:
            try:
                while True:
                    self._queue.get_nowait()
            except queue.Empty:
                pass
            self._thread.join(timeout=0.1)
        try:
            while True:
                self._queue.get_nowait()
        except queue.Empty:
            pass


def split_chunk(chunk, nb_slices):
    """Split a (K, ...) host chunk into ``nb_slices`` contiguous step-axis
    slices (views, no copy; ``np.array_split`` boundaries, so slice shapes
    are a pure function of (K, nb_slices) — stable across chunks, one
    compiled transfer/assemble program per pipeline)."""
    leaves = list(chunk.values())
    k = leaves[0].shape[0]
    nb_slices = max(1, min(int(nb_slices), k))
    bounds = [k * i // nb_slices for i in range(nb_slices + 1)]
    return [
        {name: value[lo:hi] for name, value in chunk.items()}
        for lo, hi in zip(bounds[:-1], bounds[1:]) if hi > lo
    ]


class ChunkPipeline:
    """Three-stage pipelined host→device input for the unrolled trainer.

    Replaces the chunk-path ``DevicePrefetcher`` (measured SLOWER than
    synchronous dispatch, BENCH_r05: 2.62 vs 2.74 steps/s — its one daemon
    thread serially re-did the whole gather + one monolithic ``device_put``
    the sync path pays anyway).  Here each stage overlaps with the next
    *and* with device compute:

    1. **parallel zero-re-copy gather** — ``iterator.next_many(unroll,
       out=...)`` refills one of TWO preallocated ping-pong host buffers,
       the row copies sharded over the gather pool (``sharded_take``);
    2. **sliced transfer** — the chunk is split into ``slices`` step-axis
       slices (``split_chunk``) and each is issued as its own async
       ``put`` (= ``engine.shard_batches``), so the wire starts moving
       after the first 1/S of the chunk instead of after all of it;
    3. **device-side assemble** — ``assemble`` (= ``engine.
       assemble_batches``, a jitted concatenate compiled once) turns the
       slice transfers into the one (K, n, ...) chunk the scanned trainer
       consumes, all while the PREVIOUS chunk's scan occupies the device.

    **Aliasing safety** (the ping-pong contract): buffer ``i % 2`` is
    re-gathered for chunk ``i+2`` only after chunk ``i``'s *assembled*
    device chunk is materialized (``block_until_ready``) — at that point
    the concatenate has consumed the slice buffers, so even a zero-copy
    ``device_put`` that aliased host memory can no longer observe the
    overwrite.  Consumers therefore never receive a chunk whose backing
    store a later gather may touch.

    The producer is FINITE (``nb_chunks``) for the same reason the old
    chunk prefetcher was: it shares ``iterator`` with the caller's tail
    path, so it must consume exactly the chunks the loop will, then exit —
    after exhaustion (or ``close()``), the caller's direct ``iterator``
    use cannot race the daemon.

    Overlap is *measured*, not presumed: with a ``registry``
    (obs/metrics.py) the pipeline exports ``input_gather_seconds_total`` /
    ``input_put_seconds_total`` (producer busy time), ``input_wait_seconds_
    total`` (consumer blocked in ``__next__`` — the true input gap),
    ``input_chunks_total``, a live ``input_queue_depth`` gauge and the
    derived ``input_overlap_fraction`` (1 - wait/busy: the fraction of
    input work hidden under compute); the producer stages also emit
    ``input.gather`` / ``input.put`` trace spans next to the runner's
    ``host_gap``.
    """

    def __init__(self, iterator, unroll, nb_chunks, put, assemble,
                 depth=2, slices=4, registry=None):
        import queue

        self._iterator = iterator
        self._unroll = int(unroll)
        self._nb_chunks = int(nb_chunks)
        self._put = put
        self._assemble = assemble
        self._slices = max(1, int(slices))
        self._queue = queue.Queue(maxsize=max(1, int(depth)))
        self._stop = threading.Event()
        self._terminal = None
        self._buffers = [None, None]  # ping-pong host chunks (lazy alloc)
        self._retire = [None, None]   # assembled device chunk per buffer
        self._wait_s = 0.0
        self._gauge_depth = None
        if registry is not None:
            self._c_gather = registry.counter(
                "input_gather_seconds_total",
                "Producer time in the sharded host gather")
            self._c_put = registry.counter(
                "input_put_seconds_total",
                "Producer time issuing slice transfers + assemble")
            self._c_wait = registry.counter(
                "input_wait_seconds_total",
                "Consumer time blocked waiting for an input chunk")
            self._c_chunks = registry.counter(
                "input_chunks_total", "Chunks produced by the input pipeline")
            self._gauge_depth = registry.gauge(
                "input_queue_depth", "Device-ready input chunks queued")
            self._gauge_depth.set_function(self._queue.qsize)
            gather, put_c, wait = self._c_gather, self._c_put, self._c_wait

            def overlap_fraction():
                busy = gather.value + put_c.value
                if busy <= 0.0:
                    return 0.0
                return max(0.0, min(1.0, 1.0 - wait.value / busy))

            registry.gauge(
                "input_overlap_fraction",
                "Fraction of input-pipeline work hidden under device compute "
                "(1 - wait/busy)",
            ).set_function(overlap_fraction)
        else:
            class _Null:
                value = 0.0

                def inc(self, amount=1.0):
                    pass

            self._c_gather = self._c_put = self._c_wait = self._c_chunks = _Null()
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="input-pipeline"
        )
        self._thread.start()

    # producer ---------------------------------------------------------- #

    def _run(self):
        import time

        import jax

        from ..obs import trace

        try:
            for index in range(self._nb_chunks):
                if self._stop.is_set():
                    return
                slot = index % 2
                if self._retire[slot] is not None:
                    # aliasing safety: chunk index-2's assemble must have
                    # consumed this buffer's slice transfers before regather
                    jax.block_until_ready(self._retire[slot])
                t0 = time.perf_counter()
                with trace.span("input.gather", cat="input"):
                    host = self._iterator.next_many(
                        self._unroll, out=self._buffers[slot]
                    )
                self._buffers[slot] = host
                self._c_gather.inc(time.perf_counter() - t0)
                if self._stop.is_set():
                    return
                t0 = time.perf_counter()
                with trace.span("input.put", cat="input"):
                    parts = [self._put(s) for s in split_chunk(host, self._slices)]
                    device_chunk = self._assemble(parts)
                self._c_put.inc(time.perf_counter() - t0)
                self._retire[slot] = device_chunk
                self._c_chunks.inc()
                self._queue.put(device_chunk)
            self._queue.put(_PrefetchError(StopIteration()))
        except BaseException as exc:  # surfaced on the consumer side
            self._queue.put(_PrefetchError(exc))

    # consumer ---------------------------------------------------------- #

    def __iter__(self):
        return self

    def __next__(self):
        import time

        if self._terminal is not None:  # iterator protocol: stay terminal
            raise self._terminal
        t0 = time.perf_counter()
        item = self._queue.get()
        waited = time.perf_counter() - t0
        self._c_wait.inc(waited)
        self._wait_s += waited
        if isinstance(item, _PrefetchError):
            self._terminal = item.exc
            raise item.exc
        return item

    @property
    def wait_seconds(self):
        """Total time THIS consumer spent blocked in ``__next__`` (the
        registry counter is process-cumulative across pipelines)."""
        return self._wait_s

    def close(self):
        """Stop and join the producer; afterwards the shared ``iterator``
        is exclusively the caller's again (the guardian-rollback /
        tail-handoff contract).  Same bounded drain-and-join discipline as
        ``DevicePrefetcher.close``; idempotent."""
        import queue
        import time

        self._stop.set()
        self._terminal = StopIteration()
        deadline = time.monotonic() + 5.0
        while self._thread.is_alive() and time.monotonic() < deadline:
            try:
                while True:
                    self._queue.get_nowait()
            except queue.Empty:
                pass
            self._thread.join(timeout=0.1)
        try:
            while True:
                self._queue.get_nowait()
        except queue.Empty:
            pass
        if self._gauge_depth is not None:
            self._gauge_depth.set(0.0)  # drop the qsize closure pinning us
            self._gauge_depth = None
        self._buffers = [None, None]
        self._retire = [None, None]

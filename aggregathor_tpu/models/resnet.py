"""ResNet v1 + v2 (pre-activation) families, TPU-first.

Capability parity with the reference's vendored slim resnet_v1
(external/slim/nets/resnet_v1.py:281+, including its resnet_v1_18 addition
and the 34/50/101/152/200 depths from nets_factory.py:39-60) and the
``resnet_v2_50/101/152/200`` factory entries (nets_factory.py:39-60; v2 =
pre-activation: norm+ReLU precede each conv, identity-clean shortcuts, one
final norm+ReLU before pooling) — written fresh as flax modules:

- **GroupNorm instead of BatchNorm**: the robust-DP engine treats model state
  as pure parameters (one canonical replicated copy, SURVEY.md §7 design
  stance); BatchNorm's mutable batch statistics would either leak information
  across Byzantine workers (shared stats) or desynchronize the replicas
  (per-worker stats).  GroupNorm is stateless, batch-size independent, and
  its normalization math fuses cleanly in XLA.
- NHWC layout, 3x3/1x1 convs and the stride-2 downsampling exactly as in v1;
  bfloat16-friendly (params float32, compute dtype configurable).
"""

import flax.linen as nn
import jax.numpy as jnp


class BasicBlock(nn.Module):
    """Two 3x3 convs + identity/projection shortcut (depths 18/34)."""

    filters: int
    stride: int = 1
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        residual = x
        y = nn.Conv(self.filters, (3, 3), (self.stride, self.stride), padding="SAME",
                    use_bias=False, dtype=self.dtype, name="conv1")(x)
        y = nn.GroupNorm(num_groups=min(32, self.filters), dtype=self.dtype, name="norm1")(y)
        y = nn.relu(y)
        y = nn.Conv(self.filters, (3, 3), padding="SAME", use_bias=False,
                    dtype=self.dtype, name="conv2")(y)
        y = nn.GroupNorm(num_groups=min(32, self.filters), dtype=self.dtype, name="norm2")(y)
        if residual.shape != y.shape:
            residual = nn.Conv(self.filters, (1, 1), (self.stride, self.stride),
                               use_bias=False, dtype=self.dtype, name="shortcut")(residual)
            residual = nn.GroupNorm(num_groups=min(32, self.filters), dtype=self.dtype,
                                    name="shortcut_norm")(residual)
        return nn.relu(residual + y)


class BottleneckBlock(nn.Module):
    """1x1 -> 3x3 -> 1x1(x4) bottleneck (depths 50/101/152/200)."""

    filters: int
    stride: int = 1
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        residual = x
        out_filters = 4 * self.filters
        y = nn.Conv(self.filters, (1, 1), use_bias=False, dtype=self.dtype, name="conv1")(x)
        y = nn.GroupNorm(num_groups=min(32, self.filters), dtype=self.dtype, name="norm1")(y)
        y = nn.relu(y)
        y = nn.Conv(self.filters, (3, 3), (self.stride, self.stride), padding="SAME",
                    use_bias=False, dtype=self.dtype, name="conv2")(y)
        y = nn.GroupNorm(num_groups=min(32, self.filters), dtype=self.dtype, name="norm2")(y)
        y = nn.relu(y)
        y = nn.Conv(out_filters, (1, 1), use_bias=False, dtype=self.dtype, name="conv3")(y)
        y = nn.GroupNorm(num_groups=min(32, out_filters), dtype=self.dtype, name="norm3")(y)
        if residual.shape != y.shape:
            residual = nn.Conv(out_filters, (1, 1), (self.stride, self.stride),
                               use_bias=False, dtype=self.dtype, name="shortcut")(residual)
            residual = nn.GroupNorm(num_groups=min(32, out_filters), dtype=self.dtype,
                                    name="shortcut_norm")(residual)
        return nn.relu(residual + y)


class PreactBottleneckBlock(nn.Module):
    """v2 bottleneck: norm+ReLU *before* each conv, un-normalized shortcut."""

    filters: int
    stride: int = 1
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        out_filters = 4 * self.filters
        y = nn.GroupNorm(num_groups=min(32, x.shape[-1]), dtype=self.dtype, name="norm1")(x)
        y = nn.relu(y)
        # Projection reads the pre-activated tensor (resnet_v2 convention);
        # identity shortcuts bypass normalization entirely.
        residual = x
        if x.shape[-1] != out_filters or self.stride != 1:
            residual = nn.Conv(out_filters, (1, 1), (self.stride, self.stride),
                               use_bias=False, dtype=self.dtype, name="shortcut")(y)
        y = nn.Conv(self.filters, (1, 1), use_bias=False, dtype=self.dtype, name="conv1")(y)
        y = nn.GroupNorm(num_groups=min(32, self.filters), dtype=self.dtype, name="norm2")(y)
        y = nn.relu(y)
        y = nn.Conv(self.filters, (3, 3), (self.stride, self.stride), padding="SAME",
                    use_bias=False, dtype=self.dtype, name="conv2")(y)
        y = nn.GroupNorm(num_groups=min(32, self.filters), dtype=self.dtype, name="norm3")(y)
        y = nn.relu(y)
        y = nn.Conv(out_filters, (1, 1), use_bias=False, dtype=self.dtype, name="conv3")(y)
        return residual + y


# depth -> (block class, stage sizes); nets_factory.py's resnet_v1 variants
RESNET_DEPTHS = {
    18: (BasicBlock, (2, 2, 2, 2)),
    34: (BasicBlock, (3, 4, 6, 3)),
    50: (BottleneckBlock, (3, 4, 6, 3)),
    101: (BottleneckBlock, (3, 4, 23, 3)),
    152: (BottleneckBlock, (3, 8, 36, 3)),
    200: (BottleneckBlock, (3, 24, 36, 3)),
}

# nets_factory.py's resnet_v2 variants (bottleneck-only, same stage tables)
RESNET_V2_DEPTHS = {
    50: (PreactBottleneckBlock, (3, 4, 6, 3)),
    101: (PreactBottleneckBlock, (3, 4, 23, 3)),
    152: (PreactBottleneckBlock, (3, 8, 36, 3)),
    200: (PreactBottleneckBlock, (3, 24, 36, 3)),
}


class ResNet(nn.Module):
    """ResNet v1/v2 classifier.

    ``small_inputs`` switches the stem from the ImageNet 7x7/2 + 3x3/2-pool to
    a CIFAR-style 3x3/1 conv (no pool), the standard adaptation for 32x32.
    ``preact=True`` selects the v2 pre-activation family: a bare stem conv
    (normalization happens inside the first block) and a final norm+ReLU
    before pooling.
    """

    depth: int = 50
    classes: int = 1000
    small_inputs: bool = False
    preact: bool = False
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        block_cls, stages = (RESNET_V2_DEPTHS if self.preact else RESNET_DEPTHS)[self.depth]
        x = x.astype(self.dtype)
        if self.small_inputs:
            x = nn.Conv(64, (3, 3), padding="SAME", use_bias=False, dtype=self.dtype, name="stem")(x)
        else:
            x = nn.Conv(64, (7, 7), (2, 2), padding=[(3, 3), (3, 3)], use_bias=False,
                        dtype=self.dtype, name="stem")(x)
        if not self.preact:
            x = nn.GroupNorm(num_groups=32, dtype=self.dtype, name="stem_norm")(x)
            x = nn.relu(x)
        if not self.small_inputs:
            x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        for stage, nb_blocks in enumerate(stages):
            for block in range(nb_blocks):
                stride = 2 if (stage > 0 and block == 0) else 1
                x = block_cls(64 * (2 ** stage), stride, self.dtype,
                              name="stage%d_block%d" % (stage + 1, block))(x)
        if self.preact:
            x = nn.GroupNorm(num_groups=32, dtype=self.dtype, name="final_norm")(x)
            x = nn.relu(x)
        x = jnp.mean(x, axis=(1, 2))  # global average pool
        return nn.Dense(self.classes, dtype=jnp.float32, name="logits")(x)

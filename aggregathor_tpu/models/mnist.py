"""MNIST MLP experiment: 784-100-10 dense ReLU classifier.

Parity with the reference's mnist experiment (experiments/mnist.py:83-148):
same topology (one hidden layer of 100 ReLU units), sparse softmax
cross-entropy per-worker loss, full-test-set top-1 accuracy, default batch 32.
Expressed as a flax.linen module; variable sharing across workers is implicit
(replicated params), replacing tf.get_variable + AUTO_REUSE.
"""

import flax.linen as nn
import jax
import jax.numpy as jnp
import optax

from ..utils import parse_keyval
from . import Experiment, register
from .datasets import WorkerBatchIterator, eval_batches, load_mnist


class MLP(nn.Module):
    hidden: int = 100
    classes: int = 10

    @nn.compact
    def __call__(self, x):
        x = x.reshape((x.shape[0], -1))
        x = nn.relu(nn.Dense(self.hidden, name="hidden")(x))
        return nn.Dense(self.classes, name="logits")(x)


class MNISTExperiment(Experiment):
    # Subclass hooks (e.g. models/digits.py swaps in the real 8x8 corpus
    # while inheriting the loss/metrics/iterator machinery unchanged).
    sample_shape = (28, 28, 1)
    load_dataset = staticmethod(load_mnist)

    def __init__(self, args):
        super().__init__(args)
        kv = parse_keyval(args, {"batch-size": 32, "eval-batch-size": 256, "hidden": 100})
        self.batch_size = kv["batch-size"]
        self.eval_batch_size = kv["eval-batch-size"]
        self.model = MLP(hidden=kv["hidden"])
        self.dataset = self.load_dataset()

    def init(self, rng):
        sample = jnp.zeros((1,) + self.sample_shape, jnp.float32)
        return self.model.init(rng, sample)

    def loss(self, params, batch):
        logits = self.model.apply(params, batch["image"])
        return jnp.mean(optax.softmax_cross_entropy_with_integer_labels(logits, batch["label"]))

    def metrics(self, params, batch):
        logits = self.model.apply(params, batch["image"])
        hit = (jnp.argmax(logits, axis=-1) == batch["label"]).astype(jnp.float32)
        valid = batch.get("valid")
        if valid is not None:
            hit = hit * valid
            count = jnp.sum(valid)
            xent = optax.softmax_cross_entropy_with_integer_labels(logits, batch["label"]) * valid
        else:
            count = jnp.float32(hit.shape[0])
            xent = optax.softmax_cross_entropy_with_integer_labels(logits, batch["label"])
        return {"accuracy": (jnp.sum(hit), count), "cross-entropy": (jnp.sum(xent), count)}

    def make_train_iterator(self, nb_workers, seed=0):
        return WorkerBatchIterator(
            self.dataset.x_train, self.dataset.y_train, nb_workers, self.batch_size, seed=seed
        )

    def make_eval_iterator(self, nb_workers):
        return eval_batches(self.dataset.x_test, self.dataset.y_test, nb_workers, self.eval_batch_size)

    def train_arrays(self):
        # transform-free iterator: a uniform row gather is the same stream
        return {"image": self.dataset.x_train, "label": self.dataset.y_train}


register("mnist", MNISTExperiment)

"""Inception v1 (GoogLeNet), v2 (BN-Inception), v3, v4 and
Inception-ResNet-v2 families, TPU-first.

Capability parity with the reference's slim nets_factory entries
``inception_v1`` / ``inception_v2`` / ``inception_v3`` / ``inception_v4`` /
``inception_resnet_v2`` (external/slim/nets/nets_factory.py:39-60)
including the auxiliary-logits training head the reference's slims
experiment wires into the loss (experiments/slims.py:122-124) — written
fresh as flax modules with the same design stance as resnet.py:

- GroupNorm instead of BatchNorm (stateless; no cross-worker statistic
  leakage in the Byzantine-DP setting — see models/resnet.py docstring).
- NHWC, SAME padding throughout; mixed-precision compute via ``dtype`` with
  float32 params and logits.
- Small inputs (e.g. CIFAR's 32x32) are bilinearly upsampled to the stem's
  minimum viable size instead of failing like slim's VALID-padded stems do.
"""

import flax.linen as nn
import jax.numpy as jnp

from .common import group_norm as _norm, resize_min


class ConvNorm(nn.Module):
    """Conv + GroupNorm + ReLU, the inception building unit."""

    features: int
    kernel: tuple
    stride: int = 1
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        x = nn.Conv(
            self.features,
            self.kernel,
            (self.stride, self.stride),
            padding="SAME",
            use_bias=False,
            dtype=self.dtype,
            name="conv",
        )(x)
        return nn.relu(_norm(x, "norm", self.dtype))


class InceptionBlockV1(nn.Module):
    """The classic 4-branch mixed block (1x1 / 3x3 / 5x5 / pool-proj)."""

    b0: int
    b1: tuple  # (reduce, out)
    b2: tuple  # (reduce, out)
    b3: int
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        d = self.dtype
        br0 = ConvNorm(self.b0, (1, 1), dtype=d, name="b0")(x)
        br1 = ConvNorm(self.b1[0], (1, 1), dtype=d, name="b1_reduce")(x)
        br1 = ConvNorm(self.b1[1], (3, 3), dtype=d, name="b1")(br1)
        br2 = ConvNorm(self.b2[0], (1, 1), dtype=d, name="b2_reduce")(x)
        br2 = ConvNorm(self.b2[1], (5, 5), dtype=d, name="b2")(br2)
        br3 = nn.max_pool(x, (3, 3), (1, 1), padding="SAME")
        br3 = ConvNorm(self.b3, (1, 1), dtype=d, name="b3")(br3)
        return jnp.concatenate([br0, br1, br2, br3], axis=-1)


# GoogLeNet mixed-block channel table (inception 3a..5b)
_V1_BLOCKS = [
    (64, (96, 128), (16, 32), 32),
    (128, (128, 192), (32, 96), 64),
    "pool",
    (192, (96, 208), (16, 48), 64),
    (160, (112, 224), (24, 64), 64),
    (128, (128, 256), (24, 64), 64),
    (112, (144, 288), (32, 64), 64),
    (256, (160, 320), (32, 128), 128),
    "pool",
    (256, (160, 320), (32, 128), 128),
    (384, (192, 384), (48, 128), 128),
]


class InceptionV1(nn.Module):
    """GoogLeNet; ``with_aux=True`` also returns the mid-network aux logits."""

    classes: int = 1000
    dtype: jnp.dtype = jnp.float32
    min_size: int = 64

    @nn.compact
    def __call__(self, x, with_aux=False):
        d = self.dtype
        x = resize_min(x, self.min_size).astype(d)
        x = ConvNorm(64, (7, 7), 2, dtype=d, name="stem1")(x)
        x = nn.max_pool(x, (3, 3), (2, 2), padding="SAME")
        x = ConvNorm(64, (1, 1), dtype=d, name="stem2")(x)
        x = ConvNorm(192, (3, 3), dtype=d, name="stem3")(x)
        x = nn.max_pool(x, (3, 3), (2, 2), padding="SAME")
        aux = None
        for i, spec in enumerate(_V1_BLOCKS):
            if spec == "pool":
                x = nn.max_pool(x, (3, 3), (2, 2), padding="SAME")
                continue
            b0, b1, b2, b3 = spec
            x = InceptionBlockV1(b0, b1, b2, b3, dtype=d, name="mixed_%d" % i)(x)
            if i == 6 and with_aux:  # after 4d, like GoogLeNet's second aux head
                a = nn.avg_pool(x, (5, 5), (3, 3), padding="SAME")
                a = ConvNorm(128, (1, 1), dtype=d, name="aux_proj")(a)
                a = jnp.mean(a, axis=(1, 2)).astype(jnp.float32)
                aux = nn.Dense(self.classes, dtype=jnp.float32, name="aux_logits")(a)
        x = jnp.mean(x, axis=(1, 2)).astype(jnp.float32)  # global average pool
        logits = nn.Dense(self.classes, dtype=jnp.float32, name="logits")(x)
        return (logits, aux) if with_aux else logits


def _aux_head(x, classes, d):
    """The 17x17 auxiliary-logits head shared by v3/v4/inception-resnet-v2.

    Called inside the owning module's ``@nn.compact`` scope so the parameter
    names (aux_proj1/aux_proj2/aux_logits) attach to the net itself.
    """
    a = nn.avg_pool(x, (5, 5), (3, 3), padding="SAME")
    a = ConvNorm(128, (1, 1), dtype=d, name="aux_proj1")(a)
    a = ConvNorm(768, (5, 5), dtype=d, name="aux_proj2")(a)
    a = jnp.mean(a, axis=(1, 2)).astype(jnp.float32)
    return nn.Dense(classes, dtype=jnp.float32, name="aux_logits")(a)


class _MixedA(nn.Module):
    """35x35 block: 1x1 / 5x5 / double-3x3 / pool branches."""

    pool_features: int
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        d = self.dtype
        b0 = ConvNorm(64, (1, 1), dtype=d, name="b0")(x)
        b1 = ConvNorm(48, (1, 1), dtype=d, name="b1_1")(x)
        b1 = ConvNorm(64, (5, 5), dtype=d, name="b1_2")(b1)
        b2 = ConvNorm(64, (1, 1), dtype=d, name="b2_1")(x)
        b2 = ConvNorm(96, (3, 3), dtype=d, name="b2_2")(b2)
        b2 = ConvNorm(96, (3, 3), dtype=d, name="b2_3")(b2)
        b3 = nn.avg_pool(x, (3, 3), (1, 1), padding="SAME")
        b3 = ConvNorm(self.pool_features, (1, 1), dtype=d, name="b3")(b3)
        return jnp.concatenate([b0, b1, b2, b3], axis=-1)


class _MixedB(nn.Module):
    """17x17 block: factorized 7x7 branches."""

    channels: int
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        d, c = self.dtype, self.channels
        b0 = ConvNorm(192, (1, 1), dtype=d, name="b0")(x)
        b1 = ConvNorm(c, (1, 1), dtype=d, name="b1_1")(x)
        b1 = ConvNorm(c, (1, 7), dtype=d, name="b1_2")(b1)
        b1 = ConvNorm(192, (7, 1), dtype=d, name="b1_3")(b1)
        b2 = ConvNorm(c, (1, 1), dtype=d, name="b2_1")(x)
        b2 = ConvNorm(c, (7, 1), dtype=d, name="b2_2")(b2)
        b2 = ConvNorm(c, (1, 7), dtype=d, name="b2_3")(b2)
        b2 = ConvNorm(c, (7, 1), dtype=d, name="b2_4")(b2)
        b2 = ConvNorm(192, (1, 7), dtype=d, name="b2_5")(b2)
        b3 = nn.avg_pool(x, (3, 3), (1, 1), padding="SAME")
        b3 = ConvNorm(192, (1, 1), dtype=d, name="b3")(b3)
        return jnp.concatenate([b0, b1, b2, b3], axis=-1)


class _MixedC(nn.Module):
    """8x8 block: expanded-filter-bank outputs."""

    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        d = self.dtype
        b0 = ConvNorm(320, (1, 1), dtype=d, name="b0")(x)
        b1 = ConvNorm(384, (1, 1), dtype=d, name="b1_1")(x)
        b1 = jnp.concatenate(
            [ConvNorm(384, (1, 3), dtype=d, name="b1_2a")(b1), ConvNorm(384, (3, 1), dtype=d, name="b1_2b")(b1)],
            axis=-1,
        )
        b2 = ConvNorm(448, (1, 1), dtype=d, name="b2_1")(x)
        b2 = ConvNorm(384, (3, 3), dtype=d, name="b2_2")(b2)
        b2 = jnp.concatenate(
            [ConvNorm(384, (1, 3), dtype=d, name="b2_3a")(b2), ConvNorm(384, (3, 1), dtype=d, name="b2_3b")(b2)],
            axis=-1,
        )
        b3 = nn.avg_pool(x, (3, 3), (1, 1), padding="SAME")
        b3 = ConvNorm(192, (1, 1), dtype=d, name="b3")(b3)
        return jnp.concatenate([b0, b1, b2, b3], axis=-1)


class _ReductionA(nn.Module):
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        d = self.dtype
        b0 = ConvNorm(384, (3, 3), 2, dtype=d, name="b0")(x)
        b1 = ConvNorm(64, (1, 1), dtype=d, name="b1_1")(x)
        b1 = ConvNorm(96, (3, 3), dtype=d, name="b1_2")(b1)
        b1 = ConvNorm(96, (3, 3), 2, dtype=d, name="b1_3")(b1)
        b2 = nn.max_pool(x, (3, 3), (2, 2), padding="SAME")
        return jnp.concatenate([b0, b1, b2], axis=-1)


class _ReductionB(nn.Module):
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        d = self.dtype
        b0 = ConvNorm(192, (1, 1), dtype=d, name="b0_1")(x)
        b0 = ConvNorm(320, (3, 3), 2, dtype=d, name="b0_2")(b0)
        b1 = ConvNorm(192, (1, 1), dtype=d, name="b1_1")(x)
        b1 = ConvNorm(192, (1, 7), dtype=d, name="b1_2")(b1)
        b1 = ConvNorm(192, (7, 1), dtype=d, name="b1_3")(b1)
        b1 = ConvNorm(192, (3, 3), 2, dtype=d, name="b1_4")(b1)
        b2 = nn.max_pool(x, (3, 3), (2, 2), padding="SAME")
        return jnp.concatenate([b0, b1, b2], axis=-1)


class InceptionV3(nn.Module):
    """Inception v3; ``with_aux=True`` also returns the 17x17 aux logits."""

    classes: int = 1000
    dtype: jnp.dtype = jnp.float32
    min_size: int = 96

    @nn.compact
    def __call__(self, x, with_aux=False):
        d = self.dtype
        x = resize_min(x, self.min_size).astype(d)
        x = ConvNorm(32, (3, 3), 2, dtype=d, name="stem1")(x)
        x = ConvNorm(32, (3, 3), dtype=d, name="stem2")(x)
        x = ConvNorm(64, (3, 3), dtype=d, name="stem3")(x)
        x = nn.max_pool(x, (3, 3), (2, 2), padding="SAME")
        x = ConvNorm(80, (1, 1), dtype=d, name="stem4")(x)
        x = ConvNorm(192, (3, 3), dtype=d, name="stem5")(x)
        x = nn.max_pool(x, (3, 3), (2, 2), padding="SAME")

        x = _MixedA(32, dtype=d, name="mixed_5b")(x)
        x = _MixedA(64, dtype=d, name="mixed_5c")(x)
        x = _MixedA(64, dtype=d, name="mixed_5d")(x)
        x = _ReductionA(dtype=d, name="mixed_6a")(x)
        x = _MixedB(128, dtype=d, name="mixed_6b")(x)
        x = _MixedB(160, dtype=d, name="mixed_6c")(x)
        x = _MixedB(160, dtype=d, name="mixed_6d")(x)
        x = _MixedB(192, dtype=d, name="mixed_6e")(x)

        aux = _aux_head(x, self.classes, d) if with_aux else None

        x = _ReductionB(dtype=d, name="mixed_7a")(x)
        x = _MixedC(dtype=d, name="mixed_7b")(x)
        x = _MixedC(dtype=d, name="mixed_7c")(x)
        x = jnp.mean(x, axis=(1, 2)).astype(jnp.float32)
        logits = nn.Dense(self.classes, dtype=jnp.float32, name="logits")(x)
        return (logits, aux) if with_aux else logits


class _MixedV2(nn.Module):
    """BN-Inception 4-branch block: 1x1 / 3x3 / double-3x3 / pool-proj.

    Inception v2 replaces v1's 5x5 branch with two stacked 3x3s; ``pool``
    selects avg (most blocks) or max (the last one) per the v2 table.
    """

    b0: int
    b1: tuple  # (reduce, out)
    b2: tuple  # (reduce, out) -- out used twice (double 3x3)
    b3: int
    pool: str = "avg"
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        d = self.dtype
        br0 = ConvNorm(self.b0, (1, 1), dtype=d, name="b0")(x)
        br1 = ConvNorm(self.b1[0], (1, 1), dtype=d, name="b1_reduce")(x)
        br1 = ConvNorm(self.b1[1], (3, 3), dtype=d, name="b1")(br1)
        br2 = ConvNorm(self.b2[0], (1, 1), dtype=d, name="b2_reduce")(x)
        br2 = ConvNorm(self.b2[1], (3, 3), dtype=d, name="b2_1")(br2)
        br2 = ConvNorm(self.b2[1], (3, 3), dtype=d, name="b2_2")(br2)
        pool = nn.avg_pool if self.pool == "avg" else nn.max_pool
        br3 = pool(x, (3, 3), (1, 1), padding="SAME")
        br3 = ConvNorm(self.b3, (1, 1), dtype=d, name="b3")(br3)
        return jnp.concatenate([br0, br1, br2, br3], axis=-1)


class _ReductionV2(nn.Module):
    """BN-Inception stride-2 block (Mixed_4a / Mixed_5a): 3x3 / double-3x3 / pool."""

    b0: tuple  # (reduce, out)
    b1: tuple  # (reduce, out)
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        d = self.dtype
        br0 = ConvNorm(self.b0[0], (1, 1), dtype=d, name="b0_reduce")(x)
        br0 = ConvNorm(self.b0[1], (3, 3), 2, dtype=d, name="b0")(br0)
        br1 = ConvNorm(self.b1[0], (1, 1), dtype=d, name="b1_reduce")(x)
        br1 = ConvNorm(self.b1[1], (3, 3), dtype=d, name="b1_1")(br1)
        br1 = ConvNorm(self.b1[1], (3, 3), 2, dtype=d, name="b1_2")(br1)
        br2 = nn.max_pool(x, (3, 3), (2, 2), padding="SAME")
        return jnp.concatenate([br0, br1, br2], axis=-1)


# The slim inception_v2 mixed-block channel table (Mixed_3b .. Mixed_5c)
_V2_BLOCKS = [
    (64, (64, 64), (64, 96), 32, "avg"),       # 3b
    (64, (64, 96), (64, 96), 64, "avg"),       # 3c
    "reduce_4a",
    (224, (64, 96), (96, 128), 128, "avg"),    # 4b
    (192, (96, 128), (96, 128), 128, "avg"),   # 4c
    (160, (128, 160), (128, 160), 96, "avg"),  # 4d
    (96, (128, 192), (160, 192), 96, "avg"),   # 4e
    "reduce_5a",
    (352, (192, 320), (160, 224), 128, "avg"), # 5b
    (352, (192, 320), (192, 224), 128, "max"), # 5c
]


class InceptionV2(nn.Module):
    """BN-Inception: v1 topology with double-3x3 branches, separable stem."""

    classes: int = 1000
    dtype: jnp.dtype = jnp.float32
    min_size: int = 64

    @nn.compact
    def __call__(self, x):
        d = self.dtype
        x = resize_min(x, self.min_size).astype(d)
        # slim's depthwise-separable 7x7/2 stem (inception_v2.py): depthwise
        # then 1x1 pointwise, one norm+relu at the end.
        channels = x.shape[-1]
        x = nn.Conv(channels * 8, (7, 7), (2, 2), padding="SAME",
                    feature_group_count=channels, use_bias=False, dtype=d, name="stem_dw")(x)
        x = nn.Conv(64, (1, 1), use_bias=False, dtype=d, name="stem_pw")(x)
        x = nn.relu(_norm(x, "stem_norm", d))
        x = nn.max_pool(x, (3, 3), (2, 2), padding="SAME")
        x = ConvNorm(64, (1, 1), dtype=d, name="stem2")(x)
        x = ConvNorm(192, (3, 3), dtype=d, name="stem3")(x)
        x = nn.max_pool(x, (3, 3), (2, 2), padding="SAME")
        for i, spec in enumerate(_V2_BLOCKS):
            if spec == "reduce_4a":
                x = _ReductionV2((128, 160), (64, 96), dtype=d, name="mixed_4a")(x)
            elif spec == "reduce_5a":
                x = _ReductionV2((128, 192), (192, 256), dtype=d, name="mixed_5a")(x)
            else:
                b0, b1, b2, b3, pool = spec
                x = _MixedV2(b0, b1, b2, b3, pool, dtype=d, name="mixed_%d" % i)(x)
        x = jnp.mean(x, axis=(1, 2)).astype(jnp.float32)
        return nn.Dense(self.classes, dtype=jnp.float32, name="logits")(x)


class _V4InceptionA(nn.Module):
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        d = self.dtype
        b0 = ConvNorm(96, (1, 1), dtype=d, name="b0")(x)
        b1 = ConvNorm(64, (1, 1), dtype=d, name="b1_1")(x)
        b1 = ConvNorm(96, (3, 3), dtype=d, name="b1_2")(b1)
        b2 = ConvNorm(64, (1, 1), dtype=d, name="b2_1")(x)
        b2 = ConvNorm(96, (3, 3), dtype=d, name="b2_2")(b2)
        b2 = ConvNorm(96, (3, 3), dtype=d, name="b2_3")(b2)
        b3 = nn.avg_pool(x, (3, 3), (1, 1), padding="SAME")
        b3 = ConvNorm(96, (1, 1), dtype=d, name="b3")(b3)
        return jnp.concatenate([b0, b1, b2, b3], axis=-1)


class _V4ReductionA(nn.Module):
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        d = self.dtype
        b0 = ConvNorm(384, (3, 3), 2, dtype=d, name="b0")(x)
        b1 = ConvNorm(192, (1, 1), dtype=d, name="b1_1")(x)
        b1 = ConvNorm(224, (3, 3), dtype=d, name="b1_2")(b1)
        b1 = ConvNorm(256, (3, 3), 2, dtype=d, name="b1_3")(b1)
        b2 = nn.max_pool(x, (3, 3), (2, 2), padding="SAME")
        return jnp.concatenate([b0, b1, b2], axis=-1)


class _V4InceptionB(nn.Module):
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        d = self.dtype
        b0 = ConvNorm(384, (1, 1), dtype=d, name="b0")(x)
        b1 = ConvNorm(192, (1, 1), dtype=d, name="b1_1")(x)
        b1 = ConvNorm(224, (1, 7), dtype=d, name="b1_2")(b1)
        b1 = ConvNorm(256, (7, 1), dtype=d, name="b1_3")(b1)
        b2 = ConvNorm(192, (1, 1), dtype=d, name="b2_1")(x)
        b2 = ConvNorm(192, (7, 1), dtype=d, name="b2_2")(b2)
        b2 = ConvNorm(224, (1, 7), dtype=d, name="b2_3")(b2)
        b2 = ConvNorm(224, (7, 1), dtype=d, name="b2_4")(b2)
        b2 = ConvNorm(256, (1, 7), dtype=d, name="b2_5")(b2)
        b3 = nn.avg_pool(x, (3, 3), (1, 1), padding="SAME")
        b3 = ConvNorm(128, (1, 1), dtype=d, name="b3")(b3)
        return jnp.concatenate([b0, b1, b2, b3], axis=-1)


class _V4ReductionB(nn.Module):
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        d = self.dtype
        b0 = ConvNorm(192, (1, 1), dtype=d, name="b0_1")(x)
        b0 = ConvNorm(192, (3, 3), 2, dtype=d, name="b0_2")(b0)
        b1 = ConvNorm(256, (1, 1), dtype=d, name="b1_1")(x)
        b1 = ConvNorm(256, (1, 7), dtype=d, name="b1_2")(b1)
        b1 = ConvNorm(320, (7, 1), dtype=d, name="b1_3")(b1)
        b1 = ConvNorm(320, (3, 3), 2, dtype=d, name="b1_4")(b1)
        b2 = nn.max_pool(x, (3, 3), (2, 2), padding="SAME")
        return jnp.concatenate([b0, b1, b2], axis=-1)


class _V4InceptionC(nn.Module):
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        d = self.dtype
        b0 = ConvNorm(256, (1, 1), dtype=d, name="b0")(x)
        b1 = ConvNorm(384, (1, 1), dtype=d, name="b1_1")(x)
        b1 = jnp.concatenate(
            [ConvNorm(256, (1, 3), dtype=d, name="b1_2a")(b1),
             ConvNorm(256, (3, 1), dtype=d, name="b1_2b")(b1)], axis=-1)
        b2 = ConvNorm(384, (1, 1), dtype=d, name="b2_1")(x)
        b2 = ConvNorm(448, (3, 1), dtype=d, name="b2_2")(b2)
        b2 = ConvNorm(512, (1, 3), dtype=d, name="b2_3")(b2)
        b2 = jnp.concatenate(
            [ConvNorm(256, (1, 3), dtype=d, name="b2_4a")(b2),
             ConvNorm(256, (3, 1), dtype=d, name="b2_4b")(b2)], axis=-1)
        b3 = nn.avg_pool(x, (3, 3), (1, 1), padding="SAME")
        b3 = ConvNorm(256, (1, 1), dtype=d, name="b3")(b3)
        return jnp.concatenate([b0, b1, b2, b3], axis=-1)


class InceptionV4(nn.Module):
    """Inception v4; ``with_aux=True`` also returns the 17x17 aux logits."""

    classes: int = 1000
    dtype: jnp.dtype = jnp.float32
    min_size: int = 96

    @nn.compact
    def __call__(self, x, with_aux=False):
        d = self.dtype
        x = resize_min(x, self.min_size).astype(d)
        # v4 stem: conv stack with two filter-concat joins
        x = ConvNorm(32, (3, 3), 2, dtype=d, name="stem1")(x)
        x = ConvNorm(32, (3, 3), dtype=d, name="stem2")(x)
        x = ConvNorm(64, (3, 3), dtype=d, name="stem3")(x)
        x = jnp.concatenate(
            [nn.max_pool(x, (3, 3), (2, 2), padding="SAME"),
             ConvNorm(96, (3, 3), 2, dtype=d, name="stem4")(x)], axis=-1)
        y0 = ConvNorm(64, (1, 1), dtype=d, name="stem5a_1")(x)
        y0 = ConvNorm(96, (3, 3), dtype=d, name="stem5a_2")(y0)
        y1 = ConvNorm(64, (1, 1), dtype=d, name="stem5b_1")(x)
        y1 = ConvNorm(64, (7, 1), dtype=d, name="stem5b_2")(y1)
        y1 = ConvNorm(64, (1, 7), dtype=d, name="stem5b_3")(y1)
        y1 = ConvNorm(96, (3, 3), dtype=d, name="stem5b_4")(y1)
        x = jnp.concatenate([y0, y1], axis=-1)
        x = jnp.concatenate(
            [ConvNorm(192, (3, 3), 2, dtype=d, name="stem6")(x),
             nn.max_pool(x, (3, 3), (2, 2), padding="SAME")], axis=-1)

        for i in range(4):
            x = _V4InceptionA(dtype=d, name="mixed_5%c" % (98 + i))(x)
        x = _V4ReductionA(dtype=d, name="mixed_6a")(x)
        for i in range(7):
            x = _V4InceptionB(dtype=d, name="mixed_6%c" % (98 + i))(x)

        aux = _aux_head(x, self.classes, d) if with_aux else None

        x = _V4ReductionB(dtype=d, name="mixed_7a")(x)
        for i in range(3):
            x = _V4InceptionC(dtype=d, name="mixed_7%c" % (98 + i))(x)
        x = jnp.mean(x, axis=(1, 2)).astype(jnp.float32)
        logits = nn.Dense(self.classes, dtype=jnp.float32, name="logits")(x)
        return (logits, aux) if with_aux else logits


class _ResBlock(nn.Module):
    """Inception-ResNet residual unit: branches -> concat -> linear 1x1 ->
    scaled residual add (the stabilizing scale from the paper)."""

    out_channels: int
    scale: float
    branches: tuple  # tuple of tuples of (features, kernel) conv chains
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        d = self.dtype
        outs = []
        for bi, chain in enumerate(self.branches):
            y = x
            for ci, (features, kernel) in enumerate(chain):
                y = ConvNorm(features, kernel, dtype=d, name="b%d_%d" % (bi, ci))(y)
            outs.append(y)
        up = jnp.concatenate(outs, axis=-1)
        up = nn.Conv(self.out_channels, (1, 1), dtype=d, name="up")(up)  # linear
        return nn.relu(x + self.scale * up)


class InceptionResNetV2(nn.Module):
    """Inception-ResNet-v2; ``with_aux=True`` returns the 17x17 aux logits.

    10x block35 (scale 0.17), 20x block17 (scale 0.10), 10x block8
    (scale 0.20) between the v4-style reductions, as in the paper/slim.
    """

    classes: int = 1000
    dtype: jnp.dtype = jnp.float32
    min_size: int = 96

    @nn.compact
    def __call__(self, x, with_aux=False):
        d = self.dtype
        x = resize_min(x, self.min_size).astype(d)
        x = ConvNorm(32, (3, 3), 2, dtype=d, name="stem1")(x)
        x = ConvNorm(32, (3, 3), dtype=d, name="stem2")(x)
        x = ConvNorm(64, (3, 3), dtype=d, name="stem3")(x)
        x = nn.max_pool(x, (3, 3), (2, 2), padding="SAME")
        x = ConvNorm(80, (1, 1), dtype=d, name="stem4")(x)
        x = ConvNorm(192, (3, 3), dtype=d, name="stem5")(x)
        x = nn.max_pool(x, (3, 3), (2, 2), padding="SAME")
        # Mixed_5b
        b0 = ConvNorm(96, (1, 1), dtype=d, name="m5b_b0")(x)
        b1 = ConvNorm(48, (1, 1), dtype=d, name="m5b_b1_1")(x)
        b1 = ConvNorm(64, (5, 5), dtype=d, name="m5b_b1_2")(b1)
        b2 = ConvNorm(64, (1, 1), dtype=d, name="m5b_b2_1")(x)
        b2 = ConvNorm(96, (3, 3), dtype=d, name="m5b_b2_2")(b2)
        b2 = ConvNorm(96, (3, 3), dtype=d, name="m5b_b2_3")(b2)
        b3 = nn.avg_pool(x, (3, 3), (1, 1), padding="SAME")
        b3 = ConvNorm(64, (1, 1), dtype=d, name="m5b_b3")(b3)
        x = jnp.concatenate([b0, b1, b2, b3], axis=-1)  # 320

        block35 = (((32, (1, 1)),), ((32, (1, 1)), (32, (3, 3))),
                   ((32, (1, 1)), (48, (3, 3)), (64, (3, 3))))
        for i in range(10):
            x = _ResBlock(320, 0.17, block35, dtype=d, name="block35_%d" % i)(x)
        # Reduction A with the inception-resnet widths (k,l,m,n = 256,256,384,384)
        r0 = ConvNorm(384, (3, 3), 2, dtype=d, name="m6a_b0")(x)
        r1 = ConvNorm(256, (1, 1), dtype=d, name="m6a_b1_1")(x)
        r1 = ConvNorm(256, (3, 3), dtype=d, name="m6a_b1_2")(r1)
        r1 = ConvNorm(384, (3, 3), 2, dtype=d, name="m6a_b1_3")(r1)
        r2 = nn.max_pool(x, (3, 3), (2, 2), padding="SAME")
        x = jnp.concatenate([r0, r1, r2], axis=-1)  # -> 1088

        block17 = (((192, (1, 1)),), ((128, (1, 1)), (160, (1, 7)), (192, (7, 1))))
        for i in range(20):
            x = _ResBlock(1088, 0.10, block17, dtype=d, name="block17_%d" % i)(x)

        aux = _aux_head(x, self.classes, d) if with_aux else None

        # Reduction B (inception-resnet variant: three conv branches + pool)
        b0 = ConvNorm(256, (1, 1), dtype=d, name="m7a_b0_1")(x)
        b0 = ConvNorm(384, (3, 3), 2, dtype=d, name="m7a_b0_2")(b0)
        b1 = ConvNorm(256, (1, 1), dtype=d, name="m7a_b1_1")(x)
        b1 = ConvNorm(288, (3, 3), 2, dtype=d, name="m7a_b1_2")(b1)
        b2 = ConvNorm(256, (1, 1), dtype=d, name="m7a_b2_1")(x)
        b2 = ConvNorm(288, (3, 3), dtype=d, name="m7a_b2_2")(b2)
        b2 = ConvNorm(320, (3, 3), 2, dtype=d, name="m7a_b2_3")(b2)
        b3 = nn.max_pool(x, (3, 3), (2, 2), padding="SAME")
        x = jnp.concatenate([b0, b1, b2, b3], axis=-1)  # 2080

        block8 = (((192, (1, 1)),), ((192, (1, 1)), (224, (1, 3)), (256, (3, 1))))
        for i in range(10):
            x = _ResBlock(2080, 0.20, block8, dtype=d, name="block8_%d" % i)(x)
        x = ConvNorm(1536, (1, 1), dtype=d, name="final_conv")(x)
        x = jnp.mean(x, axis=(1, 2)).astype(jnp.float32)
        logits = nn.Dense(self.classes, dtype=jnp.float32, name="logits")(x)
        return (logits, aux) if with_aux else logits

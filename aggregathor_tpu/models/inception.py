"""Inception v1 (GoogLeNet) and v3 families, TPU-first.

Capability parity with the reference's slim nets_factory entries
``inception_v1``/``inception_v3`` (external/slim/nets/nets_factory.py:39-60)
including the auxiliary-logits training head the reference's slims
experiment wires into the loss (experiments/slims.py:122-124) — written
fresh as flax modules with the same design stance as resnet.py:

- GroupNorm instead of BatchNorm (stateless; no cross-worker statistic
  leakage in the Byzantine-DP setting — see models/resnet.py docstring).
- NHWC, SAME padding throughout; mixed-precision compute via ``dtype`` with
  float32 params and logits.
- Small inputs (e.g. CIFAR's 32x32) are bilinearly upsampled to the stem's
  minimum viable size instead of failing like slim's VALID-padded stems do.
"""

import flax.linen as nn
import jax.numpy as jnp

from .common import group_norm as _norm, resize_min


class ConvNorm(nn.Module):
    """Conv + GroupNorm + ReLU, the inception building unit."""

    features: int
    kernel: tuple
    stride: int = 1
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        x = nn.Conv(
            self.features,
            self.kernel,
            (self.stride, self.stride),
            padding="SAME",
            use_bias=False,
            dtype=self.dtype,
            name="conv",
        )(x)
        return nn.relu(_norm(x, "norm", self.dtype))


class InceptionBlockV1(nn.Module):
    """The classic 4-branch mixed block (1x1 / 3x3 / 5x5 / pool-proj)."""

    b0: int
    b1: tuple  # (reduce, out)
    b2: tuple  # (reduce, out)
    b3: int
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        d = self.dtype
        br0 = ConvNorm(self.b0, (1, 1), dtype=d, name="b0")(x)
        br1 = ConvNorm(self.b1[0], (1, 1), dtype=d, name="b1_reduce")(x)
        br1 = ConvNorm(self.b1[1], (3, 3), dtype=d, name="b1")(br1)
        br2 = ConvNorm(self.b2[0], (1, 1), dtype=d, name="b2_reduce")(x)
        br2 = ConvNorm(self.b2[1], (5, 5), dtype=d, name="b2")(br2)
        br3 = nn.max_pool(x, (3, 3), (1, 1), padding="SAME")
        br3 = ConvNorm(self.b3, (1, 1), dtype=d, name="b3")(br3)
        return jnp.concatenate([br0, br1, br2, br3], axis=-1)


# GoogLeNet mixed-block channel table (inception 3a..5b)
_V1_BLOCKS = [
    (64, (96, 128), (16, 32), 32),
    (128, (128, 192), (32, 96), 64),
    "pool",
    (192, (96, 208), (16, 48), 64),
    (160, (112, 224), (24, 64), 64),
    (128, (128, 256), (24, 64), 64),
    (112, (144, 288), (32, 64), 64),
    (256, (160, 320), (32, 128), 128),
    "pool",
    (256, (160, 320), (32, 128), 128),
    (384, (192, 384), (48, 128), 128),
]


class InceptionV1(nn.Module):
    """GoogLeNet; ``with_aux=True`` also returns the mid-network aux logits."""

    classes: int = 1000
    dtype: jnp.dtype = jnp.float32
    min_size: int = 64

    @nn.compact
    def __call__(self, x, with_aux=False):
        d = self.dtype
        x = resize_min(x, self.min_size).astype(d)
        x = ConvNorm(64, (7, 7), 2, dtype=d, name="stem1")(x)
        x = nn.max_pool(x, (3, 3), (2, 2), padding="SAME")
        x = ConvNorm(64, (1, 1), dtype=d, name="stem2")(x)
        x = ConvNorm(192, (3, 3), dtype=d, name="stem3")(x)
        x = nn.max_pool(x, (3, 3), (2, 2), padding="SAME")
        aux = None
        for i, spec in enumerate(_V1_BLOCKS):
            if spec == "pool":
                x = nn.max_pool(x, (3, 3), (2, 2), padding="SAME")
                continue
            b0, b1, b2, b3 = spec
            x = InceptionBlockV1(b0, b1, b2, b3, dtype=d, name="mixed_%d" % i)(x)
            if i == 6 and with_aux:  # after 4d, like GoogLeNet's second aux head
                a = nn.avg_pool(x, (5, 5), (3, 3), padding="SAME")
                a = ConvNorm(128, (1, 1), dtype=d, name="aux_proj")(a)
                a = jnp.mean(a, axis=(1, 2)).astype(jnp.float32)
                aux = nn.Dense(self.classes, dtype=jnp.float32, name="aux_logits")(a)
        x = jnp.mean(x, axis=(1, 2)).astype(jnp.float32)  # global average pool
        logits = nn.Dense(self.classes, dtype=jnp.float32, name="logits")(x)
        return (logits, aux) if with_aux else logits


class _MixedA(nn.Module):
    """35x35 block: 1x1 / 5x5 / double-3x3 / pool branches."""

    pool_features: int
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        d = self.dtype
        b0 = ConvNorm(64, (1, 1), dtype=d, name="b0")(x)
        b1 = ConvNorm(48, (1, 1), dtype=d, name="b1_1")(x)
        b1 = ConvNorm(64, (5, 5), dtype=d, name="b1_2")(b1)
        b2 = ConvNorm(64, (1, 1), dtype=d, name="b2_1")(x)
        b2 = ConvNorm(96, (3, 3), dtype=d, name="b2_2")(b2)
        b2 = ConvNorm(96, (3, 3), dtype=d, name="b2_3")(b2)
        b3 = nn.avg_pool(x, (3, 3), (1, 1), padding="SAME")
        b3 = ConvNorm(self.pool_features, (1, 1), dtype=d, name="b3")(b3)
        return jnp.concatenate([b0, b1, b2, b3], axis=-1)


class _MixedB(nn.Module):
    """17x17 block: factorized 7x7 branches."""

    channels: int
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        d, c = self.dtype, self.channels
        b0 = ConvNorm(192, (1, 1), dtype=d, name="b0")(x)
        b1 = ConvNorm(c, (1, 1), dtype=d, name="b1_1")(x)
        b1 = ConvNorm(c, (1, 7), dtype=d, name="b1_2")(b1)
        b1 = ConvNorm(192, (7, 1), dtype=d, name="b1_3")(b1)
        b2 = ConvNorm(c, (1, 1), dtype=d, name="b2_1")(x)
        b2 = ConvNorm(c, (7, 1), dtype=d, name="b2_2")(b2)
        b2 = ConvNorm(c, (1, 7), dtype=d, name="b2_3")(b2)
        b2 = ConvNorm(c, (7, 1), dtype=d, name="b2_4")(b2)
        b2 = ConvNorm(192, (1, 7), dtype=d, name="b2_5")(b2)
        b3 = nn.avg_pool(x, (3, 3), (1, 1), padding="SAME")
        b3 = ConvNorm(192, (1, 1), dtype=d, name="b3")(b3)
        return jnp.concatenate([b0, b1, b2, b3], axis=-1)


class _MixedC(nn.Module):
    """8x8 block: expanded-filter-bank outputs."""

    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        d = self.dtype
        b0 = ConvNorm(320, (1, 1), dtype=d, name="b0")(x)
        b1 = ConvNorm(384, (1, 1), dtype=d, name="b1_1")(x)
        b1 = jnp.concatenate(
            [ConvNorm(384, (1, 3), dtype=d, name="b1_2a")(b1), ConvNorm(384, (3, 1), dtype=d, name="b1_2b")(b1)],
            axis=-1,
        )
        b2 = ConvNorm(448, (1, 1), dtype=d, name="b2_1")(x)
        b2 = ConvNorm(384, (3, 3), dtype=d, name="b2_2")(b2)
        b2 = jnp.concatenate(
            [ConvNorm(384, (1, 3), dtype=d, name="b2_3a")(b2), ConvNorm(384, (3, 1), dtype=d, name="b2_3b")(b2)],
            axis=-1,
        )
        b3 = nn.avg_pool(x, (3, 3), (1, 1), padding="SAME")
        b3 = ConvNorm(192, (1, 1), dtype=d, name="b3")(b3)
        return jnp.concatenate([b0, b1, b2, b3], axis=-1)


class _ReductionA(nn.Module):
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        d = self.dtype
        b0 = ConvNorm(384, (3, 3), 2, dtype=d, name="b0")(x)
        b1 = ConvNorm(64, (1, 1), dtype=d, name="b1_1")(x)
        b1 = ConvNorm(96, (3, 3), dtype=d, name="b1_2")(b1)
        b1 = ConvNorm(96, (3, 3), 2, dtype=d, name="b1_3")(b1)
        b2 = nn.max_pool(x, (3, 3), (2, 2), padding="SAME")
        return jnp.concatenate([b0, b1, b2], axis=-1)


class _ReductionB(nn.Module):
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        d = self.dtype
        b0 = ConvNorm(192, (1, 1), dtype=d, name="b0_1")(x)
        b0 = ConvNorm(320, (3, 3), 2, dtype=d, name="b0_2")(b0)
        b1 = ConvNorm(192, (1, 1), dtype=d, name="b1_1")(x)
        b1 = ConvNorm(192, (1, 7), dtype=d, name="b1_2")(b1)
        b1 = ConvNorm(192, (7, 1), dtype=d, name="b1_3")(b1)
        b1 = ConvNorm(192, (3, 3), 2, dtype=d, name="b1_4")(b1)
        b2 = nn.max_pool(x, (3, 3), (2, 2), padding="SAME")
        return jnp.concatenate([b0, b1, b2], axis=-1)


class InceptionV3(nn.Module):
    """Inception v3; ``with_aux=True`` also returns the 17x17 aux logits."""

    classes: int = 1000
    dtype: jnp.dtype = jnp.float32
    min_size: int = 96

    @nn.compact
    def __call__(self, x, with_aux=False):
        d = self.dtype
        x = resize_min(x, self.min_size).astype(d)
        x = ConvNorm(32, (3, 3), 2, dtype=d, name="stem1")(x)
        x = ConvNorm(32, (3, 3), dtype=d, name="stem2")(x)
        x = ConvNorm(64, (3, 3), dtype=d, name="stem3")(x)
        x = nn.max_pool(x, (3, 3), (2, 2), padding="SAME")
        x = ConvNorm(80, (1, 1), dtype=d, name="stem4")(x)
        x = ConvNorm(192, (3, 3), dtype=d, name="stem5")(x)
        x = nn.max_pool(x, (3, 3), (2, 2), padding="SAME")

        x = _MixedA(32, dtype=d, name="mixed_5b")(x)
        x = _MixedA(64, dtype=d, name="mixed_5c")(x)
        x = _MixedA(64, dtype=d, name="mixed_5d")(x)
        x = _ReductionA(dtype=d, name="mixed_6a")(x)
        x = _MixedB(128, dtype=d, name="mixed_6b")(x)
        x = _MixedB(160, dtype=d, name="mixed_6c")(x)
        x = _MixedB(160, dtype=d, name="mixed_6d")(x)
        x = _MixedB(192, dtype=d, name="mixed_6e")(x)

        aux = None
        if with_aux:
            a = nn.avg_pool(x, (5, 5), (3, 3), padding="SAME")
            a = ConvNorm(128, (1, 1), dtype=d, name="aux_proj1")(a)
            a = ConvNorm(768, (5, 5), dtype=d, name="aux_proj2")(a)
            a = jnp.mean(a, axis=(1, 2)).astype(jnp.float32)
            aux = nn.Dense(self.classes, dtype=jnp.float32, name="aux_logits")(a)

        x = _ReductionB(dtype=d, name="mixed_7a")(x)
        x = _MixedC(dtype=d, name="mixed_7b")(x)
        x = _MixedC(dtype=d, name="mixed_7c")(x)
        x = jnp.mean(x, axis=(1, 2)).astype(jnp.float32)
        logits = nn.Dense(self.classes, dtype=jnp.float32, name="logits")(x)
        return (logits, aux) if with_aux else logits

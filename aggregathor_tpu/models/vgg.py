"""VGG family (a/11, 16, 19), slims zoo parity.

The reference's slims experiments expose ``vgg_a``, ``vgg_16``, ``vgg_19``
through nets_factory (external/slim/nets/nets_factory.py:39-60).  Fresh flax
implementation: conv3x3 stacks + 2x2 max-pool stages, classifier head as
dense layers (the fully-convolutional head of the original is an inference
optimization that buys nothing under jit).
"""

import flax.linen as nn
import jax.numpy as jnp

# name -> convs per stage (stage filters are 64,128,256,512,512)
VGG_STAGES = {
    "vgg_a": (1, 1, 2, 2, 2),   # VGG-11
    "vgg_16": (2, 2, 3, 3, 3),
    "vgg_19": (2, 2, 4, 4, 4),
}


class VGG(nn.Module):
    variant: str = "vgg_16"
    classes: int = 1000
    dense_units: int = 4096
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        x = x.astype(self.dtype)
        for stage, nb_convs in enumerate(VGG_STAGES[self.variant]):
            filters = min(64 * (2 ** stage), 512)
            for conv in range(nb_convs):
                x = nn.Conv(filters, (3, 3), padding="SAME", dtype=self.dtype,
                            name="stage%d_conv%d" % (stage + 1, conv))(x)
                x = nn.relu(x)
            x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = x.reshape((x.shape[0], -1))
        x = nn.relu(nn.Dense(self.dense_units, dtype=self.dtype, name="fc1")(x))
        x = nn.relu(nn.Dense(self.dense_units, dtype=self.dtype, name="fc2")(x))
        return nn.Dense(self.classes, dtype=jnp.float32, name="logits")(x)

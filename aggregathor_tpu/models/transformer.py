"""Llama-style transformer with TPU-native 4D parallelism.

The reference has no attention models at all (SURVEY.md §5) — this family
exists for the driver's stretch config 5 ("Llama-class fine-tune with
per-layer Krum", BASELINE.md) and makes long-context + multi-axis sharding
first-class citizens of the framework:

- **TP** — SwiGLU MLP weights are column/row-sharded over the ``model`` mesh
  axis, Megatron-SP style: activations stay *sequence*-sharded between
  blocks, one ``all_gather`` enters the MLP, one ``psum_scatter`` leaves it.
- **SP (long context)** — ring attention over the ``model`` axis: K/V blocks
  rotate around the ring with ``ppermute`` while a numerically-stable online
  softmax accumulates, so no device ever materializes the (S, S) score
  matrix or the full sequence. Peak activation memory is O(S/T) per device.
- **EP** — optional switch-routed MoE MLPs; experts are sharded over the
  ``model`` axis and tokens travel through one ``all_to_all`` each way.
- **PP** — GPipe microbatch pipelining over the ``pipe`` axis: stages pass
  activations with ``ppermute`` inside a ``lax.scan`` over M + P - 1 ticks;
  autodiff flows backwards through the same ring (transpose of ppermute).

Everything is written to run *inside* ``jax.shard_map`` (see
parallel/engine.py, sharded mode) and degrades to plain single-device math when the
mesh axes have size 1 — the same code path serves the 8-device CPU test mesh
and a multi-host TPU pod.

Parameters are a plain pytree of arrays whose leading dimension is the
pipeline stage; ``param_specs`` gives the matching ``PartitionSpec`` tree.
"""

import dataclasses
import math
import os

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from .. import config as global_config

_NEG = -1e30  # finite mask value: keeps the online softmax NaN-free


@dataclasses.dataclass(frozen=True)
class TransformerConfig:
    """Static architecture hyper-parameters (Llama-style defaults)."""

    vocab_size: int = 256
    d_model: int = 64
    n_heads: int = 4
    n_layers: int = 4
    d_ff: int = 0            # 0 -> 4 * d_model
    n_experts: int = 0       # 0 -> dense SwiGLU MLP; > 0 -> switch MoE
    capacity_factor: float = 1.5
    rope_theta: float = 10000.0
    norm_eps: float = 1e-5
    dtype: object = jnp.float32
    remat: bool = True

    @property
    def head_dim(self):
        return self.d_model // self.n_heads

    @property
    def ff_dim(self):
        return self.d_ff if self.d_ff else 4 * self.d_model


# --------------------------------------------------------------------------- #
#  Parameter construction                                                     #
# --------------------------------------------------------------------------- #


#: Leaves with NO leading (n_stages, layers/stage) stage dims — every other
#: leaf is stage-stacked.  Shared by the dense forward, the pipeline loss,
#: and the stage-collapse in ``sharded_to_dense_params`` so a new
#: non-stacked leaf only needs declaring once.
NON_STACKED_LEAVES = ("embed", "unembed", "final_norm")


def init_params(cfg, key, n_stages=1):
    """Build the global parameter pytree; leaves lead with the stage dim."""
    if cfg.n_layers % n_stages != 0:
        raise ValueError("n_layers (%d) must divide into %d stages" % (cfg.n_layers, n_stages))
    lp = cfg.n_layers // n_stages
    d, h, dh, f, v, e = cfg.d_model, cfg.n_heads, cfg.head_dim, cfg.ff_dim, cfg.vocab_size, cfg.n_experts
    ks = iter(jax.random.split(key, 16))

    def dense(k, *shape):
        fan_in = shape[-2] if len(shape) >= 2 else shape[-1]
        return (jax.random.normal(k, shape) / math.sqrt(fan_in)).astype(cfg.dtype)

    params = {
        "embed": dense(next(ks), v, d),
        "unembed": dense(next(ks), d, v),
        "final_norm": jnp.ones((d,), cfg.dtype),
        "attn_norm": jnp.ones((n_stages, lp, d), cfg.dtype),
        "mlp_norm": jnp.ones((n_stages, lp, d), cfg.dtype),
        "wq": dense(next(ks), n_stages, lp, d, h * dh),
        "wk": dense(next(ks), n_stages, lp, d, h * dh),
        "wv": dense(next(ks), n_stages, lp, d, h * dh),
        "wo": dense(next(ks), n_stages, lp, h * dh, d),
    }
    if e:
        params.update(
            {
                "router": dense(next(ks), n_stages, lp, d, e),
                "we_gate": dense(next(ks), n_stages, lp, e, d, f),
                "we_up": dense(next(ks), n_stages, lp, e, d, f),
                "we_down": dense(next(ks), n_stages, lp, e, f, d),
            }
        )
    else:
        params.update(
            {
                "w_gate": dense(next(ks), n_stages, lp, d, f),
                "w_up": dense(next(ks), n_stages, lp, d, f),
                "w_down": dense(next(ks), n_stages, lp, f, d),
            }
        )
    return params


def param_specs(cfg):
    """PartitionSpec per leaf over the (worker, pipe, model) mesh.

    Workers replicate every parameter (the Byzantine-DP axis never shards
    weights); ``pipe`` shards the stage dim; MLP weights (or experts) shard
    over ``model``; everything else is replicated over ``model`` because
    activations are sequence-sharded there.
    """
    pa, ma = global_config.pipe_axis, global_config.model_axis
    specs = {
        "embed": P(),
        "unembed": P(),
        "final_norm": P(),
        "attn_norm": P(pa, None, None),
        "mlp_norm": P(pa, None, None),
        "wq": P(pa, None, None, None),
        "wk": P(pa, None, None, None),
        "wv": P(pa, None, None, None),
        "wo": P(pa, None, None, None),
    }
    if cfg.n_experts:
        specs.update(
            {
                "router": P(pa, None, None, None),
                "we_gate": P(pa, None, ma, None, None),
                "we_up": P(pa, None, ma, None, None),
                "we_down": P(pa, None, ma, None, None),
            }
        )
    else:
        specs.update(
            {
                "w_gate": P(pa, None, None, ma),
                "w_up": P(pa, None, None, ma),
                "w_down": P(pa, None, ma, None),
            }
        )
    return specs


# --------------------------------------------------------------------------- #
#  Building blocks                                                            #
# --------------------------------------------------------------------------- #


def rms_norm(x, scale, eps):
    var = jnp.mean(jnp.square(x.astype(jnp.float32)), axis=-1, keepdims=True)
    return (x * jax.lax.rsqrt(var + eps)).astype(x.dtype) * scale


def rope(x, positions, theta):
    """Rotary embedding; ``positions`` are *global* so SP blocks stay aligned."""
    b, s, h, dh = x.shape
    freqs = jnp.exp(-jnp.arange(0, dh, 2, dtype=jnp.float32) * (math.log(theta) / dh))
    angles = positions.astype(jnp.float32)[:, None] * freqs[None, :]  # (s, dh/2)
    cos, sin = jnp.cos(angles), jnp.sin(angles)
    x1, x2 = x[..., 0::2], x[..., 1::2]
    rx1 = x1 * cos[None, :, None, :] - x2 * sin[None, :, None, :]
    rx2 = x1 * sin[None, :, None, :] + x2 * cos[None, :, None, :]
    return jnp.concatenate([rx1[..., None], rx2[..., None]], axis=-1).reshape(b, s, h, dh).astype(x.dtype)


def _attend_block(q, k, v, q_pos, k_pos, num, den, mx):
    """One online-softmax accumulation step of blockwise causal attention."""
    scale = 1.0 / math.sqrt(q.shape[-1])
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * scale
    mask = q_pos[:, None] >= k_pos[None, :]
    scores = jnp.where(mask[None, None], scores, _NEG)
    new_mx = jnp.maximum(mx, scores.max(axis=-1))
    corr = jnp.exp(mx - new_mx)
    p = jnp.exp(scores - new_mx[..., None])
    num = num * corr[..., None] + jnp.einsum("bhqk,bkhd->bhqd", p, v.astype(jnp.float32))
    den = den * corr + p.sum(axis=-1)
    return num, den, new_mx


def ring_attention(q, k, v, positions, axis):
    """Blockwise causal attention; K/V ride a ``ppermute`` ring over ``axis``.

    q/k/v: (B, S_blk, H, Dh) sequence-sharded over ``axis`` (or the full
    sequence when ``axis`` is None). ``positions``: (S_blk,) global positions
    of the local block. Returns (B, S_blk, H, Dh).
    """
    b, sb, h, dh = q.shape
    num = jnp.zeros((b, h, sb, dh), jnp.float32)
    den = jnp.zeros((b, h, sb), jnp.float32)
    mx = jnp.full((b, h, sb), _NEG, jnp.float32)
    if axis is None:
        num, den, mx = _attend_block(q, k, v, positions, positions, num, den, mx)
    else:
        t_size = jax.lax.psum(1, axis)
        my = jax.lax.axis_index(axis)
        perm = [(i, (i + 1) % t_size) for i in range(t_size)]

        def body(carry, i):
            kc, vc, num, den, mx = carry
            src = (my - i) % t_size  # who produced the K/V block we now hold
            k_pos = src * sb + jnp.arange(sb)
            num, den, mx = _attend_block(q, kc, vc, positions, k_pos, num, den, mx)
            kc = jax.lax.ppermute(kc, axis, perm)
            vc = jax.lax.ppermute(vc, axis, perm)
            return (kc, vc, num, den, mx), None

        body = jax.checkpoint(body)
        (_, _, num, den, mx), _ = jax.lax.scan(body, (k, v, num, den, mx), jnp.arange(t_size))
    out = num / jnp.maximum(den[..., None], 1e-30)
    return out.transpose(0, 2, 1, 3).astype(q.dtype)  # (B, S_blk, H, Dh)


def attention_block(x, positions, wq, wk, wv, wo, cfg, axis):
    b, sb, d = x.shape
    h, dh = cfg.n_heads, cfg.head_dim
    q = rope((x @ wq).reshape(b, sb, h, dh), positions, cfg.rope_theta)
    k = rope((x @ wk).reshape(b, sb, h, dh), positions, cfg.rope_theta)
    v = (x @ wv).reshape(b, sb, h, dh)
    out = ring_attention(q, k, v, positions, axis)
    return out.reshape(b, sb, h * dh) @ wo


def mlp_block(x, w_gate, w_up, w_down, axis):
    """Megatron-SP SwiGLU: gather seq -> TP matmuls -> psum_scatter seq."""
    if axis is not None and jax.lax.psum(1, axis) > 1:
        xg = jax.lax.all_gather(x, axis, axis=1, tiled=True)  # (B, S, D)
        y = (jax.nn.silu(xg @ w_gate) * (xg @ w_up)) @ w_down  # partial over F
        return jax.lax.psum_scatter(y, axis, scatter_dimension=1, tiled=True)
    return (jax.nn.silu(x @ w_gate) * (x @ w_up)) @ w_down


def moe_block(x, router, we_gate, we_up, we_down, cfg, axis):
    """Switch (top-1) MoE with experts sharded over ``axis``.

    Tokens are dispatched into per-expert capacity slots (static shapes for
    XLA), travel to the expert owners through one ``all_to_all``, and return
    the same way. Returns (output, load-balancing aux loss).
    """
    b, sb, d = x.shape
    tokens = x.reshape(b * sb, d)
    n = tokens.shape[0]
    e = cfg.n_experts
    t_size = 1 if axis is None else jax.lax.psum(1, axis)
    el = e // t_size  # local experts per device

    logits = tokens @ router  # (N, E)
    gates = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    expert = jnp.argmax(gates, axis=-1)
    gate = jnp.max(gates, axis=-1)
    onehot = jax.nn.one_hot(expert, e, dtype=jnp.float32)  # (N, E)

    # Load-balancing aux (Switch Transformer): E * <fraction routed> . <mean gate>
    aux = e * jnp.mean(jnp.mean(onehot, axis=0) * jnp.mean(gates, axis=0))

    cap = max(1, int(math.ceil(n * cfg.capacity_factor / e)))
    pos = jnp.einsum("ne,ne->n", jnp.cumsum(onehot, axis=0) - 1.0, onehot).astype(jnp.int32)
    keep = (pos < cap).astype(jnp.float32)
    dispatch = onehot * keep[:, None]  # (N, E) tokens that fit capacity
    disp_tensor = dispatch[..., None] * jax.nn.one_hot(pos, cap, dtype=jnp.float32)[:, None, :]  # (N, E, C)

    expert_in = jnp.einsum("nec,nd->ecd", disp_tensor, tokens.astype(jnp.float32))  # (E, C, D)
    if t_size > 1:
        ei = expert_in.reshape(t_size, el, cap, d)
        ei = jax.lax.all_to_all(ei, axis, split_axis=0, concat_axis=0, tiled=True)
        expert_in = ei.reshape(t_size, el, cap, d).transpose(1, 0, 2, 3).reshape(el, t_size * cap, d)
    h = jax.nn.silu(jnp.einsum("ecd,edf->ecf", expert_in, we_gate)) * jnp.einsum(
        "ecd,edf->ecf", expert_in, we_up
    )
    expert_out = jnp.einsum("ecf,efd->ecd", h, we_down)  # (El, T*C, D)
    if t_size > 1:
        eo = expert_out.reshape(el, t_size, cap, d).transpose(1, 0, 2, 3)  # (T, El, C, D)
        eo = jax.lax.all_to_all(eo, axis, split_axis=0, concat_axis=0, tiled=True)
        expert_out = eo.reshape(e, cap, d)
    combine = disp_tensor * gate[:, None, None]
    out = jnp.einsum("nec,ecd->nd", combine, expert_out)
    return out.reshape(b, sb, d).astype(x.dtype), aux.astype(jnp.float32)


def _layer(x, positions, lp_params, cfg, axis):
    """One pre-norm transformer block on a (B, S_blk, D) activation."""
    x = x + attention_block(
        rms_norm(x, lp_params["attn_norm"], cfg.norm_eps),
        positions,
        lp_params["wq"],
        lp_params["wk"],
        lp_params["wv"],
        lp_params["wo"],
        cfg,
        axis,
    )
    h = rms_norm(x, lp_params["mlp_norm"], cfg.norm_eps)
    if cfg.n_experts:
        y, aux = moe_block(
            h, lp_params["router"], lp_params["we_gate"], lp_params["we_up"], lp_params["we_down"], cfg, axis
        )
    else:
        y, aux = mlp_block(h, lp_params["w_gate"], lp_params["w_up"], lp_params["w_down"], axis), 0.0
    return x + y, aux


def stage_forward(x, positions, stage_params, cfg, axis):
    """Apply this stage's layers (scanned over the layer dim) to one microbatch."""

    def body(carry, lp_params):
        x, aux = carry
        x, a = _layer(x, positions, lp_params, cfg, axis)
        return (x, aux + a), None

    if cfg.remat:
        body = jax.checkpoint(body)
    (x, aux), _ = jax.lax.scan(body, (x, jnp.float32(0.0)), stage_params)
    return x, aux


# --------------------------------------------------------------------------- #
#  Dense (collective-free) path — DP engine / tests / bench                   #
# --------------------------------------------------------------------------- #


def forward_dense(params, tokens, cfg):
    """Plain single-device forward: (B, S) int tokens -> (B, S, V) logits.

    Vmappable and collective-free; this is what the registered experiment
    uses under the data-parallel RobustEngine.
    """
    stage_params = {
        k: v[0] for k, v in params.items() if k not in NON_STACKED_LEAVES
    }
    x = params["embed"][tokens]
    positions = jnp.arange(tokens.shape[1])
    x, aux = stage_forward(x, positions, stage_params, cfg, axis=None)
    x = rms_norm(x, params["final_norm"], cfg.norm_eps)
    return x @ params["unembed"], aux


def loss_dense(params, batch, cfg, aux_weight=1e-2):
    logits, aux = forward_dense(params, batch["tokens"], cfg)
    targets = batch["targets"]
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    return jnp.mean(nll) + aux_weight * aux


# --------------------------------------------------------------------------- #
#  Pipelined, fully-sharded path — runs inside shard_map                      #
# --------------------------------------------------------------------------- #


def make_pipeline_loss(cfg, n_stages, microbatches, aux_weight=1e-2):
    """Build loss(params_local, batch_local) for use INSIDE shard_map.

    The returned function sees *local* parameter shards (leading stage dim of
    size 1) and a per-worker batch dict with ``tokens``/``targets`` of shape
    (B, S); B must divide into ``microbatches``. It uses collectives over the
    ``pipe`` axis (GPipe activation ring) and the ``model`` axis (ring
    attention, Megatron-SP gathers, MoE all_to_all).

    It returns the **local partial loss**: the sum over the (pipe, model)
    worker group equals the batch loss. Differentiate it as-is — the
    transposes of the in-group collectives assemble the exact gradient of
    that sum on each device (a final in-loss psum would instead *overcount*
    cotangents by the group size under shard_map without replication
    tracking). Callers psum the value over (pipe, model) for reporting.
    """
    pa, ma = global_config.pipe_axis, global_config.model_axis

    def loss_fn(params, batch):
        tokens, targets = batch["tokens"], batch["targets"]
        bsz, seq = tokens.shape
        t_size = jax.lax.psum(1, ma)
        p_size = jax.lax.psum(1, pa)
        stage = jax.lax.axis_index(pa)
        midx = jax.lax.axis_index(ma)
        if bsz % microbatches != 0:
            raise ValueError("batch %d not divisible into %d microbatches" % (bsz, microbatches))
        if seq % t_size != 0:
            raise ValueError("sequence %d not divisible over model axis %d" % (seq, t_size))
        mb = bsz // microbatches
        sb = seq // t_size

        # Local sequence block of every microbatch (SP sharding of activations)
        positions = midx * sb + jnp.arange(sb)
        tok_mb = tokens.reshape(microbatches, mb, seq)
        tgt_mb = targets.reshape(microbatches, mb, seq)
        tok_mb = jax.lax.dynamic_slice_in_dim(tok_mb, midx * sb, sb, axis=2)
        tgt_mb = jax.lax.dynamic_slice_in_dim(tgt_mb, midx * sb, sb, axis=2)

        stage_params = {
            k: v[0] for k, v in params.items() if k not in NON_STACKED_LEAVES
        }
        perm = [(i, (i + 1) % p_size) for i in range(p_size)]
        n_ticks = microbatches + p_size - 1

        def tick(carry, t):
            buf, loss_sum, aux_sum = carry
            feed_idx = jnp.clip(t, 0, microbatches - 1)
            # First stage embeds; the vocab gather is skipped elsewhere (the
            # predicate is uniform per stage, so each device runs one branch).
            x = jax.lax.cond(
                stage == 0,
                lambda: params["embed"][
                    jax.lax.dynamic_index_in_dim(tok_mb, feed_idx, keepdims=False)
                ].astype(cfg.dtype),
                lambda: buf,
            )
            x, aux = stage_forward(x, positions, stage_params, cfg, ma)

            # Last stage consumes finished microbatches t - (P-1) .. while
            # valid; the unembed projection (the largest matmul at real vocab
            # sizes) only runs on the last stage thanks to the cond.
            out_idx = jnp.clip(t - (p_size - 1), 0, microbatches - 1)

            def loss_tail():
                xf = rms_norm(x, params["final_norm"], cfg.norm_eps)
                logits = (xf @ params["unembed"]).astype(jnp.float32)
                tgt = jax.lax.dynamic_index_in_dim(tgt_mb, out_idx, keepdims=False)
                logp = jax.nn.log_softmax(logits, axis=-1)
                return jnp.sum(-jnp.take_along_axis(logp, tgt[..., None], axis=-1)[..., 0])

            tick_valid = (t >= p_size - 1).astype(jnp.float32)
            contrib = jax.lax.cond(stage == p_size - 1, loss_tail, lambda: jnp.float32(0.0))
            loss_sum = loss_sum + tick_valid * contrib
            # A stage holds a *real* microbatch (not pipeline-bubble padding)
            # only for ticks stage <= t < stage + M.
            real_mb = jnp.logical_and(t >= stage, t - stage < microbatches)
            aux_sum = aux_sum + jnp.where(real_mb, aux, 0.0)
            buf = jax.lax.ppermute(x, pa, perm) if p_size > 1 else x
            return (buf, loss_sum, aux_sum), None

        buf0 = jnp.zeros((mb, sb, cfg.d_model), cfg.dtype)
        (_, loss_sum, aux_sum), _ = jax.lax.scan(
            tick, (buf0, jnp.float32(0.0), jnp.float32(0.0)), jnp.arange(n_ticks)
        )
        # Local partial: non-final stages contributed 0 to loss_sum; summing
        # over (pipe, model) yields the token-mean CE plus the layer-summed,
        # microbatch/shard-mean aux.
        return loss_sum / (bsz * seq) + aux_weight * aux_sum / (microbatches * t_size)

    return loss_fn


# --------------------------------------------------------------------------- #
#  Registered experiment (dense path, synthetic corpus)                       #
# --------------------------------------------------------------------------- #


def synthetic_corpus(vocab_size, length, seed=0):
    """Deterministic order-2 Markov byte stream — learnable structure with no
    external dataset (the reference's datasets are all downloads/symlinks,
    experiments/mnist.py:51-81; an LM corpus has no such source here)."""
    import numpy as np

    rng = np.random.default_rng(seed)
    trans = rng.dirichlet(np.full(vocab_size, 0.1), size=(vocab_size, vocab_size))
    cum = trans.cumsum(axis=-1)
    uniforms = rng.random(length)
    out = np.empty(length, np.int32)
    a = b = 0
    for i in range(length):
        c = min(int(np.searchsorted(cum[a, b], uniforms[i])), vocab_size - 1)
        out[i] = c
        a, b = b, c
    return out


def code_corpus(max_bytes=4_000_000):
    """REAL byte-level text with zero egress: the Python standard library's
    own source files (PSF-licensed, read locally), concatenated in sorted
    order for determinism.  Code-plus-docstrings has the skewed byte
    statistics and long-range structure a language model actually exploits —
    unlike the uniform/Markov synthetic streams — so bits-per-byte numbers
    against the unigram-entropy baseline mean something (the role real
    MNIST plays for the vision experiments; see also datasets.load_digits8x8).
    """
    import glob as _glob
    import sysconfig

    stdlib = sysconfig.get_paths()["stdlib"]
    chunks, total = [], 0
    for path in sorted(_glob.glob(os.path.join(stdlib, "*.py"))):
        try:
            data = open(path, "rb").read()
        except OSError:
            continue
        chunks.append(data)
        total += len(data)
        if total >= max_bytes:
            break
    blob = b"".join(chunks)[:max_bytes]
    # Fall back only when the STDLIB ran dry (we could not gather what was
    # asked for and what we got is tiny) — an explicitly small max_bytes
    # that was fully satisfied is honored, not silently replaced.
    if len(blob) < max_bytes and len(blob) < 65536:
        return None
    import numpy as np

    return np.frombuffer(blob, np.uint8).astype(np.int32)


from . import Experiment, register  # noqa: E402  (after module-level helpers)
from ..utils import parse_keyval  # noqa: E402


class TransformerExperiment(Experiment):
    """Next-token LM, dense path.

    Args (key:value): vocab:64 d-model:64 heads:4 layers:4 d-ff:0 experts:0
    seq:128 batch-size:16 corpus:65536 corpus-source:markov.

    ``corpus-source:code`` trains on REAL bytes (the Python stdlib's own
    sources, ``code_corpus``) with a held-out final-10% eval split and
    byte vocab 256; the default ``markov`` keeps the deterministic
    synthetic stream (eval windows drawn from the same stream — its
    generator IS the test distribution).  ``.synthetic`` says which.
    """

    def __init__(self, args):
        super().__init__(args)
        kv = parse_keyval(
            args,
            defaults={
                "vocab": 64,
                "d-model": 64,
                "heads": 4,
                "layers": 4,
                "d-ff": 0,
                "experts": 0,
                "seq": 128,
                "batch-size": 16,
                "corpus": 65536,
                "corpus-source": "markov",
            },
        )
        source = str(kv["corpus-source"])
        if source == "code":
            # Real bytes need the full byte vocab regardless of the default.
            kv["vocab"] = max(int(kv["vocab"]), 256)
        self.cfg = TransformerConfig(
            vocab_size=int(kv["vocab"]),
            d_model=int(kv["d-model"]),
            n_heads=int(kv["heads"]),
            n_layers=int(kv["layers"]),
            d_ff=int(kv["d-ff"]),
            n_experts=int(kv["experts"]),
        )
        self.seq = int(kv["seq"])
        self.batch_size = int(kv["batch-size"])
        corpus = code_corpus(int(kv["corpus"])) if source == "code" else None
        if corpus is not None:
            # Held-out eval: the last 10% of REAL text is never trained on.
            split = int(len(corpus) * 0.9)
            self.corpus, self.eval_corpus = corpus[:split], corpus[split:]
            self.synthetic = False
            if self.seq + 1 > len(self.eval_corpus):
                from ..utils import UserException

                # Fail at construction, not after all training at eval time.
                raise UserException(
                    "seq:%d needs at least %d eval bytes but the held-out "
                    "split of corpus:%s has %d — raise corpus or lower seq"
                    % (self.seq, self.seq + 1, kv["corpus"], len(self.eval_corpus)))
        else:
            if source == "code":
                from ..utils import warning

                warning("corpus-source:code unavailable (stdlib too small); "
                        "using the synthetic Markov stream")
            self.corpus = synthetic_corpus(self.cfg.vocab_size, int(kv["corpus"]))
            self.eval_corpus = self.corpus
            self.synthetic = True

    supports_sharded = True

    def init(self, rng):
        return init_params(self.cfg, rng, n_stages=1)

    # --- sharded-engine hooks (cli/runner.py --mesh W,PP,TP) ---
    def sharded_init(self, n_stages):
        return lambda key: init_params(self.cfg, key, n_stages=n_stages)

    def sharded_specs(self):
        return param_specs(self.cfg)

    def sharded_loss(self, n_stages, microbatches):
        return make_pipeline_loss(self.cfg, n_stages=n_stages, microbatches=microbatches)

    def sharded_to_dense_params(self, params):
        """Collapse the stage dim of a (host-resident) stage-stacked pytree:
        (S, L/S, ...) -> (1, L, ...), the ``n_stages=1`` layout every dense
        entry point (forward_dense, metrics) consumes.  Lets the sharded CLI
        path report real eval metrics (accuracy/nll) on a dense replica
        instead of loss only."""
        out = {}
        for name, leaf in params.items():
            if name in NON_STACKED_LEAVES:
                out[name] = leaf
            else:
                out[name] = leaf.reshape((1, leaf.shape[0] * leaf.shape[1]) + leaf.shape[2:])
        return out

    def loss(self, params, batch):
        return loss_dense(params, batch, self.cfg)

    def metrics(self, params, batch):
        logits, _ = forward_dense(params, batch["tokens"], self.cfg)
        pred = jnp.argmax(logits, axis=-1)
        hits = jnp.sum(pred == batch["targets"]).astype(jnp.float32)
        count = jnp.float32(batch["targets"].size)
        logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        nll = -jnp.take_along_axis(logp, batch["targets"][..., None], axis=-1)
        return {"accuracy": (hits, count), "nll": (jnp.sum(nll), count)}

    def _sample(self, rng, nb_workers, batch_size, corpus=None):
        import numpy as np

        corpus = self.corpus if corpus is None else corpus
        starts = rng.integers(0, len(corpus) - self.seq - 1, size=(nb_workers, batch_size))
        idx = starts[..., None] + np.arange(self.seq + 1)
        window = corpus[idx]
        return {"tokens": window[..., :-1], "targets": window[..., 1:]}

    def make_train_iterator(self, nb_workers, seed=0):
        import numpy as np

        rng = np.random.default_rng(seed)
        while True:
            yield self._sample(rng, nb_workers, self.batch_size)

    def make_eval_iterator(self, nb_workers):
        import numpy as np

        rng = np.random.default_rng(10**9)
        for _ in range(4):
            yield self._sample(rng, nb_workers, self.batch_size, corpus=self.eval_corpus)


register("transformer", TransformerExperiment)

"""CIFAR-10 CNN experiment.

Parity with the reference's hand-built cnnet (experiments/cnnet.py:58-95):
two conv5x5-64 + 3x3/2 max-pool stages, dense 384, dense 192, linear 10 —
with local-response-norm replaced by its modern stand-in (the reference used
LRN because TF-Slim's CIFAR tutorial did; on TPU, LRN lowers poorly and
GroupNorm keeps the same "normalize early features" role).  Default batch 128
(the reference's TF-Slim provider default), sparse softmax CE loss, top-1
accuracy on the eval split.
"""

import flax.linen as nn
import jax.numpy as jnp
import optax

from ..utils import parse_keyval
from . import Experiment, register
from .datasets import WorkerBatchIterator, eval_batches, load_cifar10


class CNNet(nn.Module):
    classes: int = 10
    dtype: jnp.dtype = jnp.float32  # compute dtype; params stay float32

    @nn.compact
    def __call__(self, x):
        x = x.astype(self.dtype)
        x = nn.Conv(64, (5, 5), padding="SAME", dtype=self.dtype, name="conv1")(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        x = nn.GroupNorm(num_groups=8, dtype=self.dtype, name="norm1")(x)
        x = nn.Conv(64, (5, 5), padding="SAME", dtype=self.dtype, name="conv2")(x)
        x = nn.relu(x)
        x = nn.GroupNorm(num_groups=8, dtype=self.dtype, name="norm2")(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        x = x.reshape((x.shape[0], -1))
        x = nn.relu(nn.Dense(384, dtype=self.dtype, name="dense1")(x))
        x = nn.relu(nn.Dense(192, dtype=self.dtype, name="dense2")(x))
        # logits in f32: the softmax CE is numerically touchy in bf16
        return nn.Dense(self.classes, name="logits")(x.astype(jnp.float32))


class CNNetExperiment(Experiment):
    def __init__(self, args):
        super().__init__(args)
        kv = parse_keyval(args, {
            "batch-size": 128,
            "eval-batch-size": 256,
            # same arg surface as the reference (cnnet.py:100-107):
            # preprocessing selects the train augmentation; the thread counts
            # are accepted for drop-in compat (input threading is the
            # prefetcher's job here, cli/runner.py --prefetch)
            "preprocessing": "cifarnet",
            # augment:device moves the augmentation INSIDE the jitted step
            # (TPU-idiomatic: host does only the gather + transfer; the crop/
            # flip run fused on the VPU with in-step keyed randomness)
            "augment": "host",
            # compute dtype (params stay f32; the MXU runs bf16 at ~2x f32)
            "dtype": "float32",
            "nb-fetcher-threads": 0,
            "nb-batcher-threads": 0,
        })
        from .preprocessing import check as check_preprocessing

        self.batch_size = kv["batch-size"]
        self.eval_batch_size = kv["eval-batch-size"]
        self.preprocessing = check_preprocessing(kv["preprocessing"])  # fail fast
        if kv["augment"] not in ("host", "device"):
            from ..utils import UserException

            raise UserException("augment must be host|device, got %r" % kv["augment"])
        from .common import check_dtype

        self.augment = kv["augment"]
        self.dataset = load_cifar10()
        self.model = CNNet(classes=self.dataset.nb_classes, dtype=check_dtype(kv["dtype"]))

    def init(self, rng):
        sample = jnp.zeros((1, 32, 32, 3), jnp.float32)
        return self.model.init(rng, sample)

    def loss(self, params, batch):
        logits = self.model.apply(params, batch["image"])
        return jnp.mean(optax.softmax_cross_entropy_with_integer_labels(logits, batch["label"]))

    def metrics(self, params, batch):
        logits = self.model.apply(params, batch["image"])
        hit = (jnp.argmax(logits, axis=-1) == batch["label"]).astype(jnp.float32)
        valid = batch.get("valid")
        if valid is not None:
            hit = hit * valid
            count = jnp.sum(valid)
        else:
            count = jnp.float32(hit.shape[0])
        return {"accuracy": (jnp.sum(hit), count)}

    def make_train_iterator(self, nb_workers, seed=0):
        from .preprocessing import instantiate as make_preprocessing

        return WorkerBatchIterator(
            self.dataset.x_train, self.dataset.y_train, nb_workers, self.batch_size, seed=seed,
            transform=(None if self.augment == "device"
                       else make_preprocessing(self.preprocessing, seed=seed)),
        )

    # device_transform / train_arrays: Experiment base defaults keyed off
    # self.augment / self.preprocessing / self.dataset

    def make_eval_iterator(self, nb_workers):
        return eval_batches(self.dataset.x_test, self.dataset.y_test, nb_workers, self.eval_batch_size)


register("cnnet", CNNetExperiment)

"""Train-time input preprocessing (augmentation) registry.

Parity with the reference's slim ``preprocessing_factory`` selection
(experiments/slims.py:98-111 and cnnet.py's ``preprocessing`` arg, default
"cifarnet"): experiments accept ``preprocessing:<name>`` and apply the named
augmentation to training batches only (evaluation stays deterministic).

Implementations are numpy-side, applied inside the worker-batch iterator
(the host is where the reference's preprocessing threads ran too).  Each
worker's augmentation stream draws from its own generator keyed by
``(seed, tag, worker)`` — like ``WorkerBatchIterator``'s sample streams,
worker w's augmented data is independent of ``nb_workers`` and batch size,
so runs stay comparable across worker counts.  Transforms may mutate their
input: the iterator hands out a fresh (fancy-indexed) array every batch.

- ``none`` / ``lenet``: identity.
- ``cifarnet``: 4-pixel reflect pad, random crop back to size, random
  horizontal flip — the crop+flip core of slim's cifarnet_preprocessing
  (its brightness/contrast jitter is omitted, documented simplification).
- ``inception`` / ``vgg``: random horizontal flip (the full scale/aspect
  distortion pipelines are not reproduced for the synthetic stand-ins;
  flip is the shared core).

Each factory takes a seed and returns a ``transform(bx, by) -> (bx, by)``
over worker-major blocks, suitable for ``WorkerBatchIterator(transform=...)``.
"""

import numpy as np

from ..utils import UserException


class _PerWorkerRng:
    """Lazy per-worker generators: worker w's stream is f(seed, tag, w) only."""

    def __init__(self, seed, tag):
        self.seed = int(seed)
        self.tag = int(tag)
        self._rngs = {}

    def get(self, worker):
        if worker not in self._rngs:
            self._rngs[worker] = np.random.default_rng([self.seed, self.tag, worker])
        return self._rngs[worker]


def stateless(transform):
    """Declare ``transform`` stateless: its output depends only on its
    inputs — no RNG draws, no call-count state.  The batch iterator then
    skips it entirely on resume fast-forward (``WorkerBatchIterator.skip``
    advances only the index streams — seconds per thousand skipped steps
    saved) and applies it per-slice on the gathered ``next_many`` stack.
    Stateful transforms (the per-worker augmentation streams below,
    poisoning) must NOT be marked: their streams advance per batch."""
    transform.stateless = True
    return transform


def none_preprocessing(seed=0):
    return stateless(lambda bx, by: (bx, by))


def cifarnet_preprocessing(seed=0, pad=4):
    rngs = _PerWorkerRng(seed, 0xC1FA)

    def transform(bx, by):
        bx = np.asarray(bx)
        nb_workers, batch, height, width = bx.shape[:4]
        out = np.empty_like(bx)
        for w in range(nb_workers):
            rng = rngs.get(w)
            padded = np.pad(bx[w], ((0, 0), (pad, pad), (pad, pad), (0, 0)), mode="reflect")
            ox = rng.integers(0, 2 * pad + 1, size=batch)
            oy = rng.integers(0, 2 * pad + 1, size=batch)
            rows = ox[:, None, None] + np.arange(height)[None, :, None]
            cols = oy[:, None, None] + np.arange(width)[None, None, :]
            images = padded[np.arange(batch)[:, None, None], rows, cols, :]
            mask = rng.random(batch) < 0.5
            images[mask] = images[mask, :, ::-1]
            out[w] = images
        return out, by

    return transform


def flip_preprocessing(seed=0):
    rngs = _PerWorkerRng(seed, 0xF11B)

    def transform(bx, by):
        bx = np.asarray(bx)
        for w in range(bx.shape[0]):
            mask = rngs.get(w).random(bx.shape[1]) < 0.5
            bx[w, mask] = bx[w, mask][:, :, ::-1]
        return bx, by

    return transform


PREPROCESSING = {
    "none": none_preprocessing,
    "cifarnet": cifarnet_preprocessing,
    "inception": flip_preprocessing,
    "vgg": flip_preprocessing,
    "lenet": none_preprocessing,
}


# --------------------------------------------------------------------- #
# Device-side tier: the same augmentations as jnp transforms running
# INSIDE the jitted training step (engine ``batch_transform``), so the host
# input path is just a gather + transfer.  This is the TPU-idiomatic home
# for per-sample augmentation — the crop is a vmapped dynamic_slice (VPU
# work fused into the step, zero host cost), where the reference necessarily
# burned CPU threads on it (slim preprocessing ran on the input pipeline's
# fetcher threads, experiments/cnnet.py:115-146).
#
# Keying discipline matches the host tier: the engine derives the key from
# (run seed, step, GLOBAL worker index), so worker w's augmentation stream
# is independent of nb_workers and of the device it landed on, and a rerun
# reproduces it exactly.


def _device_cifarnet(pad=4):
    import jax
    import jax.numpy as jnp

    def transform(batch, key):
        img = batch["image"]
        b, h, w = img.shape[0], img.shape[1], img.shape[2]
        kc, kf = jax.random.split(key)
        padded = jnp.pad(img, ((0, 0), (pad, pad), (pad, pad), (0, 0)), mode="reflect")
        off = jax.random.randint(kc, (b, 2), 0, 2 * pad + 1)
        crop = jax.vmap(
            lambda im, o: jax.lax.dynamic_slice(im, (o[0], o[1], 0), (h, w, im.shape[-1]))
        )(padded, off)
        flip = jax.random.bernoulli(kf, 0.5, (b,))
        out = jnp.where(flip[:, None, None, None], crop[:, :, ::-1, :], crop)
        return dict(batch, image=out)

    return transform


def _device_flip():
    import jax
    import jax.numpy as jnp

    def transform(batch, key):
        img = batch["image"]
        flip = jax.random.bernoulli(key, 0.5, (img.shape[0],))
        out = jnp.where(flip[:, None, None, None], img[:, :, ::-1, :], img)
        return dict(batch, image=out)

    return transform


DEVICE_PREPROCESSING = {
    "none": lambda: None,
    "lenet": lambda: None,
    "cifarnet": _device_cifarnet,
    "inception": _device_flip,
    "vgg": _device_flip,
}


def device_transform(name):
    """The jnp in-step transform for ``name`` (None when it is the identity)."""
    if name not in DEVICE_PREPROCESSING:
        raise UserException(
            "Unknown preprocessing %r (accepted: %s)" % (name, ", ".join(sorted(DEVICE_PREPROCESSING)))
        )
    return DEVICE_PREPROCESSING[name]()


def check(name):
    """Validate a preprocessing name at arg-parse time (fail fast)."""
    if name not in PREPROCESSING:
        raise UserException(
            "Unknown preprocessing %r (accepted: %s)" % (name, ", ".join(sorted(PREPROCESSING)))
        )
    return name


def instantiate(name, seed=0):
    return PREPROCESSING[check(name)](seed)


def default_for(model_name):
    """slim preprocessing_factory's model-name-keyed defaults
    (external/slim/preprocessing/preprocessing_factory.py): lenet/cifarnet
    keep their own pipelines, vgg/resnet use vgg, everything else inception."""
    if model_name.startswith(("lenet",)):
        return "lenet"
    if model_name.startswith(("cifarnet",)):
        return "cifarnet"
    if model_name.startswith(("vgg", "resnet")):
        return "vgg"
    return "inception"

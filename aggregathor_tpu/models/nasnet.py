"""NASNet-A and PNASNet-5 families, TPU-first.

Capability parity with the reference's slim nets_factory entries
``nasnet_cifar`` / ``nasnet_mobile`` / ``nasnet_large`` and
``pnasnet_mobile`` / ``pnasnet_large``
(external/slim/nets/nets_factory.py:39-60) — written fresh as flax modules.

The cell wiring follows the published architectures: the NASNet-A normal and
reduction cells (Zoph et al., "Learning Transferable Architectures", fig. 4)
as 5 pairwise-combined blocks over the two previous cell outputs, and the
PNASNet-5 cell (Liu et al., "Progressive Neural Architecture Search") as one
cell type used at both strides.  Round 5 closed the two fidelity gaps the
earlier rounds documented (VERDICT r4 "what's missing" 2): separable convs
now apply TWICE per op (stride on the first application only — slim's
nasnet_utils.py loop), and the "previous" input aligns to the current
spatial size by slim's factorized reduction (two parallel stride-2 1x1
paths, the second on a one-pixel-shifted view, concatenated) instead of an
average pool.  The one remaining deliberate deviation — per the repo-wide
design stance (models/resnet.py) — is GroupNorm in place of BatchNorm.
Variant sizing (cells N, penultimate filters) matches slim's: cifar (N=6,
F=32), mobile (N=4, F=44), large (N=6, F=168); pnasnet mobile (N=3, F=54),
large (N=4, F=216).
"""

import flax.linen as nn
import jax.numpy as jnp

from .common import group_norm as _norm, resize_min


class _SepConv(nn.Module):
    """(ReLU -> depthwise kxk -> pointwise 1x1 -> norm) applied TWICE.

    The published NASNet op (slim nasnet_utils' 2-layer separable stack):
    the stride applies on the first application only, the second always
    runs at stride 1 over the op's own output."""

    features: int
    kernel: int
    stride: int = 1
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        d = self.dtype
        y = x
        for i, stride in enumerate((self.stride, 1)):
            channels = y.shape[-1]
            y = nn.relu(y)
            y = nn.Conv(channels, (self.kernel, self.kernel), (stride, stride),
                        padding="SAME", feature_group_count=channels, use_bias=False,
                        dtype=d, name="depthwise_%d" % i)(y)
            y = nn.Conv(self.features, (1, 1), use_bias=False, dtype=d,
                        name="pointwise_%d" % i)(y)
            y = _norm(y, "norm_%d" % i, d)
        return y


class _FactorizedReduce(nn.Module):
    """Slim's factorized_reduction: two parallel stride-s 1x1 paths (the
    second over a one-pixel-shifted view) concatenated, then norm — the
    published alignment of the previous cell output to a reduced spatial
    size, information-preserving where a pool would discard phase."""

    features: int
    stride: int
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        d, s = self.dtype, self.stride
        y = nn.relu(x)
        p1 = nn.Conv(self.features // 2, (1, 1), (s, s), use_bias=False,
                     dtype=d, name="path1")(y)
        shifted = jnp.pad(y, ((0, 0), (0, 1), (0, 1), (0, 0)))[:, 1:, 1:, :]
        p2 = nn.Conv(self.features - self.features // 2, (1, 1), (s, s),
                     use_bias=False, dtype=d, name="path2")(shifted)
        return _norm(jnp.concatenate([p1, p2], axis=-1), "norm", d)


class _Squeeze(nn.Module):
    """ReLU -> 1x1 conv -> norm, aligning an input to F filters."""

    features: int
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        y = nn.Conv(self.features, (1, 1), use_bias=False, dtype=self.dtype, name="proj")(nn.relu(x))
        return _norm(y, "norm", self.dtype)


def _pool(kind, x, stride):
    op = nn.avg_pool if kind == "avg" else nn.max_pool
    return op(x, (3, 3), (stride, stride), padding="SAME")


class _NasnetCell(nn.Module):
    """One NASNet-A cell over (prev, cur) with 5 combination blocks.

    ``reduction=True`` applies the reduction-cell op set at stride 2.
    Outputs the concatenation of the unconsumed block outputs, the standard
    NASNet-A combination rule.
    """

    filters: int
    reduction: bool = False
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, prev, cur):
        d, f = self.dtype, self.filters
        s = 2 if self.reduction else 1
        # Align both inputs to F filters; align prev to cur's spatial size
        # by slim's factorized reduction (which also sets its filters, so
        # the squeeze is skipped on that path).
        if prev.shape[1] != cur.shape[1]:
            # ceil-div stride: SAME stride-2 reductions produce ceil(n/2), so
            # odd sizes (25 -> 13) need stride ceil(25/13) = 2, not floor = 1
            s_align = -(-prev.shape[1] // cur.shape[1])
            h0 = _FactorizedReduce(f, s_align, dtype=d, name="fr_prev")(prev)
        else:
            h0 = _Squeeze(f, dtype=d, name="sq_prev")(prev)
        h1 = _Squeeze(f, dtype=d, name="sq_cur")(cur)
        if self.reduction:
            # NASNet-A reduction cell (5 blocks, stride-2 first uses)
            b0 = _SepConv(f, 7, s, dtype=d, name="b0_l")(h0) + _SepConv(f, 5, s, dtype=d, name="b0_r")(h1)
            b1 = _pool("max", h1, s) + _SepConv(f, 7, s, dtype=d, name="b1_r")(h0)
            b2 = _pool("avg", h1, s) + _SepConv(f, 5, s, dtype=d, name="b2_r")(h0)
            b3 = _pool("max", h1, s) + _SepConv(f, 3, 1, dtype=d, name="b3_r")(b0)
            b4 = _pool("avg", b0, 1) + b1
            return jnp.concatenate([b1, b2, b3, b4], axis=-1)
        # NASNet-A normal cell (5 blocks, all stride 1)
        b0 = _SepConv(f, 3, dtype=d, name="b0_l")(h1) + h1
        b1 = _SepConv(f, 3, dtype=d, name="b1_l")(h0) + _SepConv(f, 5, dtype=d, name="b1_r")(h1)
        b2 = _pool("avg", h1, 1) + h0
        b3 = _pool("avg", h0, 1) + _pool("avg", h0, 1)
        b4 = _SepConv(f, 5, dtype=d, name="b4_l")(h0) + _SepConv(f, 3, dtype=d, name="b4_r")(h0)
        return jnp.concatenate([b0, b1, b2, b3, b4], axis=-1)


class _PnasnetCell(nn.Module):
    """One PNASNet-5 cell (same op set at stride 1 or 2)."""

    filters: int
    reduction: bool = False
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, prev, cur):
        d, f = self.dtype, self.filters
        s = 2 if self.reduction else 1
        if prev.shape[1] != cur.shape[1]:
            # ceil-div stride: SAME stride-2 reductions produce ceil(n/2), so
            # odd sizes (25 -> 13) need stride ceil(25/13) = 2, not floor = 1
            s_align = -(-prev.shape[1] // cur.shape[1])
            h0 = _FactorizedReduce(f, s_align, dtype=d, name="fr_prev")(prev)
        else:
            h0 = _Squeeze(f, dtype=d, name="sq_prev")(prev)
        h1 = _Squeeze(f, dtype=d, name="sq_cur")(cur)
        # PNASNet-5 blocks: (sep5x5, max3x3)(h0,h0); (sep7x7, max3x3)(h1,h1);
        # (sep5x5, sep3x3)(h1,h1); (sep3x3, none)(b?,h1); (sep3x3, none)(h0,h0)
        b0 = _SepConv(f, 5, s, dtype=d, name="b0_l")(h0) + _pool("max", h0, s)
        b1 = _SepConv(f, 7, s, dtype=d, name="b1_l")(h1) + _pool("max", h1, s)
        b2 = _SepConv(f, 5, s, dtype=d, name="b2_l")(h1) + _SepConv(f, 3, s, dtype=d, name="b2_r")(h1)
        b3 = _SepConv(f, 3, 1, dtype=d, name="b3_l")(b2) + b1
        b4 = _SepConv(f, 3, s, dtype=d, name="b4_l")(h0) + (h1 if s == 1 else _pool("max", h1, s))
        return jnp.concatenate([b0, b1, b2, b3, b4], axis=-1)


#: name -> (cell class, cells-per-stack N, first-stack cell filters F,
#: imagenet stem) — N and F are slim's num_cells/num_conv_filters per variant
#: (nasnet.py/pnasnet.py configs); filters double at each reduction.
NASNET_VARIANTS = {
    "nasnet_cifar": (_NasnetCell, 6, 32, False),
    "nasnet_mobile": (_NasnetCell, 4, 44, True),
    "nasnet_large": (_NasnetCell, 6, 168, True),
    "pnasnet_mobile": (_PnasnetCell, 3, 54, True),
    "pnasnet_large": (_PnasnetCell, 4, 216, True),
}


class NASNet(nn.Module):
    """NASNet-A / PNASNet-5 classifier: stem, 3 stacks of N cells separated
    by reduction cells, global pool, logits."""

    variant: str = "nasnet_cifar"
    classes: int = 10
    dtype: jnp.dtype = jnp.float32
    min_size: int = 32

    @nn.compact
    def __call__(self, x):
        cell_cls, n_cells, f, imagenet_stem = NASNET_VARIANTS[self.variant]
        d = self.dtype
        x = resize_min(x, self.min_size).astype(d)
        if imagenet_stem:
            x = nn.Conv(32, (3, 3), (2, 2), padding="SAME", use_bias=False, dtype=d, name="stem")(x)
        else:
            x = nn.Conv(32, (3, 3), padding="SAME", use_bias=False, dtype=d, name="stem")(x)
        x = _norm(x, "stem_norm", d)
        prev, cur = x, x
        idx = 0
        for stack in range(3):
            filters = f * (2 ** stack)
            if stack > 0:
                prev, cur = cur, cell_cls(filters, reduction=True, dtype=d,
                                          name="reduce_%d" % stack)(prev, cur)
            for _ in range(n_cells):
                prev, cur = cur, cell_cls(filters, dtype=d, name="cell_%d" % idx)(prev, cur)
                idx += 1
        x = nn.relu(cur)
        x = jnp.mean(x, axis=(1, 2)).astype(jnp.float32)
        return nn.Dense(self.classes, dtype=jnp.float32, name="logits")(x)

"""The model zoo: ``slim-<model>-<dataset>`` experiments.

Parity with the reference's slims experiments (experiments/slims.py:193-196),
which register every nets_factory network crossed with every locally present
dataset.  Here the factory maps names to fresh flax builders (resnet v1
family, vgg family) and the datasets are cifar10 and the ImageNet-shaped
stand-in; the experiment names keep the reference's ``slim-`` prefix so
driver scripts carry over unchanged.

Args (same surface as slims.py:69-76): ``batch-size``, ``eval-batch-size``,
``weight-decay``, ``label-smoothing``, ``labels-offset``, plus TPU-first
``dtype`` (float32/bfloat16 compute) and ``image-size`` for the ImageNet
stand-in.
"""

import jax
import jax.numpy as jnp
import optax

from ..utils import parse_keyval
from . import Experiment, register
from .classic import AlexNetV2, CifarNet, LeNet, OverFeat
from .datasets import (
    WorkerBatchIterator,
    eval_batches,
    load_cifar10,
    load_digits_upscaled,
    load_imagenet,
)
from .inception import InceptionResNetV2, InceptionV1, InceptionV2, InceptionV3, InceptionV4
from .mobilenet import (
    MOBILENET_MULTIPLIERS,
    MOBILENET_V2_MULTIPLIERS,
    MobileNetV1,
    MobileNetV2,
)
from .nasnet import NASNET_VARIANTS, NASNet
from .resnet import RESNET_DEPTHS, RESNET_V2_DEPTHS, ResNet
from .vgg import VGG_STAGES, VGG


def _make_factory():
    factory = {}
    for depth in RESNET_DEPTHS:
        factory["resnet_v1_%d" % depth] = (
            lambda classes, small, dtype, depth=depth: ResNet(
                depth=depth, classes=classes, small_inputs=small, dtype=dtype
            )
        )
    for depth in RESNET_V2_DEPTHS:
        factory["resnet_v2_%d" % depth] = (
            lambda classes, small, dtype, depth=depth: ResNet(
                depth=depth, classes=classes, small_inputs=small, preact=True, dtype=dtype
            )
        )
    for variant in VGG_STAGES:
        factory[variant] = (
            lambda classes, small, dtype, variant=variant: VGG(
                variant=variant, classes=classes, dense_units=512 if small else 4096, dtype=dtype
            )
        )
    factory["inception_v1"] = lambda classes, small, dtype: InceptionV1(classes=classes, dtype=dtype)
    factory["inception_v2"] = lambda classes, small, dtype: InceptionV2(classes=classes, dtype=dtype)
    factory["inception_v3"] = lambda classes, small, dtype: InceptionV3(classes=classes, dtype=dtype)
    factory["inception_v4"] = lambda classes, small, dtype: InceptionV4(classes=classes, dtype=dtype)
    factory["inception_resnet_v2"] = (
        lambda classes, small, dtype: InceptionResNetV2(classes=classes, dtype=dtype)
    )
    for name, mult in MOBILENET_MULTIPLIERS.items():
        factory[name] = (
            lambda classes, small, dtype, mult=mult: MobileNetV1(
                classes=classes, multiplier=mult, dtype=dtype
            )
        )
    for name, mult in MOBILENET_V2_MULTIPLIERS.items():
        factory[name] = (
            lambda classes, small, dtype, mult=mult: MobileNetV2(
                classes=classes, multiplier=mult, dtype=dtype
            )
        )
    for variant in NASNET_VARIANTS:
        factory[variant] = (
            lambda classes, small, dtype, variant=variant: NASNet(
                variant=variant, classes=classes, dtype=dtype
            )
        )
    factory["lenet"] = lambda classes, small, dtype: LeNet(classes=classes, dtype=dtype)
    factory["cifarnet"] = lambda classes, small, dtype: CifarNet(classes=classes, dtype=dtype)
    factory["alexnet_v2"] = (
        lambda classes, small, dtype: AlexNetV2(
            classes=classes, dense_units=512 if small else 4096, dtype=dtype
        )
    )
    factory["overfeat"] = (
        lambda classes, small, dtype: OverFeat(
            classes=classes, dense_units=512 if small else 3072, dtype=dtype
        )
    )
    return factory


MODEL_FACTORY = _make_factory()

#: Models with an auxiliary training head (the reference adds the aux-logits
#: loss for inception nets, experiments/slims.py:122-124; like slim, v2/BN-
#: inception has no aux head)
AUX_CAPABLE = {"inception_v1", "inception_v3", "inception_v4", "inception_resnet_v2"}

DATASETS = {
    "cifar10": lambda kv: load_cifar10(),
    "imagenet": lambda kv: load_imagenet(image_size=kv["image-size"]),
    # REAL data on a zero-egress box (datasets.load_digits_upscaled): the
    # zoo's accuracy-parity anchor — cifar10/imagenet above fall back to
    # synthetic stand-ins when no local shards exist, so committed zoo
    # accuracies that must mean something (VERDICT r4 task 6) train here.
    "digits32": lambda kv: load_digits_upscaled(32),
}


class ZooExperiment(Experiment):
    """One (model, dataset) pair from the factory."""

    model_name = None
    dataset_name = None

    def __init__(self, args):
        super().__init__(args)
        kv = parse_keyval(
            args,
            {
                "batch-size": 32,
                "eval-batch-size": 64,
                "weight-decay": 0.0,
                "label-smoothing": 0.0,
                "labels-offset": 0,
                "image-size": 224,
                "dtype": "float32",
                "aux-weight": 0.4,
                # slims.py:69-76 arg surface: train augmentation selection
                # (preprocessing_factory) + thread counts accepted for
                # drop-in compat (threading is --prefetch's job here)
                "preprocessing": "",
                "nb-fetcher-threads": 0,
                "nb-batcher-threads": 0,
                # host (reference-faithful: fetcher threads transform each
                # batch) or device (the same augmentation as a jnp transform
                # INSIDE the jitted step — frees the host path to a plain
                # gather and enables --input-source device; like cnnet's)
                "augment": "host",
            },
        )
        self.batch_size = kv["batch-size"]
        self.eval_batch_size = kv["eval-batch-size"]
        self.weight_decay = kv["weight-decay"]
        self.label_smoothing = kv["label-smoothing"]
        self.labels_offset = kv["labels-offset"]
        from .preprocessing import check as check_preprocessing, default_for

        # default follows the model name like slim's preprocessing_factory
        self.preprocessing = check_preprocessing(
            kv["preprocessing"] or default_for(self.model_name)
        )
        self.augment = kv["augment"]
        if self.augment not in ("host", "device"):
            from ..utils import UserException

            raise UserException("augment must be host|device, got %r" % (self.augment,))
        self.aux_weight = kv["aux-weight"] if self.model_name in AUX_CAPABLE else 0.0
        self.dataset = DATASETS[self.dataset_name](kv)
        from .common import check_dtype

        dtype = check_dtype(kv["dtype"])
        classes = self.dataset.nb_classes - self.labels_offset
        small = self.dataset.x_train.shape[1] <= 64
        self.model = MODEL_FACTORY[self.model_name](classes, small, dtype)
        self.sample_shape = self.dataset.x_train.shape[1:]

    def init(self, rng):
        sample = jnp.zeros((1,) + tuple(self.sample_shape), jnp.float32)
        if self.aux_weight > 0.0:  # also materializes the aux-head params
            return self.model.init(rng, sample, with_aux=True)
        return self.model.init(rng, sample)

    def _logits_labels(self, params, batch):
        return self.model.apply(params, batch["image"]), batch["label"] - self.labels_offset

    def _ce(self, logits, labels):
        if self.label_smoothing > 0.0:
            classes = logits.shape[-1]
            soft = optax.smooth_labels(jax.nn.one_hot(labels, classes), self.label_smoothing)
            return jnp.mean(optax.softmax_cross_entropy(logits, soft))
        return jnp.mean(optax.softmax_cross_entropy_with_integer_labels(logits, labels))

    def loss(self, params, batch):
        labels = batch["label"] - self.labels_offset
        if self.aux_weight > 0.0:
            logits, aux_logits = self.model.apply(params, batch["image"], with_aux=True)
            loss = self._ce(logits, labels) + self.aux_weight * self._ce(aux_logits, labels)
        else:
            logits = self.model.apply(params, batch["image"])
            loss = self._ce(logits, labels)
        if self.weight_decay > 0.0:
            # slim's l2_regularizer targets conv/fc kernels only, never norm
            # scales or biases (slims.py:69-76) — rank>1 leaves here.
            loss = loss + self.weight_decay * sum(
                jnp.sum(p.astype(jnp.float32) ** 2)
                for p in jax.tree_util.tree_leaves(params)
                if jnp.ndim(p) > 1
            )
        return loss

    def metrics(self, params, batch):
        logits, labels = self._logits_labels(params, batch)
        hit = (jnp.argmax(logits, axis=-1) == labels).astype(jnp.float32)
        valid = batch.get("valid")
        if valid is not None:
            hit = hit * valid
            count = jnp.sum(valid)
        else:
            count = jnp.float32(hit.shape[0])
        return {"accuracy": (jnp.sum(hit), count)}

    def make_train_iterator(self, nb_workers, seed=0):
        from .preprocessing import instantiate as make_preprocessing

        return WorkerBatchIterator(
            self.dataset.x_train, self.dataset.y_train, nb_workers, self.batch_size, seed=seed,
            transform=(None if self.augment == "device"
                       else make_preprocessing(self.preprocessing, seed=seed)),
        )

    # device_transform / train_arrays: Experiment base defaults keyed off
    # self.augment / self.preprocessing / self.dataset

    def make_eval_iterator(self, nb_workers):
        return eval_batches(self.dataset.x_test, self.dataset.y_test, nb_workers, self.eval_batch_size)


def _register_all():
    for model_name in MODEL_FACTORY:
        for dataset_name in DATASETS:
            name = "slim-%s-%s" % (model_name, dataset_name)
            cls = type(
                "Zoo_%s_%s" % (model_name, dataset_name),
                (ZooExperiment,),
                {"model_name": model_name, "dataset_name": dataset_name},
            )
            register(name, cls)


_register_all()

"""Tiny atomic JSON state files, shared by the capture/benchmark harnesses.

One load/save pair instead of three copies (watcher stage state, per-cell
robustness resume, per-config train_configs resume): load tolerates a
missing/corrupt/non-dict file by returning the default, save goes through a
tmp file + os.replace so a kill mid-write can never leave a half-written
state behind (the watcher's children are routinely killed by watchdogs).
"""

import json
import os


def load_json(path, default=None):
    """The dict stored at ``path``, or ``default`` (fresh {}) if unreadable."""
    try:
        with open(path) as fd:
            data = json.load(fd)
    except (OSError, ValueError):
        data = None
    if not isinstance(data, dict):
        return {} if default is None else default
    return data


def save_json_atomic(path, state):
    tmp = path + ".tmp"
    with open(tmp, "w") as fd:
        json.dump(state, fd, indent=1)
    os.replace(tmp, path)

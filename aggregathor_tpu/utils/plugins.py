"""Directory-based plugin auto-import.

The reference auto-imports every ``.py`` file in ``aggregators/`` and
``experiments/`` so plugins self-register at import time (reference:
tools/__init__.py:263-318).  Here plugins are regular modules inside a
package; ``import_directory`` imports every sibling module of the calling
package so drop-in files self-register the same way.
"""

import importlib
import pkgutil

from . import logging as log


def import_directory(package_name, package_path, skip=()):
    """Import every module in a package directory (plugins self-register on import).

    Args:
      package_name: the package's ``__name__``.
      package_path: the package's ``__path__``.
      skip:         module basenames to skip.
    Returns:
      list of imported module objects.
    """
    imported = []
    for modinfo in pkgutil.iter_modules(package_path):
        if modinfo.name.startswith("_") or modinfo.name in skip:
            continue
        try:
            imported.append(importlib.import_module(package_name + "." + modinfo.name))
        except log.UserException:
            raise
        except Exception as err:  # plugin failure must not take down the framework
            log.warning("Plugin module %r failed to import and was skipped: %s" % (modinfo.name, err))
    return imported

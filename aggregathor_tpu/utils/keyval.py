"""Typed ``key:value`` sub-argument parsing.

The reference passes plugin-specific options as lists of ``key:value`` strings
(e.g. ``--learning-rate-args initial-rate:0.05``) parsed against typed
defaults (reference: tools/misc.py:140-170).  Same contract here: the value
string is coerced to the type of the default when one is supplied; without a
default the value is auto-coerced (int, then float, then bool-ish, then str).
"""

from . import logging as log


def _auto(value):
    for cast in (int, float):
        try:
            return cast(value)
        except ValueError:
            pass
    low = value.lower()
    if low in ("true", "yes", "on"):
        return True
    if low in ("false", "no", "off"):
        return False
    return value


def _coerce(value, default):
    if isinstance(default, bool):
        return _auto(value) in (True, 1)
    return type(default)(value)


def parse_keyval(pairs, defaults=None, strict=False):
    """Parse a list of ``"key:value"`` strings into a dict.

    Args:
      pairs:    iterable of ``key:value`` strings (value may contain ':').
      defaults: optional dict of typed defaults; parsed values are coerced to
                the default's type, and missing keys take the default value.
      strict:   reject keys not present in ``defaults`` (catches typo'd or
                unsupported options instead of silently ignoring them).
    Returns:
      dict of key -> typed value.
    """
    result = dict(defaults) if defaults else {}
    seen = set()
    for pair in pairs or []:
        if ":" not in pair:
            raise log.UserException("Expected 'key:value' argument, got %r" % (pair,))
        key, value = pair.split(":", 1)
        if key in seen:
            raise log.UserException("Key %r had already been specified" % (key,))
        seen.add(key)
        if strict and key not in (defaults or {}):
            raise log.UserException(
                "Unknown key %r (accepted: %s)"
                % (key, ", ".join(sorted(defaults)) if defaults else "none")
            )
        if defaults is not None and key in defaults and defaults[key] is not None:
            try:
                result[key] = _coerce(value, defaults[key])
            except (TypeError, ValueError):
                raise log.UserException(
                    "Invalid value %r for key %r (expected %s)" % (value, key, type(defaults[key]).__name__)
                )
        else:
            result[key] = _auto(value)
    return result

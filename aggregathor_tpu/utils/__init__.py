"""Shared utilities: context logging, class registry, key:value parsing.

TPU-native re-design of the reference's ``tools/`` layer (reference:
tools/__init__.py, tools/misc.py).  Only behaviourally relevant pieces are
kept: the nested-context colored logger, the universal plugin registry and the
typed ``key:value`` CLI sub-argument parser.  TF-specific helpers
(trace_graph, device_from_tuple) are replaced by JAX-idiomatic equivalents in
``obs``/``parallel``.
"""

from .logging import (  # noqa: F401
    Context,
    UserException,
    trace,
    info,
    success,
    warning,
    error,
    fatal,
    replicate_streams,
)
from .registry import ClassRegister  # noqa: F401
from .keyval import parse_keyval  # noqa: F401
from .plugins import import_directory  # noqa: F401
from .access import can_access  # noqa: F401

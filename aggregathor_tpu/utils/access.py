"""Filesystem access pre-checks.

Parity with the reference's ``tools.access.can_access`` (tools/access.py:42-79),
which validates dataset/checkpoint directories up front so a long run fails
at startup rather than mid-training.  Written fresh on ``os.access`` — the
kernel's answer to "can this process read/write this path", which also
honors ACLs and capabilities that raw uid/gid/mode-bit arithmetic (the
reference's approach) cannot see.
"""

import os


def can_access(path, read=False, write=False, recurse=False):
    """Check that ``path`` exists with the requested access.

    For directories, checks listability plus the requested access on every
    entry — descending into subdirectories only when ``recurse`` is set
    (same contract as the reference).  Returns False on any failure,
    including the path not existing; never raises.
    """
    mode = os.F_OK | (os.R_OK if read else 0) | (os.W_OK if write else 0)
    try:
        if not os.path.exists(path):
            return False
        if os.path.isdir(path):
            if not os.access(path, mode | os.X_OK):  # X on a dir = traversable
                return False
            for entry in os.scandir(path):
                if entry.is_dir(follow_symlinks=True):
                    if recurse and not can_access(entry.path, read, write, recurse):
                        return False
                elif not os.access(entry.path, mode):
                    return False
            return True
        return os.access(path, mode)
    except OSError:
        return False

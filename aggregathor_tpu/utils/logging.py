"""Nested-context colored logging.

Re-implements the observable behaviour of the reference's ``tools.Context``
stack (reference: tools/__init__.py:52-227): log lines are prefixed with the
chain of active ``[context]`` headers for the current thread, severity
shortcuts colorize output when attached to a TTY, and ``fatal`` raises a
``UserException`` that the CLI converts into a clean ``exit(1)`` instead of a
traceback (reference: tools/__init__.py:232-258).

The implementation is deliberately simpler than the reference's stdout/stderr
stream wrapping: we format explicit log calls only, which keeps worker
processes (multi-host JAX) from fighting over a monkey-patched sys.stdout.
"""

import os
import sys
import threading

_LOCAL = threading.local()

_COLORS = {
    "trace": "\033[90m",
    "info": "\033[0m",
    "success": "\033[32m",
    "warning": "\033[33m",
    "error": "\033[31m",
    "fatal": "\033[1;31m",
}
_RESET = "\033[0m"


class UserException(RuntimeError):
    """Error caused by the user; reported without a traceback (reference: tools/__init__.py:232-244)."""


def _stack():
    stack = getattr(_LOCAL, "stack", None)
    if stack is None:
        stack = _LOCAL.stack = []
    return stack


class Context:
    """Context manager pushing a ``[name]`` header onto the current thread's log prefix."""

    def __init__(self, name):
        self.name = str(name)

    def __enter__(self):
        _stack().append(self.name)
        return self

    def __exit__(self, *exc):
        _stack().pop()
        return False


def _use_color(stream):
    if os.environ.get("NO_COLOR"):
        return False
    return hasattr(stream, "isatty") and stream.isatty()


def _emit(level, *args, stream=None):
    stream = stream if stream is not None else (sys.stderr if level in ("warning", "error", "fatal") else sys.stdout)
    prefix = "".join("[%s] " % name for name in _stack())
    thread = threading.current_thread()
    if thread is not threading.main_thread():
        prefix = "[%s] %s" % (thread.name, prefix)
    text = " ".join(str(a) for a in args)
    if _use_color(stream):
        stream.write("%s%s%s%s\n" % (_COLORS[level], prefix, text, _RESET))
    else:
        stream.write("%s%s\n" % (prefix, text))
    stream.flush()


def trace(*args):
    _emit("trace", *args)


def info(*args):
    _emit("info", *args)


def success(*args):
    _emit("success", *args)


def warning(*args):
    _emit("warning", "[warning]", *args)


def error(*args):
    _emit("error", "[error]", *args)


def fatal(*args):
    """Log at fatal severity and raise UserException (clean exit path)."""
    _emit("fatal", "[fatal]", *args)
    raise UserException(" ".join(str(a) for a in args))


class _Tee:
    """Write-through to a primary stream plus a log file (reference: tools/misc.py:45-78).

    Everything not overridden (fileno, buffer, encoding, ...) delegates to the
    primary stream, so low-level consumers (subprocess, faulthandler, C-level
    logging) keep working; only the text-mode ``write`` path is duplicated
    into the file.
    """

    def __init__(self, primary, path):
        self._primary = primary
        self._file = open(path, "a")

    def write(self, text):
        count = self._primary.write(text)
        self._file.write(text)
        self._file.flush()
        return count

    def flush(self):
        self._primary.flush()
        self._file.flush()

    def isatty(self):
        return False

    def __getattr__(self, name):
        return getattr(self._primary, name)


def replicate_streams(stdout_path=None, stderr_path=None):
    """Tee stdout/stderr into files (the reference's ``--stdout-to/--stderr-to``)."""
    if stdout_path:
        sys.stdout = _Tee(sys.stdout, stdout_path)
    if stderr_path:
        sys.stderr = _Tee(sys.stderr, stderr_path)

"""Universal plugin registry.

The reference wires experiments, aggregators and native ops through one
``ClassRegister`` (reference: tools/misc.py:83-135).  We keep the same three
verbs — ``itemize`` / ``register`` / ``instantiate`` — so every subsystem
(GARs, experiments, attacks, optimizers, schedules) resolves names the same
way from the CLI.
"""

from . import logging as log


class ClassRegister:
    """Name -> class register with uniform error reporting."""

    def __init__(self, singular, plural=None):
        self._singular = singular
        self._plural = plural or (singular + "s")
        self._register = {}

    def itemize(self):
        """List the registered names, sorted."""
        return sorted(self._register.keys())

    def register(self, name, cls):
        """Register ``cls`` under ``name``; warns and overwrites on duplicate."""
        if name in self._register:
            log.warning("%s %r is already registered; overwriting" % (self._singular.capitalize(), name))
        self._register[name] = cls
        return cls

    def get(self, name):
        """Return the registered class, or raise UserException listing the alternatives."""
        if name not in self._register:
            raise log.UserException(
                "Unknown %s %r; available %s: %s"
                % (self._singular, name, self._plural, ", ".join(self.itemize()) or "<none>")
            )
        return self._register[name]

    def instantiate(self, name, *args, **kwargs):
        """Build an instance of the class registered under ``name``."""
        return self.get(name)(*args, **kwargs)

    def __contains__(self, name):
        return name in self._register

"""Process-lifecycle helpers shared by the benchmark/capture entry points.

One concern lives here: making SIGTERM unwind the interpreter instead of
killing the process outright.  The capture watcher (scripts/tpu_capture.py)
and bench.py's watchdog escalate TERM-before-KILL so a timed-out child can
close its tunneled-backend connection cleanly — hard-killing a client
mid-RPC is a plausible trigger for wedging the backend for every subsequent
client (both multi-hour chip-down records in benchmarks/tpu_capture.jsonl
start right after a SIGKILL mid-operation).  CPython's DEFAULT SIGTERM
disposition terminates as abruptly as SIGKILL, so every TERM-able entry
point must install this handler for the escalation to buy anything.
"""

import signal
import sys


def graceful_sigterm(code=143):
    """Install a SIGTERM handler that raises SystemExit(code).

    SystemExit unwinds the main thread: ``finally`` blocks and ``atexit``
    hooks run, which is where the JAX backend client tears down its
    connection.  143 = 128 + SIGTERM, the conventional shell exit code.
    """
    signal.signal(signal.SIGTERM, lambda *_: sys.exit(code))

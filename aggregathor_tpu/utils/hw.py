"""Accelerator hardware constants shared by the benchmark harnesses.

One place for the chip envelope so a hardware change edits one file
(consumers: bench.py, benchmarks/opt_sweep.py, benchmarks/mfu_probe.py).
Values are for the TPU v5e (v5litepod) chip this environment tunnels to.
"""

#: bf16 matmul peak, FLOP/s per chip
V5E_PEAK_BF16_FLOPS = 1.97e14

#: HBM bandwidth, bytes/s per chip
V5E_HBM_BYTES_PER_S = 8.19e11

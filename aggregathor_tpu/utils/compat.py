"""JAX cross-version shims for the two engine-facing APIs that moved.

The engines target the current ``jax.shard_map`` / ``jax.set_mesh`` surface;
older installations (<= 0.4.x) ship the same functionality as
``jax.experimental.shard_map.shard_map`` (whose replication check is spelled
``check_rep`` rather than ``check_vma``) and have no ``set_mesh`` — there the
``Mesh`` object itself is the context manager.  Everything else the engines
use lowers identically on both surfaces, so these two adapters are the whole
compatibility story (tier-1 runs them on whichever JAX the box has).
"""

import jax


def shard_map(f, *, mesh, in_specs, out_specs, check_vma):
    """``jax.shard_map`` with graceful fallback to the experimental API.

    ``check_vma`` is deliberately REQUIRED: ``jax.shard_map`` defaults it to
    True and the engines always pass False — a shim default would silently
    invert one contract or the other for future call sites."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, check_vma=check_vma
        )
    from jax.experimental.shard_map import shard_map as _shard_map

    return _shard_map(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, check_rep=check_vma
    )


def set_mesh(mesh):
    """Context manager installing ``mesh`` as the ambient mesh."""
    if hasattr(jax, "set_mesh"):
        return jax.set_mesh(mesh)
    # Pre-0.5 JAX: the Mesh object is its own context manager.
    return mesh

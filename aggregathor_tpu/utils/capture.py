"""Shared predicate: is a benchmark result row a COMPLETE TPU capture?

Two consumers must agree on this or they diverge (they did, once): the
up-window watcher (scripts/tpu_capture.py) uses it to decide stage
retirement, and bench.py uses it to pick which banked row to surface as
TPU evidence when the chip is down at measurement time.
"""


def is_complete_tpu_datum(row):
    """True iff ``row`` is a real, complete TPU-captured number.

    A harness may exit 0 yet carry only CPU-fallback, error, or
    phase-partial rows (bench.py emits an updated row after EVERY phase) —
    those must not count as a finished capture.
    """
    if row.get("error"):
        return False
    detail = row.get("detail") or {}
    if detail.get("banked_capture"):
        # An ECHO: bench.py re-emits a previously banked TPU row as its
        # primary result on chip-down (provenance in banked_capture_ts).
        # It must never retire a stage or be re-selected as evidence —
        # no measurement ran.
        return False
    platform = row.get("platform") or detail.get("platform") or ""
    if str(row.get("metric", "")).startswith("cnnet_cifar10_multikrum"):
        # bench.py rows: complete only once the LAST phase (the bf16
        # secondary's resident rate) has been written.
        return (platform == "tpu"
                and bool((detail.get("bfloat16") or {}).get("steps_per_s_resident_batch")))
    if platform:
        return platform == "tpu"
    tier = row.get("tier", "")
    if tier:  # gar_kernels rows carry a tier, not a platform
        return tier == "pallas" or tier.endswith(":tpu")
    if row.get("metric") == "pallas_tpu_check":  # script itself exits 2 off-TPU
        return row.get("parity") == "ok"
    return False

"""Cluster-spec resolution for multi-host bring-up.

The reference's ``tools/cluster.py`` (:48-91) turns a ``--cluster`` argument
— inline JSON, a JSON file, or the special ``'G5k'`` keyword that reads
Grid'5000's ``$OAR_FILE_NODES`` nodefile — into the TF ClusterSpec
(``{"ps": [first:7000], "workers": [rest:7000]}``) its deployer wires up.

Under single-controller SPMD there is no ps/worker split to build; what a
deployment still needs from the same inputs is the
``jax.distributed.initialize`` triple: *(coordinator_address,
num_processes, process_id)*.  This module maps each reference input form to
that triple:

- inline JSON — ``'["a","b"]'`` or ``'{"hosts": ["a","b"], "port": 7000}'``
  (the reference's explicit-spec form, tools/cluster.py:81-87);
- a path to a file holding that JSON, or a plain nodefile (one host per
  line, duplicates collapsed — the OAR file format);
- ``'G5k'`` — read the nodefile named by ``$OAR_FILE_NODES``
  (tools/cluster.py:48-68), coordinator = first host, like the reference
  electing it the PS.

``process_id`` is resolved by matching the local hostname against the host
list (OAR gives no rank env), overridable via ``$AGGREGATHOR_PROCESS_ID``
for launchers that do export a rank.
"""

import json
import os
import socket

from . import UserException

DEFAULT_PORT = 7000  # the reference's fixed port (tools/cluster.py:60)


def parse_nodefile(path):
    """Unique hostnames in first-seen order (OAR repeats one line per core)."""
    try:
        with open(path) as fd:
            lines = [line.strip() for line in fd]
    except OSError as exc:
        raise UserException("Cannot read nodefile %r: %s" % (path, exc))
    hosts = []
    for line in lines:
        if line and line not in hosts:
            hosts.append(line)
    if not hosts:
        raise UserException("Nodefile %r lists no hosts" % (path,))
    return hosts


def _hosts_from_json(value):
    """Accept ``["a", "b"]`` or ``{"hosts": [...], "port": N}``."""
    port = None
    if isinstance(value, dict):
        port = value.get("port")
        if port is not None and not isinstance(port, int):
            raise UserException(
                'Cluster JSON "port" must be an integer (got %r)' % (port,)
            )
        value = value.get("hosts")
    if not isinstance(value, (list, tuple)) or not value or not all(
        isinstance(h, str) and h for h in value
    ):
        raise UserException(
            "Cluster JSON must be a non-empty host list or "
            '{"hosts": [...], "port": N}'
        )
    return list(value), port


def _local_names():
    names = {socket.gethostname()}
    try:
        names.add(socket.getfqdn())
    except OSError:
        pass
    names.update({n.split(".")[0] for n in tuple(names)})
    return names


def resolve_process_id(hosts):
    """This host's rank: $AGGREGATHOR_PROCESS_ID, else hostname match."""
    override = os.environ.get("AGGREGATHOR_PROCESS_ID")
    if override is not None:
        try:
            rank = int(override)
        except ValueError:
            raise UserException(
                "AGGREGATHOR_PROCESS_ID=%r is not an integer rank" % (override,)
            )
        if not 0 <= rank < len(hosts):
            raise UserException(
                "AGGREGATHOR_PROCESS_ID=%d out of range for %d hosts" % (rank, len(hosts))
            )
        return rank
    local = _local_names()
    for rank, host in enumerate(hosts):
        bare = host.split(":")[0]
        if bare in local or bare.split(".")[0] in {n.split(".")[0] for n in local}:
            return rank
    raise UserException(
        "Cannot resolve this host's rank: %s matches none of %s; set "
        "AGGREGATHOR_PROCESS_ID" % (sorted(local), hosts)
    )


def cluster_spec(argument, port=None):
    """``--cluster`` argument -> (coordinator_address, num_processes, process_id).

    Reference parity: the same three input forms as ``cluster_parse``
    (tools/cluster.py:81-91), mapped to the SPMD bring-up triple instead of
    a ps/workers ClusterSpec."""
    spec_port = None
    if argument.strip() == "G5k":  # the reference's special parser keyword
        nodefile = os.environ.get("OAR_FILE_NODES")
        if not nodefile:
            raise UserException(
                "--cluster G5k needs $OAR_FILE_NODES (run inside an OAR job, "
                "tools/cluster.py:48-68)"
            )
        hosts = parse_nodefile(nodefile)
    else:
        stripped = argument.strip()
        if stripped[:1] in ("[", "{"):
            try:
                value = json.loads(stripped)
            except ValueError as exc:
                raise UserException("Invalid cluster JSON: %s" % (exc,))
            hosts, spec_port = _hosts_from_json(value)
        elif os.path.exists(stripped):
            try:
                with open(stripped) as fd:
                    content = fd.read()
            except OSError as exc:
                raise UserException("Cannot read cluster spec %r: %s" % (stripped, exc))
            if content[:1] in ("[", "{"):
                try:
                    value = json.loads(content)
                except ValueError as exc:
                    raise UserException(
                        "Invalid cluster JSON in %r: %s" % (stripped, exc)
                    )
                hosts, spec_port = _hosts_from_json(value)
            else:
                hosts = parse_nodefile(stripped)
        else:
            raise UserException(
                "--cluster must be 'G5k', inline JSON, or a readable "
                "nodefile/JSON path (got %r)" % (argument,)
            )
    use_port = port if port is not None else (spec_port if spec_port else DEFAULT_PORT)
    coordinator = hosts[0]
    if ":" not in coordinator:
        coordinator = "%s:%d" % (coordinator, use_port)
    return coordinator, len(hosts), resolve_process_id(hosts)

"""Static configuration defaults.

Mirrors the reference's tunable defaults (reference: config.py:42-66) minus the
parameter-server job names, which have no equivalent in the single-controller
SPMD design (there is no PS process; the GAR reduction point lives inside the
jitted step function).
"""

# Training (reference: config.py:47-51)
default_max_step = 10000
default_learning_rate = 1e-3
default_end_learning_rate = 1e-4
default_decay_step = 10000
default_decay_rate = 0.96

# Evaluation / checkpointing / summaries (reference: config.py:54-61)
default_evaluation_file_name = "eval"
default_evaluation_delta = -1
default_evaluation_period = 10.0
default_checkpoint_base_name = "model"
default_checkpoint_delta = -1
default_checkpoint_period = 120.0
default_summary_delta = -1
default_summary_period = 30.0

# Delay in the polling loop of the eval/checkpoint/summary daemon threads
# (reference: config.py:66)
thread_idle_delay = 1.0

# Mesh axis names used throughout the parallel engine
worker_axis = "worker"   # data-parallel Byzantine-worker axis
pipe_axis = "pipe"       # pipeline-parallel stage axis inside each worker
model_axis = "model"     # tensor-parallel axis inside each stage; sequence
                         # parallelism (ring attention / Megatron-SP gathers)
                         # and expert parallelism (MoE all_to_all) ride this
                         # same axis in different ops, the standard TPU layout

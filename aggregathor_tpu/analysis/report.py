"""Machine-readable analysis report — schema ``aggregathor.analysis.report.v1``.

One JSON document per run (registered in BENCHMARKS.md's schema index like
every other measurement artifact in this repo), consumed by
``scripts/run_analysis.sh`` and any CI that wants structure instead of
exit codes.  ``validate_report`` is the shared schema check used by the
tests and the smoke script — the same pattern as
``aggregathor.chaos.resilience-matrix.v1`` et al.
"""

import json
import time

SCHEMA = "aggregathor.analysis.report.v1"


def build_report(root, checkers, unbaselined, baselined, issues,
                 baseline_path=None, justifications=None):
    """Assemble the report document from ``baseline.apply`` output."""
    justifications = justifications or {}

    def rows(findings, status):
        out = []
        for f in findings:
            doc = f.to_json()
            doc["status"] = status
            if status == "baselined":
                doc["justification"] = justifications.get(f.fingerprint, "")
            out.append(doc)
        return out

    findings = (
        rows(unbaselined, "unbaselined")
        + rows(baselined, "baselined")
        + rows(issues, "baseline-issue")
    )
    return {
        "schema": SCHEMA,
        "generated_at": time.strftime("%Y-%m-%dT%H:%M:%S"),
        "root": root,
        "checkers": list(checkers),
        "baseline": baseline_path,
        "counts": {
            "total": len(unbaselined) + len(baselined) + len(issues),
            "unbaselined": len(unbaselined),
            "baselined": len(baselined),
            "baseline_issues": len(issues),
        },
        "clean": not unbaselined and not issues,
        "findings": findings,
    }


def validate_report(doc):
    """Raise ValueError unless ``doc`` is a well-formed v1 report."""
    if not isinstance(doc, dict):
        raise ValueError("report wants a JSON object")
    if doc.get("schema") != SCHEMA:
        raise ValueError("report schema %r wants %r" % (doc.get("schema"), SCHEMA))
    for field in ("generated_at", "root", "checkers", "counts", "clean", "findings"):
        if field not in doc:
            raise ValueError("report misses field %r" % field)
    counts = doc["counts"]
    for field in ("total", "unbaselined", "baselined", "baseline_issues"):
        if not isinstance(counts.get(field), int):
            raise ValueError("report counts miss integer %r" % field)
    if counts["total"] != len(doc["findings"]):
        raise ValueError("counts.total %d != %d findings"
                         % (counts["total"], len(doc["findings"])))
    if counts["total"] != (counts["unbaselined"] + counts["baselined"]
                           + counts["baseline_issues"]):
        raise ValueError("counts do not add up")
    statuses = {"unbaselined", "baselined", "baseline-issue"}
    for row in doc["findings"]:
        for field in ("checker", "code", "path", "line", "scope", "symbol",
                      "message", "fingerprint", "status"):
            if field not in row:
                raise ValueError("finding row misses field %r" % field)
        if row["status"] not in statuses:
            raise ValueError("finding status %r unknown" % row["status"])
    if doc["clean"] != (counts["unbaselined"] == 0 and counts["baseline_issues"] == 0):
        raise ValueError("clean flag disagrees with counts")
    return doc


def save_report(path, doc):
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(doc, fh, indent=2, sort_keys=True)
        fh.write("\n")

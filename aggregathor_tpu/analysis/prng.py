"""PRNG-hygiene checker: one key, one consumer.

JAX keys are values, not streams — feeding the same key to two samplers
yields IDENTICAL draws, and reusing a key after splitting it reuses the
randomness the split already spent.  In this codebase that is not a
style nit: the attack, lossy-link and GAR permutation streams are all
derived from one per-step key by ``fold_in`` tags (``GAR_KEY_TAG``), and a
collision silently correlates the adversary with the defense.  Dynamic
tests only notice when the correlated draws happen to change a golden;
this checker proves the absence of the reuse *patterns* package-wide.

Rules (per function body, forward dataflow over local names):

- **PK001 key reuse** — a key name consumed twice with no intervening
  rebind.  Consumption = passing the key to a sampler (``jax.random.*``),
  to ``split`` (without rebinding the same name), or to any other callable
  (a "consumer" — two different consumers of one key is exactly the bug).
  ``fold_in(key, tag)`` does NOT consume: folding distinct data mints
  distinct keys (the engine idiom) — but two *textually identical*
  ``fold_in`` calls in one straight-line region are a reuse.
- **PK002 dropped split** — a ``split``/``fold_in`` result that is never
  bound (bare expression statement) or a split target never read
  afterwards: randomness was minted and thrown away, which almost always
  means some consumer is still holding the parent key.

Approximation contract (docs/analysis.md): branches fork the state and
merge optimistically (a kill in one arm does not kill after the join);
loop bodies are analyzed once with no cross-iteration carry — both choices
trade recall for a near-zero false-positive rate, the right trade for a
gate that must stay green on every PR.
"""

import ast

from .core import Finding, callee_name

CHECKER = "prng"

#: callee tails that mint keys
KEY_MAKERS = frozenset({"PRNGKey", "key", "split", "fold_in"})

#: jax.random sampler tails that consume a key (first arg or ``key=``)
SAMPLERS = frozenset({
    "normal", "uniform", "bernoulli", "permutation", "randint", "choice",
    "gumbel", "truncated_normal", "categorical", "bits", "exponential",
    "laplace", "shuffle", "beta", "dirichlet", "gamma", "poisson",
    "rademacher", "ball", "orthogonal", "multivariate_normal",
})

#: parameter-name shapes that declare a key argument
KEY_PARAM_NAMES = frozenset({"key", "rng", "prng", "prng_key", "rng_key"})

LIVE, CONSUMED = "live", "consumed"

#: roots under which ``split``/``fold_in``/``PRNGKey`` are the jax.random
#: ones (``setting.split("=")`` must not look like key surgery)
RANDOM_ROOTS = frozenset({"jax", "random", "jrandom", "jr"})

#: call roots that never consume a key stream: passing a key through
#: numerical/structural ops (jnp.stack of keys, a debug norm) is not a
#: second CONSUMER in the reuse sense
NONCONSUMING_ROOTS = frozenset({"jnp", "np", "numpy", "lax", "math", "len",
                                "print", "repr", "str", "int", "float",
                                "isinstance", "type", "list", "tuple"})


def _key_op(call):
    """``split``/``fold_in``/``PRNGKey``/``key`` when ``call`` is a
    jax.random operation (bare name, or dotted under a random-ish root),
    else None."""
    name = callee_name(call)
    if name is None:
        return None
    parts = name.split(".")
    tail = parts[-1]
    if tail not in KEY_MAKERS:
        return None
    if len(parts) == 1:
        return tail  # ``from jax import random`` style bare import
    return tail if parts[0] in RANDOM_ROOTS else None


def _is_key_param(name):
    return name in KEY_PARAM_NAMES or name.endswith("_key") or name.endswith("_rng")


def _store_names(target):
    return [n.id for n in ast.walk(target)
            if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Store)]


class _FunctionState:
    """Per-linear-region key liveness; forked at branches."""

    def __init__(self):
        self.keys = {}        # name -> LIVE | CONSUMED
        self.consumed_at = {}  # name -> (line, how)
        self.folds = {}       # name -> {call-dump}

    def fork(self):
        child = _FunctionState()
        child.keys = dict(self.keys)
        child.consumed_at = dict(self.consumed_at)
        child.folds = {k: set(v) for k, v in self.folds.items()}
        return child

    def merge(self, *branches):
        # optimistic join: a key is CONSUMED after the join only when EVERY
        # branch consumed it (a kill in one arm must not convict the other)
        for name in list(self.keys):
            states = [b.keys.get(name, self.keys[name]) for b in branches]
            if all(s == CONSUMED for s in states) and states:
                self.keys[name] = CONSUMED
                for b in branches:
                    if name in b.consumed_at:
                        self.consumed_at[name] = b.consumed_at[name]
                        break
        for b in branches:
            for name, dumps in b.folds.items():
                # every arm's folds stay recorded past the join: a later
                # textually identical fold collides with WHICHEVER arm ran
                # (duplicates ACROSS arms are distinct paths — each arm was
                # checked in isolation, so they were never flagged)
                self.folds.setdefault(name, set()).update(dumps)


def _param_names(func):
    """POSITIONAL parameter names, in binding order (used to map caller
    positional args onto callee params)."""
    args = func.args
    return [a.arg for a in list(args.posonlyargs) + list(args.args)]


def _all_param_names(func):
    """Every parameter name incl. keyword-only (used to SEED the
    derive-only table — a kw-only ``def draw(*, key)`` is as much a key
    consumer surface as a positional one)."""
    args = func.args
    return _param_names(func) + [a.arg for a in args.kwonlyargs]


def _calls_taking(func, param):
    """Call nodes in ``func`` with ``param`` as a direct argument."""
    for node in ast.walk(func):
        if isinstance(node, ast.Call):
            direct = list(node.args) + [kw.value for kw in node.keywords]
            if any(isinstance(a, ast.Name) and a.id == param for a in direct):
                yield node


def _resolve_callee(module, call):
    """Function defs a call may denote, intra-module (bare name or
    ``self.X``/``cls.X`` against every class — the over-approximation the
    concurrency checker also uses)."""
    fn = call.func
    if isinstance(fn, ast.Name):
        return [f for f in module.functions() if f.name == fn.id]
    if isinstance(fn, ast.Attribute) and isinstance(fn.value, ast.Name) \
            and fn.value.id in ("self", "cls"):
        return [f for f in module.functions() if f.name == fn.attr]
    return []


def _receiving_params(call, callee, param):
    """Names of ``callee``'s params bound to caller-side name ``param``."""
    params = _param_names(callee)
    method = bool(params) and params[0] in ("self", "cls")
    if method:
        params = params[1:]
    received = []
    for i, arg in enumerate(call.args):
        if isinstance(arg, ast.Name) and arg.id == param and i < len(params):
            received.append(params[i])
    for kw in call.keywords:
        if isinstance(kw.value, ast.Name) and kw.value.id == param and kw.arg:
            received.append(kw.arg)
    return received


def derive_only_params(module):
    """Greatest-fixpoint set of ``(function, param)`` pairs where the key
    param is only ever DERIVED from (``fold_in`` with fresh data, or handed
    to another derive-only param) — the engine idiom: one per-step key,
    disjoint ``fold_in`` tags per consumer (``GAR_KEY_TAG``).  Passing a
    key to such a function is not a consumption."""
    table = {}
    for func in module.functions():
        for param in _all_param_names(func):
            if _is_key_param(param):
                table[(func, param)] = True
    changed = True
    while changed:
        changed = False
        for (func, param), ok in list(table.items()):
            if not ok:
                continue
            for call in _calls_taking(func, param):
                if _key_op(call) == "fold_in":
                    continue
                root = (callee_name(call) or "").split(".")[0]
                if root in NONCONSUMING_ROOTS:
                    continue
                callees = _resolve_callee(module, call)
                if callees and all(
                    table.get((c, q), False)
                    for c in callees
                    for q in (_receiving_params(call, c, param) or [None])
                ) and all(_receiving_params(call, c, param) for c in callees):
                    continue  # delegated to (currently) derive-only params
                table[(func, param)] = False
                changed = True
                break
    return {pair for pair, ok in table.items() if ok}


class Checker:
    def __init__(self, module, func, derive_only=frozenset()):
        self.module = module
        self.func = func
        self.scope = module.qualname(func)
        self.derive_only = derive_only
        self.findings = []
        self.split_targets = {}  # name -> line (for the unread-split pass)

    def finding(self, code, line, symbol, message):
        self.findings.append(Finding(
            CHECKER, code, self.module.path, line, self.scope, symbol, message,
        ))

    # ------------------------------------------------------------------ #

    def run(self):
        state = _FunctionState()
        args = self.func.args
        for a in list(args.posonlyargs) + list(args.args) + list(args.kwonlyargs):
            if _is_key_param(a.arg):
                state.keys[a.arg] = LIVE
        self._block(self.func.body, state)
        self._unread_splits()
        return self.findings

    def _unread_splits(self):
        """PK002: split targets never read after their binding."""
        loads = {}
        for node in ast.walk(self.func):
            if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load):
                loads.setdefault(node.id, []).append(node.lineno)
        for name, line in self.split_targets.items():
            if name.startswith("_"):
                continue  # explicit discard
            if not any(at > line or at == line for at in loads.get(name, [])):
                self.finding(
                    "PK002", line, name,
                    "split result %r is never consumed: randomness minted "
                    "and dropped — the parent key is probably still doing "
                    "its job" % name,
                )

    # ------------------------------------------------------------------ #

    def _block(self, stmts, state):
        for stmt in stmts:
            self._stmt(stmt, state)

    def _stmt(self, stmt, state):
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return  # nested defs get their own Checker
        if isinstance(stmt, ast.If):
            self._expr(stmt.test, state)
            then, other = state.fork(), state.fork()
            self._block(stmt.body, then)
            self._block(stmt.orelse, other)
            state.merge(then, other)
            return
        if isinstance(stmt, (ast.For, ast.AsyncFor, ast.While)):
            if isinstance(stmt, (ast.For, ast.AsyncFor)):
                self._expr(stmt.iter, state)
            else:
                self._expr(stmt.test, state)
            body = state.fork()
            # fresh fold/consumption memory per iteration: cross-iteration
            # reuse of fold_in(key, i) with loop-varying data is the IDIOM
            for name in list(body.folds):
                body.folds[name] = set()
            self._block(stmt.body, body)
            self._block(stmt.orelse, state)
            return
        if isinstance(stmt, (ast.Try,)):
            body = state.fork()
            self._block(stmt.body, body)
            for handler in stmt.handlers:
                self._block(handler.body, state.fork())
            self._block(stmt.orelse, body)
            self._block(stmt.finalbody, body)
            state.merge(body)
            return
        if isinstance(stmt, ast.With):
            for item in stmt.items:
                self._expr(item.context_expr, state)
            self._block(stmt.body, state)
            return
        if isinstance(stmt, ast.Assign):
            self._assign(stmt.targets, stmt.value, state)
            return
        if isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
            self._assign([stmt.target], stmt.value, state)
            return
        if isinstance(stmt, ast.Expr):
            call = stmt.value
            if isinstance(call, ast.Call) and _key_op(call) in ("split", "fold_in"):
                self.finding(
                    "PK002", stmt.lineno, _key_op(call),
                    "%s(...) result discarded: the fresh key is lost and "
                    "the parent key stays in circulation" % _key_op(call),
                )
                return
            self._expr(stmt.value, state)
            return
        if isinstance(stmt, ast.Return) and stmt.value is not None:
            # returning the key ITSELF hands ownership out (not a
            # consumption) — but samplers inside the returned expression
            # absolutely consume (`return normal(key, ...)`)
            if not isinstance(stmt.value, ast.Name):
                self._expr(stmt.value, state)
            return
        for child in ast.iter_child_nodes(stmt):
            if isinstance(child, ast.expr):
                self._expr(child, state)
            elif isinstance(child, ast.stmt):
                self._stmt(child, state)

    # ------------------------------------------------------------------ #

    def _key_args(self, call, state):
        """Tracked key names appearing as arguments of ``call``."""
        names = []
        for arg in list(call.args) + [kw.value for kw in call.keywords]:
            if isinstance(arg, ast.Name) and arg.id in state.keys:
                names.append(arg.id)
        return names

    def _consume(self, name, state, line, how):
        if state.keys.get(name) == CONSUMED:
            prev_line, prev_how = state.consumed_at.get(name, (line, how))
            self.finding(
                "PK001", line, name,
                "key %r consumed twice without an intervening split/fold_in "
                "(first %s at line %d, again %s here): both consumers see "
                "IDENTICAL randomness" % (name, prev_how, prev_line, how),
            )
        state.keys[name] = CONSUMED
        state.consumed_at[name] = (line, how)

    def _is_sampler(self, call):
        name = callee_name(call)
        if name is None:
            return False
        parts = name.split(".")
        if parts[-1] not in SAMPLERS:
            return False
        return len(parts) == 1 or parts[0] in RANDOM_ROOTS

    def _fold(self, call, state):
        for name in self._key_args(call, state):
            dump = ast.dump(call)
            seen = state.folds.setdefault(name, set())
            if dump in seen:
                self.finding(
                    "PK001", call.lineno, name,
                    "identical fold_in of key %r twice in one region: both "
                    "folds mint the SAME key" % name,
                )
            seen.add(dump)

    def _assign(self, targets, value, state):
        stores = []
        for t in targets:
            stores.extend(_store_names(t))
        if isinstance(value, ast.Call):
            op = _key_op(value)
            key_args = self._key_args(value, state)
            if op == "split":
                for name in key_args:
                    if name not in stores:
                        # split without rebinding the parent: the parent key
                        # is spent — any later consumer reuses it (PK001 via
                        # _consume when it was already spent here)
                        self._consume(name, state, value.lineno, "by split")
                for name in stores:
                    state.keys[name] = LIVE
                    self.split_targets.setdefault(name, value.lineno)
                return
            if op == "fold_in":
                self._fold(value, state)
                for name in stores:
                    state.keys[name] = LIVE
                return
            if op in ("PRNGKey", "key"):
                for name in stores:
                    state.keys[name] = LIVE
                return
            # not a key op: sampler / generic call — consumes its key args
            self._expr(value, state)
            for name in stores:
                if name in state.keys:
                    # rebound from a non-key value: stop tracking as a key
                    del state.keys[name]
            return
        # non-call value: alias/rebind clears tracking for the target names
        for name in stores:
            if name in state.keys:
                del state.keys[name]
        self._expr(value, state)

    def _expr(self, expr, state):
        for node in ast.walk(expr):
            if not isinstance(node, ast.Call):
                continue
            op = _key_op(node)
            if op == "fold_in":
                self._fold(node, state)
                continue
            if op == "split":
                for name in self._key_args(node, state):
                    self._consume(name, state, node.lineno, "by split")
                continue
            if op is not None:
                continue  # PRNGKey(...) mints, consumes nothing
            key_args = self._key_args(node, state)
            if not key_args:
                continue
            if self._is_sampler(node):
                for name in key_args:
                    self._consume(
                        name, state, node.lineno,
                        "by sampler %s" % (callee_name(node) or "?"),
                    )
                continue
            root = (callee_name(node) or "").split(".")[0]
            if root in NONCONSUMING_ROOTS:
                continue  # numerical/structural op, not a stream consumer
            callees = _resolve_callee(self.module, node)
            for name in key_args:
                if callees and all(
                    (c, q) in self.derive_only
                    for c in callees
                    for q in _receiving_params(node, c, name)
                ) and all(_receiving_params(node, c, name) for c in callees):
                    continue  # callee only fold_ins the key: not a consumer
                self._consume(
                    name, state, node.lineno,
                    "by %s" % (callee_name(node) or "a call"),
                )


def check_module(module):
    findings = []
    derive_only = derive_only_params(module)
    for func in module.functions():
        findings.extend(Checker(module, func, derive_only).run())
    return findings


def check(modules):
    findings = []
    for module in modules:
        findings.extend(check_module(module))
    return findings

"""Journal-event checker: every ``emit`` names a declared event type.

The causal run journal (``obs/events.py``) is only a timeline if every
event type is DECLARED in its schema registry — an undeclared emit would
raise at the moment the decision it records fires (the worst possible
time), and a dynamically-computed type name cannot be validated at all.
Runtime validation catches the configured paths; this checker proves the
property over the WHOLE package, the graftcheck way (docs/analysis.md):

- **EV001** — a resolved journal ``emit(...)`` call whose event type is
  (a) a string literal NOT declared in ``obs.events.EVENT_TYPES``, (b) not
  a string literal at all (unverifiable statically), or (c) missing.
- **EV002** — a resolved journal ``emit(...)`` of an ACTION event type
  (``obs.events.ACTION_EVENT_TYPES`` — restarts, retunes, rollbacks,
  retries, exclusions: the events that CHANGE the fleet) without an
  explicit ``cause=`` keyword.  ``cause=None`` is legal — some actions
  genuinely have no journal-event trigger (a liveness restart's evidence
  is the ABSENCE of scrapes) — but the author must SAY so at the emit
  site; an action event silently minted without the kwarg is exactly how
  orphan actions (obs/causal.py) enter a postmortem.

Resolution is conservative and import-driven: a call counts as a journal
emit only when its callee resolves to the events module through the file's
own imports (``from ..obs import events; events.emit(...)``,
``from ..obs import events as obs_events``, ``from ..obs.events import
emit``, or an absolute ``import aggregathor_tpu.obs.events``) — other
``.emit`` attributes (asyncio, user classes) are never convicted.  The
implementation module itself (``obs/events.py``) is excluded: its
``Journal.emit`` body necessarily handles the type as a variable.
"""

import ast

from .core import Finding

CHECKER = "events"

#: files whose emit machinery IS the implementation under test
EXCLUDED_PATHS = ("obs/events.py",)


def _emit_aliases(module):
    """(module_aliases, function_aliases) bound to obs.events / its emit."""
    module_aliases, function_aliases = set(), set()
    for node in ast.walk(module.tree):
        if isinstance(node, ast.ImportFrom):
            source = node.module or ""
            if source == "obs" or source.endswith(".obs") or (
                source == "" and node.level  # "from . import events" in obs/
                and module.path.startswith("obs/")
            ):
                for alias in node.names:
                    if alias.name == "events":
                        module_aliases.add(alias.asname or "events")
            if source == "obs.events" or source.endswith(".obs.events") or (
                source == "events" and module.path.startswith("obs/")
            ):
                for alias in node.names:
                    if alias.name == "emit":
                        function_aliases.add(alias.asname or "emit")
        elif isinstance(node, ast.Import):
            for alias in node.names:
                if alias.name.endswith("obs.events"):
                    module_aliases.add(alias.asname or alias.name)
    return module_aliases, function_aliases


def _is_events_emit(call, module_aliases, function_aliases):
    func = call.func
    if isinstance(func, ast.Name):
        return func.id in function_aliases
    if isinstance(func, ast.Attribute) and func.attr == "emit":
        parts = []
        node = func.value
        while isinstance(node, ast.Attribute):
            parts.append(node.attr)
            node = node.value
        if isinstance(node, ast.Name):
            parts.append(node.id)
            dotted = ".".join(reversed(parts))
            return dotted in module_aliases
    return False


def _declared_types():
    from ..obs.events import EVENT_TYPES

    return EVENT_TYPES


def _action_types():
    from ..obs.events import ACTION_EVENT_TYPES

    return ACTION_EVENT_TYPES


def check(modules):
    """Run EV001/EV002 over parsed modules; returns Finding records."""
    declared = _declared_types()
    actions = _action_types()
    findings = []
    for module in modules:
        if module.path in EXCLUDED_PATHS:
            continue
        module_aliases, function_aliases = _emit_aliases(module)
        if not module_aliases and not function_aliases:
            continue
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            if not _is_events_emit(node, module_aliases, function_aliases):
                continue
            enclosing = _enclosing_def(module, node)
            scope = module.qualname(enclosing) if enclosing is not None else ""
            if not node.args:
                findings.append(Finding(
                    checker=CHECKER, code="EV001", path=module.path,
                    line=node.lineno, scope=scope, symbol="<missing>",
                    message="journal emit without an event type argument",
                ))
                continue
            first = node.args[0]
            if not (isinstance(first, ast.Constant)
                    and isinstance(first.value, str)):
                findings.append(Finding(
                    checker=CHECKER, code="EV001", path=module.path,
                    line=node.lineno, scope=scope, symbol="<dynamic>",
                    message="journal emit with a non-literal event type "
                            "cannot be verified against the schema registry",
                ))
                continue
            if first.value not in declared:
                findings.append(Finding(
                    checker=CHECKER, code="EV001", path=module.path,
                    line=node.lineno, scope=scope, symbol=first.value,
                    message="journal emit of UNDECLARED event type %r "
                            "(declare it in obs.events.EVENT_TYPES)"
                            % first.value,
                ))
                continue
            if first.value in actions and not any(
                    kw.arg == "cause" for kw in node.keywords):
                findings.append(Finding(
                    checker=CHECKER, code="EV002", path=module.path,
                    line=node.lineno, scope=scope, symbol=first.value,
                    message="action event %r emitted without an explicit "
                            "cause= keyword (pass cause=None if no journal "
                            "event triggered it — the causal plane wants "
                            "the author to say so)" % first.value,
                ))
    return findings


def _enclosing_def(module, node):
    parent = module.parent(node)
    while parent is not None:
        if isinstance(parent, (ast.FunctionDef, ast.AsyncFunctionDef,
                               ast.ClassDef)):
            return parent
        parent = module.parent(parent)
    return None

"""Concurrency lint: host threads never touch shared state unlocked.

The package runs real threads in production paths — the bounded-wait
submission pool (``parallel/bounded.py``), the input ``ChunkPipeline``
(``models/datasets.py``), the serve ``ContinuousBatcher`` lane pool,
autoscaler and checkpoint watcher (``serve/continuous.py``,
``serve/autoscale.py``, ``serve/weights.py``), the live exporter
(``obs/live.py``) and the background checkpoint writer
(``obs/checkpoint.py``).  The dynamic tests
exercise each at one schedule; this checker proves the *pattern* —
unlocked attribute writes on thread-reachable code paths — is absent (or
explicitly baselined with its safety argument) package-wide.

Algorithm:

1. **Spawn sites**: every ``threading.Thread(target=X)``,
   ``threading.Timer(_, X)`` and ``<pool>.submit(X, ...)`` in the module.
   ``X`` resolves intra-module (bare names, nested defs, ``self.method``);
   unresolvable targets (stdlib callables like ``serve_forever``) are
   skipped — their bodies are not ours to lint.
2. **Reachability**: the transitive intra-module call closure from the
   spawn targets (``core.reachable_functions``) — the set of functions
   that may execute on a non-main thread.
3. **CC001**: inside that set, an attribute write (``obj.attr = ...``,
   ``obj.attr += ...``, ``obj.attr[i] = ...``) whose base object is not
   function-local, not lexically inside a ``with <lock>`` block, and not
   in ``__init__`` (construction happens before the thread exists).
   Lock recognition is lexical: the context expression's last segment
   contains ``lock``/``mutex``/``cond``/``guard``/``sem``.

What a CC001 baseline entry must argue (docs/analysis.md): why the write
is safe — single-writer with GIL-atomic reference assignment, an
Event/queue handshake ordering the read after the write, or monotonic
telemetry where staleness is tolerated.  "It has not crashed yet" is not
an argument; an empty justification is itself a finding (BL002).
"""

import ast
import re

from .core import (
    Finding,
    callee_name,
    callee_tail,
    dotted_name,
    enclosing_function,
    reachable_functions,
)

CHECKER = "concurrency"

LOCKISH = frozenset({
    "lock", "rlock", "mutex", "cond", "condition", "sem", "semaphore",
    "guard", "latch",
})


def _is_lockish(expr):
    """Last name segment of a with-context looks like a lock.

    Token match, not substring: the name is split on underscores and camel
    humps and a token must EQUAL a lock word (or end with ``lock``, for
    ``qlock``-style names) — ``assembler`` must not whitelist a block just
    because it contains ``sem``."""
    name = callee_name(expr) if isinstance(expr, ast.Call) else dotted_name(expr)
    if not name:
        return False
    tail = name.rsplit(".", 1)[-1]
    tokens = [t for t in re.split(r"_|(?<=[a-z0-9])(?=[A-Z])", tail) if t]
    return any(t.lower() in LOCKISH or t.lower().endswith("lock")
               for t in tokens)


def _spawn_targets(module):
    """Function defs handed to Thread(target=)/Timer/pool.submit."""
    targets = []

    def resolve(arg, site):
        """ALL function defs ``arg`` may denote (a ``self.X`` spawn in a
        module with several classes defining ``X`` must cover every one —
        the conservative over-approximation)."""
        if isinstance(arg, ast.Name):
            caller = enclosing_function(module, site)
            scope = caller
            while scope is not None:
                for node in ast.walk(scope):
                    if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                            and node.name == arg.id:
                        return [node]
                scope = enclosing_function(module, scope)
            return [
                node for node in module.tree.body
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
                and node.name == arg.id
            ]
        if isinstance(arg, ast.Attribute) and isinstance(arg.value, ast.Name) \
                and arg.value.id in ("self", "cls"):
            return [
                stmt
                for node in ast.walk(module.tree)
                if isinstance(node, ast.ClassDef)
                for stmt in node.body
                if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef))
                and stmt.name == arg.attr
            ]
        return []

    for node in ast.walk(module.tree):
        if not isinstance(node, ast.Call):
            continue
        tail = callee_tail(node)
        if tail in ("Thread", "Timer"):
            for kw in node.keywords:
                if kw.arg == "target":
                    targets.extend(resolve(kw.value, node))
            if tail == "Timer" and len(node.args) >= 2:
                targets.extend(resolve(node.args[1], node))
        elif tail == "submit" and node.args:
            targets.extend(resolve(node.args[0], node))
    return targets


def _attr_write_base(target):
    """(base-name, attr-symbol) of an attribute-write target, else None.

    ``self.x = _``        -> ("self", "x")
    ``pending.error = _`` -> ("pending", "error")
    ``self.buf[i] = _``   -> ("self", "buf[]")
    """
    if isinstance(target, ast.Subscript):
        inner = _attr_write_base(target.value)
        if inner is not None:
            return inner[0], inner[1] + "[]"
        if isinstance(target.value, ast.Name):
            return None  # plain local-subscript writes are the owner's call
        return None
    if isinstance(target, ast.Attribute):
        cur = target.value
        while isinstance(cur, ast.Attribute):
            cur = cur.value
        if isinstance(cur, ast.Name):
            return cur.id, target.attr
    return None


def _local_names(func):
    """Names bound by plain (non-attribute) assignment/for/with in ``func``
    — writes through them are writes to objects this function created or
    was handed privately ONLY when they never alias shared state; we treat
    params as shared (the spawn call passes shared objects in)."""
    created = set()
    params = {
        a.arg
        for a in list(func.args.posonlyargs) + list(func.args.args)
        + list(func.args.kwonlyargs)
    }

    def reads_shared(value):
        # an alias of shared state (``st = self.state``) is NOT private: a
        # one-line alias must not defeat the lint
        return any(
            isinstance(n, ast.Name) and n.id in ("self", "cls")
            for n in ast.walk(value)
        )

    for node in ast.walk(func):
        if isinstance(node, ast.Assign):
            if reads_shared(node.value):
                continue
            for t in node.targets:
                if isinstance(t, ast.Name):
                    created.add(t.id)
        elif isinstance(node, (ast.For, ast.AsyncFor)):
            for n in ast.walk(node.target):
                if isinstance(n, ast.Name):
                    created.add(n.id)
        elif isinstance(node, ast.With):
            for item in node.items:
                if item.optional_vars is not None:
                    for n in ast.walk(item.optional_vars):
                        if isinstance(n, ast.Name):
                            created.add(n.id)
    return created - params - {"self", "cls"}


def check_module(module):
    findings = []
    spawned = _spawn_targets(module)
    if not spawned:
        return findings
    for func in reachable_functions(module, spawned):
        if func.name == "__init__":
            continue
        scope = module.qualname(func)
        locals_ = _local_names(func)

        def lock_depth(node, func=func):
            depth = 0
            cur = module.parent(node)
            while cur is not None and cur is not func:
                if isinstance(cur, (ast.With, ast.AsyncWith)):
                    if any(_is_lockish(item.context_expr) for item in cur.items):
                        depth += 1
                cur = module.parent(cur)
            return depth

        for node in ast.walk(func):
            if enclosing_function(module, node) is not func:
                continue  # nested defs are checked via their own reachability
            targets = []
            if isinstance(node, ast.Assign):
                targets = node.targets
            elif isinstance(node, ast.AugAssign):
                targets = [node.target]
            elif isinstance(node, ast.AnnAssign) and node.value is not None:
                targets = [node.target]  # a bare annotation writes nothing
            for target in targets:
                base = _attr_write_base(target)
                if base is None:
                    continue
                base_name, symbol = base
                if base_name in locals_:
                    continue  # object this function created itself
                if lock_depth(node) > 0:
                    continue
                findings.append(Finding(
                    CHECKER, "CC001", module.path, node.lineno, scope,
                    "%s.%s" % (base_name, symbol),
                    "unlocked write to %s.%s on a thread-reachable path: "
                    "hold the owning lock, or baseline with the safety "
                    "argument (single-writer handshake, GIL-atomic "
                    "reference, tolerated-staleness telemetry)"
                    % (base_name, symbol),
                ))
    return findings


def check(modules):
    findings = []
    for module in modules:
        findings.extend(check_module(module))
    return findings

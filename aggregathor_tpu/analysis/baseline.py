"""Findings baseline: accepted violations are named, justified, and expire.

The gate's contract (docs/analysis.md): a NEW violation fails loudly, an
ACCEPTED one is checked in here with a one-line safety argument.  The
baseline is itself linted —

- **BL001 stale entry** — a baseline entry matching no current finding:
  the violation was fixed (delete the entry) or the code moved in a way
  that changed its fingerprint (re-justify the new one).  Either way the
  baseline must not accrete dead weight that would mask a future
  regression landing on the same fingerprint.
- **BL002 empty justification** — an entry with no justification is not an
  accepted violation, it is an unreviewed one; ``--write-baseline`` emits
  empty justifications on purpose so the gate stays red until a human
  argues each one.

Format (checked in at ``aggregathor_tpu/analysis/baseline.json``)::

    {"version": 1,
     "entries": [{"fingerprint": "CC001 serve/batcher.py ...",
                  "justification": "single dispatcher thread; ..."}]}

Fingerprints are line-number-free (core.Finding.fingerprint), so pure code
motion does not churn the baseline; editing the flagged statement does.
"""

import json
import os

from .core import Finding

BASELINE_VERSION = 1


def default_baseline_path():
    return os.path.join(os.path.dirname(os.path.abspath(__file__)), "baseline.json")


def load(path):
    """Parse a baseline file -> {fingerprint: justification}.  A missing
    file is an empty baseline; a malformed one raises ValueError (a gate
    must never silently run without its accept-list)."""
    if not os.path.exists(path):
        return {}
    with open(path, "r", encoding="utf-8") as fh:
        doc = json.load(fh)
    if not isinstance(doc, dict) or doc.get("version") != BASELINE_VERSION:
        raise ValueError(
            "baseline %r wants {'version': %d, 'entries': [...]}"
            % (path, BASELINE_VERSION)
        )
    entries = {}
    for entry in doc.get("entries", ()):
        if not isinstance(entry, dict) or "fingerprint" not in entry:
            raise ValueError("baseline entry %r wants a 'fingerprint'" % (entry,))
        entries[entry["fingerprint"]] = str(entry.get("justification", ""))
    return entries


def save(path, entries):
    """Write {fingerprint: justification} sorted for stable diffs."""
    doc = {
        "version": BASELINE_VERSION,
        "entries": [
            {"fingerprint": fp, "justification": entries[fp]}
            for fp in sorted(entries)
        ],
    }
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(doc, fh, indent=2, sort_keys=True)
        fh.write("\n")


def apply(findings, entries, active_codes=None):
    """Split findings against the baseline.

    Returns ``(unbaselined, baselined, issues)`` where ``issues`` are the
    baseline's own findings (BL001 stale / BL002 empty justification) —
    both gate-failing, like any unbaselined finding.

    ``active_codes``: code prefixes (``("RT", "PK", ...)``) of the checkers
    that actually RAN.  An entry owned by a checker that did not run is out
    of scope — neither matched nor stale — so a ``--checkers`` subset run
    cannot misreport the other checkers' justified entries as BL001.
    ``None`` means every checker ran (the default gate).
    """
    unbaselined, baselined = [], []
    matched = set()
    for finding in findings:
        if finding.fingerprint in entries:
            matched.add(finding.fingerprint)
            baselined.append(finding)
        else:
            unbaselined.append(finding)
    issues = []
    for fingerprint in sorted(entries):
        if active_codes is not None and not fingerprint.startswith(
            tuple("%s" % code for code in active_codes)
        ):
            continue  # owning checker did not run: out of scope this pass
        if fingerprint not in matched:
            issues.append(Finding(
                checker="baseline", code="BL001", path="analysis/baseline.json",
                line=0, scope="baseline", symbol=fingerprint,
                message="stale baseline entry %r matches no current finding "
                        "— delete it (fixed) or re-justify its successor "
                        "(moved)" % fingerprint,
            ))
        elif not entries[fingerprint].strip():
            issues.append(Finding(
                checker="baseline", code="BL002", path="analysis/baseline.json",
                line=0, scope="baseline", symbol=fingerprint,
                message="baseline entry %r has no justification: an "
                        "unreviewed acceptance is not an acceptance"
                        % fingerprint,
            ))
    return unbaselined, baselined, issues

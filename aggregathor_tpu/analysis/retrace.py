"""Retrace / host-sync lint: the zero-recompile discipline, statically.

The engines' contract (docs/engine.md) is ONE steady-state executable per
step shape — every compile after warmup is a regression the flight
recorder's ``CompileWatch`` only catches at the configs a run happens to
exercise.  This checker flags the four mistake shapes that break the
discipline anywhere in the package:

- **RT001** — a ``jax.jit``/``pjit``/``pmap`` wrapper constructed inside a
  loop body or inside traced code: a fresh jit object has a fresh cache, so
  every call recompiles.
- **RT002** — host synchronisation on a traced value inside a traced scope:
  ``.item()`` / ``float()`` / ``int()`` / ``bool()`` / ``np.asarray()`` /
  ``np.array()`` / ``jax.device_get()`` force a device round-trip (or a
  ``ConcretizationTypeError``) in the middle of the graph.
- **RT003** — a Python ``if``/``while`` on a traced value: the branch is
  resolved at TRACE time, so each taken arm bakes a different program
  (retrace per boolean) or fails to trace outright.
- **RT004** — ``static_argnums``/``static_argnames`` naming a parameter
  whose default is a mutable literal (list/dict/set): unhashable statics
  fail at call time, and even a hashable wrapper defeats cache hits.

**Traced scopes** are found syntactically: a function is traced when it is
decorated with (or passed by name to) one of the JAX tracing wrappers
(``jit``/``pjit``/``pmap``/``vmap``/``grad``/``value_and_grad``/
``shard_map``/``scan``/``cond``/``while_loop``/``fori_loop``/``switch``/
``remat``/``checkpoint``/``custom_vjp``), including through one assignment
alias (``sharded = shard_map(body, ...); jax.jit(sharded)`` — the engine
idiom), plus everything lexically nested in, or intra-module-reachable
from, a traced function.  **Traced values** are the traced function's
parameters and anything assigned from an expression that reads one;
``.shape``/``.ndim``/``.dtype``/``len()``/``isinstance()``/``is None``
projections are static and never flagged.

This is a conservative approximation: closure variables are treated as
static (they are, w.r.t. tracing), unresolvable aliases are skipped, and a
value smuggled through a container is invisible.  The checker proves the
absence of the *patterns*, the compile-count tests prove the end-to-end
property at the sampled configs — both, on every PR (docs/analysis.md).
"""

import ast

from .core import (
    Finding,
    callee_name,
    callee_tail,
    dotted_name,
    enclosing_function,
    reachable_functions,
)

CHECKER = "retrace"

#: callables whose function argument becomes traced code
TRACING_WRAPPERS = frozenset({
    "jit", "pjit", "pmap", "vmap", "grad", "value_and_grad", "shard_map",
    "scan", "cond", "while_loop", "fori_loop", "switch", "remat",
    "checkpoint", "custom_vjp", "custom_jvp", "eval_shape", "make_jaxpr",
})

#: wrappers that create a fresh compilation cache (RT001 when per-call)
JIT_WRAPPERS = frozenset({"jit", "pjit", "pmap"})

#: attribute projections of a traced array that are static at trace time
STATIC_ATTRS = frozenset({"shape", "ndim", "dtype", "size", "sharding", "aval"})

#: calls whose result on a traced argument is static at trace time
STATIC_CALLS = frozenset({"len", "isinstance", "type", "id", "repr", "getattr", "hasattr"})

HOST_SYNC_BUILTINS = frozenset({"float", "int", "bool", "complex"})
HOST_SYNC_NUMPY = frozenset({"asarray", "array", "copy", "ascontiguousarray"})
NUMPY_ROOTS = frozenset({"np", "numpy", "onp"})


def _decorator_traces(dec):
    """True when a decorator expression invokes a tracing wrapper."""
    if isinstance(dec, ast.Call):
        tail = callee_tail(dec)
        if tail == "partial":
            return any(_tail_of(arg) in TRACING_WRAPPERS for arg in dec.args)
        return tail in TRACING_WRAPPERS
    return _tail_of(dec) in TRACING_WRAPPERS


def _tail_of(node):
    name = dotted_name(node)
    return name.rsplit(".", 1)[-1] if name else None


def _functions_by_name_in_scope(module):
    """Map function name -> def nodes (module-level and nested)."""
    table = {}
    for func in module.functions():
        table.setdefault(func.name, []).append(func)
    return table


def find_traced_functions(module):
    """The set of function defs that execute under a JAX trace."""
    by_name = _functions_by_name_in_scope(module)
    traced = []

    def mark(func):
        if func is not None and not any(func is f for f in traced):
            traced.append(func)

    # pass 1: decorators
    for func in module.functions():
        if any(_decorator_traces(dec) for dec in func.decorator_list):
            mark(func)

    # pass 2: names passed to tracing wrappers, through one alias hop
    # (``sharded = shard_map(body, ...)`` then ``jax.jit(sharded)`` marks
    # ``body`` via the shard_map call directly)
    for node in ast.walk(module.tree):
        if not (isinstance(node, ast.Call) and callee_tail(node) in TRACING_WRAPPERS):
            continue
        for arg in list(node.args) + [kw.value for kw in node.keywords]:
            if isinstance(arg, ast.Name):
                caller = enclosing_function(module, node)
                # prefer a def in the same lexical function, else module level
                candidates = by_name.get(arg.id, [])
                chosen = None
                for cand in candidates:
                    if caller is not None and enclosing_function(module, cand) is caller:
                        chosen = cand
                        break
                if chosen is None and candidates:
                    chosen = candidates[0]
                mark(chosen)
            elif isinstance(arg, ast.Lambda):
                pass  # lambdas handled below via containment in traced scopes

    # pass 3: lexical nesting — a def inside a traced def is traced
    changed = True
    while changed:
        changed = False
        for func in module.functions():
            if any(func is f for f in traced):
                continue
            parent = enclosing_function(module, func)
            while parent is not None:
                if any(parent is f for f in traced):
                    mark(func)
                    changed = True
                    break
                parent = enclosing_function(module, parent)

    # pass 4: intra-module reachability — helpers CALLED from traced code
    # run under the same trace (the engine body calling _finalize_step)
    return reachable_functions(module, traced)


# --------------------------------------------------------------------- #
# Traced-value dataflow inside one traced function


def _names_in(node):
    return {n.id for n in ast.walk(node) if isinstance(n, ast.Name)}


def _assigned_names(target):
    return {n.id for n in ast.walk(target) if isinstance(n, ast.Name)
            and isinstance(n.ctx, (ast.Store,))}


#: parameter names that are static-by-convention inside traced code: mesh
#: axis NAMES (strings, the shard_map API), config records (hashable
#: statics), and the trace machinery itself
STATIC_PARAM_NAMES = frozenset({"self", "cls", "cfg", "config", "axis", "axis_name"})


def traced_names(func):
    """Parameter-derived names inside ``func`` (forward propagation in
    statement order through :func:`is_dynamic` — a name assigned from a
    static projection like ``n, d = x.shape`` stays static; no kill —
    once traced, always suspect)."""
    args = func.args
    names = {
        a.arg
        for a in list(args.posonlyargs) + list(args.args) + list(args.kwonlyargs)
        if a.arg not in STATIC_PARAM_NAMES and not a.arg.endswith("_axis")
    }
    for extra in (args.vararg, args.kwarg):
        if extra is not None:
            names.add(extra.arg)
    changed = True
    while changed:
        changed = False
        for node in ast.walk(func):
            value = None
            targets = []
            if isinstance(node, ast.Assign):
                value, targets = node.value, node.targets
            elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
                value, targets = node.value, [node.target]
            elif isinstance(node, ast.For):
                value, targets = node.iter, [node.target]
            elif isinstance(node, (ast.NamedExpr,)):
                value, targets = node.value, [node.target]
            if value is None:
                continue
            if is_dynamic(value, names):
                for target in targets:
                    new = _assigned_names(target) - names
                    if new:
                        names |= new
                        changed = True
    return names


def is_dynamic(expr, traced):
    """True when ``expr`` reads a traced name OUTSIDE a static projection."""

    def walk(node):
        if isinstance(node, ast.Name):
            return node.id in traced
        if isinstance(node, ast.Attribute):
            if node.attr in STATIC_ATTRS:
                return False
            return walk(node.value)
        if isinstance(node, ast.Call):
            tail = callee_tail(node)
            if tail in STATIC_CALLS:
                return False
            return any(walk(child) for child in list(node.args)
                       + [kw.value for kw in node.keywords]) or walk(node.func)
        if isinstance(node, ast.Compare):
            # ``x is None`` / ``x is not None`` is a static config check
            if all(isinstance(op, (ast.Is, ast.IsNot)) for op in node.ops):
                return False
            return any(walk(c) for c in [node.left] + node.comparators)
        if isinstance(node, ast.Subscript):
            return walk(node.value) or walk(node.slice)
        return any(walk(child) for child in ast.iter_child_nodes(node))

    return walk(expr)


def _in_loop(module, node, stop_at):
    """True when ``node`` sits inside a for/while loop body below ``stop_at``."""
    cur = module.parent(node)
    while cur is not None and cur is not stop_at:
        if isinstance(cur, (ast.For, ast.While, ast.AsyncFor)):
            return True
        cur = module.parent(cur)
    return False


def _static_params(call, target_def):
    """Parameter names declared static by a jit call, resolved on the
    jitted function's signature.  Returns [] when unresolvable."""
    if target_def is None:
        return []
    params = [a.arg for a in target_def.args.args]
    names = []
    for kw in call.keywords:
        if kw.arg == "static_argnames":
            for el in ast.walk(kw.value):
                if isinstance(el, ast.Constant) and isinstance(el.value, str):
                    names.append(el.value)
        elif kw.arg == "static_argnums":
            for el in ast.walk(kw.value):
                if isinstance(el, ast.Constant) and isinstance(el.value, int):
                    if 0 <= el.value < len(params):
                        names.append(params[el.value])
    return names


def check_module(module):
    findings = []
    traced_funcs = find_traced_functions(module)
    by_name = _functions_by_name_in_scope(module)

    # RT001 / RT004: every jit-wrapper construction site in the module
    for node in ast.walk(module.tree):
        if not (isinstance(node, ast.Call) and callee_tail(node) in JIT_WRAPPERS):
            continue
        name = callee_name(node) or ""
        if not (name in JIT_WRAPPERS or name.startswith(("jax.", "compat."))):
            continue  # someone else's jit/pmap attribute
        func = enclosing_function(module, node)
        scope = module.qualname(func) if func is not None else ""
        if _in_loop(module, node, func):
            findings.append(Finding(
                CHECKER, "RT001", module.path, node.lineno, scope, name,
                "%s(...) constructed inside a loop body: a fresh wrapper has "
                "a fresh compile cache, every iteration recompiles — build "
                "once outside the loop" % name,
            ))
        if func is not None and any(func is f for f in traced_funcs):
            findings.append(Finding(
                CHECKER, "RT001", module.path, node.lineno, scope, name + ".traced",
                "%s(...) constructed inside traced code: the wrapper is "
                "rebuilt on every trace — hoist it to build time" % name,
            ))
        # RT004: static params with mutable literal defaults
        target = None
        if node.args and isinstance(node.args[0], ast.Name):
            for cand in by_name.get(node.args[0].id, []):
                target = cand
                break
        statics = _static_params(node, target)
        if statics and target is not None:
            defaults = target.args.defaults
            params = [a.arg for a in target.args.args]
            offset = len(params) - len(defaults)
            for i, default in enumerate(defaults):
                pname = params[offset + i]
                if pname in statics and isinstance(
                    default, (ast.List, ast.Dict, ast.Set)
                ):
                    findings.append(Finding(
                        CHECKER, "RT004", module.path, node.lineno,
                        module.qualname(target), pname,
                        "static argument %r of %r defaults to a mutable "
                        "(unhashable) literal: jit statics must be hashable "
                        "or every call fails/recompiles" % (pname, target.name),
                    ))

    # RT002 / RT003: inside each traced function
    for func in traced_funcs:
        traced = traced_names(func)
        scope = module.qualname(func)

        def owned(node, func=func):
            """Node belongs to this func, not a nested def (checked itself)."""
            cur = enclosing_function(module, node)
            return cur is func

        for node in ast.walk(func):
            if not owned(node):
                continue
            if isinstance(node, ast.Call):
                tail = callee_tail(node)
                name = callee_name(node) or ""
                root = name.split(".", 1)[0]
                args = list(node.args) + [kw.value for kw in node.keywords]
                dynamic_arg = any(is_dynamic(a, traced) for a in args)
                if (
                    isinstance(node.func, ast.Attribute)
                    and node.func.attr == "item"
                    and not node.args
                    and is_dynamic(node.func.value, traced)
                ):
                    findings.append(Finding(
                        CHECKER, "RT002", module.path, node.lineno, scope, "item",
                        ".item() on a traced value inside traced code forces "
                        "a host sync (or a ConcretizationTypeError)",
                    ))
                elif tail in HOST_SYNC_BUILTINS and name == tail and dynamic_arg:
                    findings.append(Finding(
                        CHECKER, "RT002", module.path, node.lineno, scope, tail,
                        "%s() on a traced value inside traced code "
                        "concretizes the tracer on the host" % tail,
                    ))
                elif root in NUMPY_ROOTS and tail in HOST_SYNC_NUMPY and dynamic_arg:
                    findings.append(Finding(
                        CHECKER, "RT002", module.path, node.lineno, scope, name,
                        "%s() on a traced value pulls the array to the host "
                        "mid-graph — use jnp inside traced code" % name,
                    ))
                elif name.endswith("device_get") and dynamic_arg:
                    findings.append(Finding(
                        CHECKER, "RT002", module.path, node.lineno, scope, name,
                        "device_get inside traced code is a host round-trip "
                        "per trace",
                    ))
            elif isinstance(node, (ast.If, ast.While)):
                if is_dynamic(node.test, traced):
                    culprits = sorted(_names_in(node.test) & traced)
                    findings.append(Finding(
                        CHECKER, "RT003", module.path, node.lineno, scope,
                        ",".join(culprits) or "test",
                        "Python %s on a traced value: the branch is resolved "
                        "at trace time (retrace per boolean) — use "
                        "jnp.where/lax.cond" % (
                            "while" if isinstance(node, ast.While) else "if",
                        ),
                    ))
    return findings


def check(modules):
    findings = []
    for module in modules:
        findings.extend(check_module(module))
    return findings

"""CLI: ``python -m aggregathor_tpu.analysis`` — the graftcheck gate.

Exit code 0 iff every finding is baselined with a justification and no
baseline entry is stale — the contract ``scripts/run_analysis.sh --check``
and the clean-package test assert.  ``--write-baseline`` seeds acceptance
entries with EMPTY justifications on purpose: the gate stays red (BL002)
until a human argues each one in ``baseline.json``.
"""

import argparse
import sys

from . import (
    CHECKERS,
    active_codes,
    baseline as baseline_mod,
    report as report_mod,
    run_checkers,
)
from .core import package_root


def main(argv=None):
    parser = argparse.ArgumentParser(
        prog="python -m aggregathor_tpu.analysis",
        description="graftcheck: repo-native static analysis "
                    "(retrace, prng, concurrency, gar-contract, events)",
    )
    parser.add_argument("--root", default=None,
                        help="package root to scan (default: the installed "
                             "aggregathor_tpu package)")
    parser.add_argument("--checkers", default=None,
                        help="comma-separated subset of: %s"
                             % ", ".join(sorted(CHECKERS)))
    parser.add_argument("--baseline", default=None,
                        help="baseline JSON (default: analysis/baseline.json)")
    parser.add_argument("--no-baseline", action="store_true",
                        help="ignore the baseline: report every finding raw")
    parser.add_argument("--json", dest="json_path", default=None,
                        help="write the aggregathor.analysis.report.v1 "
                             "document here")
    parser.add_argument("--write-baseline", action="store_true",
                        help="accept the current unbaselined findings into "
                             "the baseline (EMPTY justifications: the gate "
                             "stays red until each is argued)")
    parser.add_argument("--check", action="store_true",
                        help="gate mode (the default behavior, named for "
                             "scripts): exit nonzero on any unbaselined "
                             "finding or baseline issue")
    parser.add_argument("--list-checkers", action="store_true")
    parser.add_argument("-q", "--quiet", action="store_true",
                        help="summary line only")
    args = parser.parse_args(argv)

    if args.write_baseline and args.no_baseline:
        parser.error("--write-baseline with --no-baseline would overwrite "
                     "the baseline (and every justification in it) with "
                     "empty entries; drop one of the flags")

    if args.list_checkers:
        for name in sorted(CHECKERS):
            doc = (CHECKERS[name].__doc__ or "").strip().splitlines()
            print("%-14s %s" % (name, doc[0] if doc else ""))
        return 0

    root = args.root or package_root()
    checkers = args.checkers.split(",") if args.checkers else None
    if checkers:
        unknown = [c for c in checkers if c not in CHECKERS]
        if unknown:
            parser.error("unknown checker(s) %s; available: %s"
                         % (", ".join(unknown), ", ".join(sorted(CHECKERS))))
    findings, scan_errors = run_checkers(root=root, checkers=checkers)
    findings = scan_errors + findings

    baseline_path = args.baseline or baseline_mod.default_baseline_path()
    entries = {} if args.no_baseline else baseline_mod.load(baseline_path)
    codes = active_codes(checkers)
    unbaselined, baselined, issues = baseline_mod.apply(findings, entries,
                                                        active_codes=codes)

    if args.write_baseline:
        for finding in unbaselined:
            entries.setdefault(finding.fingerprint, "")
        baseline_mod.save(baseline_path, entries)
        print("baseline: wrote %d entr%s to %s (justify each — empty "
              "justifications keep the gate red)"
              % (len(unbaselined), "y" if len(unbaselined) == 1 else "ies",
                 baseline_path))
        unbaselined, baselined, issues = baseline_mod.apply(
            findings, entries, active_codes=codes)

    doc = report_mod.build_report(
        root=root, checkers=checkers or sorted(CHECKERS),
        unbaselined=unbaselined, baselined=baselined, issues=issues,
        baseline_path=None if args.no_baseline else baseline_path,
        justifications=entries,
    )
    if args.json_path:
        report_mod.save_report(args.json_path, report_mod.validate_report(doc))

    if not args.quiet:
        for finding in unbaselined + issues:
            print(finding.render())
        if baselined and not unbaselined and not issues:
            by_code = {}
            for f in baselined:
                by_code[f.code] = by_code.get(f.code, 0) + 1
            print("baselined: %s" % ", ".join(
                "%s x%d" % (code, count) for code, count in sorted(by_code.items())
            ))
    verdict = "clean" if doc["clean"] else "FAILING"
    print("graftcheck: %s — %d finding(s): %d unbaselined, %d baselined, "
          "%d baseline issue(s)"
          % (verdict, doc["counts"]["total"], doc["counts"]["unbaselined"],
             doc["counts"]["baselined"], doc["counts"]["baseline_issues"]))
    return 0 if doc["clean"] else 1


if __name__ == "__main__":
    sys.exit(main())

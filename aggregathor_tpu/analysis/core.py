"""graftcheck core: findings, the cached package scan, and call-graph glue.

The static pass exists because the load-bearing invariants of this codebase
— zero steady-state recompiles, PRNG keys never reused across consumers,
host threads never touching shared state unlocked, every GAR honoring its
declared contract — are otherwise enforced only *dynamically*, at the
specific configurations the tests happen to run.  A checker proves (a
conservative approximation of) the property everywhere in the package, on
every PR (docs/analysis.md).

Design rules shared by every checker:

- **Findings are data.**  A checker returns :class:`Finding` records; it
  never prints, never exits.  Presentation, baselining and exit codes live
  in ``baseline.py`` / ``__main__.py``.
- **Fingerprints are line-number-free.**  A finding's identity is
  ``CODE path scope symbol`` — moving code inside a file never churns the
  baseline.  The deliberate cost: a SECOND violation of the same kind on
  the same symbol in the same scope rides the existing entry (one entry ==
  one accepted *pattern* per scope, not one statement) — the trade that
  keeps pure refactors baseline-neutral.
- **Parse once per process.**  Whole-package AST scans go through
  :func:`scan_modules`, memoized on ``(path, mtime, size)`` — the tests run
  four checkers plus the clean-package assertion over the same ~100 files
  and must stay inside their tier-1 budget.
"""

import ast
import dataclasses
import os


@dataclasses.dataclass(frozen=True)
class Finding:
    """One checker verdict.

    Attributes:
      checker: checker name (``retrace`` / ``prng`` / ``concurrency`` /
        ``gar-contract`` / ``baseline``).
      code: stable rule code (``RT002``, ``PK001``, ...) — the unit docs
        and baselines speak in.
      path: package-relative file path (or a symbolic path such as
        ``gars/<spec>`` for semantic findings with no single source line).
      line: 1-based line number, 0 when not tied to a line.
      scope: dotted function qualname (or GAR spec) the finding lives in.
      symbol: the short stable detail (attribute name, callee, key name)
        that disambiguates two findings in one scope.
      message: human sentence, shown in reports.
    """

    checker: str
    code: str
    path: str
    line: int
    scope: str
    symbol: str
    message: str

    @property
    def fingerprint(self):
        """Stable identity for baselining: everything but the line number."""
        return "%s %s %s %s" % (self.code, self.path, self.scope, self.symbol)

    def render(self):
        return "%s:%d: %s [%s] %s (in %s)" % (
            self.path, self.line, self.checker, self.code, self.message,
            self.scope or "<module>",
        )

    def to_json(self):
        doc = dataclasses.asdict(self)
        doc["fingerprint"] = self.fingerprint
        return doc


class Module:
    """One parsed source file: path, source, AST with parent links."""

    def __init__(self, root, relpath, source):
        self.root = root
        self.path = relpath
        self.source = source
        self.tree = ast.parse(source, filename=relpath)
        for node in ast.walk(self.tree):
            for child in ast.iter_child_nodes(node):
                child._graft_parent = node

    def parent(self, node):
        return getattr(node, "_graft_parent", None)

    def qualname(self, node):
        """Dotted qualname of a FunctionDef/ClassDef by walking parents."""
        names = []
        while node is not None:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
                names.append(node.name)
            node = self.parent(node)
        return ".".join(reversed(names))

    def functions(self):
        """Every (async) function definition in the module."""
        return [
            node for node in ast.walk(self.tree)
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
        ]


#: (root, relpath) -> (mtime, size, Module) — the per-process scan cache
#: the tier-1 budget relies on (four checkers + the clean-package
#: assertion re-scan the same files).  Keyed on BOTH root and relpath: the
#: same file reached through two different --root values must yield
#: Modules whose ``path`` (and therefore fingerprints) match each request.
_MODULE_CACHE = {}


def load_module(root, relpath):
    abspath = os.path.join(root, relpath)
    stat = os.stat(abspath)
    key = (os.path.abspath(root), relpath)
    cached = _MODULE_CACHE.get(key)
    if cached is not None and cached[0] == stat.st_mtime_ns and cached[1] == stat.st_size:
        return cached[2]
    with open(abspath, "r", encoding="utf-8") as fh:
        source = fh.read()
    module = Module(root, relpath, source)
    _MODULE_CACHE[key] = (stat.st_mtime_ns, stat.st_size, module)
    return module


def package_root():
    """The installed ``aggregathor_tpu`` package directory."""
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def iter_package_paths(root):
    """Package-relative paths of every ``.py`` file under ``root``, sorted."""
    found = []
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = sorted(d for d in dirnames if d != "__pycache__")
        for name in sorted(filenames):
            if name.endswith(".py"):
                found.append(os.path.relpath(os.path.join(dirpath, name), root))
    return found


def scan_modules(root=None, paths=None):
    """Parse (cached) every requested file; returns a list of Modules.

    Files that fail to parse surface as a synthetic ``core``/``PARSE``
    finding by the caller (`run_checkers`) rather than an exception — a
    syntax error in one file must not hide every other finding.
    """
    root = root or package_root()
    modules, errors = [], []
    for relpath in (paths if paths is not None else iter_package_paths(root)):
        try:
            modules.append(load_module(root, relpath))
        except (SyntaxError, OSError) as exc:
            errors.append(
                Finding(
                    checker="core", code="PARSE", path=relpath,
                    line=getattr(exc, "lineno", 0) or 0, scope="", symbol="parse",
                    message="file does not parse: %s" % (exc,),
                )
            )
    return modules, errors


# --------------------------------------------------------------------- #
# Shared AST helpers


def dotted_name(node):
    """``a.b.c`` for Name/Attribute chains, else None."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def callee_name(call):
    """Dotted callee of a Call node (``jax.jit`` / ``split``), else None."""
    return dotted_name(call.func)


def callee_tail(call):
    """Last segment of the callee (``jit`` for ``jax.jit``), else None."""
    name = callee_name(call)
    return name.rsplit(".", 1)[-1] if name else None


def enclosing_function(module, node):
    """Innermost (async) function definition containing ``node``."""
    node = module.parent(node)
    while node is not None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return node
        node = module.parent(node)
    return None


def enclosing_class(module, node):
    """Innermost class definition containing ``node``."""
    node = module.parent(node)
    while node is not None:
        if isinstance(node, ast.ClassDef):
            return node
        node = module.parent(node)
    return None


def local_call_targets(module, func):
    """Function defs in the SAME module that ``func``'s body may call.

    Intra-module resolution only (the conservative approximation every
    checker shares): bare names resolve to module-level or lexically
    enclosing function defs, ``self.X``/``cls.X`` to methods of the
    enclosing class.  Unresolvable callees (stdlib, other modules) are
    ignored — a checker that needs them must say so in its docs.
    """
    by_name = {}
    for node in module.functions():
        parent = module.parent(node)
        if isinstance(parent, ast.Module):
            by_name.setdefault(node.name, node)
    # lexically enclosing defs (nested helpers)
    enclosing = {}
    scope = func
    while scope is not None:
        for stmt in ast.walk(scope):
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)) and stmt is not scope:
                enclosing.setdefault(stmt.name, stmt)
        scope = enclosing_function(module, scope)
    cls = enclosing_class(module, func)
    methods = {}
    if cls is not None:
        for stmt in cls.body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                methods[stmt.name] = stmt
    targets = []
    for call in [n for n in ast.walk(func) if isinstance(n, ast.Call)]:
        fn = call.func
        if isinstance(fn, ast.Name):
            target = enclosing.get(fn.id) or by_name.get(fn.id)
            if target is not None:
                targets.append(target)
        elif isinstance(fn, ast.Attribute) and isinstance(fn.value, ast.Name):
            if fn.value.id in ("self", "cls") and fn.attr in methods:
                targets.append(methods[fn.attr])
    return targets


def reachable_functions(module, seeds):
    """Transitive closure of ``local_call_targets`` from ``seeds``."""
    seen, frontier = [], list(seeds)
    while frontier:
        func = frontier.pop()
        if any(func is f for f in seen):
            continue
        seen.append(func)
        frontier.extend(local_call_targets(module, func))
    return seen

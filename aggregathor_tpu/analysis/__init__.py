"""graftcheck: the repo-native static-analysis pass (docs/analysis.md).

Four checkers prove — everywhere in the package, on every PR — the
invariants the tests only sample at the configs they happen to run:

- **retrace** (``retrace.py``): the zero-recompile discipline — no jit
  built per call, no host sync / Python branch on traced values, no
  unhashable statics.
- **prng** (``prng.py``): key hygiene — no key consumed twice without a
  split/fold_in, no minted-and-dropped randomness.
- **concurrency** (``concurrency.py``): no unlocked attribute writes on
  thread-reachable code paths.
- **gar-contract** (``gar_contract.py``): every registered GAR spec honors
  its declared contract (NaN tolerance, parse-time feasibility,
  participation scatter, dtype preservation) under ``eval_shape`` + tiny
  concrete probes.
- **events** (``events_check.py``): every journal ``emit`` anywhere in the
  package names an event type DECLARED in the ``obs/events.py`` schema
  registry (EV001 — an undeclared or dynamic emit would raise at decision
  time, or defeat validation entirely).

Run as a CLI (``python -m aggregathor_tpu.analysis``), as tier-1 tests
(``tests/test_analysis.py``) and from ``scripts/run_analysis.sh``.
Accepted findings live in ``baseline.json`` with per-entry justifications;
new findings, stale entries and empty justifications all fail the gate.
"""

from . import (
    baseline,
    concurrency,
    core,
    events_check,
    gar_contract,
    prng,
    report,
    retrace,
)
from .core import Finding

#: name -> (module, needs_source): the checker registry the CLI and tests
#: iterate — adding a checker means adding a module with ``check(modules)``
#: and one line here (docs/analysis.md "Adding a checker")
CHECKERS = {
    "retrace": retrace,
    "prng": prng,
    "concurrency": concurrency,
    "gar-contract": gar_contract,
    "events": events_check,
}

#: finding-code prefixes owned by each checker (plus the pass's own):
#: baseline staleness (BL001) is only asserted for entries whose owning
#: checker actually ran, so a ``--checkers`` subset cannot misreport the
#: others' justified entries as stale
CHECKER_CODES = {
    "retrace": ("RT",),
    "prng": ("PK",),
    "concurrency": ("CC",),
    "gar-contract": ("GC",),
    "events": ("EV",),
}


def active_codes(checkers=None):
    """Code prefixes for a checker selection (None = every checker ran,
    plus the scan's own PARSE findings)."""
    selected = list(CHECKERS) if checkers is None else list(checkers)
    codes = ["PARSE"]
    for name in selected:
        codes.extend(CHECKER_CODES.get(name, ()))
    return tuple(codes)


def run_checkers(root=None, paths=None, checkers=None, gar_specs=None):
    """Run the selected checkers; returns (findings, scan_errors).

    AST checkers share one cached module scan (core.scan_modules); the
    gar-contract checker ignores the scan and probes the live registry.
    """
    root = root or core.package_root()
    selected = list(CHECKERS) if checkers is None else list(checkers)
    unknown = [name for name in selected if name not in CHECKERS]
    if unknown:
        raise ValueError(
            "unknown checker(s) %r; available: %s"
            % (unknown, ", ".join(sorted(CHECKERS)))
        )
    needs_scan = any(name != "gar-contract" for name in selected)
    modules, errors = core.scan_modules(root, paths) if needs_scan else ([], [])
    findings = []
    for name in selected:
        if name == "gar-contract":
            findings.extend(gar_contract.check(specs=gar_specs))
        else:
            findings.extend(CHECKERS[name].check(modules))
    return findings, errors

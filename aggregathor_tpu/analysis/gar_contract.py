"""GAR contract checker: every registered rule proves its declared contract.

A GAR's class attributes are load-bearing declarations, not documentation:
``nan_row_tolerant`` licenses the lossy link, the bounded-wait timeout path
and the quarantine to inject NaN rows *inside the declared-f budget*;
``worker_participation`` feeds reputation and forensics; parse-time
feasibility is what the guardian's escalation ladder relies on when it
re-sizes ``f``; dtype preservation is the exchange-compression contract.
A rule registered with a false declaration breaks subsystems that never
import it directly — so registration itself must be checkable.

This checker is semantic, not AST: it discovers every registered spec
through ``gars/__init__.py`` (``itemize``/``parse_spec``), instantiates
each at a small feasible ``(n, f)`` found by probing, and verifies under
``jax.eval_shape`` plus tiny concrete probes (n <= 16, d = 8, CPU-friendly):

- **GC001 nan-poison** — with ``nan_row_tolerant`` declared, ``f`` all-NaN
  rows must leave the aggregate finite (the budget the whole straggler /
  lossy / quarantine stack spends).
- **GC002 infeasibility accepted** — ``f >= n`` must be rejected at parse
  time with a ``UserException`` for EVERY rule (you cannot tolerate a
  Byzantine majority of everyone), and the rejection must be a parse
  error, not a crash deep in aggregation.
- **GC003 participation** — when ``worker_participation`` is defined it
  must be an (n,) vector summing to 1 (the scatter the forensics ledger
  and reputation EMA consume).
- **GC004 dtype/shape drift** — float32 ``(n, d)`` in, float32 ``(d,)``
  out, proven abstractly by ``jax.eval_shape`` (no compile, no FLOPs).
- **GC005 int8-wire survival** — the compressed-exchange contract
  (parallel/compress.py, ``--exchange int8``): finite rows squeezed
  through the int8 wire round-trip (quantization moves every value and
  zeroes small coordinates exactly) must still aggregate finite.  A rule
  that silently breaks under the quantized wire is a GC finding, not a
  surprise at the first compressed run.
- **GC000 probe crash** — any probe raising something other than the
  contract's expected exception is itself a finding: a rule the checker
  cannot exercise is a rule the next PR can silently break.

Composite specs (``hier:``/``bucketing:`` nestings) go through the same
probes — the sweep in ``tests/test_analysis.py`` asserts coverage of 100%
of the registry against ``itemize()``, not a hand-kept list.
"""

import functools

from .core import Finding

CHECKER = "gar-contract"

#: small feasible-(n, f) candidates, probed in order (bulyan needs
#: n >= 4f + 3, hier needs divisible groups, bucketing reduced inner ...)
CANDIDATES = ((8, 1), (8, 2), (12, 2), (16, 2), (11, 3), (16, 3), (9, 1),
              (6, 1), (16, 1), (32, 4))

#: probe width: big enough for coordinate medians to be meaningful, small
#: enough that 30+ rules x 4 probes stay inside the tier-1 test budget
PROBE_D = 8

#: composite nestings swept IN ADDITION to every registered name — the
#: meta-rule compositions the engines accept anywhere a GAR name is
COMPOSITE_SPECS = (
    "hier:g=2,inner=median,outer=krum",
    "bucketing:s=2,inner=krum",
    "bucketing:s=2,inner=hier(g=2,inner=median,outer=average-nan)",
    "hier:g=4,inner=bucketing(s=2,inner=median),outer=average-nan",
    # the aggregation tree (topology/spec.py) in BOTH nesting directions:
    # composites inside a tree level, and a tree as another meta-rule's
    # outer — the registry accepts it anywhere a GAR name is
    "tree:g=2x2,rules=median>median>average-nan",
    "tree:g=4,rules=bucketing(s=2,inner=median)>krum",
    "hier:g=2,inner=median,outer=tree(g=2,rules=median>average-nan)",
)


def default_specs():
    """Every registered GAR name (auto-discovered — a rule cannot register
    without entering this sweep) plus the composite nestings."""
    from .. import gars

    return tuple(gars.itemize()) + COMPOSITE_SPECS


def _finding(code, spec, symbol, message):
    return Finding(
        checker=CHECKER, code=code, path="gars/%s" % spec.split(":", 1)[0],
        line=0, scope=spec, symbol=symbol, message=message,
    )


def _instantiate(spec, n, f):
    from .. import gars

    return gars.instantiate(spec, n, f)


def _feasible(spec):
    """(gar, n, f) at the first feasible candidate; (None, None, reason)
    when none is.  A non-UserException from a rule's constructor is a
    CRASH, not an infeasibility — it must surface as a GC000 finding, not
    kill the whole checker run (the module-docstring contract)."""
    from ..utils import UserException

    crash = None
    for n, f in CANDIDATES:
        try:
            return _instantiate(spec, n, f), n, f
        except UserException:
            continue
        except Exception as exc:
            crash = "(n=%d, f=%d) crashed: %s: %s" % (n, f, type(exc).__name__, exc)
    return None, None, crash


def check_spec(spec):
    """All contract probes for one spec; returns a list of findings."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from ..gars.common import pairwise_sq_distances
    from ..utils import UserException

    findings = []
    gar, n, f = _feasible(spec)
    if gar is None:
        detail = f  # _feasible's third slot carries the crash reason if any
        return [_finding(
            "GC000", spec, "feasibility",
            detail or "no feasible (n, f) among %r: the contract cannot be "
            "exercised" % (CANDIDATES,),
        )]

    base_key = jax.random.PRNGKey(0)
    # one derived key per probe (fresh fold_in data each — the hygiene the
    # prng checker enforces on this file like any other)
    shape_key, clean_key, nan_key, part_key, int8_key = (
        jax.random.fold_in(base_key, tag) for tag in range(5)
    )
    rng = np.random.default_rng(0x6A2)
    grads = rng.normal(size=(n, PROBE_D)).astype(np.float32)

    # GC004: dtype/shape under eval_shape — abstract, no compile
    try:
        out = jax.eval_shape(
            lambda g, k: gar.aggregate(g, key=k),
            jax.ShapeDtypeStruct((n, PROBE_D), jnp.float32),
            jax.ShapeDtypeStruct(np.shape(shape_key), np.asarray(shape_key).dtype),
        )
        if tuple(out.shape) != (PROBE_D,):
            findings.append(_finding(
                "GC004", spec, "shape",
                "aggregate of (%d, %d) returned shape %r, wants (%d,)"
                % (n, PROBE_D, tuple(out.shape), PROBE_D),
            ))
        if out.dtype != jnp.float32:
            findings.append(_finding(
                "GC004", spec, "dtype",
                "float32 input aggregated to %s: the exchange-dtype "
                "round-trip in the engines relies on dtype preservation"
                % out.dtype,
            ))
    except Exception as exc:
        findings.append(_finding(
            "GC000", spec, "eval_shape",
            "eval_shape probe crashed: %s: %s" % (type(exc).__name__, exc),
        ))

    # concrete clean aggregate: finite
    try:
        clean = np.asarray(gar.aggregate(jnp.asarray(grads), key=clean_key))
        if not np.all(np.isfinite(clean)):
            findings.append(_finding(
                "GC001", spec, "clean-finite",
                "aggregate of finite gradients is not finite at (n=%d, f=%d)"
                % (n, f),
            ))
    except Exception as exc:
        findings.append(_finding(
            "GC000", spec, "aggregate",
            "concrete aggregate probe crashed: %s: %s"
            % (type(exc).__name__, exc),
        ))
        return findings  # later probes would only repeat the crash

    # GC001: declared NaN tolerance actually absorbs f NaN rows
    if gar.nan_row_tolerant and f >= 1:
        poisoned = grads.copy()
        poisoned[:f] = np.nan
        try:
            out = np.asarray(gar.aggregate(jnp.asarray(poisoned), key=nan_key))
            if not np.all(np.isfinite(out)):
                findings.append(_finding(
                    "GC001", spec, "nan-rows",
                    "declares nan_row_tolerant but %d NaN row(s) within "
                    "f=%d poison the aggregate — the lossy/straggler/"
                    "quarantine NaN budget is a lie for this rule" % (f, f),
                ))
        except Exception as exc:
            findings.append(_finding(
                "GC000", spec, "nan-probe",
                "NaN-tolerance probe crashed: %s: %s"
                % (type(exc).__name__, exc),
            ))

    # GC005: int8-wire survival — quantized finite rows aggregate finite
    # (the probe the compressed exchange relies on; run_compress_smoke.sh
    # exercises it through the real CLI).  One coordinate per row is
    # amplified 1000x before the round-trip: the per-row scale then
    # quantizes every small coordinate to an EXACT zero — real gradient
    # rows have heavy coordinates, and that zeroing is precisely the
    # structure a fragile rule breaks on.
    try:
        from ..parallel.compress import Int8Codec

        spiky = grads.copy()
        spiky[:, 0] *= 1000.0
        quantized = Int8Codec().roundtrip_rows(jnp.asarray(spiky))
        out = np.asarray(gar.aggregate(quantized, key=int8_key))
        if not np.all(np.isfinite(out)):
            findings.append(_finding(
                "GC005", spec, "int8-wire",
                "aggregate of int8-roundtripped finite gradients is not "
                "finite at (n=%d, f=%d) — the rule breaks under the "
                "compressed exchange (--exchange int8)" % (n, f),
            ))
    except Exception as exc:
        findings.append(_finding(
            "GC000", spec, "int8-probe",
            "int8-wire probe crashed: %s: %s" % (type(exc).__name__, exc),
        ))

    # GC003: participation scatter sums to 1
    try:
        dist2 = pairwise_sq_distances(jnp.asarray(grads)) if gar.needs_distances else None
        _, part = gar.aggregate_block_and_participation(
            jnp.asarray(grads), dist2, key=part_key
        )
        if part is not None:
            part = np.asarray(part)
            if part.shape != (n,):
                findings.append(_finding(
                    "GC003", spec, "participation-shape",
                    "worker_participation returned shape %r, wants (%d,)"
                    % (part.shape, n),
                ))
            elif not np.isclose(float(np.sum(part)), 1.0, atol=1e-3):
                findings.append(_finding(
                    "GC003", spec, "participation-sum",
                    "worker_participation sums to %.6f, wants 1 — the "
                    "reputation/forensics scatter double- or under-counts"
                    % float(np.sum(part)),
                ))
    except Exception as exc:
        findings.append(_finding(
            "GC000", spec, "participation",
            "participation probe crashed: %s: %s" % (type(exc).__name__, exc),
        ))

    # GC002: f >= n must be a parse-time UserException, never accepted and
    # never a crash from aggregation depths
    try:
        _instantiate(spec, 3, 3)
        findings.append(_finding(
            "GC002", spec, "infeasible-accepted",
            "(n=3, f=3) accepted at parse time: a rule cannot tolerate a "
            "Byzantine majority of everyone — feasibility must reject "
            "f >= n before a step ever runs",
        ))
    except UserException:
        pass  # the contract: loud, typed, at parse time
    except Exception as exc:
        findings.append(_finding(
            "GC002", spec, "infeasible-crash",
            "infeasible (n=3, f=3) crashed with %s instead of a parse-time "
            "UserException: %s" % (type(exc).__name__, exc),
        ))
    return findings


@functools.lru_cache(maxsize=4)
def _check_cached(specs):
    findings = []
    for spec in specs:
        findings.extend(check_spec(spec))
    return tuple(findings)


def check(modules=None, specs=None):
    """Checker entry point.  ``modules`` is accepted (and ignored) for
    signature parity with the AST checkers; results are cached per spec
    tuple — the CLI and the test sweep share one probe pass per process."""
    del modules
    return list(_check_cached(tuple(specs) if specs is not None else default_specs()))

"""Resilience-campaign harness: sweep attack x GAR x schedule grids.

Turns the engine's robustness machinery into a measurement product: every
cell of the (GAR x chaos scenario) grid trains the SAME experiment through
the real :class:`RobustEngine` under a :class:`ChaosSchedule`, and the
campaign emits

- a machine-readable **resilience matrix** (JSON, schema
  ``aggregathor.chaos.resilience-matrix.v1``) with per-cell loss
  trajectories and converged/diverged verdicts — the contract
  ``scripts/run_campaign_smoke.sh`` and tests/test_chaos.py assert;
- a **markdown report** with the verdict grid and, under ``--breakdown``,
  an empirical check of each rule's f-breakdown boundary: the same attack
  scenario re-run with ``r = f`` real attackers (the declared budget —
  expect convergence) and with ``r`` beyond the rule's breakdown point
  (a strict majority, n//2 + 1 — expect failure).

Scenario sources: ``--attacks NAME[,k=v...]`` is shorthand for the
single-regime schedule ``0:attack=NAME[,k=v...]``; ``--schedules
NAME=SPEC`` passes any schedule DSL string (see chaos/schedule.py for the
grammar).  A ``calm`` scenario (no adversity) is always prepended as the
baseline row.

Example (the smoke campaign, CPU, <60 s)::

  python -m aggregathor_tpu.chaos.campaign \
      --experiment mnist --experiment-args batch-size:16 \
      --nb-workers 8 --nb-decl-byz-workers 2 --nb-real-byz-workers 2 \
      --gars average median krum --attacks empire,epsilon=4.0 \
      --schedules storm="0:calm 10:drop=0.3" \
      --nb-steps 25 --output matrix.json --report report.md
"""

import argparse
import json
import sys

SCHEMA = "aggregathor.chaos.resilience-matrix.v1"

#: matrix keys every cell must carry (the smoke script asserts these)
CELL_KEYS = (
    "gar", "scenario", "schedule", "nb_real_byz", "declared_byz",
    "first_loss", "final_loss", "min_loss", "converged", "diverged", "losses",
    "compile_count",
)


def build_parser():
    parser = argparse.ArgumentParser(
        prog="aggregathor-tpu campaign",
        description="Resilience campaign: attack x GAR x schedule grid through the robust engine",
    )
    parser.add_argument("--experiment", default="mnist", help="experiment name (models registry)")
    parser.add_argument("--experiment-args", nargs="*", default=[], help="key:value experiment arguments")
    parser.add_argument("--nb-workers", type=int, default=8, help="number n of logical workers")
    parser.add_argument("--nb-decl-byz-workers", type=int, default=2, help="declared Byzantine count f")
    parser.add_argument("--nb-real-byz-workers", type=int, default=2,
                        help="actual attacker count r for attack scenarios")
    parser.add_argument("--gars", nargs="+", default=["average", "median", "krum"],
                        help="GAR names to sweep (gars registry)")
    parser.add_argument("--gar-args", nargs="*", default=[], help="key:value arguments for every GAR")
    parser.add_argument("--attacks", nargs="*", default=[],
                        help="attack scenarios NAME[,k=v...] (single-regime schedules)")
    parser.add_argument("--schedules", nargs="*", default=[],
                        help="named schedule scenarios NAME=SPEC (full chaos DSL)")
    parser.add_argument("--chaos-args", nargs="*", default=[],
                        help="key:value schedule-wide options (packet-coords, straggle-workers, ...)")
    parser.add_argument("--nb-steps", type=int, default=25, help="train steps per cell")
    parser.add_argument("--learning-rate", type=float, default=0.05)
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--nb-devices", type=int, default=1,
                        help="devices on the worker mesh axis (1 = fastest on CPU)")
    parser.add_argument("--breakdown", action="store_true",
                        help="empirically probe each robust rule's f-breakdown boundary "
                             "(re-runs the first attack scenario at r=f and r=n//2+1)")
    parser.add_argument("--guardian", action="store_true",
                        help="run every cell under the guardian recovery layer "
                             "(guardian/): cells report diverged-then-recovered "
                             "instead of stopping at the first non-finite loss")
    parser.add_argument("--guardian-args", nargs="*", default=[],
                        help="key:value watchdog options (patience:N, spike:X, "
                             "retries:N, ladder:..., see docs/guardian.md)")
    parser.add_argument("--forensics", action="store_true",
                        help="run every cell with a Byzantine forensics ledger "
                             "(obs/forensics.py) and assert ATTRIBUTION, not "
                             "just convergence: the cell records which workers "
                             "the ledger names Byzantine vs the injected "
                             "coalition (workers 0..r-1), with step-range "
                             "overlap against the attack-active regimes")
    parser.add_argument("--output", default=None, metavar="JSON", help="resilience matrix output path")
    parser.add_argument("--report", default=None, metavar="MD", help="markdown report output path")
    parser.add_argument("--platform", default=None, help="force a JAX platform (tpu/cpu)")
    return parser


def _scenarios(args):
    """[(name, schedule spec or None)] — calm baseline first.  Names must be
    unique: they key the matrix cells and the report grid (two variants of
    one attack need distinct --schedules names)."""
    from ..utils import UserException

    out = [("calm", None)]
    for item in args.attacks:
        name = item.split(",", 1)[0]
        out.append((name, "0:attack=%s" % item))
    for item in args.schedules:
        if "=" not in item:
            raise UserException("--schedules wants NAME=SPEC (got %r)" % (item,))
        name, spec = item.split("=", 1)
        out.append((name, spec))
    names = [name for name, _ in out]
    duplicates = sorted({name for name in names if names.count(name) > 1})
    if duplicates:
        raise UserException(
            "Duplicate scenario name(s) %s would collide in the matrix/report; "
            "give variants distinct names via --schedules NAME=SPEC"
            % ", ".join(duplicates)
        )
    return out


def _declares_attack(spec, nb_workers):
    """Does this schedule spec activate any attack regime?  (Probed with a
    1-member coalition; the main grid has already surfaced parse errors.)"""
    from ..utils import UserException
    from .schedule import ChaosSchedule

    try:
        return ChaosSchedule(spec, nb_workers, nb_real_byz=1).has_attacks
    except UserException:
        return False


def run_cell(exp_name, exp_args, gar_name, gar_args, n, f, r, schedule_spec,
             chaos_args, nb_steps, lr, seed, nb_devices=1, guardian=None,
             forensics=False):
    """Train one grid cell; returns the cell record (see CELL_KEYS).

    With ``guardian`` (a :class:`guardian.GuardianConfig`), the cell runs
    under the recovery layer with IN-MEMORY last-known-good snapshots (no
    checkpoint directory per cell): on divergence it rolls back, climbs the
    escalation ladder and replays — the cell then reports
    ``rollbacks``/``escalations``/``recovered`` instead of stopping at the
    first non-finite loss, closing the loop where an injected breakdown
    regime becomes the test harness for the recovery layer.

    With ``forensics``, the cell runs with per-worker suspicion diagnostics
    on and a :class:`obs.forensics.ForensicsLedger` fed per step; the cell
    record gains a ``forensics`` block comparing the ledger's attribution
    (named workers + suspect step ranges) against the injected coalition
    (workers ``0..r-1``) and the attack-active step range — the campaign
    then asserts WHO, not just WHETHER."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from .. import gars, models
    from ..core import build_optimizer, build_schedule
    from ..parallel import RobustEngine, make_mesh
    from ..utils import UserException, warning
    from .schedule import ChaosSchedule

    experiment = models.instantiate(exp_name, exp_args)
    chaos = (
        ChaosSchedule(schedule_spec, n, nb_real_byz=r, args=chaos_args)
        if schedule_spec else None
    )
    # forge/tamper regimes (docs/security.md) are coalition behavior too:
    # the first r workers run them, exactly like attack regimes
    nb_real = r if (
        chaos is not None and (chaos.has_attacks or chaos.has_forgery)
    ) else 0
    mesh = make_mesh(nb_workers=nb_devices)

    def build(ov):
        """(engine, tx, step) for an Overrides record — rebuilt per rung."""
        gar = gars.instantiate(ov.gar_name, n, ov.f, list(ov.gar_args))
        tx = build_optimizer(
            "sgd", build_schedule("fixed", ["initial-rate:%s" % (lr * ov.lr_scale)])
        )
        engine = RobustEngine(
            mesh, gar, n, nb_real_byz=nb_real, chaos=chaos,
            worker_metrics=bool(forensics),
            reputation_decay=ov.reputation_decay,
            quarantine_threshold=ov.quarantine_threshold,
            # forgery schedules run under the secure submission layer: the
            # whole point of a forge/tamper cell is that verification
            # rejects-and-NAMES the coalition (a tampered bit is invisible
            # to the statistical diagnostics by design, docs/security.md)
            secure=bool(chaos is not None and chaos.has_forgery),
        )
        return engine, tx, engine.build_step(experiment.loss, tx)

    from ..guardian import RESEED_STRIDE, RNG_PERTURB_TAG, Overrides, Watchdog

    overrides = Overrides(f, gar_name, tuple(gar_args or []))
    watchdog = Watchdog(guardian) if guardian is not None else None
    engine, tx, step = build(overrides)
    state = engine.init_state(experiment.init(jax.random.PRNGKey(seed)), tx, seed=seed + 1)
    it = experiment.make_train_iterator(n, seed=seed + 2)

    ledger = None
    if forensics:
        from ..obs.forensics import ForensicsLedger

        ledger = ForensicsLedger(n)
    # the aggregator role for secure cells: per-step HMAC sign/verify over
    # the step's digests, verdicts fed to the ledger as forgery evidence
    secure_auth = None
    if chaos is not None and chaos.has_forgery:
        from ..secure import SubmissionAuthenticator

        secure_auth = SubmissionAuthenticator(b"campaign-session-secret", n)

    losses = []
    diverged = False
    failed = False
    rollbacks = 0
    escalations = []
    recovered = False
    good = None  # (host serialized fields, len(losses)) at last healthy step
    snap_every = max(1, nb_steps // 8)
    s = 0
    while s < nb_steps:
        state, metrics = step(state, engine.shard_batch(next(it)))
        loss = float(jax.device_get(metrics["total_loss"]))
        losses.append(loss)
        s += 1
        if ledger is not None:
            # ledger steps are 1-based (step s executed under the regime
            # governing 0-based index s-1), matching the runner's feed
            probe = metrics.get("probe")
            ridx = chaos.regime_at(s - 1) if chaos is not None else None
            dist = metrics.get("worker_sq_dist")
            forgery = None
            if secure_auth is not None and "secure" in metrics:
                sec = {
                    name: np.asarray(jax.device_get(value))
                    for name, value in metrics["secure"].items()
                }
                forgery = ~secure_auth.process_step(
                    s, sec["digest_sent"], sec["digest_recv"],
                    forged=sec["forged"],
                )
            ledger.observe(
                s,
                worker_sq_dist=None if dist is None else jax.device_get(dist),
                worker_nan=(
                    jax.device_get(probe["worker_nan_rows"])
                    if probe is not None else None
                ),
                regime=ridx,
                regime_desc=chaos.describe(ridx) if ridx is not None else None,
                forgery=forgery,
            )
        if watchdog is None:
            if not np.isfinite(loss):
                # params are poisoned; every later loss is NaN too — stop
                # paying for steps that can no longer change the verdict
                diverged = True
                break
            continue
        probe = metrics["probe"]
        action = watchdog.observe(
            s, loss, bool(int(jax.device_get(probe["loss_finite"]))),
            float(jax.device_get(probe["spike"])),
        )
        if action == "recovered":
            recovered = rollbacks > 0
            continue
        if action != "rollback":
            if watchdog.healthy and s % snap_every == 0:
                good = ({
                    name: jax.device_get(getattr(state, name))
                    for name in ("step", "params", "opt_state", "rng")
                }, len(losses))
            continue
        diverged = True  # the cell DID diverge; recovery may still save it
        if watchdog.exhausted:
            failed = True
            break
        target_len = good[1] if good is not None else 0
        attempt = watchdog.note_rollback(
            int(good[0]["step"]) if good is not None else 0
        )
        rollbacks += 1
        rung = guardian.ladder.rung(attempt)
        if rung is not None:
            try:
                new_overrides = rung.apply(overrides)
                engine, tx, step = build(new_overrides)
                overrides = new_overrides
                escalations.append(rung.describe())
            except UserException as exc:
                warning("guardian cell: rung %r rejected: %s" % (rung.describe(), exc))
        fresh = engine.init_state(
            experiment.init(jax.random.PRNGKey(seed)), tx,
            seed=seed + 1 + RESEED_STRIDE * (attempt + 1) if good is None else seed + 1,
        )
        if good is not None:
            snap, _ = good
            host = jax.device_get(fresh.replace(carry=None, momentum=None))
            host = host.replace(
                step=snap["step"], params=snap["params"], opt_state=snap["opt_state"],
                rng=jax.device_get(jax.random.fold_in(
                    jnp.asarray(snap["rng"]), RNG_PERTURB_TAG + attempt
                )),
            )
            state = engine.put_state(host.replace(carry=fresh.carry, momentum=fresh.momentum))
        else:
            state = fresh
        losses = losses[:target_len]
        s = target_len
        if ledger is not None:
            ledger.truncate_after(target_len)
            ledger.note_guardian(target_len, "rollback", {"attempt": attempt})
    finite = [x for x in losses if np.isfinite(x)]
    first = losses[0] if losses else float("nan")
    final = losses[-1] if losses else float("nan")
    cell = {
        "gar": gar_name,
        "nb_real_byz": nb_real,
        "declared_byz": f,
        # Steady-state compile proof (the large-n acceptance bar): ONE
        # compilation for the whole cell — logical workers decoupled from
        # devices must not retrace, whatever n.  Guardian escalations
        # legitimately rebuild the step (a new `step`), so the count is per
        # final stack either way.
        "compile_count": int(step._cache_size()),
        "first_loss": first,
        "final_loss": final,
        "min_loss": min(finite) if finite else float("nan"),
        "converged": bool(
            (watchdog is None or not failed)
            and np.isfinite(first) and np.isfinite(final) and final < first
        ),
        "diverged": diverged if watchdog is None else bool(failed or not np.isfinite(final)),
        "losses": losses,
    }
    if watchdog is not None:
        cell["guardian"] = True
        cell["rollbacks"] = rollbacks
        cell["escalations"] = escalations
        # diverged-then-recovered: the injected regime broke the configured
        # rule AND the recovery layer brought the run back to a finite,
        # improving trajectory
        cell["recovered"] = bool(
            rollbacks > 0 and not failed and np.isfinite(final) and recovered
        )
    if ledger is not None:
        freport = ledger.report()
        expected = list(range(nb_real))
        # 1-based ledger steps whose governing regime runs coalition
        # behavior: an attack, or a forge/tamper storm (the submission-
        # integrity failure modes are attributable the same way)
        attack_steps = set()
        if chaos is not None and (chaos.has_attacks or chaos.has_forgery):
            for sx in range(nb_steps):
                regime = chaos.regimes[chaos.regime_at(sx)]
                if (regime.attack is not None or regime.forge_rate > 0
                        or regime.tamper_rate > 0):
                    attack_steps.add(sx + 1)

        def overlaps_attack(worker):
            return any(
                iv["start"] <= sx <= iv["end"]
                for iv in freport["workers"][worker]["intervals"]
                for sx in attack_steps
            )

        suspects = freport["suspects"]
        # correct attribution: exactly the injected coalition is named, and
        # every coalition member's suspect ranges overlap the attack window
        # (a calm cell is correct when NOBODY is named)
        correct = sorted(suspects) == expected and all(
            overlaps_attack(w) for w in expected
        )
        cell["forensics"] = {
            "suspects": suspects,
            "expected": expected,
            "attack_steps": (
                [min(attack_steps), max(attack_steps)] if attack_steps else None
            ),
            "attribution_correct": bool(correct),
            "suspect_intervals": {
                str(w): freport["workers"][w]["intervals"] for w in suspects
            },
        }
    return cell


def run_campaign(args):
    """Run the full grid; returns the resilience-matrix dict."""
    from ..utils import UserException, info, warning

    n, f, r = args.nb_workers, args.nb_decl_byz_workers, args.nb_real_byz_workers
    if r > n:
        raise UserException("More real Byzantine workers (%d) than workers (%d)" % (r, n))
    guardian = None
    if getattr(args, "guardian", False):
        from ..guardian import GuardianConfig

        guardian = GuardianConfig(args.guardian_args)
    scenarios = _scenarios(args)
    cells = []
    for gar_name in args.gars:
        for scenario, spec in scenarios:
            info("campaign cell: gar=%s scenario=%s" % (gar_name, scenario))
            cell = run_cell(
                args.experiment, args.experiment_args, gar_name, args.gar_args,
                n, f, r, spec, args.chaos_args, args.nb_steps,
                args.learning_rate, args.seed, nb_devices=args.nb_devices,
                guardian=guardian, forensics=getattr(args, "forensics", False),
            )
            cell["scenario"] = scenario
            cell["schedule"] = spec
            cells.append(cell)
            verdict = ("DIVERGED" if cell["diverged"]
                       else ("converged" if cell["converged"] else "degraded"))
            if cell.get("recovered"):
                verdict = "recovered (%d rollback(s))" % cell["rollbacks"]
            if "forensics" in cell:
                fx = cell["forensics"]
                verdict += ", attribution %s (named %s, expected %s)" % (
                    "CORRECT" if fx["attribution_correct"] else "WRONG",
                    fx["suspects"] or "nobody", fx["expected"] or "nobody",
                )
            info("  -> %s (first %.4f final %.4f)"
                 % (verdict, cell["first_loss"], cell["final_loss"]))
    breakdown = []
    if args.breakdown:
        # only ATTACK scenarios can probe the Byzantine boundary — a
        # drop/straggler-only schedule has no coalition to size, and probing
        # it would compare two identical attacker-free runs
        attack_specs = [
            (name, spec) for name, spec in scenarios
            if spec is not None and _declares_attack(spec, n)
        ]
        if not attack_specs:
            raise UserException(
                "--breakdown needs at least one attack scenario (--attacks "
                "NAME or a --schedules spec with an attack= regime)"
            )
        probe_name, probe_spec = attack_specs[0]
        r_beyond = n // 2 + 1  # strict Byzantine majority: beyond EVERY rule's bound
        for gar_name in args.gars:
            if gar_name.startswith("average"):
                continue  # no declared bound to probe
            entry = {"gar": gar_name, "scenario": probe_name, "declared_byz": f,
                     "r_within": f, "r_beyond": r_beyond}
            for tag, rr in (("within", f), ("beyond", r_beyond)):
                try:
                    cell = run_cell(
                        args.experiment, args.experiment_args, gar_name, args.gar_args,
                        n, f, rr, probe_spec, args.chaos_args, args.nb_steps,
                        args.learning_rate, args.seed, nb_devices=args.nb_devices,
                    )
                except UserException as exc:
                    warning("breakdown %s/%s skipped: %s" % (gar_name, tag, exc))
                    entry["%s_error" % tag] = str(exc)
                    continue
                entry["%s_converged" % tag] = cell["converged"]
                entry["%s_final_loss" % tag] = cell["final_loss"]
                entry["%s_compile_count" % tag] = cell["compile_count"]
            if "within_converged" in entry and "beyond_converged" in entry:
                # the empirical boundary: the declared budget holds, a
                # Byzantine majority does not
                entry["bound_holds"] = bool(
                    entry["within_converged"] and not entry["beyond_converged"]
                )
            breakdown.append(entry)
    return {
        "schema": SCHEMA,
        "experiment": args.experiment,
        "experiment_args": list(args.experiment_args),
        "nb_workers": n,
        "declared_byz": f,
        "nb_real_byz": r,
        "nb_steps": args.nb_steps,
        "learning_rate": args.learning_rate,
        "seed": args.seed,
        "cells": cells,
        "breakdown": breakdown,
    }


def render_report(matrix):
    """Markdown verdict grid + breakdown table for a resilience matrix."""
    scenarios = []
    for cell in matrix["cells"]:
        if cell["scenario"] not in scenarios:
            scenarios.append(cell["scenario"])
    by_key = {(c["gar"], c["scenario"]): c for c in matrix["cells"]}
    lines = [
        "# Resilience matrix — %s, n=%d, f=%d declared, %d steps"
        % (matrix["experiment"], matrix["nb_workers"], matrix["declared_byz"],
           matrix["nb_steps"]),
        "",
        "Verdicts: `ok` loss decreased (first -> final), `degraded` finite but",
        "not decreasing, `DIVERGED` non-finite loss (params poisoned),",
        "`recovered` diverged then healed by the guardian (rollback count).",
        "",
        "| GAR | " + " | ".join(scenarios) + " |",
        "|---|" + "---|" * len(scenarios),
    ]
    for gar_name in dict.fromkeys(c["gar"] for c in matrix["cells"]):
        row = ["| %s" % gar_name]
        for scenario in scenarios:
            cell = by_key.get((gar_name, scenario))
            if cell is None:
                row.append("—")
            elif cell.get("recovered"):
                row.append("recovered x%d (%.3f→%.3f)" % (
                    cell["rollbacks"], cell["first_loss"], cell["final_loss"]))
            elif cell["diverged"]:
                row.append("DIVERGED")
            elif cell["converged"]:
                row.append("ok (%.3f→%.3f)" % (cell["first_loss"], cell["final_loss"]))
            else:
                row.append("degraded (%.3f→%.3f)" % (cell["first_loss"], cell["final_loss"]))
        lines.append(" | ".join(row) + " |")
    if any("forensics" in cell for cell in matrix["cells"]):
        lines += [
            "",
            "## Forensics attribution",
            "",
            "Per cell: the workers the ledger (obs/forensics.py) named",
            "Byzantine vs the injected coalition; `correct` means exactly the",
            "coalition was named with suspect ranges overlapping the attack",
            "window (calm cells: correct = nobody named).",
            "",
            "| GAR | scenario | named | expected | correct |",
            "|---|---|---|---|---|",
        ]
        for cell in matrix["cells"]:
            fx = cell.get("forensics")
            if fx is None:
                continue
            lines.append("| %s | %s | %s | %s | %s |" % (
                cell["gar"], cell["scenario"],
                ",".join(str(w) for w in fx["suspects"]) or "—",
                ",".join(str(w) for w in fx["expected"]) or "—",
                "**yes**" if fx["attribution_correct"] else "NO",
            ))
    if matrix["breakdown"]:
        lines += [
            "",
            "## Empirical f-breakdown boundary",
            "",
            "Same attack scenario at `r = f` (inside the declared budget) and",
            "`r = n//2 + 1` (Byzantine majority — beyond every rule's bound).",
            "",
            "| GAR | scenario | r=f converged | r=majority converged | bound holds |",
            "|---|---|---|---|---|",
        ]
        for entry in matrix["breakdown"]:
            lines.append("| %s | %s | %s | %s | %s |" % (
                entry["gar"], entry["scenario"],
                entry.get("within_converged", entry.get("within_error", "?")),
                entry.get("beyond_converged", entry.get("beyond_error", "?")),
                entry.get("bound_holds", "?"),
            ))
    return "\n".join(lines) + "\n"


def main(argv=None):
    args = build_parser().parse_args(argv)
    if args.platform:
        import os

        os.environ["JAX_PLATFORMS"] = args.platform
        import jax

        jax.config.update("jax_platforms", args.platform)
    from ..utils import info

    matrix = run_campaign(args)
    text = json.dumps(matrix, indent=1)
    if args.output:
        with open(args.output, "w") as fd:
            fd.write(text + "\n")
        info("resilience matrix -> %s" % args.output)
    else:
        print(text)
    if args.report:
        with open(args.report, "w") as fd:
            fd.write(render_report(matrix))
        info("markdown report -> %s" % args.report)
    return 0


def cli():
    from ..cli import console_entry

    return console_entry(main)


if __name__ == "__main__":
    sys.exit(cli())

"""Per-worker straggler simulation: late workers drop out or go stale.

The failure mode the base engines lack: in real clusters the tail is not
Byzantine, it is LATE — a worker whose gradient misses the aggregation
deadline (straggler/tail literature: "Efficient AllReduce with Stragglers",
arXiv:2505.23523; OptiReduce's tail-latency motivation, arXiv:2310.06993).
Under a synchronous parameter server there are exactly two things the
aggregator can do with a late worker's slot, and both already have
machinery here:

- **drop** — the row simply is not there this round.  Modeled as a whole
  row of NaN, the same convention as a fully-lossy link
  (``parallel/lossy.py``): NaN-aware rules (average-nan, median,
  Krum/Bulyan's +inf-distance convention) exclude it, plain ``average`` is
  poisoned — faithfully reproducing why you must size ``f`` to cover
  stragglers (docs/robustness.md "Choosing f");
- **stale** — the aggregator reuses the worker's PREVIOUS submission (the
  asynchronous/stale-gradient model).  Implemented on the worker-sharded
  ``TrainState.carry`` the CLEVER infill already threads through both
  engines (``parallel/engine.py``): a worker late for k consecutive steps
  keeps re-submitting the same gradient, exactly like a CLEVER reassembly
  buffer that received nothing — at drop-rate 1.0 the two paths are
  bit-identical (asserted by tests/test_chaos.py).

Lateness is i.i.d. per (worker, step) with the schedule's regime-indexed
rate, drawn from a per-(step, worker) key the engines keep disjoint from
every other stream: the flat engine folds tag 5 onto the per-worker key
(disjoint from attack (1) / lossy (2) / augment (3) / sampling (4)); the
sharded engine derives the per-worker key in its 30_000+ offset namespace
first, because there the plain per-worker key is the PARENT of the
per-leaf streams.  Either way a chaotic run is deterministic in
(seed, step, global worker index) and device-layout invariant, like every
other perturbation.
"""

import jax
import jax.numpy as jnp

#: fold_in tag of the straggler lateness stream (see module docstring)
STRAGGLER_KEY_TAG = 5


class StragglerModel:
    """Static straggler config; per-step rate/mode come from the schedule."""

    def __init__(self, nb_workers, nb_eligible=0):
        self.nb_workers = int(nb_workers)
        # 0 means every worker is eligible; K > 0 restricts lateness to the
        # first K global workers (the --UDP first-k convention)
        self.nb_eligible = int(nb_eligible)
        if self.nb_eligible < 0 or self.nb_eligible > self.nb_workers:
            from ..utils import UserException

            raise UserException(
                "straggle-workers must lie in [0, nb_workers]=%d (got %d)"
                % (self.nb_workers, self.nb_eligible)
            )

    def is_late(self, worker_key, worker_index, rate):
        """(traced) bool: is this worker late this step?  ``worker_key`` is
        the per-(step, worker) key; ``rate`` the regime's traced rate."""
        late = jax.random.bernoulli(jax.random.fold_in(worker_key, STRAGGLER_KEY_TAG), rate)
        if self.nb_eligible:
            late = late & (worker_index < self.nb_eligible)
        return late

    def apply(self, grad, late, stale, previous=None):
        """Replace a late worker's (d,) gradient with its regime's infill.

        ``stale`` is the regime's traced mode flag; ``previous`` the
        worker's carried previous submission (None when no regime in the
        schedule needs the carry — then every late row NaN-drops).
        """
        nan_row = jnp.full_like(grad, jnp.nan)
        if previous is None:
            infill = nan_row
        else:
            infill = jnp.where(stale, previous, nan_row)
        return jnp.where(late, infill, grad)

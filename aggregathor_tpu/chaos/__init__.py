"""Chaos engineering for Byzantine-resilient training.

The reference (and the base engines) model adversity as *static whole-run
knobs*: one ``--attack`` for every step, one ``--UDP`` loss rate forever
(reference: runner.py:145-155, deploy.py:119-122).  Real Byzantine/tail
behavior is bursty and time-varying — transient packet-loss storms and
stragglers dominate cloud training tails (OptiReduce, arXiv:2310.06993;
"Efficient AllReduce with Stragglers", arXiv:2505.23523).  This package
makes adversity *schedulable* and turns the attack/lossy/GAR machinery into
a systematic resilience-evaluation product:

- ``schedule``:   a deterministic piecewise fault-regime DSL
  (``0:calm 500:drop=0.3 1000:attack=empire``) compiled to step-indexed
  arrays, so regime switches happen INSIDE the jitted step (array indexing
  + ``lax.switch``) with zero recompilation;
- ``stragglers``: the per-worker straggler/stale-gradient failure mode the
  base engines lack — a "late" worker's row is either NaN-dropped (absorbed
  by the NaN-aware GARs, like ``parallel/lossy.py``) or replaced by its
  previous-step gradient (reusing the worker-sharded ``TrainState.carry``
  CLEVER machinery, ``parallel/engine.py``);
- ``campaign``:   a resilience-campaign harness sweeping attack x GAR x
  schedule grids through the real engine, emitting a machine-readable
  resilience matrix (JSON) plus a markdown report, including an empirical
  check of the f-breakdown-point boundary;
- ``replica_faults``: the SERVING-side fault regimes — per-replica
  parameter corruption (nan / scale / zero / noise / stale) driving the
  replicated robust inference path (``serve/``), swept by the serve
  campaign the way ``campaign`` sweeps training regimes.

Both engines accept a ``ChaosSchedule`` (``RobustEngine(..., chaos=...)``);
the CLI spells it ``--chaos "<schedule>" --chaos-args key:value...``.
"""

from .schedule import ChaosSchedule  # noqa: F401
from .stragglers import StragglerModel  # noqa: F401
from .replica_faults import (  # noqa: F401
    PARAM_FAULTS,
    REPLICA_FAULTS,
    corrupt_params,
    parse_poison,
)

"""Piecewise fault-regime schedule DSL, compiled for in-step dispatch.

Grammar (whitespace-separated segments, ``parse_keyval``-style values)::

  SCHEDULE := SEGMENT (" " SEGMENT)*
  SEGMENT  := STEP ":" REGIME            # STEP is a non-negative integer
  REGIME   := "calm" | SETTING ("," SETTING)*
  SETTING  := KEY "=" VALUE

Known keys:

- ``attack=NAME``          activate a registered gradient attack
  (``parallel/attacks.py``) for this regime; any UNKNOWN key in the same
  regime is forwarded to the attack as a ``key:value`` sub-argument
  (``attack=empire,epsilon=4.0``);
- ``drop=RATE``            i.i.d. per-packet datagram loss in [0, 1] on
  EVERY worker's gradient (a network loss storm — unlike the static
  ``--UDP k`` first-k-workers knob), NaN infill like the reference's UDP
  transport (mpi_rendezvous_mgr.patch:833-841);
- ``straggle=RATE``        per-step probability in [0, 1] that a worker is
  "late" this step (i.i.d. per worker, see ``stragglers.py``);
- ``straggle-mode=MODE``   what a late worker's row becomes: ``drop``
  (whole row NaN — the NaN-aware GARs exclude it) or ``stale`` (the
  previous-step submission, via the CLEVER ``TrainState.carry``);
- ``jitter=SIGMA``         heavy-tail lateness (SIGMA >= 0, needs
  ``straggle=RATE`` in the same regime): under bounded-wait
  (``--step-deadline``), a late worker's wall-clock stall becomes
  lognormal around ``--straggler-stall`` (median = stall, sigma =
  SIGMA — the realistic arrival distribution the adaptive deadline
  controller is exercised on, ``parallel/deadline.py``).  The in-graph
  simulation's lateness is binary (there is no wall clock inside the
  step), so jitter shapes the HOST straggler model only;
- ``forge=RATE``           per-step probability that each coalition worker
  (the first ``nb_real_byz``) submits as an IMPERSONATOR without the
  session secret: its row is replaced by noise and its submission tag is
  minted under the wrong key (secure/submit.py).  Under ``--secure`` the
  aggregator's verification rejects the row (NaN, named ``forgery``
  evidence); without it the forged row enters aggregation;
- ``tamper=RATE``          per-step probability that each coalition
  worker's row is bit-flipped IN TRANSIT, after honest signing — the tag
  no longer matches the received bytes, so ``--secure`` rejects it;
  without verification the corrupted row enters aggregation;
- ``kill=NAME(+NAME)*``    PROCESS plane (fleet soak, ``cli.supervise``):
  SIGKILL the named fleet instance(s) at regime entry — the supervisor
  must notice through the scrape plane and restart them.  Host-side ONLY
  and further gated: a ``ChaosSchedule`` built without
  ``allow_process_faults=True`` (every training engine) REJECTS
  schedules containing process-fault keys, because a training step has
  no business killing fleet processes;
- ``hang=NAME(+NAME)*``    like ``kill`` but SIGSTOP: the instance stays
  alive yet stops answering scrapes — the hung-instance detection path
  (consecutive scrape misses), distinct from the dead-process path.

A regime named ``calm`` (or any segment's unset keys) means: no attack,
no loss, no stragglers.  Segments sort by step; the regime starting at
step ``s`` governs every step ``t`` with ``s <= t < next_start`` — the
switch lands at EXACTLY step ``s``.  If no segment starts at 0, an
implicit ``0:calm`` is prepended.

Compiled form: the per-regime scalar knobs live in step-indexed arrays and
the active regime is ``searchsorted(starts, step) - 1`` on the TRACED step
counter, so one compiled program covers the whole schedule — regime
switches cost an array index and a ``lax.switch``, never a retrace
(asserted by tests/test_chaos.py).

Schedule-wide options (the CLI's ``--chaos-args``):

- ``packet-coords:N``     datagram size of the ``drop`` link (default: the
  UDP 65000-byte datagram, ``parallel/lossy.py``);
- ``min-coords:N``        minimum gradient size for ``drop`` to engage
  (default 0: chaos storms hit every tensor, unlike the reference's ~1 MB
  UDP threshold);
- ``straggle-workers:K``  only the first K global workers ever straggle
  (default 0 = all workers are eligible).
"""

import numpy as np

from ..utils import UserException, parse_keyval
from .replica_faults import PROCESS_FAULTS, parse_process_targets

#: sub-aggregator fault keys (the TOPOLOGY plane, topology/tree.py): a
#: ``corrupt-agg`` unit signs its custody tag without the session secret,
#: a ``straggle-agg`` unit stalls past its level window.  Targets are
#: ``LEVEL.UNIT`` pairs joined with ``+`` (``corrupt-agg=1.0+2.1``) —
#: tree nodes, NOT workers.  Gated like the process faults: only a
#: consumer that actually runs a tree (``--topology``) may accept them.
TOPOLOGY_FAULTS = ("corrupt-agg", "straggle-agg")

#: regime keys the DSL itself consumes; anything else must ride an ``attack=``
_REGIME_KEYS = ("attack", "drop", "straggle", "straggle-mode", "jitter",
                "forge", "tamper") + PROCESS_FAULTS + TOPOLOGY_FAULTS

_CALM = "calm"


def parse_topology_targets(key, value):
    """``1.0+2.1`` -> ((1, 0), (2, 1)) — (level, unit) sub-aggregator
    targets (1-based level, 0-based unit within the level).  Structural
    validation only; the TreeSpec bounds-check the targets against the
    live tree (``validate_fault_target``) at wiring time."""
    targets = []
    for part in value.split("+"):
        part = part.strip()
        pieces = part.split(".")
        try:
            level, unit = (int(p) for p in pieces)
        except ValueError:
            raise UserException(
                "Chaos %s=%r: each target must be LEVEL.UNIT (two "
                "integers, e.g. %s=1.0+2.1)" % (key, value, key)
            )
        if level < 1:
            raise UserException(
                "Chaos %s=%r: levels are 1-based (got level %d)"
                % (key, value, level)
            )
        if unit < 0:
            raise UserException(
                "Chaos %s=%r: unit indices are >= 0 (got %d)"
                % (key, value, unit)
            )
        targets.append((level, unit))
    if not targets:
        raise UserException("Chaos %s= names no targets" % key)
    return tuple(targets)


class Regime:
    """One parsed schedule segment (static Python config, no arrays)."""

    __slots__ = ("start", "spec", "attack", "drop_rate", "straggler_rate",
                 "straggler_stale", "straggler_jitter", "forge_rate",
                 "tamper_rate", "kills", "hangs", "agg_corrupt",
                 "agg_straggle")

    def __init__(self, start, spec, attack=None, drop_rate=0.0,
                 straggler_rate=0.0, straggler_stale=False,
                 straggler_jitter=0.0, forge_rate=0.0, tamper_rate=0.0,
                 kills=(), hangs=(), agg_corrupt=(), agg_straggle=()):
        self.start = int(start)
        self.spec = spec
        self.attack = attack
        self.drop_rate = float(drop_rate)
        self.straggler_rate = float(straggler_rate)
        self.straggler_stale = bool(straggler_stale)
        self.straggler_jitter = float(straggler_jitter)
        self.forge_rate = float(forge_rate)
        self.tamper_rate = float(tamper_rate)
        #: process-plane fault targets (instance names), empty everywhere
        #: the training engines run — never compiled, never traced
        self.kills = tuple(kills)
        self.hangs = tuple(hangs)
        #: topology-plane fault targets ((level, unit) tree nodes), empty
        #: outside ``--topology`` runs — host-side only, never traced
        self.agg_corrupt = tuple(agg_corrupt)
        self.agg_straggle = tuple(agg_straggle)


def _parse_rate(key, value):
    try:
        rate = float(value)
    except ValueError:
        raise UserException("Chaos %s=%r is not a number" % (key, value))
    if not 0.0 <= rate <= 1.0:
        raise UserException("Chaos %s=%r must lie in [0, 1]" % (key, value))
    return rate


def _parse_regime(start, text, nb_workers, nb_real_byz):
    """Parse one REGIME body into a :class:`Regime`."""
    from ..parallel import attacks as attack_registry

    if text == _CALM:
        return Regime(start, _CALM)
    attack_name = None
    attack_args = []
    drop_rate = 0.0
    straggler_rate = None
    straggler_stale = None
    straggler_jitter = None
    forge_rate = 0.0
    tamper_rate = 0.0
    kills = ()
    hangs = ()
    agg_corrupt = ()
    agg_straggle = ()
    seen = set()
    for setting in text.split(","):
        if "=" not in setting:
            raise UserException(
                "Chaos regime setting %r at step %d: expected KEY=VALUE (or the "
                "bare regime name 'calm')" % (setting, start)
            )
        key, value = setting.split("=", 1)
        if key in seen:
            raise UserException("Chaos regime at step %d sets %r twice" % (start, key))
        seen.add(key)
        if key == "attack":
            if value not in attack_registry.itemize():
                raise UserException(
                    "Unknown chaos attack %r (registered: %s)"
                    % (value, ", ".join(sorted(attack_registry.itemize())))
                )
            attack_name = value
        elif key == "drop":
            drop_rate = _parse_rate(key, value)
        elif key == "straggle":
            straggler_rate = _parse_rate(key, value)
        elif key == "forge":
            forge_rate = _parse_rate(key, value)
        elif key == "tamper":
            tamper_rate = _parse_rate(key, value)
        elif key == "kill":
            kills = parse_process_targets(key, value)
        elif key == "hang":
            hangs = parse_process_targets(key, value)
        elif key == "corrupt-agg":
            agg_corrupt = parse_topology_targets(key, value)
        elif key == "straggle-agg":
            agg_straggle = parse_topology_targets(key, value)
        elif key == "straggle-mode":
            if value not in ("drop", "stale"):
                raise UserException(
                    "Chaos straggle-mode=%r must be 'drop' or 'stale'" % (value,)
                )
            straggler_stale = value == "stale"
        elif key == "jitter":
            try:
                straggler_jitter = float(value)
            except ValueError:
                raise UserException(
                    "Chaos jitter=%r is not a number" % (value,)
                )
            if straggler_jitter < 0.0:
                raise UserException(
                    "Chaos jitter=%r must be >= 0 (the lognormal sigma "
                    "around the straggler stall)" % (value,)
                )
        else:
            attack_args.append("%s:%s" % (key, value))
    if attack_args and attack_name is None:
        raise UserException(
            "Chaos regime at step %d passes attack arguments (%s) without "
            "attack=NAME" % (start, ", ".join(attack_args))
        )
    if straggler_stale is not None and straggler_rate is None:
        raise UserException(
            "Chaos regime at step %d sets straggle-mode without straggle=RATE" % start
        )
    if straggler_jitter is not None and straggler_rate is None:
        raise UserException(
            "Chaos regime at step %d sets jitter without straggle=RATE" % start
        )
    attack = None
    if attack_name is not None:
        if nb_real_byz < 1:
            raise UserException(
                "Chaos schedule declares attack regimes (step %d: attack=%s) but "
                "nb_real_byz is 0; pass --nb-real-byz-workers > 0 so the "
                "coalition has members" % (start, attack_name)
            )
        attack = attack_registry.instantiate(attack_name, nb_workers, nb_real_byz, attack_args)
    if (forge_rate or tamper_rate) and nb_real_byz < 1:
        raise UserException(
            "Chaos regime at step %d sets forge/tamper rates but nb_real_byz "
            "is 0; pass --nb-real-byz-workers > 0 so the forging coalition "
            "has members" % start
        )
    return Regime(
        start, text, attack=attack, drop_rate=drop_rate,
        straggler_rate=straggler_rate or 0.0,
        straggler_stale=bool(straggler_stale),
        straggler_jitter=straggler_jitter or 0.0,
        forge_rate=forge_rate, tamper_rate=tamper_rate,
        kills=kills, hangs=hangs,
        agg_corrupt=agg_corrupt, agg_straggle=agg_straggle,
    )


class ChaosSchedule:
    """A parsed + compiled fault-regime schedule both engines consume.

    The compiled arrays (``_starts`` and the per-regime knob vectors) are
    tiny host constants; ``regime_index``/``drop_rate``/... index them with
    the traced step so the whole schedule lives inside ONE compiled step
    program.  Attack dispatch is a ``lax.switch`` over per-regime branches
    (identity for attack-free regimes) — every branch is traced once at
    compile time, and regime transitions never retrace.
    """

    def __init__(self, spec, nb_workers, nb_real_byz=0, args=None,
                 allow_process_faults=False, allow_topology_faults=False):
        from ..parallel.lossy import PACKET_COORDS, LossyLink

        kv = parse_keyval(args or [], {
            "packet-coords": PACKET_COORDS,
            "min-coords": 0,
            "straggle-workers": 0,
        }, strict=True)
        self.spec = str(spec)
        self.nb_workers = int(nb_workers)
        self.nb_real_byz = int(nb_real_byz)
        segments = self.spec.split()
        if not segments:
            raise UserException("Empty chaos schedule (expected e.g. '0:calm 500:drop=0.3')")
        regimes = []
        for segment in segments:
            if ":" not in segment:
                raise UserException(
                    "Chaos segment %r: expected STEP:REGIME (e.g. '500:drop=0.3')" % (segment,)
                )
            step_text, regime_text = segment.split(":", 1)
            try:
                start = int(step_text)
            except ValueError:
                raise UserException("Chaos segment %r: step %r is not an integer" % (segment, step_text))
            if start < 0:
                raise UserException("Chaos segment %r: negative start step" % (segment,))
            regimes.append(_parse_regime(start, regime_text, self.nb_workers, self.nb_real_byz))
        starts = [r.start for r in regimes]
        if len(set(starts)) != len(starts):
            dup = sorted(s for s in set(starts) if starts.count(s) > 1)
            raise UserException("Chaos schedule has duplicate start steps: %s" % dup)
        regimes.sort(key=lambda r: r.start)
        if regimes[0].start != 0:
            regimes.insert(0, Regime(0, _CALM))
        self.regimes = regimes
        #: any regime kills or hangs a fleet process — the soak driver's
        #: dispatch flag, and the gate below for everyone else
        self.has_process_faults = any(r.kills or r.hangs for r in regimes)
        if self.has_process_faults and not allow_process_faults:
            offender = next(r for r in regimes if r.kills or r.hangs)
            raise UserException(
                "Chaos regime %d:%s declares process-level faults "
                "(kill=/hang=) but this consumer is a training engine — "
                "a training step cannot kill fleet processes.  Those keys "
                "belong to the fleet plane: benchmarks/soak.py and "
                "cli.supervise build their schedule with "
                "allow_process_faults=True" % (offender.start, offender.spec)
            )
        #: any regime faults a sub-aggregator — only meaningful when a
        #: tree topology actually runs (the gate below: a star has no
        #: sub-aggregators to corrupt, so accepting the keys silently
        #: would no-op the declared fault)
        self.has_topology_faults = any(
            r.agg_corrupt or r.agg_straggle for r in regimes
        )
        if self.has_topology_faults and not allow_topology_faults:
            offender = next(
                r for r in regimes if r.agg_corrupt or r.agg_straggle
            )
            raise UserException(
                "Chaos regime %d:%s declares sub-aggregator faults "
                "(corrupt-agg=/straggle-agg=) but this run has no "
                "aggregation tree — a parameter-server star has no "
                "sub-aggregators to fault.  Those keys need --topology "
                "tree:... (the runner then builds its schedule with "
                "allow_topology_faults=True)"
                % (offender.start, offender.spec)
            )
        self._starts = np.asarray([r.start for r in regimes], np.int32)
        self._drop_rates = np.asarray([r.drop_rate for r in regimes], np.float32)
        self._straggler_rates = np.asarray([r.straggler_rate for r in regimes], np.float32)
        self._straggler_stale = np.asarray([r.straggler_stale for r in regimes], np.bool_)
        #: wall-clock heavy-tail sigma per regime — consumed by the HOST
        #: straggler model only (parallel/bounded.py); the in-graph
        #: lateness simulation is binary
        self._straggler_jitter = np.asarray(
            [r.straggler_jitter for r in regimes], np.float32
        )
        self._forge_rates = np.asarray([r.forge_rate for r in regimes], np.float32)
        self._tamper_rates = np.asarray([r.tamper_rate for r in regimes], np.float32)
        self.has_drop = bool((self._drop_rates > 0).any())
        self.has_stragglers = bool((self._straggler_rates > 0).any())
        #: any regime forges or tampers submissions — the engines then run
        #: the submission-forgery pipeline (parallel/engine.py)
        self.has_forgery = bool(
            (self._forge_rates > 0).any() or (self._tamper_rates > 0).any()
        )
        #: stale stragglers re-send the previous submission, so the engine
        #: must thread the CLEVER carry through the step
        self.needs_carry = bool(
            ((self._straggler_rates > 0) & self._straggler_stale).any()
        )
        self.has_local_attacks = any(
            r.attack is not None and not r.attack.omniscient for r in regimes
        )
        self.has_omniscient_attacks = any(
            r.attack is not None and r.attack.omniscient for r in regimes
        )
        self.has_attacks = self.has_local_attacks or self.has_omniscient_attacks
        self.link = None
        if self.has_drop:
            self.link = LossyLink(self.nb_workers, [
                "drop-rate:0.0",  # always overridden per step by drop_rate()
                "packet-coords:%d" % int(kv["packet-coords"]),
                "min-coords:%d" % int(kv["min-coords"]),
            ])
        from .stragglers import StragglerModel

        self.stragglers = StragglerModel(self.nb_workers, nb_eligible=int(kv["straggle-workers"]))

    # ------------------------------------------------------------------ #
    # traced accessors (used inside the jitted step)

    def regime_index(self, step):
        """(traced) int32 index of the regime governing ``step``."""
        import jax.numpy as jnp

        idx = jnp.searchsorted(jnp.asarray(self._starts), step, side="right") - 1
        return jnp.maximum(idx, 0).astype(jnp.int32)

    def drop_rate(self, ridx):
        import jax.numpy as jnp

        return jnp.asarray(self._drop_rates)[ridx]

    def straggler_rate(self, ridx):
        import jax.numpy as jnp

        return jnp.asarray(self._straggler_rates)[ridx]

    def straggler_stale(self, ridx):
        import jax.numpy as jnp

        return jnp.asarray(self._straggler_stale)[ridx]

    def forge_rate(self, ridx):
        import jax.numpy as jnp

        return jnp.asarray(self._forge_rates)[ridx]

    def tamper_rate(self, ridx):
        import jax.numpy as jnp

        return jnp.asarray(self._tamper_rates)[ridx]

    def apply_local_attacks(self, ridx, grad, key):
        """lax.switch dispatch of the active regime's LOCAL attack (identity
        for regimes without one).  The caller gates by Byzantine worker
        index, exactly like the static-attack path (engine._perturb_local)."""
        import jax

        branches = []
        for regime in self.regimes:
            attack = regime.attack
            if attack is not None and not attack.omniscient:
                branches.append(lambda g, k, a=attack: a.apply_local(g, k))
            else:
                branches.append(lambda g, k: g)
        return jax.lax.switch(ridx, branches, grad, key)

    def apply_omniscient_attacks(self, ridx, matrix, byz_mask, key):
        """lax.switch dispatch of the active regime's OMNISCIENT attack on
        the gathered (n, d_block) rows (identity for regimes without one)."""
        import jax

        branches = []
        for regime in self.regimes:
            attack = regime.attack
            if attack is not None and attack.omniscient:
                branches.append(lambda m, b, k, a=attack: a.apply_matrix(m, b, k))
            else:
                branches.append(lambda m, b, k: m)
        return jax.lax.switch(ridx, branches, matrix, byz_mask, key)

    # ------------------------------------------------------------------ #
    # host-side helpers (logging, campaign reports)

    def regime_at(self, step):
        """Python int index of the regime governing host-side ``step``."""
        return max(int(np.searchsorted(self._starts, int(step), side="right")) - 1, 0)

    def describe(self, index):
        """Human-readable ``start:spec`` for regime ``index``."""
        regime = self.regimes[index]
        return "%d:%s" % (regime.start, regime.spec)

    def transitions(self):
        """[(start_step, spec), ...] for every regime, in order."""
        return [(r.start, r.spec) for r in self.regimes]

    def process_faults(self):
        """[(start_step, kills, hangs), ...] for regimes carrying
        process-plane faults — what the soak driver walks, firing each
        entry ONCE when its start step (tick) is reached."""
        return [(r.start, r.kills, r.hangs)
                for r in self.regimes if r.kills or r.hangs]

    def __len__(self):
        return len(self.regimes)

"""Replica-parameter fault modes: chaos/ regimes for the SERVING path.

Training chaos corrupts per-worker *gradients* (schedule.py regimes: drop,
straggle, attack); serving chaos corrupts per-replica *parameters* — the
failure modes an inference fleet actually sees:

- ``nan``          a crashed/truncated replica: every parameter reads NaN,
  so its logits read NaN — absorbed by the NaN-last GAR convention exactly
  like a dead worker's gradient row (``gars/median.py``);
- ``scale[=X]``    a corrupted replica (bit-rot, botched quantization, an
  adversarial substitution): parameters multiplied by X (default 100);
- ``zero``         a wiped replica: all-zeros parameters (uniform logits);
- ``noise[=S]``    a perturbed replica: i.i.d. Gaussian noise of scale S
  times each leaf's std added (default 0.1) — models near-agreeing
  replicas (distinct fine-tunes), NOT a Byzantine fault;
- ``stale``        an out-of-date replica — no transform here: the caller
  restores an EARLIER checkpoint step instead (``cli/serve.py`` resolves
  ``stale`` to the oldest on-disk snapshot; ``serve/campaign.py`` to an
  under-trained copy).

Spec grammar (CLI ``--poison-replica``, campaign scenario lists)::

  SPEC := INDEX ":" MODE ("=" VALUE)?     e.g.  1:nan   2:scale=50   0:stale

The serve campaign (``serve/campaign.py``) sweeps these modes x GARs and
proves the median-of-replicas vote keeps served predictions at the clean bar
while plain ``average`` degrades — the serving-side breakdown probe.
"""

import numpy as np

import jax

from ..utils import UserException

#: modes that transform a parameter pytree in place (stale is resolved by
#: the caller to an earlier checkpoint instead)
PARAM_FAULTS = ("nan", "scale", "zero", "noise")

#: every accepted mode name
REPLICA_FAULTS = PARAM_FAULTS + ("stale",)

#: PROCESS-level fault keys the schedule DSL accepts (``kill=`` SIGKILLs
#: the named fleet instance at regime entry, ``hang=`` SIGSTOPs it so its
#: scrapes go stale without the process dying).  Host/fleet plane ONLY —
#: the training engines never see them (``ChaosSchedule`` rejects them
#: unless the caller opts in with ``allow_process_faults=True``; the
#: supervisor soak is that caller).
PROCESS_FAULTS = ("kill", "hang")

_DEFAULTS = {"scale": 100.0, "noise": 0.1}


def parse_process_targets(key, value):
    """Parse a process-fault target list -> tuple of instance names.

    Grammar: ``NAME("+"NAME)*`` — ``kill=serve_b`` or ``hang=train+router``
    (``+`` separates targets because ``,`` already separates regime
    settings).  Names are fleet-spec instance names (cli/supervise.py);
    the schedule cannot validate them against a fleet it has never seen,
    so it checks shape only and the soak driver fails loudly on an
    unknown name.
    """
    if key not in PROCESS_FAULTS:
        raise UserException(
            "Unknown process fault %r (accepted: %s)"
            % (key, ", ".join(PROCESS_FAULTS))
        )
    targets = tuple(value.split("+"))
    for target in targets:
        if not target or target != target.strip():
            raise UserException(
                "Chaos %s=%r: empty or padded instance name in target "
                "list (expected NAME or NAME+NAME)" % (key, value)
            )
        if any(c in target for c in ":,= "):
            raise UserException(
                "Chaos %s=%r: instance name %r may not contain "
                "':' ',' '=' or spaces" % (key, value, target)
            )
    if len(set(targets)) != len(targets):
        raise UserException(
            "Chaos %s=%r names the same instance twice" % (key, value)
        )
    return targets


def parse_poison(spec):
    """Parse one ``INDEX:MODE[=VALUE]`` spec -> (index, mode, value).

    ``value`` is None for modes without a knob (nan/zero/stale).
    """
    if ":" not in spec:
        raise UserException(
            "Poison spec %r: expected INDEX:MODE[=VALUE] (modes: %s)"
            % (spec, ", ".join(REPLICA_FAULTS))
        )
    index_text, mode = spec.split(":", 1)
    try:
        index = int(index_text)
    except ValueError:
        raise UserException("Poison spec %r: replica index %r is not an integer"
                            % (spec, index_text))
    if index < 0:
        raise UserException("Poison spec %r: replica index must be >= 0" % (spec,))
    value = None
    if "=" in mode:
        mode, value_text = mode.split("=", 1)
        try:
            value = float(value_text)
        except ValueError:
            raise UserException("Poison spec %r: value %r is not a number"
                                % (spec, value_text))
    if mode not in REPLICA_FAULTS:
        raise UserException(
            "Unknown replica fault %r (accepted: %s)"
            % (mode, ", ".join(REPLICA_FAULTS))
        )
    if value is not None and mode not in _DEFAULTS:
        raise UserException("Replica fault %r takes no value (got %r)" % (mode, value))
    if value is None:
        value = _DEFAULTS.get(mode)
    return index, mode, value


def corrupt_params(params, mode, value=None, seed=0):
    """Apply a parameter fault mode to a replica's pytree (host-side numpy;
    the corrupted copy is device_put by the serving engine like any other
    replica).  ``stale`` is a restore-time mode and is rejected here."""
    if mode not in PARAM_FAULTS:
        raise UserException(
            "corrupt_params handles %s; %r is resolved at restore time"
            % ("/".join(PARAM_FAULTS), mode)
        )
    if value is None:
        value = _DEFAULTS.get(mode)
    leaves, treedef = jax.tree_util.tree_flatten(params)
    rng = np.random.default_rng(seed)
    out = []
    for leaf in leaves:
        leaf = np.asarray(leaf)
        if mode == "nan":
            out.append(np.full_like(leaf, np.nan))
        elif mode == "zero":
            out.append(np.zeros_like(leaf))
        elif mode == "scale":
            out.append(leaf * np.asarray(value, leaf.dtype))
        else:  # noise
            sigma = float(np.std(leaf)) or 1.0
            out.append(leaf + rng.normal(
                0.0, float(value) * sigma, size=leaf.shape
            ).astype(leaf.dtype))
    return jax.tree_util.tree_unflatten(treedef, out)

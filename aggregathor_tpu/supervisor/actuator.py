"""Fleet supervision actuator: the impure half of the supervisor.

``FleetSupervisor`` owns the fleet's processes.  It spawns every
:class:`InstanceSpec`, scrapes them through the PR-15 ``FleetCollector``,
tails their journals with the incremental cursor (``obs/events.py
tail_journal`` — no re-reading whole files every tick), watches their
sentinel verdict files (obs/slo.py), and each :meth:`tick` feeds all of
it to the pure :class:`~.policy.SupervisorPolicy` and EXECUTES the
returned actions:

- **Restart**: SIGKILL the remains (a hung process survives its down
  judgment), respawn the same argv, wait for the ready-file handshake.
- **Quarantine**: kill and DO NOT respawn; the spec is marked so even a
  supervisor restart will not resurrect the crash-looper.
- **Retune**: rewrite the instance's argv through its rung
  (:func:`apply_rung` — ``KEY=VALUE`` sets a flag, ``KEY*X`` scales a
  numeric one), then SIGTERM -> wait -> respawn: the Overrides
  rebuild discipline at fleet level — never mutate a running instance,
  rebuild its config and pay one restart on the rare path.
- **Rollback**: custody-verify the restore target (secure/custody.py,
  fail-closed without a session secret unless ``allow_unsigned``), then
  ``Checkpoints.discard_after`` the regressed tail so every later
  restore — auto-restore, serve followers — lands on the
  rolled-back-to snapshot.  Serving replicas only ever swap NEWER
  steps in (serve/weights.py), so the rollback is never client-visible.

Every executed action is one typed journal event
(``supervisor_restart/quarantine/retune/rollback/observe``) carrying the
policy's triggering evidence — the causal chain from symptom to action
replays from the merged fleet journal (benchmarks/soak.py proves it).

The causal plane (docs/observability.md): action events are emitted
BEFORE the respawn so the freshly minted ``(run_id, seq)`` can be handed
to the child as ``--cause INSTANCE:RUN_ID:SEQ`` (specs opt in with
``cause_flag``); the child's ``run_start`` then cites the exact
``supervisor_restart``/``supervisor_retune`` that spawned it, and
``cli.postmortem`` replays the cross-process chain from the journals
alone.  Retune events additionally cite the LAST streak-forming journal
record of the retuned instance (the policy's evidence refs plus the
tailed stream's current ``run_id``).
"""

import json
import os
import signal
import subprocess
import sys
import time

from ..obs import events
from ..obs.fleet import FleetCollector
from ..utils import UserException, info, warning
from .policy import (
    InstanceObs, Observe, Quarantine, Restart, Retune, Rollback,
    SupervisorConfig, SupervisorPolicy,
)


def apply_rung(argv, rung):
    """Rewrite an argv through one retune rung; returns the NEW argv.

    Grammar: ``KEY=VALUE`` sets ``--KEY VALUE`` (replacing the existing
    occurrence, appending when absent); ``KEY*X`` multiplies the existing
    numeric value of ``--KEY`` by X (the flag must already be present).
    """
    argv = list(argv)
    if "*" in rung and "=" not in rung:
        key, factor_text = rung.split("*", 1)
        flag = "--" + key
        try:
            factor = float(factor_text)
        except ValueError:
            raise UserException(
                "Retune rung %r: factor %r is not a number" % (rung, factor_text))
        try:
            at = argv.index(flag)
        except ValueError:
            raise UserException(
                "Retune rung %r scales %s but the instance argv does not "
                "carry it — scaling rungs need an explicit baseline"
                % (rung, flag))
        if at + 1 >= len(argv):
            raise UserException(
                "Retune rung %r: %s is the last argv token (no value)"
                % (rung, flag))
        try:
            current = float(argv[at + 1])
        except ValueError:
            raise UserException(
                "Retune rung %r: current %s value %r is not numeric"
                % (rung, flag, argv[at + 1]))
        scaled = current * factor
        argv[at + 1] = ("%d" % int(scaled)
                        if float(int(scaled)) == scaled else repr(scaled))
        return argv
    if "=" in rung:
        key, value = rung.split("=", 1)
        if not key:
            raise UserException("Retune rung %r has an empty key" % (rung,))
        flag = "--" + key
        try:
            at = argv.index(flag)
        except ValueError:
            argv.extend([flag, value])
            return argv
        if at + 1 >= len(argv):
            raise UserException(
                "Retune rung %r: %s is the last argv token (no value)"
                % (rung, flag))
        argv[at + 1] = value
        return argv
    raise UserException(
        "Retune rung %r: expected KEY=VALUE or KEY*X" % (rung,))


def validate_retunes(retunes):
    """Shape-check a {instance: [rung, ...]} ladder map at startup — a
    malformed rung must fail the fleet launch, not a 3 a.m. retune."""
    for name, rungs in (retunes or {}).items():
        for rung in rungs:
            if "*" in rung and "=" not in rung:
                key, _, factor = rung.partition("*")
                try:
                    float(factor)
                except ValueError:
                    raise UserException(
                        "Retune ladder for %r: rung %r factor is not a "
                        "number" % (name, rung))
                if not key:
                    raise UserException(
                        "Retune ladder for %r: rung %r has an empty key"
                        % (name, rung))
            elif "=" in rung:
                if not rung.partition("=")[0]:
                    raise UserException(
                        "Retune ladder for %r: rung %r has an empty key"
                        % (name, rung))
            else:
                raise UserException(
                    "Retune ladder for %r: rung %r is neither KEY=VALUE "
                    "nor KEY*X" % (name, rung))


class InstanceSpec:
    """One supervised fleet member (parsed from the ``--fleet`` JSON).

    ``argv`` is the full command (a leading ``"{python}"`` token resolves
    to ``sys.executable``); ``url`` is the static ``host:port`` to scrape
    (or None to resolve it from ``ready_file`` after spawn, or to skip
    scraping entirely); ``journal`` is the instance's journal file to
    tail; ``verdict`` the sentinel verdict JSON the instance writes
    (``--slo-verdict``); ``checkpoint_dir``/``session_secret`` arm the
    rollback path; ``cause_flag`` opts the instance into causal-plane
    argv injection — action-triggered respawns then carry
    ``--cause INSTANCE:RUN_ID:SEQ`` citing the spawning action event
    (opt-in because arbitrary argvs — crash-looper one-liners, non-CLI
    processes — must not receive flags they never declared)."""

    __slots__ = ("name", "role", "argv", "env", "cwd", "url", "ready_file",
                 "ready_timeout", "journal", "verdict", "checkpoint_dir",
                 "checkpoint_base_name", "session_secret", "allow_unsigned",
                 "retunes", "log", "stop_timeout", "cause_flag")

    def __init__(self, name, role, argv, env=None, cwd=None, url=None,
                 ready_file=None, ready_timeout=180.0, journal=None,
                 verdict=None, checkpoint_dir=None,
                 checkpoint_base_name="model", session_secret=None,
                 allow_unsigned=False, retunes=(), log=None,
                 stop_timeout=20.0, cause_flag=False):
        self.name = str(name)
        self.role = str(role)
        self.argv = [sys.executable if a == "{python}" else str(a)
                     for a in argv]
        self.env = dict(env) if env else None
        self.cwd = cwd
        self.url = url
        self.ready_file = ready_file
        self.ready_timeout = float(ready_timeout)
        self.journal = journal
        self.verdict = verdict
        self.checkpoint_dir = checkpoint_dir
        self.checkpoint_base_name = checkpoint_base_name
        self.session_secret = session_secret
        self.allow_unsigned = bool(allow_unsigned)
        self.retunes = tuple(retunes)
        self.log = log
        self.stop_timeout = float(stop_timeout)
        self.cause_flag = bool(cause_flag)
        if not self.argv:
            raise UserException("Instance %r has an empty argv" % (self.name,))


def load_fleet_spec(path):
    """Parse the ``--fleet`` JSON file -> list of :class:`InstanceSpec`.

    Shape: ``{"instances": [{"name": ..., "role": ..., "argv": [...],
    ...InstanceSpec keywords...}, ...]}``.  Relative paths in the spec are
    taken relative to the spec file's directory, so a fleet directory is
    relocatable."""
    with open(path) as fd:
        doc = json.load(fd)
    if not isinstance(doc, dict) or not isinstance(doc.get("instances"), list):
        raise UserException(
            "Fleet spec %r wants {\"instances\": [...]} at top level" % (path,))
    base = os.path.dirname(os.path.abspath(path))

    def _resolve(value):
        if value is None:
            return None
        return value if os.path.isabs(value) else os.path.join(base, value)

    specs = []
    for entry in doc["instances"]:
        if not isinstance(entry, dict):
            raise UserException("Fleet spec instance %r is not an object" % (entry,))
        kwargs = dict(entry)
        for key in ("ready_file", "journal", "verdict", "checkpoint_dir",
                    "log", "cwd"):
            if key in kwargs:
                kwargs[key] = _resolve(kwargs[key])
        try:
            specs.append(InstanceSpec(**kwargs))
        except TypeError as exc:
            raise UserException(
                "Fleet spec instance %r: %s" % (entry.get("name"), exc))
    names = [s.name for s in specs]
    if len(set(names)) != len(names):
        raise UserException("Fleet spec %r has duplicate instance names" % (path,))
    return specs


class _Managed:
    """Runtime state of one supervised instance (actuator-internal)."""

    __slots__ = ("spec", "proc", "url", "cursor", "verdict_stamp",
                 "quarantined", "spawned_at", "restarts", "last_run_id")

    def __init__(self, spec):
        self.spec = spec
        self.proc = None
        self.url = spec.url
        self.cursor = None            # tail_journal position
        self.verdict_stamp = None     # (mtime_ns, size) of the verdict file
        self.quarantined = False
        self.spawned_at = None
        self.restarts = 0
        self.last_run_id = None       # run_id of the last tailed record


class FleetSupervisor:
    """Spawn, watch and steer a fleet of train/serve/router instances."""

    def __init__(self, specs, config=None, retunes=None, down_after=3,
                 scrape_timeout=2.0, clock=None, instance_name="supervisor"):
        self.config = config if config is not None else SupervisorConfig()
        #: this supervisor's name in cross-journal cause references —
        #: children spawned by an action cite (instance_name, run_id, seq)
        self.instance_name = str(instance_name)
        self.specs = list(specs)
        ladder_map = dict(retunes or {})
        for spec in self.specs:
            if spec.retunes:
                ladder_map.setdefault(spec.name, tuple(spec.retunes))
        validate_retunes(ladder_map)
        self.policy = SupervisorPolicy(self.config, retunes=ladder_map)
        self.down_after = int(down_after)
        self.scrape_timeout = float(scrape_timeout)
        self.clock = clock if clock is not None else time.monotonic
        self._managed = {spec.name: _Managed(spec) for spec in self.specs}
        self._collector = None
        self._collector_urls = {}

    # ------------------------------------------------------------------ #
    # process lifecycle

    def _spawn(self, managed, wait_ready=True, cause_record=None):
        spec = managed.spec
        argv = spec.argv
        if cause_record is not None and spec.cause_flag:
            # Causal-plane injection: the action event that decided this
            # spawn was emitted first, so its (run_id, seq) exists to be
            # cited.  apply_rung's KEY=VALUE grammar sets-or-replaces
            # ``--cause`` on a COPY — spec.argv is never mutated, the
            # injection is per-spawn.
            token = events.format_cause(
                events.cause_of(cause_record, self.instance_name))
            argv = apply_rung(list(spec.argv), "cause=%s" % token)
        if spec.ready_file and os.path.exists(spec.ready_file):
            os.remove(spec.ready_file)   # a stale handshake is a lie
        log_fd = None
        if spec.log:
            os.makedirs(os.path.dirname(spec.log) or ".", exist_ok=True)
            log_fd = open(spec.log, "a")
        env = None
        if spec.env:
            env = dict(os.environ)
            env.update({str(k): str(v) for k, v in spec.env.items()})
        try:
            managed.proc = subprocess.Popen(
                argv, cwd=spec.cwd, env=env,
                stdout=log_fd if log_fd else subprocess.DEVNULL,
                stderr=subprocess.STDOUT if log_fd else subprocess.DEVNULL,
            )
        finally:
            if log_fd:
                log_fd.close()
        managed.spawned_at = self.clock()
        if spec.ready_file and wait_ready:
            deadline = time.monotonic() + spec.ready_timeout
            while time.monotonic() < deadline:
                if os.path.exists(spec.ready_file):
                    break
                if managed.proc.poll() is not None:
                    break               # died during startup: next tick sees it
                time.sleep(0.05)
            if os.path.exists(spec.ready_file):
                # serve/router write "host port pid"; the trainer's live
                # exporter writes "host port" — the pid is optional here
                # (process identity comes from Popen, not the handshake)
                fields = open(spec.ready_file).read().split()
                managed.url = "%s:%s" % (fields[0], fields[1])
        return managed.proc

    def _kill(self, managed, sig=signal.SIGKILL, wait=True):
        proc = managed.proc
        if proc is None or proc.poll() is not None:
            return
        try:
            proc.send_signal(sig)
        except OSError:
            return
        if not wait:
            return
        try:
            proc.wait(timeout=managed.spec.stop_timeout)
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.wait(timeout=managed.spec.stop_timeout)

    def start(self):
        """Spawn the whole fleet (ready-file handshakes respected)."""
        for managed in self._managed.values():
            self._spawn(managed)
        self._rebuild_collector()

    def stop(self, sig=signal.SIGTERM):
        """Stop every live instance (graceful by default: serve drains)."""
        for managed in self._managed.values():
            proc = managed.proc
            if proc is None or proc.poll() is not None:
                continue
            try:
                proc.send_signal(sig)
            except OSError:
                continue
        for managed in self._managed.values():
            proc = managed.proc
            if proc is None:
                continue
            try:
                proc.wait(timeout=managed.spec.stop_timeout)
            except subprocess.TimeoutExpired:
                proc.kill()

    def pid_of(self, name):
        """The live pid of an instance (chaos drivers SIGKILL through
        this), or None."""
        managed = self._managed[name]
        if managed.proc is None or managed.proc.poll() is not None:
            return None
        return managed.proc.pid

    def url_of(self, name):
        return self._managed[name].url

    def restarts_of(self, name):
        return self._managed[name].restarts

    def up_of(self, name):
        """The collector's live judgment of an instance (True/False), or
        None when it exposes no scrape URL or was never polled — the soak
        driver's recovery probe."""
        if self._collector is None or name not in self._collector_urls:
            return None
        return self._collector.instance_up(name)

    def is_quarantined(self, name):
        return self._managed[name].quarantined

    # ------------------------------------------------------------------ #
    # sensing

    def _rebuild_collector(self):
        urls = {name: managed.url
                for name, managed in self._managed.items()
                if managed.url and not managed.quarantined}
        if urls != self._collector_urls:
            self._collector_urls = dict(urls)
            self._collector = FleetCollector(
                urls, down_after=self.down_after,
                timeout=self.scrape_timeout,
            ) if urls else None

    def _observations(self, scraped):
        out = []
        for name, managed in self._managed.items():
            proc = managed.proc
            alive = proc is not None and proc.poll() is None
            exit_code = None if alive or proc is None else proc.returncode
            inst = (scraped or {}).get(name)
            up = None
            misses = 0
            age = None
            if inst is not None:
                misses = inst.get("consecutive_misses", 0)
                age = inst.get("last_scrape_age_seconds")
                if inst.get("up"):
                    up = True
                elif inst.get("stale"):
                    up = False        # was seen, now judged down
            out.append(InstanceObs(
                name=name, role=managed.spec.role, alive=alive,
                exit_code=exit_code, up=up, consecutive_misses=misses,
                last_scrape_age=age,
            ))
        return out

    def _tail_journals(self):
        new = []
        for name, managed in self._managed.items():
            path = managed.spec.journal
            if not path:
                continue
            try:
                records, managed.cursor = events.tail_journal(
                    path, managed.cursor)
            except ValueError as exc:
                warning("Supervisor: journal tail of %r failed: %s" % (name, exc))
                continue
            if records:
                # remember the stream's current run_id so evidence seqs
                # (policy streak refs) can be completed into full cause
                # references (instance, run_id, seq)
                managed.last_run_id = records[-1].get("run_id")
            new.extend((name, record) for record in records)
        return new

    def _fresh_verdicts(self):
        fresh = []
        for name, managed in self._managed.items():
            path = managed.spec.verdict
            if not path:
                continue
            try:
                stat = os.stat(path)
            except OSError:
                continue
            stamp = (stat.st_mtime_ns, stat.st_size)
            if stamp == managed.verdict_stamp:
                continue
            try:
                with open(path) as fd:
                    doc = json.load(fd)
            except (OSError, ValueError):
                continue              # mid-write: re-read next tick
            managed.verdict_stamp = stamp
            fresh.append((name, doc))
        return fresh

    # ------------------------------------------------------------------ #
    # the loop

    def tick(self):
        """One sense -> decide -> act round.  Returns the executed
        actions (the soak driver records their timing)."""
        scraped = None
        self._rebuild_collector()
        if self._collector is not None:
            self._collector.poll_once()
            scraped = self._collector.status_payload()["instances"]
        observations = self._observations(scraped)
        journal_events = self._tail_journals()
        verdicts = self._fresh_verdicts()
        actions = self.policy.tick(
            self.clock(), observations, journal_events, verdicts)
        for action in actions:
            self._execute(action)
        return actions

    def run(self, tick_interval=1.0, should_stop=None, max_ticks=None):
        """The supervision loop (``cli.supervise``).  ``should_stop`` is a
        callable polled between ticks; ``max_ticks`` bounds the loop for
        smokes."""
        ticks = 0
        while should_stop is None or not should_stop():
            self.tick()
            ticks += 1
            if max_ticks is not None and ticks >= max_ticks:
                break
            time.sleep(tick_interval)
        return ticks

    # ------------------------------------------------------------------ #
    # acting

    def _execute(self, action):
        if isinstance(action, Restart):
            self._execute_restart(action)
        elif isinstance(action, Quarantine):
            self._execute_quarantine(action)
        elif isinstance(action, Retune):
            self._execute_retune(action)
        elif isinstance(action, Rollback):
            self._execute_rollback(action)
        elif isinstance(action, Observe):
            events.emit("supervisor_observe", instance=action.instance,
                        reason=action.reason, evidence=action.evidence,
                        cause=self._evidence_cause(action))
        else:
            raise UserException("Unknown supervisor action %r" % (action,))

    def _evidence_cause(self, action):
        """Complete the policy's evidence refs into a full cause reference.

        Retune-path evidence carries ``events: [{"type", "seq"}, ...]`` —
        seqs of the streak-forming records in the INSTANCE's journal.  The
        policy is pure and never sees run_ids, so the actuator supplies
        the tailed stream's current one; the last streak event (the one
        that tipped the threshold) becomes the cause.  Liveness/rollback
        evidence has no journal refs — those actions carry no cause (the
        sentinel verdict is a file, cited via ``evidence.verdict_id``)."""
        evidence = getattr(action, "evidence", None) or {}
        refs = evidence.get("events")
        if not refs:
            return None
        managed = self._managed.get(action.instance)
        if managed is None or managed.last_run_id is None:
            return None
        seq = refs[-1].get("seq")
        if seq is None:
            return None
        return {"instance": action.instance,
                "run_id": managed.last_run_id, "seq": seq}

    def _execute_restart(self, action):
        managed = self._managed[action.instance]
        self._kill(managed)           # a hung process survives its judgment
        # Emit BEFORE the respawn: the child cites this record's
        # (run_id, seq) through the injected ``--cause`` flag, so the
        # reference must exist before the child's run_start is minted.
        record = events.emit(
            "supervisor_restart", instance=action.instance,
            reason=action.reason, attempt=action.attempt,
            backoff_s=action.backoff_s, evidence=action.evidence,
            cause=self._evidence_cause(action))
        self._spawn(managed, cause_record=record)
        managed.restarts += 1
        info("Supervisor: restarted %r (%s, attempt %d, next grace %.3gs)"
             % (action.instance, action.reason, action.attempt,
                action.backoff_s))

    def _execute_quarantine(self, action):
        managed = self._managed[action.instance]
        self._kill(managed)
        managed.quarantined = True
        warning("Supervisor: QUARANTINED crash-looping instance %r after "
                "%d restarts" % (action.instance, action.attempts))
        events.emit("supervisor_quarantine", instance=action.instance,
                    reason=action.reason, attempts=action.attempts,
                    evidence=action.evidence,
                    cause=self._evidence_cause(action))

    def _execute_retune(self, action):
        managed = self._managed[action.instance]
        spec = managed.spec
        old_argv = list(spec.argv)
        spec.argv = apply_rung(spec.argv, action.rung)
        self._kill(managed, sig=signal.SIGTERM)   # graceful: drains apply
        # Emit before the respawn (see _execute_restart); the retune cites
        # the streak record that tipped the threshold as its own cause.
        record = events.emit(
            "supervisor_retune", instance=action.instance,
            rung=action.rung, rung_index=action.rung_index,
            reason=action.reason,
            argv_diff={"before": old_argv, "after": list(spec.argv)},
            evidence=action.evidence, cause=self._evidence_cause(action))
        self._spawn(managed, cause_record=record)
        managed.restarts += 1
        info("Supervisor: retuned %r rung %d (%s) — argv rebuilt, "
             "instance restarted" % (action.instance, action.rung_index,
                                     action.rung))

    def _execute_rollback(self, action):
        from ..obs.checkpoint import Checkpoints

        managed = self._managed[action.instance]
        spec = managed.spec
        if not spec.checkpoint_dir:
            events.emit("supervisor_observe", instance=action.instance,
                        reason="rollback_unavailable",
                        evidence=dict(action.evidence,
                                      detail="no checkpoint_dir in spec"),
                        cause=None)
            return
        checkpoints = Checkpoints(spec.checkpoint_dir,
                                  base_name=spec.checkpoint_base_name)
        steps = checkpoints.steps()
        if len(steps) < 2:
            events.emit("supervisor_observe", instance=action.instance,
                        reason="rollback_unavailable",
                        evidence=dict(action.evidence,
                                      detail="fewer than 2 snapshots",
                                      steps=steps),
                        cause=None)
            return
        restore_step = steps[-2]
        verified = False
        path = os.path.join(
            spec.checkpoint_dir,
            "%s-%d.ckpt" % (spec.checkpoint_base_name, restore_step))
        if spec.session_secret:
            from ..secure import ChainOfCustody

            custody = ChainOfCustody(spec.session_secret.encode(),
                                     allow_unsigned=spec.allow_unsigned)
            try:
                with open(path, "rb") as fd:
                    data = fd.read()
                verified = custody.verify(path, restore_step, data)
            except (OSError, UserException) as exc:
                warning("Supervisor: rollback of %r REFUSED — custody "
                        "verification failed: %s" % (action.instance, exc))
                events.emit("supervisor_observe", instance=action.instance,
                            reason="rollback_custody_refused",
                            evidence=dict(action.evidence, error=str(exc)),
                            cause=None)
                return
        elif not spec.allow_unsigned:
            warning("Supervisor: rollback of %r REFUSED — no session "
                    "secret and allow_unsigned is off (fail-closed, the "
                    "serve restore discipline)" % (action.instance,))
            events.emit("supervisor_observe", instance=action.instance,
                        reason="rollback_custody_refused",
                        evidence=dict(action.evidence,
                                      detail="unsigned and not allowed"),
                        cause=None)
            return
        discarded = checkpoints.discard_after(restore_step)
        stopped = False
        if managed.proc is not None and managed.proc.poll() is None:
            # A live instance is gracefully STOPPED onto the restored
            # timeline — its next checkpoint would otherwise re-extend the
            # discarded tail.  It is deliberately NOT respawned: an
            # auto-retry of the run that just regressed would re-judge,
            # re-REGRESS and loop (each re-run mints a fresh verdict
            # identity, so the policy's rollback-once key cannot damp it).
            # Resuming from the restored snapshot is the liveness policy's
            # or the operator's call.
            self._kill(managed, sig=signal.SIGTERM)
            stopped = True
        info("Supervisor: rolled %r back to step %d (discarded %r, "
             "custody_verified=%r)" % (action.instance, restore_step,
                                       discarded, verified))
        # cause=None deliberately: the trigger is a sentinel VERDICT FILE,
        # not a journal event — the link to it is ``evidence.verdict_id``
        # (the postmortem resolves verdict->rollback chains through it).
        events.emit("supervisor_rollback", instance=action.instance,
                    restore_step=restore_step, discarded_steps=discarded,
                    custody_verified=verified, stopped=stopped,
                    reason=action.reason, evidence=action.evidence,
                    cause=None)

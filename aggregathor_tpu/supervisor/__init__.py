"""Fleet supervisor: the control loop the control room opened, closed.

PR 15 made every steering decision observable (journal, fleet scrape,
round timelines); PR 8's sentinel judges runs after the fact.  The
supervisor *acts* on those signals live — one rung above the guardian,
with the same separation the guardian pioneered:

- :mod:`policy` — a PURE decision layer (``SupervisorPolicy``): fleet
  snapshot + journal tail + sentinel verdicts in, typed actions out.
  No I/O, no wall clock, fully exercised on a synthetic clock.
- :mod:`actuator` — ``FleetSupervisor``: spawns the fleet, scrapes it,
  tails its journals, feeds the policy and EXECUTES its actions
  (restart / quarantine / retune / rollback), journaling every one with
  its triggering evidence (``supervisor_*`` event types, obs/events.py).

``cli.supervise`` is the operator face; ``benchmarks/soak.py`` is the
proof; docs/operations.md is the long-form story.
"""

from .policy import (  # noqa: F401
    Observe,
    Quarantine,
    Restart,
    Retune,
    Rollback,
    SupervisorConfig,
    SupervisorPolicy,
)
from .actuator import FleetSupervisor, InstanceSpec  # noqa: F401

"""Fleet supervision policy: observations in, typed actions out.

PURE in the watchdog's sense (``guardian/watchdog.py``): the policy never
touches processes, sockets, files or the wall clock.  Every tick the
actuator passes ``now`` (seconds, any monotonic origin), one
:class:`InstanceObs` per fleet instance, the journal records that arrived
since the last tick and any newly-seen sentinel verdicts; the policy
returns a list of typed actions for the actuator to execute — which the
actuator MUST do immediately (the policy's backoff bookkeeping assumes an
emitted action executed at ``now``).

The action ladder (docs/operations.md "The self-driving run"):

- **Restart** — a dead (non-zero exit) or hung (alive but scrape-down)
  instance is restarted under the watchdog's exponential-backoff
  discipline: restart ``k`` opens a grace window of
  ``patience * backoff^k`` seconds during which further downs only
  **Observe** (``backoff_wait``).
- **Quarantine** — flap damping: an instance that needed
  ``max_restarts`` restarts without ever staying healthy for
  ``flap_window`` seconds is crash-looping; restarting it forever would
  thrash the fleet, so it is quarantined (killed and left down) and the
  attempt counter stops.  Staying healthy for a full ``flap_window``
  resets the counter — a one-off kill does not count against the budget
  forever.
- **Retune** — a sustained regime shift in the journal (``retune_streak``
  consecutive ``deadline_window`` at-ceiling events, or as many
  ``bounded_round`` events with timeouts) climbs the instance's declared
  retune ladder: one rung per trigger, ``retune_cooldown`` seconds of
  hysteresis between rungs (inside the cooldown the symptom is only
  **Observe**-d).  Rungs are opaque ``KEY=VALUE`` / ``KEY*X`` argv
  rewrites applied by the actuator — the Overrides rebuild discipline
  one level up: never mutate a running instance, rebuild its config and
  restart it.
- **Rollback** — a sentinel REGRESS verdict (obs/slo.py) rolls the
  instance's checkpoint timeline back through the custody path.  Once
  per verdict identity: the same REGRESS re-observed must not unwind
  the timeline again (``rollback_once``).
- **Observe** — the explicit no-op arm, emitted on REASON CHANGES only
  (not every tick), so the journal tells why nothing happened without
  drowning in heartbeats.

Everything is deterministic given the input stream — tests drive years of
fleet life in microseconds on a synthetic clock (tests/test_supervisor.py).
"""

import collections

from ..utils import UserException, parse_keyval

#: one instance's health as the actuator sees it this tick.  ``alive`` is
#: process-level (a pid that waits), ``exit_code`` is None while running;
#: ``up``/``consecutive_misses``/``last_scrape_age`` mirror the fleet
#: collector's down-judgment inputs (obs/fleet.py ``/fleet/status``) —
#: None age means never scraped.  Instances without a scrape URL pass
#: ``up=None`` (process liveness is then the only signal).
InstanceObs = collections.namedtuple(
    "InstanceObs",
    ("name", "role", "alive", "exit_code", "up", "consecutive_misses",
     "last_scrape_age"),
)

#: typed actions (the actuator maps each to one journal event type)
Restart = collections.namedtuple(
    "Restart", ("instance", "reason", "attempt", "backoff_s", "evidence"))
Quarantine = collections.namedtuple(
    "Quarantine", ("instance", "reason", "attempts", "evidence"))
Retune = collections.namedtuple(
    "Retune", ("instance", "rung", "rung_index", "reason", "evidence"))
Rollback = collections.namedtuple(
    "Rollback", ("instance", "verdict_id", "reason", "evidence"))
Observe = collections.namedtuple(
    "Observe", ("instance", "reason", "evidence"))


class SupervisorConfig:
    """Parsed ``--supervisor-args`` (key:value strings, like every registry).

    Keys: ``patience`` (base restart-backoff seconds, default 2),
    ``backoff`` (growth base, default 2), ``max-restarts`` (restarts
    within one flap window before quarantine, default 5), ``flap-window``
    (healthy seconds that reset the restart budget, default 30),
    ``retune-streak`` (consecutive at-ceiling / timeout events that
    trigger a retune rung, default 3), ``retune-cooldown`` (hysteresis
    seconds between rungs, default 30)."""

    DEFAULTS = {
        "patience": 2.0,
        "backoff": 2.0,
        "max-restarts": 5,
        "flap-window": 30.0,
        "retune-streak": 3,
        "retune-cooldown": 30.0,
    }

    def __init__(self, args=None):
        kv = parse_keyval(args or [], dict(self.DEFAULTS), strict=True)
        self.patience = float(kv["patience"])
        self.backoff = float(kv["backoff"])
        self.max_restarts = int(kv["max-restarts"])
        self.flap_window = float(kv["flap-window"])
        self.retune_streak = int(kv["retune-streak"])
        self.retune_cooldown = float(kv["retune-cooldown"])
        if self.patience <= 0:
            raise UserException(
                "supervisor patience must be > 0 (got %g)" % self.patience)
        if self.backoff < 1.0:
            raise UserException(
                "supervisor backoff must be >= 1 (got %g) — a shrinking "
                "grace window restarts faster the more it flaps" % self.backoff)
        if self.max_restarts < 1:
            raise UserException(
                "supervisor max-restarts must be >= 1 (got %d)" % self.max_restarts)
        if self.retune_streak < 1:
            raise UserException(
                "supervisor retune-streak must be >= 1 (got %d)" % self.retune_streak)

    def describe(self):
        return ("patience=%gs backoff=%g max-restarts=%d flap-window=%gs "
                "retune-streak=%d retune-cooldown=%gs"
                % (self.patience, self.backoff, self.max_restarts,
                   self.flap_window, self.retune_streak, self.retune_cooldown))


class _InstanceState:
    """Per-instance supervision bookkeeping (policy-internal)."""

    __slots__ = ("attempts", "not_before", "quarantined", "healthy_since",
                 "ceiling_streak", "timeout_streak", "retunes_applied",
                 "last_retune_at", "rollbacks_done", "last_observe_reason",
                 "streak_refs")

    def __init__(self):
        self.attempts = 0           # restarts issued this flap episode
        self.not_before = None      # no restart before this time (backoff)
        self.quarantined = False
        self.healthy_since = None   # when the instance last became healthy
        self.ceiling_streak = 0     # consecutive at-ceiling deadline moves
        self.timeout_streak = 0     # consecutive rounds with timeouts
        self.retunes_applied = 0    # rungs climbed
        self.last_retune_at = None
        self.rollbacks_done = set() # verdict identities already rolled back
        #: last Observe reason per domain ("liveness"/"retune"/"rollback") —
        #: Observe fires on reason CHANGES within its domain, so a liveness
        #: recovery does not re-arm a still-true retune observation
        self.last_observe_reason = {}
        self.streak_refs = []       # (type, seq) of streak-forming events


class SupervisorPolicy:
    """The pure fleet-supervision decision layer.  ``retunes`` maps an
    instance name to its rung ladder (a sequence of opaque rung strings
    the actuator knows how to apply); instances without a ladder never
    receive Retune actions, however loud their journals get."""

    def __init__(self, config=None, retunes=None):
        self.config = config if config is not None else SupervisorConfig()
        self.retunes = {
            str(name): tuple(rungs) for name, rungs in (retunes or {}).items()
        }
        self._states = {}

    def state_of(self, name):
        return self._states.setdefault(name, _InstanceState())

    def is_quarantined(self, name):
        return self.state_of(name).quarantined

    # ------------------------------------------------------------------ #
    # the tick

    def tick(self, now, observations, journal_events=(), verdicts=()):
        """One decision round.

        ``observations``: iterable of :class:`InstanceObs`.
        ``journal_events``: iterable of ``(instance_name, record)`` — the
        records appended to each instance's journal since the last tick
        (the actuator's ``tail_journal`` cursors guarantee exactly-once).
        ``verdicts``: iterable of ``(instance_name, verdict_doc)`` —
        sentinel verdict documents (obs/slo.py) not seen before.

        Returns the actions to execute, in order; the actuator must
        execute all of them at (effectively) ``now``.
        """
        now = float(now)
        observations = list(observations)
        actions = []
        self._ingest_events(journal_events)
        for obs in observations:
            actions.extend(self._decide_liveness(now, obs))
        actions.extend(self._decide_retunes(now, observations))
        actions.extend(self._decide_rollbacks(now, verdicts))
        return actions

    # ------------------------------------------------------------------ #
    # journal ingestion (the regime-shift detectors)

    def _ingest_events(self, journal_events):
        for name, record in journal_events:
            state = self.state_of(name)
            etype = record.get("type")
            if etype == "deadline_window":
                if record.get("at_ceiling"):
                    state.ceiling_streak += 1
                    state.streak_refs.append((etype, record.get("seq")))
                else:
                    state.ceiling_streak = 0
                    if not state.timeout_streak:
                        state.streak_refs = []
            elif etype == "bounded_round":
                if record.get("timed_out"):
                    state.timeout_streak += 1
                    state.streak_refs.append((etype, record.get("seq")))
                else:
                    state.timeout_streak = 0
                    if not state.ceiling_streak:
                        state.streak_refs = []

    # ------------------------------------------------------------------ #
    # liveness: restart / quarantine / observe

    def _down_reason(self, obs):
        """None when healthy/finished, else 'dead' or 'hung'."""
        if not obs.alive:
            if obs.exit_code == 0:
                return None          # ran to completion: not a fault
            return "dead"
        if obs.up is False:
            return "hung"            # process waits, scrapes judge it down
        return None

    def _observe(self, state, name, domain, reason, evidence):
        """Emit Observe only when the reason CHANGES within its domain."""
        if state.last_observe_reason.get(domain) == reason:
            return []
        state.last_observe_reason[domain] = reason
        return [Observe(instance=name, reason=reason, evidence=evidence)]

    def _decide_liveness(self, now, obs):
        config = self.config
        state = self.state_of(obs.name)
        reason = self._down_reason(obs)
        evidence = {
            "alive": bool(obs.alive),
            "exit_code": obs.exit_code,
            "up": obs.up,
            "consecutive_misses": obs.consecutive_misses,
            "last_scrape_age_seconds": obs.last_scrape_age,
        }
        if reason is None:
            if not obs.alive:       # exit 0: finished, never restarted
                return self._observe(state, obs.name, "liveness", "finished", evidence)
            healthy = obs.up is not False
            if healthy:
                if state.healthy_since is None:
                    state.healthy_since = now
                # flap damping, the forgiving half: a full healthy window
                # refunds the restart budget
                if (state.attempts
                        and now - state.healthy_since >= config.flap_window):
                    state.attempts = 0
                    state.not_before = None
                state.last_observe_reason.pop("liveness", None)
            return []
        state.healthy_since = None
        if state.quarantined:
            return self._observe(state, obs.name, "liveness", "quarantined", evidence)
        if state.attempts >= config.max_restarts:
            # flap damping, the protective half: the budget is spent
            # without a single full healthy window — crash loop
            state.quarantined = True
            state.last_observe_reason.pop("liveness", None)
            return [Quarantine(
                instance=obs.name, reason="crash_loop",
                attempts=state.attempts, evidence=evidence,
            )]
        if state.not_before is not None and now < state.not_before:
            evidence = dict(evidence, not_before=state.not_before)
            return self._observe(state, obs.name, "liveness", "backoff_wait", evidence)
        attempt = state.attempts
        grace = config.patience * config.backoff ** attempt
        state.attempts = attempt + 1
        state.not_before = now + grace
        state.last_observe_reason.pop("liveness", None)
        return [Restart(
            instance=obs.name, reason=reason, attempt=attempt,
            backoff_s=grace, evidence=evidence,
        )]

    # ------------------------------------------------------------------ #
    # retune: sustained regime shifts climb the declared ladder

    def _decide_retunes(self, now, observations):
        config = self.config
        actions = []
        for obs in observations:
            ladder = self.retunes.get(obs.name)
            state = self.state_of(obs.name)
            streak = max(state.ceiling_streak, state.timeout_streak)
            if not ladder or streak < config.retune_streak:
                continue
            trigger = ("deadline_ceiling"
                       if state.ceiling_streak >= state.timeout_streak
                       else "timeout_wave")
            evidence = {
                "trigger": trigger,
                "streak": streak,
                "events": [
                    {"type": t, "seq": s}
                    for t, s in state.streak_refs[-streak:]
                ],
            }
            if state.retunes_applied >= len(ladder):
                actions.extend(self._observe(
                    state, obs.name, "retune", "retune_ladder_exhausted",
                    evidence))
                continue
            if (state.last_retune_at is not None
                    and now - state.last_retune_at < config.retune_cooldown):
                evidence = dict(
                    evidence,
                    cooldown_until=state.last_retune_at + config.retune_cooldown,
                )
                actions.extend(self._observe(
                    state, obs.name, "retune", "retune_hysteresis", evidence))
                continue
            rung_index = state.retunes_applied
            state.retunes_applied = rung_index + 1
            state.last_retune_at = now
            state.ceiling_streak = 0
            state.timeout_streak = 0
            state.streak_refs = []
            state.last_observe_reason.pop("retune", None)
            actions.append(Retune(
                instance=obs.name, rung=ladder[rung_index],
                rung_index=rung_index, reason=trigger, evidence=evidence,
            ))
        return actions

    # ------------------------------------------------------------------ #
    # rollback: sentinel REGRESS, once per verdict identity

    @staticmethod
    def _regressed_metrics(verdict):
        """The failing metric names: the sentinel's verdict document lists
        per-metric ``checks`` (status ``"regressed"``); a hand-built
        verdict may carry a bare ``failures`` list instead."""
        checks = verdict.get("checks")
        if checks:
            return [c.get("metric", "?") for c in checks
                    if c.get("status") == "regressed"]
        return [f.get("metric", "?") for f in verdict.get("failures", ())]

    @staticmethod
    def verdict_identity(verdict):
        """The once-only key for a sentinel verdict document: judged_at is
        unique per judgment; a verdict missing it degrades to the (run_id,
        failure set) pair — same regression, same identity."""
        judged = verdict.get("judged_at")
        if judged is not None:
            return "judged_at:%r" % (judged,)
        return "run:%r failures:%r" % (
            verdict.get("run_id"),
            sorted(SupervisorPolicy._regressed_metrics(verdict)),
        )

    def _decide_rollbacks(self, now, verdicts):
        actions = []
        for name, verdict in verdicts:
            if not isinstance(verdict, dict):
                continue
            state = self.state_of(name)
            if verdict.get("verdict") != "REGRESS":
                continue
            identity = self.verdict_identity(verdict)
            evidence = {
                "verdict_id": identity,
                "judged_at": verdict.get("judged_at"),
                "run_id": verdict.get("run_id"),
                "failures": self._regressed_metrics(verdict),
            }
            if identity in state.rollbacks_done:
                actions.extend(self._observe(
                    state, name, "rollback", "rollback_once", evidence))
                continue
            state.rollbacks_done.add(identity)
            state.last_observe_reason.pop("rollback", None)
            actions.append(Rollback(
                instance=name, verdict_id=identity,
                reason="sentinel_regress", evidence=evidence,
            ))
        return actions

"""Step-delta / wall-period cadence policy.

Reference semantics (runner.py:356-494): each daemon fires when the step
advanced by at least ``delta`` since the last firing, or when ``period``
seconds of wall time passed, whichever criterion is enabled (negative
disables); each also fires once more at coordinator stop.
"""

import time


class CadenceTrigger:
    """Fires on step-delta and/or wall-period, like the reference daemons."""

    def __init__(self, delta=-1, period=-1.0):
        self.delta = int(delta)
        self.period = float(period)
        self.last_step = None
        self.last_time = time.monotonic()

    @property
    def enabled(self):
        return self.delta >= 0 or self.period >= 0.0

    def should_fire(self, step):
        if not self.enabled:
            return False
        if self.last_step is None:
            return True  # fire once at start (reference: wait-for-first-eval, runner.py:545)
        if self.delta >= 0 and step - self.last_step >= self.delta:
            return True
        if self.period >= 0.0 and time.monotonic() - self.last_time >= self.period:
            return True
        return False

    def fired(self, step):
        self.last_step = int(step)
        self.last_time = time.monotonic()

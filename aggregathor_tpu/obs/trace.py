"""Host-side span tracer emitting Chrome trace-event JSON.

The reference's only timing story is the end-of-run steps/s printout
(runner.py:504-598); a production run needs to see WHERE a step's wall time
went — dispatch vs blocking on the device vs host-side gaps — after the
fact, per step, without attaching a profiler.  This module is that story's
host half: lightweight spans written as Chrome trace events (the
``{"traceEvents": [...]}`` JSON Array Format), loadable in Perfetto /
``chrome://tracing`` next to a ``jax.profiler`` device trace.

Design constraints (the acceptance bar in ISSUE 4):

- **Zero compiles touched** — everything here is host-side Python; the
  jitted step programs are wrapped (``traced``), never modified, so the jit
  cache is byte-identical with tracing on or off (asserted by
  tests/test_obs.py).
- **Near-zero cost disabled** — tracing is OFF until :func:`install` is
  called; the disabled fast path of :class:`span` / :func:`instant` /
  :class:`TracedCallable` is a single global ``None`` check.
- **Bounded enabled cost** — events append to an in-memory list under a
  lock (one append per span, microseconds against millisecond steps) with a
  hard event cap; past it events are counted as dropped, never written.

Usage::

    from aggregathor_tpu.obs import trace
    trace.install("run.trace.json", run_id=run_id)
    with trace.span("dispatch", cat="train", step=12):
        ...
    @trace.span("checkpoint.save")
    def save(...): ...
    trace.save()            # or trace.uninstall(save=True)

Nesting is tracked per thread (a thread-local span stack): each event
carries its stack depth and parent name in ``args``, and Perfetto nests
same-thread "X" events by time containment.  All public entry points are
thread-safe — the serving stack records from handler threads while the
batcher thread records batches.

Beyond spans: ``Tracer.track`` allocates NAMED synthetic tracks (one
Perfetto lane per logical worker — the bounded-wait submission timelines,
docs/observability.md "Reading a round timeline"), ``complete_at`` lays
events onto them with explicit timestamps, and ``counter`` emits "C"
events Perfetto renders as numeric tracks (deadline window, arrivals,
bytes on wire per round).

Two tracers pointed at ONE path no longer clobber each other: a tiny
``<path>.claim`` sidecar carries the live writer's (writer_pid, run_id)
from install time, and a tracer installing onto a path owned by a LIVE
sibling writes to a pid-suffixed variant instead — while the trace file
itself is never touched before the first real save, so a dead writer's
completed output survives until this run actually has something to say.
"""

import functools
import json
import os
import threading
import time

#: the process-wide installed tracer (None = tracing disabled)
_tracer = None

#: per-thread span stack for nesting (list of span names)
_local = threading.local()

#: hard cap on buffered events — a runaway loop degrades to a counted drop,
#: not an OOM (at ~150 B/event this caps the buffer around 150 MB)
MAX_EVENTS = 1_000_000

#: synthetic-track tids start here, far above any OS thread id width that
#: matters for display — named tracks (per-worker submission timelines,
#: counter tracks) must never collide with a real thread's tid
TRACK_TID_BASE = 1 << 48


def _claim_path(path):
    """The tiny sidecar holding a live tracer's (writer_pid, run_id)
    claim on ``path``.  A SIDECAR, not the trace file itself: the claim
    must exist from install time (or a second live tracer adopting the
    same path goes unnoticed for the whole run) without ever touching the
    trace file before its first real save (a metadata stub would destroy
    a dead writer's completed trace even if this run crashes unsaved)."""
    return path + ".claim"


def _write_claim(path, run_id):
    directory = os.path.dirname(path)
    if directory:
        os.makedirs(directory, exist_ok=True)
    tmp = _claim_path(path) + ".tmp"
    with open(tmp, "w") as fd:
        json.dump({"writer_pid": os.getpid(), "run_id": run_id}, fd)
    os.replace(tmp, _claim_path(path))


def _claimed_by_other(path, run_id):
    """Is ``path`` under a LIVE claim by another tracer?  True when its
    claim sidecar names a different (writer_pid, run_id) whose process is
    still alive (or is this very process — a sibling tracer).  A dead
    writer's claim is stale: overwriting its output at save time is the
    historical, expected behavior.  No sidecar = no claim."""
    try:
        with open(_claim_path(path)) as fd:
            other = json.load(fd)
    except Exception:
        return False
    pid, rid = other.get("writer_pid"), other.get("run_id")
    if pid is None:
        return False  # pre-claim-era trace: legacy file, no live writer
    try:
        pid = int(pid)
    except (TypeError, ValueError):
        return False
    if pid == os.getpid():
        # same process: ours only when the run_ids match AND identify a
        # writer (two default-None tracers are indistinguishable, so they
        # must not clobber each other — a second install in one process
        # never overwrites the first's output)
        return not (rid == run_id and rid is not None)
    try:
        os.kill(pid, 0)
    except ProcessLookupError:
        return False  # writer is gone: stale file
    except PermissionError:
        return True   # alive under another uid: very much a live claim
    except OSError:
        return False
    return True


def _unclaimed_path(path, run_id):
    """``path``, or a pid-suffixed variant when another LIVE tracer owns
    it — the fix for last-writer-wins clobbering when a train+serve pair
    (or two runner invocations) point at the same --trace-file."""
    if path is None or not _claimed_by_other(path, run_id):
        return path
    root, ext = os.path.splitext(path)
    candidate = "%s.%d%s" % (root, os.getpid(), ext)
    nb = 1
    while os.path.exists(candidate) and _claimed_by_other(candidate, run_id):
        candidate = "%s.%d-%d%s" % (root, os.getpid(), nb, ext)
        nb += 1
    from ..utils import warning

    warning(
        "Trace path %r is owned by another live tracer; writing to %r "
        "instead (pass distinct --trace-file paths to silence this)"
        % (path, candidate)
    )
    return candidate


def _stack():
    stack = getattr(_local, "spans", None)
    if stack is None:
        stack = _local.spans = []
    return stack


class Tracer:
    """Event buffer + clock for one trace file.  Use the module-level
    :func:`install` / :func:`save` / :func:`uninstall` in application code;
    construct directly only in tests."""

    def __init__(self, path, run_id=None, clock=None):
        # refuse to clobber a LIVE sibling's file: two tracers pointed at
        # one path (train+serve pair, two runner invocations) used to
        # silently overwrite each other through last-writer-wins os.replace
        self.path = _unclaimed_path(path, run_id)
        self.run_id = run_id
        self._clock = clock if clock is not None else time.perf_counter
        self._epoch = self._clock()
        self._lock = threading.Lock()
        self._events = []
        self._named_threads = set()
        self._tracks = {}
        self.dropped = 0
        self._pid = os.getpid()
        self._events.append({
            "ph": "M", "name": "process_name", "pid": self._pid, "tid": 0,
            "args": {"name": "aggregathor_tpu"},
        })
        if self.path is not None:
            # the claim sidecar marks this path owned by (writer_pid,
            # run_id) from THIS instant — what _claimed_by_other of a
            # later tracer reads before picking its own path; the trace
            # file itself is untouched until the first real save, so a
            # dead writer's completed trace survives a run that crashes
            # before saving anything
            _write_claim(self.path, run_id)

    # ------------------------------------------------------------------ #

    def now_us(self):
        """Microseconds since tracer epoch (the trace's ``ts`` clock)."""
        return (self._clock() - self._epoch) * 1e6

    def _append(self, event, tid):
        with self._lock:
            if tid not in self._named_threads:
                self._named_threads.add(tid)
                self._events.append({
                    "ph": "M", "name": "thread_name", "pid": self._pid,
                    "tid": tid, "args": {"name": threading.current_thread().name},
                })
            if len(self._events) >= MAX_EVENTS:
                self.dropped += 1
                return
            self._events.append(event)

    def complete(self, name, start_us, dur_us, cat="host", args=None):
        """One "X" (complete) event: a span of ``dur_us`` from ``start_us``."""
        self._append({
            "ph": "X", "name": name, "cat": cat, "pid": self._pid,
            "tid": threading.get_ident(), "ts": start_us,
            "dur": max(dur_us, 0.0), "args": args or {},
        }, threading.get_ident())

    def track(self, name):
        """A stable synthetic track (tid + thread_name metadata) for
        events that belong to a LOGICAL lane rather than a host thread —
        the per-worker submission timelines (parallel/bounded.py) render
        as one Perfetto track per worker regardless of which pool thread
        ran the submission.  Idempotent per name."""
        with self._lock:
            tid = self._tracks.get(name)
            if tid is None:
                tid = TRACK_TID_BASE + len(self._tracks)
                self._tracks[name] = tid
                self._named_threads.add(tid)
                self._events.append({
                    "ph": "M", "name": "thread_name", "pid": self._pid,
                    "tid": tid, "args": {"name": name},
                })
        return tid

    def complete_at(self, name, start_us, dur_us, tid, cat="host", args=None):
        """An "X" event on an EXPLICIT track with explicit timestamps —
        the retrospective form ``bounded-wait`` uses to lay a round's
        per-worker arrivals onto their tracks after the barrier closed."""
        self._append({
            "ph": "X", "name": name, "cat": cat, "pid": self._pid,
            "tid": int(tid), "ts": float(start_us),
            "dur": max(float(dur_us), 0.0), "args": args or {},
        }, int(tid))

    def counter(self, name, value, ts=None, cat="host", series="value"):
        """A "C" (counter) event — Perfetto renders each counter name as
        its own numeric track (the per-round deadline window, arrivals,
        stale rows, bytes on wire).  ``ts`` defaults to now."""
        self._append({
            "ph": "C", "name": name, "cat": cat, "pid": self._pid,
            "tid": 0, "ts": self.now_us() if ts is None else float(ts),
            "args": {series: float(value)},
        }, 0)

    def instant(self, name, cat="host", args=None):
        """One "i" (instant) event — discrete occurrences like a guardian
        rollback decision."""
        self._append({
            "ph": "i", "s": "t", "name": name, "cat": cat, "pid": self._pid,
            "tid": threading.get_ident(), "ts": self.now_us(),
            "args": args or {},
        }, threading.get_ident())

    def save(self):
        """Write the trace (atomic: tmp + rename).  Callable repeatedly —
        each call snapshots the events so far."""
        if self.path is None:
            return None
        with self._lock:
            events = list(self._events)
            dropped = self.dropped
        payload = {
            "traceEvents": events,
            "displayTimeUnit": "ms",
            "otherData": {
                "producer": "aggregathor_tpu.obs.trace",
                "run_id": self.run_id,
                "writer_pid": self._pid,
                "dropped_events": dropped,
            },
        }
        directory = os.path.dirname(self.path)
        if directory:
            os.makedirs(directory, exist_ok=True)
        tmp = self.path + ".tmp"
        with open(tmp, "w") as fd:
            json.dump(payload, fd)
        os.replace(tmp, self.path)
        return self.path

    @property
    def nb_events(self):
        with self._lock:
            return len(self._events)


# --------------------------------------------------------------------- #
# module-level lifecycle


def install(path, run_id=None, clock=None):
    """Enable tracing process-wide, writing to ``path`` on :func:`save`.
    Returns the :class:`Tracer`.  Installing over a live tracer replaces it
    (the old one is saved first)."""
    global _tracer
    if _tracer is not None:
        _tracer.save()
    _tracer = Tracer(path, run_id=run_id, clock=clock)
    return _tracer


def installed():
    """The active tracer, or None when tracing is disabled."""
    return _tracer


def save():
    """Flush the active tracer to its path (no-op when disabled)."""
    if _tracer is not None:
        return _tracer.save()
    return None


def uninstall(save=True):
    """Disable tracing; optionally flush first.  Returns the written path
    (or None)."""
    global _tracer
    tracer, _tracer = _tracer, None
    if tracer is not None and save:
        return tracer.save()
    return None


# --------------------------------------------------------------------- #
# spans


class span:
    """Context manager AND decorator for one named span.

    ``with span("dispatch", cat="train", step=3): ...`` times the block;
    ``@span("checkpoint.save")`` times every call of the decorated function.
    When tracing is disabled the enter/exit path is one global ``None``
    check.  ``start()``/``stop()`` expose the manual form for spans whose
    lifetime does not nest lexically (the runner's host-gap span).
    """

    __slots__ = ("name", "cat", "args", "_t0", "_tracer")

    def __init__(self, name, cat="host", **args):
        self.name = name
        self.cat = cat
        self.args = args
        self._t0 = 0.0
        self._tracer = None

    def __enter__(self):
        tracer = _tracer
        self._tracer = tracer
        if tracer is None:
            return self
        stack = _stack()
        if self.args is not None and stack:
            self.args = dict(self.args, parent=stack[-1], depth=len(stack))
        stack.append(self.name)
        self._t0 = tracer.now_us()
        return self

    def __exit__(self, exc_type, exc, tb):
        tracer = self._tracer
        if tracer is None:
            return False
        stack = _stack()
        if stack and stack[-1] == self.name:
            stack.pop()
        args = self.args or {}
        if exc_type is not None:
            args = dict(args, error=exc_type.__name__)
        tracer.complete(self.name, self._t0, tracer.now_us() - self._t0,
                        cat=self.cat, args=args)
        return False

    # manual form (non-lexical lifetimes)
    start = __enter__

    def stop(self):
        self.__exit__(None, None, None)

    def __call__(self, fn):
        name, cat, args = self.name, self.cat, self.args

        @functools.wraps(fn)
        def wrapper(*a, **kw):
            with span(name, cat=cat, **args):
                return fn(*a, **kw)

        return wrapper


def instant(name, cat="host", **args):
    """Record an instant event (no-op when tracing is disabled)."""
    tracer = _tracer
    if tracer is not None:
        tracer.instant(name, cat=cat, args=args)


class TracedCallable:
    """Wrap a callable (typically a jitted step function) so every call is
    a span — WITHOUT touching the callable itself: attribute access
    (``_cache_size``, ``lower``, ...) falls through to the wrapped function,
    so compile-count assertions and AOT APIs keep working, and the jit
    cache is untouched (tracing adds zero recompiles by construction).
    ``inner`` is the unwrapped callable (the overhead benchmark's
    uninstrumented baseline)."""

    __slots__ = ("inner", "_name", "_cat")

    def __init__(self, name, fn, cat="dispatch"):
        object.__setattr__(self, "inner", fn)
        object.__setattr__(self, "_name", name)
        object.__setattr__(self, "_cat", cat)

    def __call__(self, *args, **kwargs):
        if _tracer is None:
            return self.inner(*args, **kwargs)
        with span(self._name, cat=self._cat):
            return self.inner(*args, **kwargs)

    def __getattr__(self, item):
        return getattr(self.inner, item)


def traced(name, fn, cat="dispatch"):
    """Shorthand: ``traced("train_step.dispatch", jax.jit(f))``."""
    return TracedCallable(name, fn, cat=cat)


def validate_chrome_trace(payload):
    """Structural check that ``payload`` (a parsed trace file) is loadable
    Chrome trace JSON: ``traceEvents`` list, every event a dict with
    ``ph``/``name``/``pid``/``tid``, "X" events with numeric ``ts``/``dur``.
    Returns the event list; raises ``ValueError`` on violations.  Shared by
    tests and scripts/run_obs_smoke.sh so the smoke asserts the same schema
    the tests do."""
    if not isinstance(payload, dict) or not isinstance(payload.get("traceEvents"), list):
        raise ValueError("Chrome trace JSON wants a top-level traceEvents list")
    for event in payload["traceEvents"]:
        if not isinstance(event, dict):
            raise ValueError("trace event is not an object: %r" % (event,))
        for key in ("ph", "name", "pid", "tid"):
            if key not in event:
                raise ValueError("trace event missing %r: %r" % (key, event))
        if event["ph"] == "X":
            for key in ("ts", "dur"):
                if not isinstance(event.get(key), (int, float)):
                    raise ValueError("X event wants numeric %r: %r" % (key, event))
            if event["dur"] < 0:
                raise ValueError("X event with negative dur: %r" % (event,))
        elif event["ph"] == "i":
            if not isinstance(event.get("ts"), (int, float)):
                raise ValueError("i event wants numeric ts: %r" % (event,))
        elif event["ph"] == "C":
            if not isinstance(event.get("ts"), (int, float)):
                raise ValueError("C event wants numeric ts: %r" % (event,))
            args = event.get("args")
            if not isinstance(args, dict) or not args or not all(
                isinstance(v, (int, float)) for v in args.values()
            ):
                raise ValueError(
                    "C event wants a non-empty numeric args dict: %r" % (event,)
                )
    return payload["traceEvents"]

"""Host-side span tracer emitting Chrome trace-event JSON.

The reference's only timing story is the end-of-run steps/s printout
(runner.py:504-598); a production run needs to see WHERE a step's wall time
went — dispatch vs blocking on the device vs host-side gaps — after the
fact, per step, without attaching a profiler.  This module is that story's
host half: lightweight spans written as Chrome trace events (the
``{"traceEvents": [...]}`` JSON Array Format), loadable in Perfetto /
``chrome://tracing`` next to a ``jax.profiler`` device trace.

Design constraints (the acceptance bar in ISSUE 4):

- **Zero compiles touched** — everything here is host-side Python; the
  jitted step programs are wrapped (``traced``), never modified, so the jit
  cache is byte-identical with tracing on or off (asserted by
  tests/test_obs.py).
- **Near-zero cost disabled** — tracing is OFF until :func:`install` is
  called; the disabled fast path of :class:`span` / :func:`instant` /
  :class:`TracedCallable` is a single global ``None`` check.
- **Bounded enabled cost** — events append to an in-memory list under a
  lock (one append per span, microseconds against millisecond steps) with a
  hard event cap; past it events are counted as dropped, never written.

Usage::

    from aggregathor_tpu.obs import trace
    trace.install("run.trace.json", run_id=run_id)
    with trace.span("dispatch", cat="train", step=12):
        ...
    @trace.span("checkpoint.save")
    def save(...): ...
    trace.save()            # or trace.uninstall(save=True)

Nesting is tracked per thread (a thread-local span stack): each event
carries its stack depth and parent name in ``args``, and Perfetto nests
same-thread "X" events by time containment.  All public entry points are
thread-safe — the serving stack records from handler threads while the
batcher thread records batches.
"""

import functools
import json
import os
import threading
import time

#: the process-wide installed tracer (None = tracing disabled)
_tracer = None

#: per-thread span stack for nesting (list of span names)
_local = threading.local()

#: hard cap on buffered events — a runaway loop degrades to a counted drop,
#: not an OOM (at ~150 B/event this caps the buffer around 150 MB)
MAX_EVENTS = 1_000_000


def _stack():
    stack = getattr(_local, "spans", None)
    if stack is None:
        stack = _local.spans = []
    return stack


class Tracer:
    """Event buffer + clock for one trace file.  Use the module-level
    :func:`install` / :func:`save` / :func:`uninstall` in application code;
    construct directly only in tests."""

    def __init__(self, path, run_id=None, clock=None):
        self.path = path
        self.run_id = run_id
        self._clock = clock if clock is not None else time.perf_counter
        self._epoch = self._clock()
        self._lock = threading.Lock()
        self._events = []
        self._named_threads = set()
        self.dropped = 0
        self._pid = os.getpid()
        self._events.append({
            "ph": "M", "name": "process_name", "pid": self._pid, "tid": 0,
            "args": {"name": "aggregathor_tpu"},
        })

    # ------------------------------------------------------------------ #

    def now_us(self):
        """Microseconds since tracer epoch (the trace's ``ts`` clock)."""
        return (self._clock() - self._epoch) * 1e6

    def _append(self, event, tid):
        with self._lock:
            if tid not in self._named_threads:
                self._named_threads.add(tid)
                self._events.append({
                    "ph": "M", "name": "thread_name", "pid": self._pid,
                    "tid": tid, "args": {"name": threading.current_thread().name},
                })
            if len(self._events) >= MAX_EVENTS:
                self.dropped += 1
                return
            self._events.append(event)

    def complete(self, name, start_us, dur_us, cat="host", args=None):
        """One "X" (complete) event: a span of ``dur_us`` from ``start_us``."""
        self._append({
            "ph": "X", "name": name, "cat": cat, "pid": self._pid,
            "tid": threading.get_ident(), "ts": start_us,
            "dur": max(dur_us, 0.0), "args": args or {},
        }, threading.get_ident())

    def instant(self, name, cat="host", args=None):
        """One "i" (instant) event — discrete occurrences like a guardian
        rollback decision."""
        self._append({
            "ph": "i", "s": "t", "name": name, "cat": cat, "pid": self._pid,
            "tid": threading.get_ident(), "ts": self.now_us(),
            "args": args or {},
        }, threading.get_ident())

    def save(self):
        """Write the trace (atomic: tmp + rename).  Callable repeatedly —
        each call snapshots the events so far."""
        if self.path is None:
            return None
        with self._lock:
            events = list(self._events)
            dropped = self.dropped
        payload = {
            "traceEvents": events,
            "displayTimeUnit": "ms",
            "otherData": {
                "producer": "aggregathor_tpu.obs.trace",
                "run_id": self.run_id,
                "dropped_events": dropped,
            },
        }
        directory = os.path.dirname(self.path)
        if directory:
            os.makedirs(directory, exist_ok=True)
        tmp = self.path + ".tmp"
        with open(tmp, "w") as fd:
            json.dump(payload, fd)
        os.replace(tmp, self.path)
        return self.path

    @property
    def nb_events(self):
        with self._lock:
            return len(self._events)


# --------------------------------------------------------------------- #
# module-level lifecycle


def install(path, run_id=None, clock=None):
    """Enable tracing process-wide, writing to ``path`` on :func:`save`.
    Returns the :class:`Tracer`.  Installing over a live tracer replaces it
    (the old one is saved first)."""
    global _tracer
    if _tracer is not None:
        _tracer.save()
    _tracer = Tracer(path, run_id=run_id, clock=clock)
    return _tracer


def installed():
    """The active tracer, or None when tracing is disabled."""
    return _tracer


def save():
    """Flush the active tracer to its path (no-op when disabled)."""
    if _tracer is not None:
        return _tracer.save()
    return None


def uninstall(save=True):
    """Disable tracing; optionally flush first.  Returns the written path
    (or None)."""
    global _tracer
    tracer, _tracer = _tracer, None
    if tracer is not None and save:
        return tracer.save()
    return None


# --------------------------------------------------------------------- #
# spans


class span:
    """Context manager AND decorator for one named span.

    ``with span("dispatch", cat="train", step=3): ...`` times the block;
    ``@span("checkpoint.save")`` times every call of the decorated function.
    When tracing is disabled the enter/exit path is one global ``None``
    check.  ``start()``/``stop()`` expose the manual form for spans whose
    lifetime does not nest lexically (the runner's host-gap span).
    """

    __slots__ = ("name", "cat", "args", "_t0", "_tracer")

    def __init__(self, name, cat="host", **args):
        self.name = name
        self.cat = cat
        self.args = args
        self._t0 = 0.0
        self._tracer = None

    def __enter__(self):
        tracer = _tracer
        self._tracer = tracer
        if tracer is None:
            return self
        stack = _stack()
        if self.args is not None and stack:
            self.args = dict(self.args, parent=stack[-1], depth=len(stack))
        stack.append(self.name)
        self._t0 = tracer.now_us()
        return self

    def __exit__(self, exc_type, exc, tb):
        tracer = self._tracer
        if tracer is None:
            return False
        stack = _stack()
        if stack and stack[-1] == self.name:
            stack.pop()
        args = self.args or {}
        if exc_type is not None:
            args = dict(args, error=exc_type.__name__)
        tracer.complete(self.name, self._t0, tracer.now_us() - self._t0,
                        cat=self.cat, args=args)
        return False

    # manual form (non-lexical lifetimes)
    start = __enter__

    def stop(self):
        self.__exit__(None, None, None)

    def __call__(self, fn):
        name, cat, args = self.name, self.cat, self.args

        @functools.wraps(fn)
        def wrapper(*a, **kw):
            with span(name, cat=cat, **args):
                return fn(*a, **kw)

        return wrapper


def instant(name, cat="host", **args):
    """Record an instant event (no-op when tracing is disabled)."""
    tracer = _tracer
    if tracer is not None:
        tracer.instant(name, cat=cat, args=args)


class TracedCallable:
    """Wrap a callable (typically a jitted step function) so every call is
    a span — WITHOUT touching the callable itself: attribute access
    (``_cache_size``, ``lower``, ...) falls through to the wrapped function,
    so compile-count assertions and AOT APIs keep working, and the jit
    cache is untouched (tracing adds zero recompiles by construction).
    ``inner`` is the unwrapped callable (the overhead benchmark's
    uninstrumented baseline)."""

    __slots__ = ("inner", "_name", "_cat")

    def __init__(self, name, fn, cat="dispatch"):
        object.__setattr__(self, "inner", fn)
        object.__setattr__(self, "_name", name)
        object.__setattr__(self, "_cat", cat)

    def __call__(self, *args, **kwargs):
        if _tracer is None:
            return self.inner(*args, **kwargs)
        with span(self._name, cat=self._cat):
            return self.inner(*args, **kwargs)

    def __getattr__(self, item):
        return getattr(self.inner, item)


def traced(name, fn, cat="dispatch"):
    """Shorthand: ``traced("train_step.dispatch", jax.jit(f))``."""
    return TracedCallable(name, fn, cat=cat)


def validate_chrome_trace(payload):
    """Structural check that ``payload`` (a parsed trace file) is loadable
    Chrome trace JSON: ``traceEvents`` list, every event a dict with
    ``ph``/``name``/``pid``/``tid``, "X" events with numeric ``ts``/``dur``.
    Returns the event list; raises ``ValueError`` on violations.  Shared by
    tests and scripts/run_obs_smoke.sh so the smoke asserts the same schema
    the tests do."""
    if not isinstance(payload, dict) or not isinstance(payload.get("traceEvents"), list):
        raise ValueError("Chrome trace JSON wants a top-level traceEvents list")
    for event in payload["traceEvents"]:
        if not isinstance(event, dict):
            raise ValueError("trace event is not an object: %r" % (event,))
        for key in ("ph", "name", "pid", "tid"):
            if key not in event:
                raise ValueError("trace event missing %r: %r" % (key, event))
        if event["ph"] == "X":
            for key in ("ts", "dur"):
                if not isinstance(event.get(key), (int, float)):
                    raise ValueError("X event wants numeric %r: %r" % (key, event))
            if event["dur"] < 0:
                raise ValueError("X event with negative dur: %r" % (event,))
        elif event["ph"] == "i":
            if not isinstance(event.get("ts"), (int, float)):
                raise ValueError("i event wants numeric ts: %r" % (event,))
    return payload["traceEvents"]

"""Byzantine forensics: a per-worker reputation ledger with attribution.

The engines already compute per-step suspicion diagnostics — each worker's
squared distance to the applied aggregate (``worker_sq_dist``), the probe's
post-transport NaN-row flags (``probe.worker_nan_rows``), the reputation
EMA and quarantine counts — but the reference mindset treats them as
transient scalars: summarized, then forgotten.  Masking an attacker is not
the same as *naming* one; the accountability line of work (Kerberos-style
attributable Byzantine SGD, ByzShield — PAPERS.md) argues attribution is
what makes robust training operable.  The ledger is that memory: a
step-indexed timeline of per-worker evidence, folded into an attribution
report that says WHICH workers behaved Byzantine, over WHICH step ranges,
under WHICH chaos regime.

Evidence kinds per observed step and worker:

- ``distance``    the worker's ``worker_sq_dist`` is a robust outlier —
  above ``distance_factor`` x the median finite distance (the honest
  majority anchors the median while ``r < n/2``, the same regime where the
  GARs themselves hold);
- ``nan_row``     the worker's post-transport submission held non-finite
  coordinates (``inf`` attacks, lossy drops, dead stragglers);
- ``reputation``  the engine's reputation EMA fell below
  ``reputation_threshold`` (the quarantine signal, when enabled);
- ``rank``        the worker holds the STRICT maximum finite distance this
  step (n >= 3 only).  One rank observation means nothing — some honest
  worker is farthest every step — but *persistence* does: under a uniform
  honest spread each worker tops out ~1/n of steps, so a worker that is
  farthest far more often than that is running something (the signal that
  catches attacks subtle enough to stay under the distance factor, e.g.
  sign-flips on noisy small-batch gradients).

A worker is *suspect at a step* when any evidence fires.  Attribution is
two-tier, and both tiers run globally AND over sliding windows — an
attacker active for 10% of a long run (a time-varying chaos schedule)
must not dilute below threshold:

- **strong** (distance / nan_row / reputation): attributed when the
  strong-evidence rate reaches ``byzantine_fraction`` over the whole run
  or over any ``window`` consecutive observations;
- **rank**: attributed when the global rank rate reaches
  ``rank_fraction``, or when the rank count in some window is
  statistically impossible for an honest worker — a Binomial(L, 1/n) tail
  test at significance ``rank_alpha``, Bonferroni-corrected over the
  number of windows (so longer runs demand proportionally stronger
  evidence, and the false-positive rate stays ~``rank_alpha`` per worker
  regardless of run length).

Consecutive suspect observations merge into intervals, each carrying the
regimes it spanned — so a report line reads "worker 2: Byzantine over
steps 500-999 under ``attack=empire``".

The report serializes under schema ``aggregathor.obs.forensics.v1`` (JSON)
plus a markdown rendering; ``chaos/campaign.py --forensics`` asserts
attribution accuracy against the injected coalition, and
``scripts/run_obs_smoke.sh`` asserts the injected attacker is named.
"""

import json
import math
import os
import time

import numpy as np


def binom_sf(total, successes, p):
    """Exact Binomial survival ``P(Bin(total, p) >= successes)`` — the
    honest-null tail for the rank-persistence test (no scipy dependency)."""
    successes = int(successes)
    if successes <= 0:
        return 1.0
    if successes > total or p <= 0.0:
        return 0.0
    if p >= 1.0:
        return 1.0
    log_p, log_q = math.log(p), math.log1p(-p)
    log_total = math.lgamma(total + 1)
    acc = 0.0
    for k in range(successes, total + 1):
        acc += math.exp(
            log_total - math.lgamma(k + 1) - math.lgamma(total - k + 1)
            + k * log_p + (total - k) * log_q
        )
    return min(acc, 1.0)

SCHEMA = "aggregathor.obs.forensics.v1"

#: evidence kinds that attribute on their own (``rank`` is weak — it only
#: attributes through persistence, see :meth:`ForensicsLedger.report`).
#: ``forgery`` is the secure submission layer's verdict (secure/submit.py):
#: the worker's per-step HMAC tag failed verification — cryptographic,
#: not statistical, so it is strong by construction (reject-and-name).
STRONG_EVIDENCE = ("distance", "nan_row", "reputation", "forgery")

#: report keys every per-worker record carries
WORKER_KEYS = (
    "worker", "steps_observed", "steps_suspect", "suspicion_rate",
    "strong_rate", "strong_window_rate", "rank_rate", "rank_window_count",
    "rank_p_value", "byzantine", "evidence", "intervals",
)


class ForensicsLedger:
    """Accumulates per-step suspicion evidence; renders attribution.

    Args:
      nb_workers: worker count n (evidence vectors must be length n).
      run_id: joined with trace metadata and summary lines (obs/summaries).
      distance_factor: a finite ``worker_sq_dist`` above ``factor x median``
        of the finite distances is ``distance`` evidence.  The median needs
        an honest majority — the same n > 2r regime the GARs need.
      reputation_threshold: reputation below this is ``reputation`` evidence.
      byzantine_fraction: STRONG-evidence rate at/above which a worker is
        attributed Byzantine — over the whole run or over any window.
      rank_fraction: rank-persistence rate (fraction of observed steps the
        worker held the strict maximum distance) at/above which a worker is
        attributed Byzantine — far above the ~1/n an honest worker hits.
      window: sliding-window length (observations) for the windowed tests —
        the smallest attack burst the ledger is expected to resolve.
      rank_alpha: per-worker false-positive bound of the windowed rank
        test: the max window rank count is attributed only when its
        Binomial(window, 1/n) tail probability, Bonferroni-corrected over
        all window positions, falls at/under this.
    """

    def __init__(self, nb_workers, run_id=None, distance_factor=4.0,
                 reputation_threshold=0.5, byzantine_fraction=0.5,
                 rank_fraction=0.8, window=8, rank_alpha=0.005,
                 straggler_fraction=0.25):
        if nb_workers < 1:
            raise ValueError("ForensicsLedger wants nb_workers >= 1")
        self.nb_workers = int(nb_workers)
        self.run_id = run_id
        self.distance_factor = float(distance_factor)
        self.reputation_threshold = float(reputation_threshold)
        self.byzantine_fraction = float(byzantine_fraction)
        self.rank_fraction = float(rank_fraction)
        self.window = int(window)
        self.rank_alpha = float(rank_alpha)
        self.straggler_fraction = float(straggler_fraction)
        if self.window < 1:
            raise ValueError("ForensicsLedger wants window >= 1")
        #: [(step, {worker: set(evidence)}, regime, regime_desc)] — sparse:
        #: only workers with evidence appear in the per-step dict
        self._timeline = []
        #: [(step, kind, payload)] guardian verdicts (rollback/escalation/...)
        self._guardian = []
        #: flight-recorder post-mortems (obs/flight.py) attached at
        #: rollback/crash: {at_step, reason, path, window} references the
        #: exact per-step evidence for the window that killed the run
        self._flight = []
        #: the run's causal journal (obs/events.py) cross-ref: path + event
        #: counts by type, so a post-mortem starts from ONE file
        self._journal = None
        #: [(step, level, unit, kind, payload)] sub-aggregator verdicts
        #: from the topology plane — a separate surface from worker
        #: evidence (a forged PARENT is named as a tree node, never
        #: laundered into the leaf workers it relayed)
        self._subaggregators = []
        self._steps_observed = 0

    # ------------------------------------------------------------------ #
    # ingestion

    def observe(self, step, worker_sq_dist=None, worker_nan=None,
                reputation=None, regime=None, regime_desc=None, forgery=None,
                timeout=None, stale=None):
        """One completed training step's diagnostics.  Every vector is
        length-n (or None when the engine did not compute it); non-finite
        ``worker_sq_dist`` entries are treated as masked (no ``distance``
        evidence — the NaN-row flag is the signal for dead rows).
        ``forgery`` is the submission authenticator's per-worker verdict
        (True = this worker's tag failed verification this step).
        ``timeout`` is the bounded-wait protocol's deadline verdict
        (parallel/bounded.py): a timed-out worker gets ``straggler_timeout``
        evidence, and its NaN row is EXPLAINED by the timeout — it does not
        double as ``nan_row`` strong evidence (late is not Byzantine; the
        stragglers surface in the report's own ``stragglers`` list).
        ``stale`` marks the timed-out workers whose round was served by
        their CLEVER carry instead of a NaN drop (stale infill): named
        ``stale_infill`` evidence, weak like the timeout itself, so
        late-but-honest stays distinguishable from Byzantine — while the
        row STILL spends the declared-f budget (docs/engine.md)."""
        suspects = {}
        timed_out = None
        if timeout is not None:
            timed_out = np.asarray(timeout).reshape(-1).astype(bool)
            self._check_len("timeout", timed_out)

        def mark(worker, kind):
            suspects.setdefault(int(worker), set()).add(kind)

        if timed_out is not None:
            for worker in np.nonzero(timed_out)[0]:
                mark(worker, "straggler_timeout")
        if stale is not None:
            infilled = np.asarray(stale).reshape(-1).astype(bool)
            self._check_len("stale", infilled)
            for worker in np.nonzero(infilled)[0]:
                mark(worker, "stale_infill")
        if forgery is not None:
            forged = np.asarray(forgery).reshape(-1)
            self._check_len("forgery", forged)
            for worker in np.nonzero(forged.astype(bool))[0]:
                mark(worker, "forgery")

        if worker_sq_dist is not None:
            dist = np.asarray(worker_sq_dist, np.float64).reshape(-1)
            self._check_len("worker_sq_dist", dist)
            if timed_out is not None:
                # a timeout EXPLAINS the row that replaced this worker's
                # submission (NaN drop or stale carry): its distance
                # measures the protocol's infill, not the worker's conduct
                # this step — excused from distance/rank evidence exactly
                # like the NaN-row flag below (late is not Byzantine; an
                # aging stale carry legitimately drifts from the honest
                # mean).  The row still SPENT the f budget, and a worker
                # gaming this by straggling loses its infill at
                # stale-max-age (docs/engine.md).
                dist = np.where(timed_out, np.nan, dist)
            finite = dist[np.isfinite(dist)]
            if finite.size:
                anchor = float(np.median(finite))
                # Degenerate anchor (all-zero distances: identical
                # gradients) cannot rank anyone; positive outliers over a
                # zero anchor still flag via the epsilon floor.
                floor = max(anchor * self.distance_factor, 1e-12)
                for worker in np.nonzero(np.isfinite(dist) & (dist > floor))[0]:
                    mark(worker, "distance")
                # Rank persistence (n >= 3, strict max only — an all-equal
                # spread names nobody): weak alone, attributed only when it
                # persists at rank_fraction of steps (see report()).
                if self.nb_workers >= 3 and finite.size >= 2:
                    order = np.argsort(np.where(np.isfinite(dist), dist, -np.inf))
                    top, runner_up = order[-1], order[-2]
                    if np.isfinite(dist[top]) and dist[top] > dist[runner_up]:
                        mark(top, "rank")
        if worker_nan is not None:
            nan_rows = np.asarray(worker_nan).reshape(-1)
            self._check_len("worker_nan", nan_rows)
            if timed_out is not None:
                # a timeout's NaN infill is accounted above, not as nan_row
                nan_rows = nan_rows.astype(bool) & ~timed_out
            for worker in np.nonzero(nan_rows.astype(bool))[0]:
                mark(worker, "nan_row")
        if reputation is not None:
            rep = np.asarray(reputation, np.float64).reshape(-1)
            self._check_len("reputation", rep)
            for worker in np.nonzero(rep < self.reputation_threshold)[0]:
                mark(worker, "reputation")
        self._timeline.append((
            int(step), suspects,
            None if regime is None else int(regime),
            regime_desc,
        ))
        self._steps_observed += 1

    def note_subaggregator(self, step, level, unit, kind, payload=None):
        """Record a SUB-AGGREGATOR verdict from the topology plane
        (topology/tree.py): a (level, unit) tree node whose custody tag
        failed chain verification (``forgery``), whose subtree timed out
        as a unit (``timeout``), or whose summary was served by a
        redundant sibling shadow (``reconstructed``).

        Deliberately a SEPARATE ledger surface from worker evidence: a
        forged intermediate is an infrastructure node, and naming it as a
        (level, unit) keeps the blame where the cryptography put it —
        never laundered into the leaf workers whose honest rows it
        relayed (they keep their clean per-worker records)."""
        self._subaggregators.append({
            "step": int(step),
            "level": int(level),
            "unit": int(unit),
            "kind": str(kind),
            "payload": dict(payload or {}),
        })

    def note_guardian(self, step, kind, payload=None):
        """Record a guardian verdict (``rollback``/``escalation``/
        ``recovered``) — the recovery layer's contribution to the audit
        trail."""
        self._guardian.append((int(step), str(kind), dict(payload or {})))

    def attach_flight(self, at_step, reason, path=None, window_summary=None):
        """Reference a flight-recorder post-mortem dump (obs/flight.py) in
        the report: the in-scan ring holds EXACT per-step evidence for the
        window around a rollback or crash — including the final dispatch's
        sub-steps that a cadenced feed would summarize away.  Post-mortems
        survive ``truncate_after`` (like the rollback event itself, they
        are the audit trail of the abandoned timeline)."""
        self._flight.append({
            "at_step": int(at_step),
            "reason": str(reason),
            "path": path,
            "window": dict(window_summary or {}),
        })

    def note_journal(self, path, counts_by_type):
        """Cross-reference the run's causal journal (obs/events.py) in the
        report: the path plus per-type event counts — the report says WHO
        misbehaved, the journal says WHAT the run decided about it, and
        each points at the other."""
        counts = {str(k): int(v) for k, v in dict(counts_by_type).items()}
        self._journal = {
            "path": path,
            "nb_events": int(sum(counts.values())),
            "events_by_type": counts,
        }

    def truncate_after(self, step):
        """Drop observations and guardian events beyond ``step`` — the
        abandoned timeline after a rollback (mirrors
        ``EvalFile.truncate_after``).  Returns the dropped observation
        count."""
        step = int(step)
        before = len(self._timeline)
        self._timeline = [row for row in self._timeline if row[0] <= step]
        self._guardian = [row for row in self._guardian if row[0] <= step]
        self._subaggregators = [
            row for row in self._subaggregators if row["step"] <= step
        ]
        self._steps_observed = len(self._timeline)
        return before - len(self._timeline)

    def _check_len(self, name, vector):
        if vector.shape[0] != self.nb_workers:
            raise ValueError(
                "%s has %d entries for %d workers" % (name, vector.shape[0], self.nb_workers)
            )

    # ------------------------------------------------------------------ #
    # attribution

    def report(self):
        """The attribution report (schema ``aggregathor.obs.forensics.v1``)."""
        timeline = sorted(self._timeline, key=lambda row: row[0])
        observed = len(timeline)
        length = min(self.window, observed)
        kernel = np.ones(length, np.float64) if length else None
        nb_windows = observed - length + 1 if length else 0
        workers = []
        for worker in range(self.nb_workers):
            suspect_steps = []
            evidence_counts = {}
            strong_flags = np.zeros(observed, np.float64)
            rank_flags = np.zeros(observed, np.float64)
            for index, (step, suspects, regime, desc) in enumerate(timeline):
                kinds = suspects.get(worker)
                if kinds:
                    suspect_steps.append((step, regime, desc, sorted(kinds)))
                    if any(kind in kinds for kind in STRONG_EVIDENCE):
                        strong_flags[index] = 1.0
                    if "rank" in kinds:
                        rank_flags[index] = 1.0
                    for kind in kinds:
                        evidence_counts[kind] = evidence_counts.get(kind, 0) + 1
            intervals = self._merge_intervals(timeline, suspect_steps)
            rate = len(suspect_steps) / observed if observed else 0.0
            # Two-tier attribution, global AND windowed (see module doc):
            # strong evidence at byzantine_fraction of the run or of any
            # window; rank persistence at rank_fraction of the run, or at a
            # window count statistically impossible for an honest worker
            # (Binomial tail at rank_alpha, Bonferroni over windows).
            strong_rate = float(strong_flags.sum()) / observed if observed else 0.0
            rank_rate = float(rank_flags.sum()) / observed if observed else 0.0
            strong_window_rate = 0.0
            rank_window_count = 0
            rank_p_value = 1.0
            if length:
                strong_window_rate = float(
                    np.convolve(strong_flags, kernel, "valid").max()
                ) / length
                rank_window_count = int(
                    np.convolve(rank_flags, kernel, "valid").max()
                )
                rank_p_value = min(
                    binom_sf(length, rank_window_count, 1.0 / self.nb_workers)
                    * nb_windows,
                    1.0,
                )
            workers.append({
                "worker": worker,
                "steps_observed": observed,
                "steps_suspect": len(suspect_steps),
                "suspicion_rate": rate,
                "strong_rate": strong_rate,
                "strong_window_rate": strong_window_rate,
                "rank_rate": rank_rate,
                "rank_window_count": rank_window_count,
                "rank_p_value": rank_p_value,
                "byzantine": bool(observed and (
                    strong_rate >= self.byzantine_fraction
                    or strong_window_rate >= self.byzantine_fraction
                    or rank_rate >= self.rank_fraction
                    or rank_p_value <= self.rank_alpha
                )),
                "timeout_rate": (
                    evidence_counts.get("straggler_timeout", 0) / observed
                    if observed else 0.0
                ),
                "evidence": evidence_counts,
                "intervals": intervals,
            })
        return {
            "schema": SCHEMA,
            "run_id": self.run_id,
            "generated_at": time.time(),
            "nb_workers": self.nb_workers,
            "steps_observed": len(timeline),
            "step_range": (
                [timeline[0][0], timeline[-1][0]] if timeline else None
            ),
            "thresholds": {
                "distance_factor": self.distance_factor,
                "reputation_threshold": self.reputation_threshold,
                "byzantine_fraction": self.byzantine_fraction,
                "rank_fraction": self.rank_fraction,
                "window": self.window,
                "rank_alpha": self.rank_alpha,
                "straggler_fraction": self.straggler_fraction,
            },
            "suspects": [w["worker"] for w in workers if w["byzantine"]],
            # bounded-wait deadline offenders (parallel/bounded.py): named
            # separately from Byzantine suspects — late is a capacity
            # problem, not an integrity one, but both spend the f budget
            "stragglers": [
                w["worker"] for w in workers
                if w["timeout_rate"] >= self.straggler_fraction
            ],
            "workers": workers,
            # topology-plane verdicts (topology/tree.py): per-(level, unit)
            # sub-aggregator records, aggregated from note_subaggregator —
            # ``corrupt_subaggregators`` names every tree node with a
            # custody-forgery verdict as "LEVEL.UNIT"
            "sub_aggregators": self._subaggregator_records(),
            "corrupt_subaggregators": sorted({
                "%d.%d" % (row["level"], row["unit"])
                for row in self._subaggregators if row["kind"] == "forgery"
            }),
            "guardian_events": [
                {"step": step, "kind": kind, "payload": payload}
                for step, kind, payload in self._guardian
            ],
            "flight_postmortems": list(self._flight),
            "journal": None if self._journal is None else dict(self._journal),
        }

    def _subaggregator_records(self):
        """Aggregate the sub-aggregator timeline into per-(level, unit)
        records: step span, per-kind counts, and the corrupt verdict (any
        custody forgery names the node)."""
        records = {}
        for row in self._subaggregators:
            node = (row["level"], row["unit"])
            rec = records.setdefault(node, {
                "level": row["level"], "unit": row["unit"],
                "first_step": row["step"], "last_step": row["step"],
                "steps": 0, "evidence": {},
            })
            rec["first_step"] = min(rec["first_step"], row["step"])
            rec["last_step"] = max(rec["last_step"], row["step"])
            rec["steps"] += 1
            rec["evidence"][row["kind"]] = rec["evidence"].get(row["kind"], 0) + 1
        out = []
        for node in sorted(records):
            rec = records[node]
            rec["corrupt"] = rec["evidence"].get("forgery", 0) > 0
            out.append(rec)
        return out

    @staticmethod
    def _merge_intervals(timeline, suspect_steps):
        """Merge observations suspect at CONSECUTIVE observed steps into
        [{start, end, steps, regimes, evidence}] ranges.  Consecutive means
        adjacent in the observation sequence (cadenced feeds observe every
        k-th step; a gap in the observations is not a gap in suspicion)."""
        if not suspect_steps:
            return []
        observed_order = {step: i for i, (step, _, _, _) in enumerate(timeline)}
        intervals = []
        current = None
        for step, regime, desc, kinds in suspect_steps:
            index = observed_order[step]
            if current is not None and index == current["_last_index"] + 1:
                current["end"] = step
                current["steps"] += 1
                current["_last_index"] = index
                if regime is not None and regime not in current["regimes"]:
                    current["regimes"].append(regime)
                    if desc:
                        current["regime_specs"].append(desc)
                for kind in kinds:
                    if kind not in current["evidence"]:
                        current["evidence"].append(kind)
            else:
                current = {
                    "start": step, "end": step, "steps": 1,
                    "regimes": [] if regime is None else [regime],
                    "regime_specs": [desc] if (regime is not None and desc) else [],
                    "evidence": list(kinds),
                    "_last_index": index,
                }
                intervals.append(current)
        for interval in intervals:
            del interval["_last_index"]
        return intervals

    # ------------------------------------------------------------------ #
    # output

    def save(self, path, markdown_path=None):
        """Write the JSON report (and optionally the markdown rendering).
        Returns the report dict."""
        report = self.report()
        directory = os.path.dirname(path)
        if directory:
            os.makedirs(directory, exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "w") as fd:
            json.dump(report, fd, indent=1)
            fd.write("\n")
        os.replace(tmp, path)
        if markdown_path:
            with open(markdown_path, "w") as fd:
                fd.write(render_markdown(report))
        return report


def render_markdown(report):
    """Human-readable attribution report for one ledger report dict."""
    lines = [
        "# Byzantine forensics — run %s" % (report.get("run_id") or "?"),
        "",
        "Schema `%s`; %d worker(s), %d observed step(s)%s." % (
            report["schema"], report["nb_workers"], report["steps_observed"],
            (" over steps %d-%d" % tuple(report["step_range"])
             if report.get("step_range") else ""),
        ),
        "",
    ]
    suspects = report.get("suspects", [])
    if suspects:
        lines.append("**Attributed Byzantine: worker(s) %s.**"
                     % ", ".join(str(w) for w in suspects))
    else:
        lines.append("**No worker attributed Byzantine.**")
    stragglers = report.get("stragglers", [])
    if stragglers:
        lines.append("")
        lines.append(
            "**Deadline offenders (bounded-wait): worker(s) %s.**"
            % ", ".join(str(w) for w in stragglers)
        )
    lines += [
        "",
        "| worker | suspect/observed | rate | verdict | evidence | intervals |",
        "|---:|---:|---:|---|---|---|",
    ]
    for worker in report["workers"]:
        spans = "; ".join(
            "%d-%d%s" % (
                iv["start"], iv["end"],
                (" (regime %s)" % ",".join(str(r) for r in iv["regimes"])
                 if iv["regimes"] else ""),
            )
            for iv in worker["intervals"]
        ) or "—"
        evidence = ", ".join(
            "%s x%d" % kv for kv in sorted(worker["evidence"].items())
        ) or "—"
        lines.append("| %d | %d/%d | %.2f | %s | %s | %s |" % (
            worker["worker"], worker["steps_suspect"], worker["steps_observed"],
            worker["suspicion_rate"],
            "**BYZANTINE**" if worker["byzantine"] else "honest",
            evidence, spans,
        ))
    subaggs = report.get("sub_aggregators", [])
    if subaggs:
        corrupt = report.get("corrupt_subaggregators", [])
        lines += ["", "## Sub-aggregators (topology plane)", ""]
        if corrupt:
            lines.append("**Corrupt sub-aggregator(s): %s** (custody-chain "
                         "forgery — named as tree nodes, not workers)."
                         % ", ".join(corrupt))
            lines.append("")
        lines += [
            "| node | steps | span | verdict | evidence |",
            "|---|---:|---|---|---|",
        ]
        for rec in subaggs:
            evidence = ", ".join(
                "%s x%d" % kv for kv in sorted(rec["evidence"].items())
            ) or "—"
            lines.append("| %d.%d | %d | %d-%d | %s | %s |" % (
                rec["level"], rec["unit"], rec["steps"],
                rec["first_step"], rec["last_step"],
                "**CORRUPT**" if rec["corrupt"] else "clean",
                evidence,
            ))
    events = report.get("guardian_events", [])
    if events:
        lines += ["", "## Guardian events", ""]
        for event in events:
            lines.append("- step %d: %s %s" % (
                event["step"], event["kind"],
                json.dumps(event["payload"], sort_keys=True),
            ))
    journal = report.get("journal")
    if journal:
        lines += ["", "## Run journal", ""]
        lines.append("`%s` — %d event(s): %s" % (
            journal.get("path"), journal.get("nb_events", 0),
            ", ".join(
                "%s x%d" % kv
                for kv in sorted(journal.get("events_by_type", {}).items())
            ) or "—",
        ))
    return "\n".join(lines) + "\n"

"""Device-side profiling: step-windowed traces, compile + memory telemetry.

Three instruments, all host-side plumbing around ``jax.profiler`` /
``jax.monitoring`` (the jitted programs are never touched — the PR-4
zero-recompile discipline):

- :class:`ProfilerWindow` — a programmatic ``jax.profiler`` capture over an
  explicit step window (``--xprof A:B`` on the runner): the device trace
  starts when the step counter reaches ``A`` and stops at ``B``, and every
  dispatch inside the window is wrapped in a
  ``jax.profiler.StepTraceAnnotation`` so the PR-4 host spans join the
  device timeline on the profiler's step axis.  Under ``--unroll`` the
  boundaries land on chunk boundaries (the window is never allowed to
  split a compiled scan).
- :class:`CompileWatch` — compile observability: wrapped executables are
  polled for jit-cache growth after every call (one host attribute read);
  a cache miss becomes a named ``compile_cache_misses_total{executable=}``
  counter increment plus a tagged ``compile_cache_miss`` summary event
  carrying WHICH executable retraced and the abstract shapes of the
  dispatch that triggered it — the first diagnostic anyone needs when
  steps/s falls off a cliff.  :func:`install_compile_listener` additionally
  taps ``jax.monitoring`` for backend-compile totals (catching compiles of
  executables nobody thought to wrap).
- :func:`install_memory_gauges` — live/peak device memory bytes from
  ``Device.memory_stats()`` as scrape-time registry gauges (absent on
  backends that do not report, e.g. XLA:CPU).
"""

import contextlib
import threading

import jax

from ..utils import UserException, info


# --------------------------------------------------------------------- #
# step-windowed device traces


class ProfilerWindow:
    """One ``jax.profiler`` capture over steps ``[begin, end)``.

    ``spec`` is the CLI form ``"A:B"`` (ints, ``A < B``).  The runner calls
    :meth:`maybe_start` before each dispatch and :meth:`maybe_stop` after
    the step counter advances; :meth:`annotate` wraps the dispatch in a
    ``StepTraceAnnotation`` while the capture is live (and is a no-op
    ``nullcontext`` otherwise, so the inactive path costs one attribute
    read).  :meth:`close` stops a capture left open at shutdown."""

    def __init__(self, spec, trace_dir, registry=None):
        try:
            begin, _, end = str(spec).partition(":")
            self.begin, self.end = int(begin), int(end)
        except ValueError:
            raise UserException("--xprof wants A:B step integers (got %r)" % (spec,))
        if not 0 <= self.begin < self.end:
            raise UserException(
                "--xprof wants 0 <= A < B (got %d:%d)" % (self.begin, self.end)
            )
        self.trace_dir = trace_dir
        self.active = False
        self.done = False
        if registry is not None:
            registry.gauge(
                "profiler_window_active",
                "1 while a --xprof device capture is recording",
            ).set_function(lambda: 1.0 if self.active else 0.0)

    def maybe_start(self, step):
        """Open the capture when ``step`` enters the window (idempotent;
        never reopens a finished window)."""
        if self.active or self.done or step < self.begin or step >= self.end:
            return False
        jax.profiler.start_trace(self.trace_dir)
        self.active = True
        info("Profiler window open at step %d -> %r (steps %d:%d)"
             % (step, self.trace_dir, self.begin, self.end))
        return True

    def maybe_stop(self, step):
        """Close the capture once ``step`` passed the window end."""
        if not self.active or step < self.end:
            return False
        jax.profiler.stop_trace()
        self.active = False
        self.done = True
        info("Profiler window closed at step %d (device trace in %r)"
             % (step, self.trace_dir))
        return True

    def annotate(self, step):
        """Context manager for one dispatch: a ``StepTraceAnnotation``
        inside the live window (joining host spans to the device timeline
        per step), a free ``nullcontext`` outside it."""
        if not self.active:
            return contextlib.nullcontext()
        return jax.profiler.StepTraceAnnotation("train", step_num=int(step))

    def close(self):
        if self.active:
            jax.profiler.stop_trace()
            self.active = False
            self.done = True
        elif not self.done:
            from ..utils import warning

            # e.g. the whole window fell inside one unrolled chunk, or
            # before the resume offset — an empty trace dir with no
            # diagnostic would read as a silent success
            warning(
                "--xprof window %d:%d never opened (steps advance in "
                "chunk strides and must LAND inside the window; widen it "
                "past the unroll, or move it past the resume step)"
                % (self.begin, self.end)
            )


# --------------------------------------------------------------------- #
# compile observability

#: the jax.monitoring duration event emitted once per backend compile
BACKEND_COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"

_monitor = {"installed": False, "count": 0, "seconds": 0.0}
_monitor_lock = threading.Lock()


def _monitor_listener(event, duration, **kwargs):
    if event == BACKEND_COMPILE_EVENT:
        with _monitor_lock:
            _monitor["count"] += 1
            _monitor["seconds"] += float(duration)


def install_compile_listener(registry):
    """Count EVERY backend compile in this process (jax.monitoring) into
    scrape-time gauges ``compile_backend_total`` /
    ``compile_backend_seconds_total``.  The listener itself installs once
    per process (jax.monitoring has no per-listener removal); repeated
    calls only re-point the gauges at the shared accumulator."""
    with _monitor_lock:
        if not _monitor["installed"]:
            jax.monitoring.register_event_duration_secs_listener(_monitor_listener)
            _monitor["installed"] = True
    registry.gauge(
        "compile_backend_total",
        "Backend compiles observed by jax.monitoring in this process",
    ).set_function(lambda: float(_monitor["count"]))
    registry.gauge(
        "compile_backend_seconds_total",
        "Wall time jax.monitoring attributes to backend compiles",
    ).set_function(lambda: _monitor["seconds"])


def describe_abstract(args, kwargs=(), limit=12):
    """Compact abstract-shape descriptors (``f32[8,16,784]``-style) for the
    leaves of a dispatch's arguments — what a compile-miss event records as
    the offending shapes.  Truncated to ``limit`` leaves (the full pytree
    of a train state is hundreds of leaves; the batch and the first few
    state leaves identify the retrace)."""
    leaves = jax.tree_util.tree_leaves((args, kwargs))
    out = []
    for leaf in leaves[:limit]:
        dtype = getattr(leaf, "dtype", None)
        shape = getattr(leaf, "shape", None)
        if dtype is None or shape is None:
            out.append(type(leaf).__name__)
        else:
            out.append("%s[%s]" % (
                jax.dtypes.canonicalize_dtype(dtype).name
                if hasattr(jax.dtypes, "canonicalize_dtype") else str(dtype),
                ",".join(str(d) for d in shape),
            ))
    if len(leaves) > limit:
        out.append("... +%d leaves" % (len(leaves) - limit))
    return out


class _WatchedCallable:
    """Attribute-fallthrough wrapper (the ``TracedCallable`` idiom): every
    call compares the wrapped executable's jit-cache size before/after and
    reports growth to the owning :class:`CompileWatch`.  The wrapped
    callable is never modified — zero added recompiles by construction."""

    __slots__ = ("inner", "_watch", "_name")

    def __init__(self, watch, name, fn):
        object.__setattr__(self, "inner", fn)
        object.__setattr__(self, "_watch", watch)
        object.__setattr__(self, "_name", name)

    def _cache_len(self):
        probe = getattr(self.inner, "_cache_size", None)
        if probe is None:
            return None
        try:
            return int(probe())
        except Exception:
            return None

    def __call__(self, *args, **kwargs):
        before = self._cache_len()
        out = self.inner(*args, **kwargs)
        after = self._cache_len()
        if before is not None and after is not None and after > before:
            self._watch.note_miss(self._name, after, args, kwargs)
        return out

    def __getattr__(self, item):
        return getattr(self.inner, item)


class CompileWatch:
    """Names compile-cache misses of the executables it wraps.

    ``wrap(name, fn)`` returns the watched callable (idempotent per
    ``(name, fn)`` pair — re-wrapping after a guardian rebuild reuses the
    name).  On a miss the watch increments
    ``compile_cache_misses_total{executable=name}`` and, when a
    ``SummaryWriter`` is attached, emits a tagged ``compile_cache_miss``
    event carrying the executable name, the new cache size and the
    abstract shapes of the triggering dispatch — so "why did step 512
    stall" is answered by the summary stream, not a profiler session."""

    def __init__(self, registry, summaries=None, step_provider=None):
        self._counter = registry.counter(
            "compile_cache_misses_total",
            "Jit-cache growth observed per wrapped executable "
            "(the first compile of each executable counts once)",
            labelnames=("executable",),
        )
        self.summaries = summaries
        self.step_provider = step_provider
        self.misses = []  # [(name, cache_size, shapes)] — tests / postmortems

    def wrap(self, name, fn):
        if isinstance(fn, _WatchedCallable) and fn._watch is self:
            return fn
        return _WatchedCallable(self, str(name), fn)

    def note_miss(self, name, cache_size, args, kwargs):
        shapes = describe_abstract(args, kwargs)
        self.misses.append((name, int(cache_size), shapes))
        self._counter.labels(executable=name).inc()
        if int(cache_size) <= 1:
            # the FIRST compile of an executable is expected — it counts
            # (the smoke asserts a nonzero compile counter) but does not
            # alarm; the summary event is reserved for true RETRACES, the
            # "steps/s fell off a cliff" diagnostic
            return
        if self.summaries is not None:
            step = 0
            if self.step_provider is not None:
                try:
                    step = int(self.step_provider())
                except Exception:
                    step = 0
            self.summaries.event(step, "compile_cache_miss", {
                "executable": name,
                "cache_size": int(cache_size),
                "arg_shapes": shapes,
            })


# --------------------------------------------------------------------- #
# device memory gauges


def install_memory_gauges(registry, devices=None):
    """Scrape-time live/peak device-memory gauges from
    ``Device.memory_stats()``.

    Registered per device that actually reports stats (TPU/GPU; XLA:CPU
    returns None and registers nothing).  Returns the number of devices
    instrumented.  The callbacks re-read ``memory_stats()`` at every
    scrape — live views, no writer loop, like serve's queue gauges."""
    devices = jax.devices() if devices is None else devices
    instrumented = 0
    live = registry.gauge(
        "device_memory_live_bytes", "Bytes currently allocated on the device",
        labelnames=("device",),
    )
    peak = registry.gauge(
        "device_memory_peak_bytes", "Peak bytes ever allocated on the device",
        labelnames=("device",),
    )
    for index, device in enumerate(devices):
        try:
            stats = device.memory_stats()
        except Exception:
            stats = None
        if not stats:
            continue

        def read(dev, key, fallback=0.0):
            def value():
                try:
                    return float((dev.memory_stats() or {}).get(key, fallback))
                except Exception:
                    return fallback
            return value

        label = str(index)
        live.labels(device=label).set_function(read(device, "bytes_in_use"))
        peak.labels(device=label).set_function(read(device, "peak_bytes_in_use"))
        instrumented += 1
    return instrumented

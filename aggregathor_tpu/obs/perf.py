"""Throughput accounting: the reference's end-of-run performance report.

Reproduces runner.py:504-506, 561-569, 586-598: wall time split into
"in-graph" (blocking on the device step) vs "off-graph" (host-side work
between steps), steps/s including and excluding the first (compilation) step.
"""

import time

from ..utils import info


class PerfReport:
    def __init__(self):
        self.nb_steps = 0
        self.first_step_s = 0.0
        self.in_graph_s = 0.0
        self.start = time.monotonic()
        self._step_start = None

    def step_begin(self):
        self._step_start = time.monotonic()

    def step_end(self, nb_steps=1):
        """Account a dispatch covering ``nb_steps`` training steps (unroll)."""
        elapsed = time.monotonic() - self._step_start
        if self.nb_steps == 0:
            self.first_step_s = elapsed
        self.in_graph_s += elapsed
        self.nb_steps += int(nb_steps)

    def report(self):
        total = time.monotonic() - self.start
        off_graph = total - self.in_graph_s
        info("Performance report:")
        info("  steps                 %d" % self.nb_steps)
        info("  total wall time       %.3f s" % total)
        info("  in-graph time         %.3f s (%.1f%%)" % (self.in_graph_s, 100.0 * self.in_graph_s / max(total, 1e-9)))
        info("  off-graph time        %.3f s (%.1f%%)" % (off_graph, 100.0 * off_graph / max(total, 1e-9)))
        info("  first (compile) step  %.3f s" % self.first_step_s)
        if self.nb_steps > 0:
            info("  steps/s (all steps)   %.3f" % (self.nb_steps / max(total, 1e-9)))
        if self.nb_steps > 1:
            excl = (self.nb_steps - 1) / max(total - self.first_step_s, 1e-9)
            info("  steps/s (excl. 1st)   %.3f" % excl)

    def steps_per_s_excl_first(self):
        total = time.monotonic() - self.start
        if self.nb_steps <= 1:
            return 0.0
        return (self.nb_steps - 1) / max(total - self.first_step_s, 1e-9)

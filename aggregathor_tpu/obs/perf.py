"""Throughput accounting: the reference's end-of-run performance report.

Reproduces runner.py:504-506, 561-569, 586-598: wall time split into
"in-graph" (blocking on the device step) vs "off-graph" (host-side work
between steps), steps/s including and excluding the first (compilation) step.

``LatencyHistogram`` is the shared tail-latency accumulator: a bounded
reservoir of samples with p50/p95/p99 readout, used both by ``PerfReport``
(per-dispatch step latency spread) and by the serving stack's ``/metrics``
endpoint (request latency, ``serve/server.py``).
"""

import random
import threading
import time

from ..utils import info


class LatencyHistogram:
    """p50/p95/p99 percentiles over a bounded sample reservoir.

    Uniform reservoir sampling (Vitter's algorithm R) over everything ever
    recorded, so a long-lived server keeps a representative — not merely
    recent — tail picture in O(capacity) memory.  Thread-safe: the serving
    path records from handler threads while ``/metrics`` reads concurrently.
    """

    #: the percentiles ``percentiles()`` reports, as (name, fraction)
    POINTS = (("p50", 0.50), ("p95", 0.95), ("p99", 0.99))

    def __init__(self, capacity=4096, seed=0):
        if capacity < 1:
            raise ValueError("LatencyHistogram capacity must be >= 1 (got %d)" % capacity)
        self.capacity = int(capacity)
        self._samples = []
        self._count = 0
        self._rng = random.Random(seed)
        self._lock = threading.Lock()

    def record(self, seconds):
        """Add one latency sample (seconds; any nonnegative float works)."""
        value = float(seconds)
        with self._lock:
            self._count += 1
            if len(self._samples) < self.capacity:
                self._samples.append(value)
            else:
                slot = self._rng.randrange(self._count)
                if slot < self.capacity:
                    self._samples[slot] = value

    @property
    def count(self):
        """Total samples ever recorded (not just the retained reservoir)."""
        with self._lock:
            return self._count

    def percentiles(self):
        """{"p50": s, "p95": s, "p99": s} (seconds), or None when empty.

        Nearest-rank on the sorted reservoir — with fewer samples than the
        1/(1-q) run length the top percentiles degrade to the maximum, which
        is the honest small-sample answer for a tail estimate.
        """
        with self._lock:
            if not self._samples:
                return None
            ordered = sorted(self._samples)
        last = len(ordered) - 1
        return {
            name: ordered[min(last, int(q * len(ordered)))]
            for name, q in self.POINTS
        }


class PerfReport:
    """End-of-run throughput report; optionally registry-backed.

    With ``registry`` (an ``obs.metrics.MetricsRegistry``), the report's
    accumulators are ALSO exported as first-class metrics —
    ``train_steps_total``, ``train_in_graph_seconds_total`` and the
    ``train_step_latency_seconds`` histogram.  The printed report always
    reads this instance's own fresh reservoir (per-run percentiles), while
    the registry instruments are get-or-create and therefore cumulative
    over the process — the standard Prometheus counter/histogram contract,
    and the reason a second ``runner.main()`` in one process (tests) does
    not pollute the first's printed numbers.
    """

    def __init__(self, registry=None):
        self.nb_steps = 0
        self.first_step_s = 0.0
        self.in_graph_s = 0.0
        self.start = time.monotonic()
        self._step_start = None
        self._steps_counter = None
        self._in_graph_counter = None
        self._registry_latency = None
        # Per-dispatch latency spread (first/compile dispatch excluded so
        # the percentiles describe the steady state, like steps/s excl.
        # 1st) — ALWAYS a fresh per-run reservoir, so the printed report is
        # this run's, even when the process-global registry is shared.
        self.latency = LatencyHistogram()
        if registry is not None:
            self._registry_latency = registry.histogram(
                "train_step_latency_seconds",
                "Per-step train latency (first/compile dispatch excluded)",
            )
            self._steps_counter = registry.counter(
                "train_steps_total", "Completed training steps"
            )
            self._in_graph_counter = registry.counter(
                "train_in_graph_seconds_total",
                "Wall time spent blocked on dispatched step programs",
            )

    def step_begin(self):
        self._step_start = time.monotonic()

    def step_end(self, nb_steps=1):
        """Account a dispatch covering ``nb_steps`` training steps (unroll)."""
        elapsed = time.monotonic() - self._step_start
        if self.nb_steps == 0:
            self.first_step_s = elapsed
        else:
            self.latency.record(elapsed / max(int(nb_steps), 1))
            if self._registry_latency is not None:
                self._registry_latency.observe(elapsed / max(int(nb_steps), 1))
        self.in_graph_s += elapsed
        self.nb_steps += int(nb_steps)
        if self._steps_counter is not None:
            self._steps_counter.inc(int(nb_steps))
            self._in_graph_counter.inc(elapsed)

    def report(self):
        total = time.monotonic() - self.start
        off_graph = total - self.in_graph_s
        info("Performance report:")
        info("  steps                 %d" % self.nb_steps)
        info("  total wall time       %.3f s" % total)
        info("  in-graph time         %.3f s (%.1f%%)" % (self.in_graph_s, 100.0 * self.in_graph_s / max(total, 1e-9)))
        info("  off-graph time        %.3f s (%.1f%%)" % (off_graph, 100.0 * off_graph / max(total, 1e-9)))
        info("  first (compile) step  %.3f s" % self.first_step_s)
        tail = self.latency.percentiles()
        if tail is not None:
            info("  step latency p50/p95/p99  %.1f / %.1f / %.1f ms"
                 % tuple(tail[name] * 1e3 for name, _ in LatencyHistogram.POINTS))
        if self.nb_steps > 0:
            info("  steps/s (all steps)   %.3f" % (self.nb_steps / max(total, 1e-9)))
        if self.nb_steps > 1:
            excl = (self.nb_steps - 1) / max(total - self.first_step_s, 1e-9)
            info("  steps/s (excl. 1st)   %.3f" % excl)

    def steps_per_s_excl_first(self):
        total = time.monotonic() - self.start
        if self.nb_steps <= 1:
            return 0.0
        return (self.nb_steps - 1) / max(total - self.first_step_s, 1e-9)

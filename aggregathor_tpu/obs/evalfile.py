"""Evaluation TSV log: ``walltime<TAB>step<TAB>name:value...`` per line.

Same format as the reference's evaluation thread output (runner.py:184-187,
394-399), so existing plotting scripts keep working.
"""

import time


class EvalFile:
    def __init__(self, path):
        self.path = path
        self._fd = open(path, "a") if path else None
        self._start = time.time()

    def append(self, step, metrics):
        if self._fd is None:
            return
        fields = ["%.6f" % (time.time() - self._start), str(int(step))]
        # Integral metrics (e.g. the chaos_regime index column) keep their
        # int spelling so downstream `cut`/`awk` filters can match exactly;
        # everything else stays the reference's float repr.
        fields += [
            "%s:%s" % (name, int(value) if isinstance(value, int) and not isinstance(value, bool)
                       else float(value))
            for name, value in sorted(metrics.items())
        ]
        self._fd.write("\t".join(fields) + "\n")
        self._fd.flush()

    def close(self):
        if self._fd is not None:
            self._fd.close()
            self._fd = None

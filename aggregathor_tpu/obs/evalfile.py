"""Evaluation TSV log: ``walltime<TAB>step<TAB>name:value...`` per line.

Same format as the reference's evaluation thread output (runner.py:184-187,
394-399), so existing plotting scripts keep working.

The file is opened in append mode, so a resumed run extends its predecessor's
log.  On restore (auto-resume or a guardian rollback) the runner calls
``truncate_after(restored_step)`` first: rows written beyond the restored
step belong to a timeline the run just abandoned, and appending after them
would leave duplicate/interleaved step columns that break every downstream
``sort -n``/plot assumption.
"""

import os
import time


class EvalFile:
    def __init__(self, path):
        self.path = path
        self._fd = open(path, "a") if path else None
        self._start = time.time()

    def truncate_after(self, step):
        """Drop rows with step > ``step`` (atomic rewrite); returns the
        number of rows dropped.  Malformed lines are conservatively kept."""
        if self._fd is None or not os.path.exists(self.path):
            return 0
        self._fd.close()
        with open(self.path) as fd:
            lines = fd.readlines()
        kept, dropped = [], 0
        for line in lines:
            fields = line.split("\t")
            try:
                row_step = int(fields[1])
            except (IndexError, ValueError):
                kept.append(line)
                continue
            if row_step <= step:
                kept.append(line)
            else:
                dropped += 1
        if dropped:
            tmp = self.path + ".tmp"
            with open(tmp, "w") as fd:
                fd.writelines(kept)
            os.replace(tmp, self.path)
        self._fd = open(self.path, "a")
        return dropped

    def append(self, step, metrics):
        if self._fd is None:
            return
        fields = ["%.6f" % (time.time() - self._start), str(int(step))]
        # Integral metrics (e.g. the chaos_regime index column) keep their
        # int spelling so downstream `cut`/`awk` filters can match exactly;
        # everything else stays the reference's float repr.
        fields += [
            "%s:%s" % (name, int(value) if isinstance(value, int) and not isinstance(value, bool)
                       else float(value))
            for name, value in sorted(metrics.items())
        ]
        self._fd.write("\t".join(fields) + "\n")
        self._fd.flush()

    def close(self):
        if self._fd is not None:
            self._fd.close()
            self._fd = None

"""Scalar summary events as JSONL.

The reference writes TF summaries (learning rate, eval metrics) through a
``FileWriter`` (graph.py:243, 291-292; runner.py:454-494).  The TF event-file
format buys nothing without TensorBoard in the loop; the portable equivalent
is one JSON object per event line — trivially greppable/plottable, and
convertible to TF events offline if ever needed.

Every line is stamped with the writer's ``run_id`` (given, or generated):
multi-process runs interleave their JSONL streams in one directory, and the
same id rides the trace file's metadata (``obs/trace.py``) and the
forensics report (``obs/forensics.py``), so streams, traces and attribution
reports join after the fact on one key.
"""

import itertools
import json
import time
import uuid


_serial = itertools.count()


def make_run_id():
    """A short unique run id (shared by summaries, traces, forensics)."""
    return uuid.uuid4().hex[:12]


class SummaryWriter:
    def __init__(self, directory, run_name="run", run_id=None):
        self.path = None
        self._fd = None
        self.run_id = run_id if run_id is not None else make_run_id()
        if directory:
            import os

            os.makedirs(directory, exist_ok=True)
            # pid disambiguates concurrent processes; the serial counter
            # disambiguates back-to-back runs within one process and second.
            self.path = os.path.join(
                directory,
                "%s-%d-%d-%d.jsonl" % (run_name, int(time.time()), os.getpid(), next(_serial)),
            )
            self._fd = open(self.path, "x")

    def scalars(self, step, values):
        """Write one event; values are scalars or small 1-D vectors (e.g. the
        per-worker suspicion diagnostics), serialized as JSON numbers/lists."""
        if self._fd is None:
            return

        def coerce(value):
            import numpy as np

            def finite(x):
                # json.dumps would emit bare NaN/Infinity tokens (non-strict
                # JSON, rejected by jq and most non-Python readers); masked
                # workers' NaN distance sums reach here, so they serialize
                # as null instead.
                x = float(x)
                return x if np.isfinite(x) else None

            if isinstance(value, (int, np.integer)) and not isinstance(value, bool):
                return int(value)  # e.g. suspect_worker stays an index
            try:
                return finite(value)
            except TypeError:
                return [finite(v) for v in value]

        event = {"wall": time.time(), "step": int(step), "run_id": self.run_id}
        event.update({name: coerce(value) for name, value in values.items()})
        self._fd.write(json.dumps(event) + "\n")
        self._fd.flush()

    def event(self, step, tag, payload=None):
        """Write one TAGGED event line (``{"event": tag, ...}``) — discrete
        occurrences like chaos regime transitions, as opposed to the cadenced
        scalar stream.  ``payload`` values must be JSON-serializable; the
        reserved ``wall``/``step``/``event``/``run_id`` fields always win
        over payload keys of the same name (stream consumers filter on
        them)."""
        if self._fd is None:
            return
        record = dict(payload) if payload else {}
        record.update({
            "wall": time.time(), "step": int(step), "event": str(tag),
            "run_id": self.run_id,
        })
        self._fd.write(json.dumps(record) + "\n")
        self._fd.flush()

    def close(self):
        if self._fd is not None:
            self._fd.close()
            self._fd = None

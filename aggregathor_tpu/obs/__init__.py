"""Observability: cadenced side-duties of the training loop.

The reference runs evaluation / checkpointing / summaries as polling daemon
threads sharing the TF session (reference: runner.py:356-494, cadence knobs at
config.py:54-61).  A jitted SPMD step has no session to share — the idiomatic
translation is cadence *triggers* checked between steps on the host, firing
the same step-delta / wall-period policies, plus a final fire at shutdown.

- ``CadenceTrigger``  step-delta / wall-period firing policy
- ``Checkpoints``     step-indexed train-state snapshots, auto-restore latest
- ``EvalFile``        the reference's TSV evaluation log format
- ``SummaryWriter``   JSONL scalar event log (summary-file parity)
- ``PerfReport``      steps/s report, first (compilation) step excluded
- ``LatencyHistogram``  bounded-reservoir p50/p95/p99 tail latency (shared by
  ``PerfReport`` and the serving ``/metrics`` endpoint)
"""

from .cadence import CadenceTrigger  # noqa: F401
from .checkpoint import Checkpoints  # noqa: F401
from .evalfile import EvalFile  # noqa: F401
from .summaries import SummaryWriter  # noqa: F401
from .perf import LatencyHistogram, PerfReport  # noqa: F401

"""Observability: cadenced side-duties of the training loop.

The reference runs evaluation / checkpointing / summaries as polling daemon
threads sharing the TF session (reference: runner.py:356-494, cadence knobs at
config.py:54-61).  A jitted SPMD step has no session to share — the idiomatic
translation is cadence *triggers* checked between steps on the host, firing
the same step-delta / wall-period policies, plus a final fire at shutdown.

- ``CadenceTrigger``  step-delta / wall-period firing policy
- ``Checkpoints``     step-indexed train-state snapshots, auto-restore latest
- ``EvalFile``        the reference's TSV evaluation log format
- ``SummaryWriter``   JSONL scalar event log (summary-file parity), every
  line stamped with the writer's ``run_id``
- ``PerfReport``      steps/s report, first (compilation) step excluded
- ``LatencyHistogram``  bounded-reservoir p50/p95/p99 tail latency (shared by
  ``PerfReport`` and the serving ``/metrics`` endpoint)

The telemetry pillars (docs/observability.md):

- ``trace``           host-side span tracer -> Chrome trace-event JSON
  (Perfetto-loadable); ``span(...)`` context manager/decorator, zero
  recompiles, near-zero cost disabled
- ``metrics``         process-wide counter/gauge/histogram registry with
  Prometheus text exposition (``MetricsRegistry``, default ``REGISTRY``)
- ``ForensicsLedger`` per-worker suspicion timeline -> Byzantine
  attribution report (schema ``aggregathor.obs.forensics.v1``)

The device-side layer (docs/observability.md "Device-side observability"):

- ``flight``          in-scan flight-recorder rings: per-step telemetry
  lanes written inside the jitted scan, fetched once per summary fire,
  dumped post-mortem (schema ``aggregathor.obs.flight.v1``)
- ``profiler``        step-windowed ``jax.profiler`` captures (``--xprof``),
  compile-cache-miss observability, device memory gauges
- ``live``            ``LiveExporter`` — the training run's own
  ``/metrics`` + ``/status`` HTTP endpoint
- ``slo``             regression sentinel: baseline documents (schema
  ``aggregathor.obs.slo.v1``) judged PASS/REGRESS at run end

The control room (docs/observability.md "The control room"):

- ``events``          causal run journal — typed, append-only JSONL
  decision events (schema ``aggregathor.obs.events.v2``): guardian
  rollbacks/escalations, deadline-window moves, stale infill, forgery
  verdicts, autoscale actions, weight swaps — ONE ``emit()`` API, every
  event type declared (graftcheck EV001 proves it statically) and every
  action event citing its cause (EV002)
- ``causal``          the causal plane — edge-respecting fleet journal
  merge + the postmortem audit (``cli.postmortem``; report schema
  ``aggregathor.obs.postmortem.v1``)
- ``fleet``           one-scrape federation — ``FleetCollector`` polls N
  child ``/metrics`` + ``/status`` endpoints and serves
  ``/fleet/metrics`` / ``/fleet/status`` / ``/fleet/journal`` from one
  port; a dead instance reads ``down`` with its last sample HELD

The causal plane (docs/observability.md "The causal plane"):

- ``causal``          the reader half of schema v2's ``cause`` edges —
  the edge-respecting deterministic fleet merge, the causal DAG audit
  and the ``aggregathor.obs.postmortem.v1`` checker behind
  ``cli.postmortem`` (exit code = verdict)
"""

from . import causal  # noqa: F401
from . import events  # noqa: F401
from . import flight  # noqa: F401
from . import live  # noqa: F401
from . import metrics  # noqa: F401
from . import profiler  # noqa: F401
from . import slo  # noqa: F401
from . import trace  # noqa: F401
from .cadence import CadenceTrigger  # noqa: F401
from .checkpoint import Checkpoints  # noqa: F401
from .evalfile import EvalFile  # noqa: F401
from .flight import FlightRecorder  # noqa: F401
from .forensics import ForensicsLedger  # noqa: F401
from .live import LiveExporter  # noqa: F401
from .summaries import SummaryWriter  # noqa: F401
from .perf import LatencyHistogram, PerfReport  # noqa: F401

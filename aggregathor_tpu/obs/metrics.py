"""Process-wide metrics registry with Prometheus text exposition.

Before this module the codebase kept THREE disjoint hand-rolled metric
surfaces: the training summary scalars (cli/runner.py), the ``PerfReport``
counters (obs/perf.py), and the serving ``/metrics`` JSON dict
(serve/server.py).  The registry unifies them: counters, gauges and
histograms registered by name (get-or-create, so every subsystem reaches
the same instrument), readable as a JSON-able snapshot AND as Prometheus
text exposition (format 0.0.4) — the serving ``/metrics`` endpoint
negotiates between the two, and training dumps the same exposition via
``--metrics-file``.

- :class:`Counter`    monotonically increasing float (``inc``)
- :class:`Gauge`      settable float, or a scrape-time callback
  (``set_function`` — queue depths and compile counts are read live)
- :class:`Histogram`  bucketed counts + sum for Prometheus, backed by
  ``obs.perf.LatencyHistogram`` as the reservoir for p50/p95/p99 readout —
  ``record``/``percentiles``/``count`` keep the LatencyHistogram API, so a
  registry histogram is a drop-in for the hand-rolled ones ``PerfReport``
  and the serving latency tracker used to own.

Labels: a metric created with ``labelnames`` is a *family*; ``.labels(v1,
...)`` (or keyword form) returns the per-labelset child, created on demand.
Exposition escapes label values per the Prometheus text format (backslash,
double quote, newline).

Everything is thread-safe; ``REGISTRY`` is the process-wide default.
:func:`parse_prometheus` is a minimal text-format parser used by the tests
and the smoke script to round-trip the exposition.
"""

import bisect
import re
import threading

from ..utils import UserException

_METRIC_NAME = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_NAME = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")

#: default histogram buckets (seconds — latency-shaped, like prometheus_client)
DEFAULT_BUCKETS = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
)


def _fmt(value):
    """Prometheus sample-value formatting: +Inf/-Inf/NaN spelled out."""
    if value != value:
        return "NaN"
    if value == float("inf"):
        return "+Inf"
    if value == float("-inf"):
        return "-Inf"
    return repr(float(value))


def escape_label_value(value):
    r"""Escape a label value for the text format: ``\`` ``"`` and newline."""
    return (
        str(value).replace("\\", r"\\").replace('"', r"\"").replace("\n", r"\n")
    )


def _escape_help(text):
    return str(text).replace("\\", r"\\").replace("\n", r"\n")


# --------------------------------------------------------------------- #
# children (one per labelset)


class Counter:
    """Monotonically increasing value.  ``inc`` only; decreasing raises."""

    def __init__(self):
        self._value = 0.0
        self._lock = threading.Lock()

    def inc(self, amount=1.0):
        amount = float(amount)
        if amount < 0.0:
            raise UserException("Counter can only increase (inc %g)" % amount)
        with self._lock:
            self._value += amount

    @property
    def value(self):
        with self._lock:
            return self._value


class Gauge:
    """Settable value, or a scrape-time callback (``set_function``)."""

    def __init__(self):
        self._value = 0.0
        self._fn = None
        self._lock = threading.Lock()

    def set(self, value):
        with self._lock:
            self._fn = None
            self._value = float(value)

    def inc(self, amount=1.0):
        with self._lock:
            self._value += float(amount)

    def dec(self, amount=1.0):
        self.inc(-amount)

    def set_function(self, fn):
        """Read ``fn()`` at scrape time instead of a stored value — live
        views (queue depth, compile count) without a writer loop."""
        with self._lock:
            self._fn = fn

    @property
    def value(self):
        with self._lock:
            fn = self._fn
            if fn is None:
                return self._value
        return float(fn())


class Histogram:
    """Cumulative-bucket histogram + reservoir percentiles.

    The Prometheus side is the classic fixed-bucket form (le-bucket counts,
    ``_sum``, ``_count``); the reservoir side reuses
    ``obs.perf.LatencyHistogram`` so ``percentiles()`` reports the same
    p50/p95/p99 the perf report and the serving JSON payload always did.
    ``record`` aliases ``observe`` for LatencyHistogram API compatibility.
    """

    def __init__(self, buckets=None, reservoir=None):
        from .perf import LatencyHistogram

        bounds = tuple(sorted(float(b) for b in (buckets or DEFAULT_BUCKETS)))
        if not bounds:
            raise UserException("Histogram wants at least one bucket bound")
        self.bounds = bounds
        self.reservoir = reservoir if reservoir is not None else LatencyHistogram()
        self._counts = [0] * (len(bounds) + 1)  # last slot: +Inf
        self._sum = 0.0
        self._lock = threading.Lock()

    def observe(self, value):
        value = float(value)
        self.reservoir.record(value)
        slot = bisect.bisect_left(self.bounds, value)
        with self._lock:
            self._counts[slot] += 1
            self._sum += value

    record = observe  # LatencyHistogram-compatible

    def percentiles(self):
        return self.reservoir.percentiles()

    @property
    def count(self):
        with self._lock:
            return sum(self._counts)

    @property
    def sum(self):
        with self._lock:
            return self._sum

    def cumulative_buckets(self):
        """[(le_bound, cumulative_count)] ending with (+Inf, total)."""
        with self._lock:
            counts = list(self._counts)
        out, running = [], 0
        for bound, count in zip(self.bounds + (float("inf"),), counts):
            running += count
            out.append((bound, running))
        return out


_KINDS = {"counter": Counter, "gauge": Gauge, "histogram": Histogram}


# --------------------------------------------------------------------- #
# families


class MetricFamily:
    """One named metric + its per-labelset children.  With no
    ``labelnames`` the family IS its single child: ``inc``/``set``/
    ``observe``/... delegate straight through."""

    def __init__(self, name, kind, help="", labelnames=(), **kwargs):
        if not _METRIC_NAME.match(name):
            raise UserException("Invalid metric name %r" % name)
        for label in labelnames:
            if not _LABEL_NAME.match(label):
                raise UserException("Invalid label name %r (metric %r)" % (label, name))
        if kind not in _KINDS:
            raise UserException("Unknown metric kind %r" % kind)
        self.name = name
        self.kind = kind
        self.help = help
        self.labelnames = tuple(labelnames)
        self._kwargs = kwargs
        self._children = {}
        self._lock = threading.Lock()
        if not self.labelnames:
            self._children[()] = _KINDS[kind](**kwargs)

    def labels(self, *values, **kv):
        """The child for one labelset (created on demand).  Positional
        values follow ``labelnames`` order; keyword form also accepted."""
        if kv:
            if values:
                raise UserException("labels() wants positional OR keyword values")
            try:
                values = tuple(kv.pop(name) for name in self.labelnames)
            except KeyError as exc:
                raise UserException("Missing label %s for metric %r" % (exc, self.name))
            if kv:
                raise UserException(
                    "Unknown label(s) %s for metric %r" % (sorted(kv), self.name)
                )
        values = tuple(str(v) for v in values)
        if len(values) != len(self.labelnames):
            raise UserException(
                "Metric %r wants %d label(s) %r, got %r"
                % (self.name, len(self.labelnames), self.labelnames, values)
            )
        with self._lock:
            child = self._children.get(values)
            if child is None:
                child = self._children[values] = _KINDS[self.kind](**self._kwargs)
            return child

    def children(self):
        with self._lock:
            return dict(self._children)

    # label-less convenience: the family acts as its single child
    def _solo(self):
        if self.labelnames:
            raise UserException(
                "Metric %r has labels %r; call .labels(...) first"
                % (self.name, self.labelnames)
            )
        return self._children[()]

    def inc(self, amount=1.0):
        return self._solo().inc(amount)

    def dec(self, amount=1.0):
        return self._solo().dec(amount)

    def set(self, value):
        return self._solo().set(value)

    def set_function(self, fn):
        return self._solo().set_function(fn)

    def observe(self, value):
        return self._solo().observe(value)

    record = observe

    def percentiles(self):
        return self._solo().percentiles()

    def cumulative_buckets(self):
        return self._solo().cumulative_buckets()

    @property
    def value(self):
        return self._solo().value

    @property
    def count(self):
        return self._solo().count

    @property
    def sum(self):
        return self._solo().sum


# --------------------------------------------------------------------- #
# registry


class MetricsRegistry:
    """Named metric families, get-or-create.  Re-requesting a name returns
    the existing family (so independent subsystems share instruments); a
    kind or labelnames mismatch fails loudly instead of silently forking
    the metric."""

    def __init__(self):
        self._families = {}
        self._lock = threading.Lock()

    def _get_or_create(self, name, kind, help, labelnames, **kwargs):
        with self._lock:
            family = self._families.get(name)
            if family is not None:
                if family.kind != kind or family.labelnames != tuple(labelnames):
                    raise UserException(
                        "Metric %r already registered as %s%r; cannot re-register "
                        "as %s%r" % (name, family.kind, family.labelnames,
                                     kind, tuple(labelnames))
                    )
                if kind == "histogram":
                    # a bucket mismatch must fail loudly too — returning the
                    # first registrant's bounds would silently misfile the
                    # second caller's observations
                    have = family._kwargs.get("buckets")
                    want = kwargs.get("buckets")
                    if have != want:
                        raise UserException(
                            "Histogram %r already registered with buckets %r; "
                            "cannot re-register with %r" % (name, have, want)
                        )
                return family
            family = MetricFamily(name, kind, help=help, labelnames=labelnames, **kwargs)
            self._families[name] = family
            return family

    def counter(self, name, help="", labelnames=()):
        return self._get_or_create(name, "counter", help, labelnames)

    def gauge(self, name, help="", labelnames=()):
        return self._get_or_create(name, "gauge", help, labelnames)

    def histogram(self, name, help="", labelnames=(), buckets=None, reservoir=None):
        # normalized up front so the mismatch check compares what Histogram
        # will actually use, not the caller's spelling
        bounds = tuple(sorted(float(b) for b in (buckets or DEFAULT_BUCKETS)))
        return self._get_or_create(
            name, "histogram", help, labelnames, buckets=bounds, reservoir=reservoir
        )

    def families(self):
        with self._lock:
            return [self._families[name] for name in sorted(self._families)]

    def unregister(self, name):
        """Drop a family (tests / re-configured servers)."""
        with self._lock:
            self._families.pop(name, None)

    # ------------------------------------------------------------------ #
    # readout

    def snapshot(self):
        """JSON-able view: name -> value (label-less) or
        ``{labelset_repr: value}``; histograms -> {count, sum, percentiles}."""
        out = {}
        for family in self.families():
            def one(child):
                if family.kind == "histogram":
                    return {
                        "count": child.count,
                        "sum": child.sum,
                        "percentiles": child.percentiles(),
                    }
                return child.value
            children = family.children()
            if not family.labelnames:
                out[family.name] = one(children[()])
            else:
                out[family.name] = {
                    ",".join("%s=%s" % kv for kv in zip(family.labelnames, values)):
                        one(child)
                    for values, child in sorted(children.items())
                }
        return out

    def render_prometheus(self):
        """Prometheus text exposition (format 0.0.4) of every family."""
        lines = []
        for family in self.families():
            lines.append("# HELP %s %s" % (family.name, _escape_help(family.help)))
            lines.append("# TYPE %s %s" % (family.name, family.kind))
            for values, child in sorted(family.children().items()):
                base_labels = list(zip(family.labelnames, values))

                def render_labels(extra=()):
                    pairs = base_labels + list(extra)
                    if not pairs:
                        return ""
                    return "{%s}" % ",".join(
                        '%s="%s"' % (k, escape_label_value(v)) for k, v in pairs
                    )

                if family.kind == "histogram":
                    for bound, cumulative in child.cumulative_buckets():
                        lines.append("%s_bucket%s %s" % (
                            family.name, render_labels([("le", _fmt(bound))]),
                            _fmt(cumulative),
                        ))
                    lines.append("%s_sum%s %s" % (
                        family.name, render_labels(), _fmt(child.sum)))
                    lines.append("%s_count%s %s" % (
                        family.name, render_labels(), _fmt(child.count)))
                else:
                    lines.append("%s%s %s" % (
                        family.name, render_labels(), _fmt(child.value)))
        return "\n".join(lines) + "\n"


#: the process-wide default registry — training, guardian and serving all
#: export through this one unless a caller injects its own (tests do)
REGISTRY = MetricsRegistry()

#: Content-Type of the text exposition
PROMETHEUS_CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


# --------------------------------------------------------------------- #
# text-format round-trip (tests + smoke script)

_SAMPLE = re.compile(
    r"^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)"
    r"(?:\{(?P<labels>.*)\})?"
    r"\s+(?P<value>[^\s]+)\s*$"
)
_LABEL = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')


def _unescape(value):
    out, i = [], 0
    while i < len(value):
        c = value[i]
        if c == "\\" and i + 1 < len(value):
            nxt = value[i + 1]
            out.append({"n": "\n", "\\": "\\", '"': '"'}.get(nxt, "\\" + nxt))
            i += 2
        else:
            out.append(c)
            i += 1
    return "".join(out)


def _parse_value(text):
    if text == "+Inf":
        return float("inf")
    if text == "-Inf":
        return float("-inf")
    return float(text)  # float("NaN") handles NaN

def parse_prometheus(text):
    """Parse text exposition into
    ``{name: {"type": t, "help": h, "samples": [(labels_dict, value)]}}``.

    A deliberately strict, minimal parser: any non-comment non-empty line
    that does not match the sample grammar raises ``ValueError`` — which is
    exactly what the round-trip tests and the smoke script want (a format
    regression must fail the scrape, not parse loosely)."""
    metrics = {}
    current = None
    for raw in text.splitlines():
        line = raw.strip()
        if not line:
            continue
        if line.startswith("# HELP "):
            _, _, rest = line.partition("# HELP ")
            name, _, help_text = rest.partition(" ")
            metrics.setdefault(name, {"type": None, "help": "", "samples": []})
            metrics[name]["help"] = help_text
            current = name
            continue
        if line.startswith("# TYPE "):
            _, _, rest = line.partition("# TYPE ")
            name, _, kind = rest.partition(" ")
            metrics.setdefault(name, {"type": None, "help": "", "samples": []})
            metrics[name]["type"] = kind.strip()
            current = name
            continue
        if line.startswith("#"):
            continue
        match = _SAMPLE.match(line)
        if not match:
            raise ValueError("Unparseable exposition line: %r" % raw)
        sample_name = match.group("name")
        labels = {}
        label_text = match.group("labels")
        if label_text:
            # strict walk: label pairs separated by single commas, nothing
            # between them (finditer would skip garbage separators)
            pos = 0
            while pos < len(label_text):
                lm = _LABEL.match(label_text, pos)
                if lm is None:
                    raise ValueError("Unparseable labels in line: %r" % raw)
                labels[lm.group(1)] = _unescape(lm.group(2))
                pos = lm.end()
                if pos < len(label_text):
                    if label_text[pos] != ",":
                        raise ValueError("Unparseable labels in line: %r" % raw)
                    pos += 1  # trailing comma before "}" is legal
        # histogram series (_bucket/_sum/_count) attach to their family
        family = sample_name
        for suffix in ("_bucket", "_sum", "_count"):
            base = sample_name[: -len(suffix)] if sample_name.endswith(suffix) else None
            if base and base in metrics and metrics[base]["type"] == "histogram":
                family = base
                break
        metrics.setdefault(family, {"type": None, "help": "", "samples": []})
        metrics[family]["samples"].append(
            (sample_name, labels, _parse_value(match.group("value")))
        )
        current = family
    del current
    return metrics

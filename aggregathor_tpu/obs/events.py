"""Causal run journal: typed, append-only decision events (JSONL).

The decisions that steer a run — guardian escalations, deadline-window
moves, stale infill, forgery verdicts, autoscale actions, weight swaps —
were scattered across info lines, summary events, forensics records and
trace instants with no single causal timeline.  The journal is that
timeline: ONE append-only JSONL file per process (schema
``aggregathor.obs.events.v2``; v1 files still load), one :func:`emit`
API threaded through the
guardian, the deadline controller, bounded-wait, the secure verdicts and
serve's autoscaler/weight-watcher, so a post-mortem starts from one file
instead of five.

Design rules (the trace.py discipline, docs/observability.md):

- **Host-side only, zero compiles touched.**  Every emit is a dict + one
  buffered line write; the jitted programs never see the journal (compile
  counts asserted equal with it on and off, tests/test_events.py).
- **Typed, fail-loud.**  Every event type is DECLARED in
  :data:`EVENT_TYPES`; emitting an undeclared type raises even when no
  journal is installed — the graftcheck EV001 probe
  (``analysis/events_check.py``) proves the same property statically over
  the whole package.
- **Causally orderable.**  Every event carries the run id, the step it
  speaks about (None for step-less serving events), a ``seq`` strictly
  increasing per file, wall time (``t_wall``, joins across processes) and
  monotonic time (``t_mono``, orders within one) — so ``/fleet/journal``
  (obs/fleet.py) can merge several processes' journals into one timeline.
- **Causally LINKED (schema v2).**  An event may cite the event that
  triggered it through the optional ``cause`` field — a validated
  ``{"instance", "run_id", "seq"}`` reference (``instance`` None = the
  same journal).  Cause references survive process boundaries as tokens
  (``format_cause``/``parse_cause``: the router's ``X-Causal-Id`` header,
  the supervisor's ``--cause`` argv injection), so the fleet merge
  (obs/causal.py) can order effects after their causes even when clock
  skew says otherwise.  v1 journals (no ``cause``) still load.
- **Bounded on disk.**  A journal constructed with ``max_bytes`` rotates
  to ``path.1``, ``path.2``, … segment files once the live file crosses
  the limit; :func:`tail_journal` cursors follow the rotation loudly
  (a vanished segment raises, it is never skipped).
- **Cross-referenced.**  Events carry pointers into the OTHER evidence
  stores instead of duplicating them: a ``flight_postmortem`` event names
  the dump path (obs/flight.py), ``run_end`` names the forensics report,
  and the forensics report's ``journal`` section points back here.
- **Near-zero cost disabled.**  ``emit`` without an installed journal is a
  dict-membership check and a return.

Non-finite floats are encoded as tagged strings (``"nan"``/``"inf"``/
``"-inf"``, the flight-recorder idiom) so every line is strict JSON;
:func:`decode_event` restores them.  :func:`load_journal` validates a
whole file and is what the smoke scripts and ``/fleet/journal`` read
through.

Usage::

    from aggregathor_tpu.obs import events
    events.install("run.journal.jsonl", run_id=run_id)
    events.emit("guardian_rollback", step=120, reason="spike", attempt=0)
    events.uninstall()     # flush + close
"""

import collections
import json
import os
import threading
import time

import numpy as np

SCHEMA_V1 = "aggregathor.obs.events.v1"
SCHEMA = "aggregathor.obs.events.v2"

#: schemas :func:`validate_event` accepts on load — new journals are
#: written as v2; v1 files (pre-``cause``) remain loadable forever
ACCEPTED_SCHEMAS = (SCHEMA_V1, SCHEMA)

#: the declared event catalog: type -> one-line meaning.  EVERY ``emit``
#: call in the package must name one of these (enforced at runtime here
#: and statically by graftcheck EV001); docs/observability.md "The control
#: room" is the long-form catalog.
EVENT_TYPES = {
    "run_start": "a process opened its journal (role, config description)",
    "run_end": "a process closed its journal (final step, verdict, "
               "cross-refs to the forensics report / flight dumps)",
    "guardian_rollback_decision": "the watchdog decided to roll back "
                                  "(reason: non-finite / spike / "
                                  "straggler_timeouts / deadline_ceiling)",
    "guardian_rollback": "a rollback executed: restore step, attempt "
                         "index, cooldown horizon",
    "guardian_escalation": "an escalation-ladder rung applied (rung spec, "
                           "resulting overrides)",
    "guardian_recovered": "the run stayed healthy long enough after a "
                          "rollback to be declared recovered",
    "deadline_window": "the adaptive bounded-wait window moved, censored, "
                       "or changed its at-ceiling verdict",
    "bounded_round": "a bounded-wait round closed with timeouts, stale "
                     "infills or skipped (still-in-flight) units",
    "forgery_verdict": "submission tags failed HMAC verification "
                       "(reject-and-name, secure/submit.py)",
    "serve_autoscale": "the serving autoscaler applied a capacity-rung "
                       "move (lanes / retired replicas)",
    "serve_weight_swap": "the weight pipeline hot-swapped a newer "
                         "snapshot in",
    "serve_weight_swap_failed": "a reload was refused or failed; previous "
                                "weights kept serving",
    "flight_postmortem": "a flight-recorder window was dumped "
                         "(cross-ref: the dump path holds the per-step "
                         "evidence)",
    "serve_drain": "a serving process entered (or finished) its SIGTERM "
                   "drain: in-flight requests complete, new traffic "
                   "re-routes through the fleet router",
    "router_route": "the fleet router assigned (or re-assigned) a client "
                    "to a backend FOR A CAUSE (reason: initial / "
                    "backend_down / drain / step_pin); steady-state "
                    "least-in-flight rebalances stay off the timeline",
    "router_shed": "the fleet router refused admission (429): every "
                   "healthy backend is saturated — a FLEET decision, "
                   "never one process's registry",
    "router_retry": "a request whose backend died mid-flight was "
                    "re-dispatched onto a live backend (exactly once)",
    "router_backend_down": "a backend transitioned to down (scrape "
                           "misses or a failed forward)",
    "router_backend_up": "a down backend recovered on a successful "
                         "scrape and re-entered the routable pool",
    "router_drain": "the router observed a backend draining and stopped "
                    "routing new traffic to it",
    "router_step_pin": "a client's weights_step pin advanced — routing "
                       "is now constrained to backends at >= this step "
                       "(the fleet-wide monotone-sequence guarantee)",
    "supervisor_restart": "the fleet supervisor restarted a dead or hung "
                          "instance (attempt index, backoff horizon, the "
                          "down-judgment evidence)",
    "supervisor_quarantine": "a crash-looping instance exhausted its "
                             "restart budget and was QUARANTINED instead "
                             "of restarted forever (flap damping)",
    "supervisor_retune": "the supervisor rewrote an instance's knobs and "
                         "gracefully restarted it — the Overrides "
                         "rebuild discipline one level up (rung spec, "
                         "the sustained-regime evidence)",
    "supervisor_rollback": "a sentinel REGRESS rolled the checkpoint "
                           "timeline back through the custody path "
                           "(restore step, discarded steps, verdict ref)",
    "supervisor_observe": "the supervisor saw a symptom but is "
                          "deliberately waiting (backoff not elapsed, "
                          "hysteresis, finished instance) — the no-op "
                          "arm of the action ladder, journaled so the "
                          "causal story has no gaps",
    "topology_level_timeout": "a tree level's bounded-wait window closed "
                              "on a straggling sub-aggregator unit — the "
                              "whole subtree timed out as one row "
                              "(topology/tree.py)",
    "topology_reconstruction": "a faulted sub-aggregator's summary was "
                               "served by a verified redundant sibling "
                               "shadow instead of spending the level's f "
                               "budget",
    "topology_corruption_verdict": "a sub-aggregator's custody tag failed "
                                   "chain verification — NAMED as a "
                                   "(level, unit) sub-aggregator, not "
                                   "laundered into worker blame",
    "stale_reweight": "a stale carry row re-entered aggregation damped by "
                      "its age coefficient c(a) = 1/(1+a) (worker, age, "
                      "coefficient — bounded-wait v3, still spends the f "
                      "budget)",
    "submesh_timeout": "a (pipe x model) submesh missed its bounded-wait "
                       "window and forfeited its k logical rows as a unit "
                       "(group, forfeited — bounded-wait v3 per-submesh "
                       "deadlines)",
}

#: fields every event carries (plus the optional ``cause``); ``emit``
#: keyword fields may not shadow them
BASE_FIELDS = ("schema", "type", "run_id", "seq", "step", "t_wall", "t_mono",
               "cause")

#: event types that ACTUATE (change the fleet) rather than observe — every
#: emit of one of these must pass an explicit ``cause=`` keyword (None is
#: legal when no journal event triggered it, e.g. a liveness restart whose
#: evidence is the ABSENCE of scrapes); graftcheck EV001 proves the
#: discipline statically and obs/causal.py audits the written journals.
ACTION_EVENT_TYPES = frozenset((
    "supervisor_restart",
    "supervisor_quarantine",
    "supervisor_retune",
    "supervisor_rollback",
    "supervisor_observe",
    "router_retry",
    "guardian_rollback",
    "topology_level_timeout",
    "topology_corruption_verdict",
    "topology_reconstruction",
))

_undeclared_actions = ACTION_EVENT_TYPES - set(EVENT_TYPES)
if _undeclared_actions:       # fail-loud at import: the two catalogs may not drift
    raise AssertionError(
        "ACTION_EVENT_TYPES not in EVENT_TYPES: %s"
        % ", ".join(sorted(_undeclared_actions)))

#: the process-wide installed journal (None = journaling disabled)
_journal = None


def _encode(value):
    """Strict-JSON encoding: numpy scalars/arrays unwrapped, non-finite
    floats as tagged strings (the flight-recorder idiom — a journal must
    keep the difference between NaN and ±inf)."""
    if isinstance(value, dict):
        return {str(k): _encode(v) for k, v in value.items()}
    if isinstance(value, (list, tuple)):
        return [_encode(v) for v in value]
    if isinstance(value, np.ndarray):
        return [_encode(v) for v in value.tolist()]
    if isinstance(value, (bool, np.bool_)):
        return bool(value)
    if isinstance(value, (int, np.integer)):
        return int(value)
    if isinstance(value, (float, np.floating)):
        value = float(value)
        if value != value:
            return "nan"
        if value in (float("inf"), float("-inf")):
            return "inf" if value > 0 else "-inf"
        return value
    if value is None or isinstance(value, str):
        return value
    return str(value)


def decode_value(value):
    """Inverse of the non-finite tagging (recursive): the exact strings
    ``"nan"``/``"inf"``/``"-inf"`` become floats again.  Event fields that
    legitimately hold those strings must spell them differently."""
    if isinstance(value, dict):
        return {k: decode_value(v) for k, v in value.items()}
    if isinstance(value, list):
        return [decode_value(v) for v in value]
    if value == "nan":
        return float("nan")
    if value == "inf":
        return float("inf")
    if value == "-inf":
        return float("-inf")
    return value


def decode_event(record):
    """A copy of one journal record with tagged non-finite floats restored."""
    return {key: decode_value(value) for key, value in record.items()}


# --------------------------------------------------------------------- #
# cause references (schema v2)

#: the exact key set of a cause reference
CAUSE_KEYS = frozenset(("instance", "run_id", "seq"))


def validate_cause(cause):
    """Structural check of one cause reference.  Returns the reference;
    raises ``ValueError`` on violations.  ``instance`` None means "the
    journal this event was written to" (resolved by the fleet merge);
    ``run_id`` None cites a record whose own run_id is null."""
    if not isinstance(cause, dict):
        raise ValueError("cause reference is not an object: %r" % (cause,))
    if set(cause) != CAUSE_KEYS:
        raise ValueError(
            "cause reference wants exactly keys %s, got %s"
            % (sorted(CAUSE_KEYS), sorted(cause)))
    if not isinstance(cause["seq"], int) or isinstance(cause["seq"], bool) \
            or cause["seq"] < 0:
        raise ValueError(
            "cause reference wants an int seq >= 0: %r" % (cause,))
    for key in ("instance", "run_id"):
        value = cause[key]
        if value is not None and not isinstance(value, str):
            raise ValueError(
                "cause reference %s must be str or null: %r" % (key, value))
    return cause


def _normalize_cause(cause):
    """Accept a validated dict or an ``(instance, run_id, seq)`` triple."""
    if isinstance(cause, (tuple, list)):
        if len(cause) != 3:
            raise ValueError(
                "cause triple wants (instance, run_id, seq), got %r" % (cause,))
        cause = {"instance": cause[0], "run_id": cause[1], "seq": cause[2]}
    return validate_cause(cause)


def cause_of(record, instance=None):
    """A cause reference citing ``record`` (a loaded journal record or an
    :meth:`Journal.emit` return value).  ``instance`` names the fleet
    instance whose journal holds the record; None = the same journal the
    citing event is written to."""
    return validate_cause({
        "instance": instance,
        "run_id": record.get("run_id"),
        "seq": record["seq"],
    })


def format_cause(cause):
    """Serialize a cause reference to the one-token wire form
    ``INSTANCE:RUN_ID:SEQ`` (empty instance/run_id encode None) — the
    router's ``X-Causal-Id`` header and the supervisor's ``--cause`` argv
    flag.  ``instance`` may not contain ``:`` (run_id may — the token
    splits instance off the front and seq off the back)."""
    cause = _normalize_cause(cause)
    instance = cause["instance"] or ""
    if ":" in instance:
        raise ValueError(
            "cause instance %r may not contain ':' (the token separator)"
            % (instance,))
    return "%s:%s:%d" % (instance, cause["run_id"] or "", cause["seq"])


def parse_cause(token):
    """Inverse of :func:`format_cause`; raises ``ValueError`` on garbage."""
    if not isinstance(token, str):
        raise ValueError("cause token is not a string: %r" % (token,))
    instance, sep, rest = token.partition(":")
    if not sep:
        raise ValueError(
            "cause token %r wants INSTANCE:RUN_ID:SEQ (instance/run_id "
            "may be empty)" % (token,))
    run_id, sep, seq = rest.rpartition(":")
    if not sep:
        raise ValueError(
            "cause token %r wants INSTANCE:RUN_ID:SEQ (instance/run_id "
            "may be empty)" % (token,))
    try:
        seq = int(seq)
    except ValueError:
        raise ValueError("cause token %r: seq %r is not an int" % (token, seq))
    return validate_cause({
        "instance": instance or None,
        "run_id": run_id or None,
        "seq": seq,
    })


class Journal:
    """One append-only JSONL journal file.  Use the module-level
    :func:`install` / :func:`emit` / :func:`uninstall` in application code;
    construct directly only in tests (clocks injectable)."""

    def __init__(self, path, run_id=None, wall_clock=None, mono_clock=None,
                 max_bytes=None):
        self.path = path
        self.run_id = run_id
        self._wall = wall_clock if wall_clock is not None else time.time
        self._mono = mono_clock if mono_clock is not None else time.monotonic
        self._lock = threading.Lock()
        self._seq = 0
        self._counts = {}
        if max_bytes is not None and (not isinstance(max_bytes, int)
                                      or max_bytes < 1):
            raise ValueError(
                "journal max_bytes must be a positive int or None, got %r"
                % (max_bytes,))
        self.max_bytes = max_bytes
        directory = os.path.dirname(path)
        if directory:
            os.makedirs(directory, exist_ok=True)
        # a resumed run may find rotated segments from its predecessor:
        # continue the numbering instead of overwriting history
        self._nb_rotations = 0
        while os.path.exists("%s.%d" % (path, self._nb_rotations + 1)):
            self._nb_rotations += 1
        # append mode: a journal survives the process that wrote it and a
        # resumed run extends the same causal file instead of replacing it
        self._fd = open(path, "a")

    def _rotate_locked(self):
        """Roll the live file to ``path.N`` and start a fresh segment file
        (seq restarts at 0 — each segment file validates standalone and the
        cross-file chain reads as a resumed segment)."""
        self._fd.close()
        self._nb_rotations += 1
        os.replace(self.path, "%s.%d" % (self.path, self._nb_rotations))
        self._fd = open(self.path, "a")
        self._seq = 0

    @property
    def nb_rotations(self):
        """How many ``path.N`` segment files this journal has rolled."""
        with self._lock:
            return self._nb_rotations

    def emit(self, etype, step=None, cause=None, **fields):
        """Append one event; returns the written record (decoded form).
        ``cause`` optionally cites the triggering event — a validated
        reference dict (:func:`validate_cause`) or an ``(instance, run_id,
        seq)`` triple."""
        if etype not in EVENT_TYPES:
            raise ValueError(
                "undeclared journal event type %r (declare it in "
                "obs.events.EVENT_TYPES; registered: %s)"
                % (etype, ", ".join(sorted(EVENT_TYPES)))
            )
        clash = sorted(set(fields) & set(BASE_FIELDS))
        if clash:
            raise ValueError(
                "journal event %r fields %r shadow the base fields" % (etype, clash)
            )
        if cause is not None:
            cause = _normalize_cause(cause)
        with self._lock:
            if self._fd is None:
                raise ValueError(
                    "journal %r is closed; emit of %r refused" % (self.path, etype)
                )
            record = {
                "schema": SCHEMA,
                "type": etype,
                "run_id": self.run_id,
                "seq": self._seq,
                "step": None if step is None else int(step),
                "t_wall": self._wall(),
                "t_mono": self._mono(),
            }
            if cause is not None:
                record["cause"] = cause
            record.update(_encode(fields))
            self._seq += 1
            self._counts[etype] = self._counts.get(etype, 0) + 1
            self._fd.write(json.dumps(record) + "\n")
            self._fd.flush()
            # rotate AFTER the write: a record never splits across segments
            if self.max_bytes is not None and self._fd.tell() >= self.max_bytes:
                self._rotate_locked()
        return record

    def counts_by_type(self):
        """{event_type: emitted count} for THIS journal instance — what the
        forensics report's ``journal`` section records."""
        with self._lock:
            return dict(self._counts)

    @property
    def nb_events(self):
        with self._lock:
            return self._seq

    def close(self):
        with self._lock:
            if self._fd is not None:
                self._fd.close()
                self._fd = None


# --------------------------------------------------------------------- #
# module-level lifecycle (the trace.py shape)


def install(path, run_id=None, wall_clock=None, mono_clock=None,
            max_bytes=None):
    """Enable journaling process-wide, appending to ``path``.  Installing
    over a live journal closes the old one first."""
    global _journal
    if _journal is not None:
        _journal.close()
    _journal = Journal(path, run_id=run_id, wall_clock=wall_clock,
                       mono_clock=mono_clock, max_bytes=max_bytes)
    return _journal


def installed():
    """The active journal, or None when journaling is disabled."""
    return _journal


def emit(etype, step=None, cause=None, **fields):
    """Append one event to the installed journal (validates the type even
    when disabled — an undeclared emit must fail in every configuration)."""
    journal = _journal
    if journal is None:
        if etype not in EVENT_TYPES:
            raise ValueError(
                "undeclared journal event type %r (declare it in "
                "obs.events.EVENT_TYPES)" % (etype,)
            )
        return None
    return journal.emit(etype, step=step, cause=cause, **fields)


def uninstall():
    """Disable journaling; flush + close.  Returns the journal's path (or
    None when nothing was installed)."""
    global _journal
    journal, _journal = _journal, None
    if journal is not None:
        journal.close()
        return journal.path
    return None


# --------------------------------------------------------------------- #
# validation + load (tests, smoke scripts, /fleet/journal)


def validate_event(record):
    """Structural check of one journal record (encoded form).  Returns the
    record; raises ``ValueError`` on violations."""
    if not isinstance(record, dict):
        raise ValueError("journal event is not an object: %r" % (record,))
    schema = record.get("schema")
    if schema not in ACCEPTED_SCHEMAS:
        raise ValueError(
            "expected schema in %s, got %r" % (list(ACCEPTED_SCHEMAS), schema)
        )
    cause = record.get("cause")
    if cause is not None:
        if schema == SCHEMA_V1:
            raise ValueError(
                "journal event carries a cause under schema %r (cause "
                "references are v2): %r" % (schema, record))
        try:
            validate_cause(cause)
        except ValueError as exc:
            raise ValueError("journal event cause: %s" % (exc,))
    etype = record.get("type")
    if etype not in EVENT_TYPES:
        raise ValueError("undeclared journal event type %r" % (etype,))
    if not isinstance(record.get("seq"), int) or record["seq"] < 0:
        raise ValueError("journal event wants an int seq >= 0: %r" % (record,))
    step = record.get("step")
    if step is not None and not isinstance(step, int):
        raise ValueError("journal event step must be int or null: %r" % (step,))
    for key in ("t_wall", "t_mono"):
        if not isinstance(record.get(key), (int, float)):
            raise ValueError(
                "journal event wants numeric %r: %r" % (key, record)
            )
    run_id = record.get("run_id")
    if run_id is not None and not isinstance(run_id, str):
        raise ValueError("journal event run_id must be str or null: %r" % (run_id,))
    return record


#: resumable read position in one journal: ``offset`` is the byte offset
#: of the first unread line IN THE FILE CURRENTLY BEING READ, ``line`` the
#: 1-based number that line will carry in error messages, ``segment`` how
#: many seq-restart segments have been consumed, ``last_seq`` the seq of
#: the last validated record (None before the first), and ``rotated`` how
#: many rolled ``path.N`` files have been fully consumed (the cursor
#: currently points into ``path.{rotated+1}`` if that file exists, else
#: the live ``path``).  Immutable — each :func:`tail_journal` call returns
#: a NEW cursor, so a caller can retry a failed poll from the old one.
TailCursor = collections.namedtuple(
    "TailCursor", ("offset", "line", "segment", "last_seq", "rotated"),
    defaults=(0,))

#: the start-of-file cursor (segment 0, nothing consumed yet)
TAIL_START = TailCursor(offset=0, line=1, segment=0, last_seq=None, rotated=0)


def _validate_line(nb, line, last_seq):
    """Parse + validate ONE journal line against the chain state.  The
    single validation path under both :func:`load_journal` and
    :func:`tail_journal` — contiguity semantics cannot drift between the
    whole-file and incremental readers.  Returns ``(record, resumed)``
    where ``resumed`` flags a new segment (seq restarted at 0)."""
    try:
        record = json.loads(line)
    except json.JSONDecodeError as exc:
        raise ValueError("journal line %d does not parse: %s" % (nb, exc))
    try:
        validate_event(record)
    except ValueError as exc:
        raise ValueError("journal line %d: %s" % (nb, exc))
    if last_seq is not None:
        if record["seq"] not in (last_seq + 1, 0):
            raise ValueError(
                "journal line %d: seq %d breaks the chain "
                "(previous %d wants %d, or 0 for a resumed "
                "segment)" % (nb, record["seq"], last_seq, last_seq + 1)
            )
        return record, record["seq"] == 0
    if record["seq"] != 0:
        raise ValueError(
            "journal line %d: first segment must start at seq 0, "
            "got %d" % (nb, record["seq"])
        )
    return record, False


def load_journal(path):
    """Load + validate one journal file.  Returns the event records in file
    order (encoded form — see :func:`decode_event`); raises ``ValueError``
    on schema violations or a broken ``seq`` chain: within a segment each
    seq must be exactly the previous + 1, and a new segment (an appended
    resume — same or different run_id) must begin at 0.  Two processes
    interleaving appends into one file break contiguity within a line or
    two and fail here — point concurrent writers at DISTINCT paths (the
    fleet collector merges them)."""
    # A whole-file load of a missing journal is an error (the fleet
    # collector reports it as "not written yet") — only the incremental
    # tail treats missing-at-start-of-file as an empty poll.
    with open(path, "rb"):
        pass
    records, _ = tail_journal(path)
    return records


def _tail_file(path, offset, nb, segment, last_seq, allow_missing,
               finalize=False):
    """Read + validate one physical file from ``offset`` on.  Returns
    ``(records, offset, nb, segment, last_seq)``.  ``finalize`` marks a
    rotated (closed) segment: a torn trailing line there is permanent
    damage and raises instead of being deferred to the next poll."""
    records = []
    try:
        fd = open(path, "rb")
    except OSError:
        if offset or not allow_missing:
            raise ValueError(
                "journal %r vanished behind its tail cursor (offset %d)"
                % (path, offset))
        return records, offset, nb, segment, last_seq
    with fd:
        fd.seek(0, os.SEEK_END)
        size = fd.tell()
        if size < offset:
            raise ValueError(
                "journal %r shrank below its tail cursor (size %d < "
                "offset %d): truncated or replaced behind the reader"
                % (path, size, offset))
        fd.seek(offset)
        while True:
            line = fd.readline()
            if not line:
                break
            if not line.endswith(b"\n"):
                if finalize:
                    raise ValueError(
                        "rotated journal segment %r ends mid-line at "
                        "offset %d: the writer can never finish it"
                        % (path, offset))
                break     # a writer mid-append: re-read next poll
            offset += len(line)
            stripped = line.strip()
            if stripped:
                record, resumed = _validate_line(
                    nb, stripped.decode("utf-8"), last_seq)
                if resumed:
                    segment += 1
                last_seq = record["seq"]
                records.append(record)
            nb += 1
    return records, offset, nb, segment, last_seq


def tail_journal(path, cursor=None):
    """Incremental :func:`load_journal`: read + validate only the records
    appended since ``cursor`` (a :data:`TailCursor` from a previous call;
    None or :data:`TAIL_START` reads from the beginning).  Returns
    ``(new_records, next_cursor)``.

    The chain check continues ACROSS calls — the cursor carries the
    (segment, seq) position, so a seq break at a poll boundary fails
    exactly as it would in one whole-file load.  A trailing line without
    its newline (a writer mid-append) is left for the next call rather
    than half-parsed; a file shorter than the cursor's offset (truncated
    or replaced behind the reader) raises.  Missing file with a
    start-of-file cursor is an empty poll — the supervisor tails journals
    of instances that have not opened them yet.

    Rotation-aware: when the writer rolled the live file to ``path.N``
    (``Journal(max_bytes=...)``), the cursor follows — it finishes the
    rolled segment it was reading, then advances through younger segments
    to the live file.  A rotated segment that vanished or was torn behind
    the cursor raises (rotation must never silently drop history)."""
    if cursor is None:
        cursor = TAIL_START
    offset, nb, segment, last_seq, rotated = cursor
    records = []
    while True:
        rolled = "%s.%d" % (path, rotated + 1)
        if not os.path.exists(rolled):
            if os.path.exists("%s.%d" % (path, rotated + 2)):
                raise ValueError(
                    "rotated journal segment %r vanished behind its tail "
                    "cursor (younger segments exist)" % (rolled,))
            break
        # the file the cursor points into was rolled to ``rolled`` (or it
        # is an older rolled segment not yet consumed): finish it whole,
        # then restart at the top of the next file
        got, offset, nb, segment, last_seq = _tail_file(
            rolled, offset, nb, segment, last_seq, allow_missing=False,
            finalize=True)
        records.extend(got)
        rotated += 1
        offset = 0
        nb = 1
    # a missing live file at offset 0 is an empty poll (not opened yet, or
    # the writer is between its rotation rename and the fresh open)
    got, offset, nb, segment, last_seq = _tail_file(
        path, offset, nb, segment, last_seq, allow_missing=(offset == 0))
    records.extend(got)
    return records, TailCursor(offset=offset, line=nb, segment=segment,
                               last_seq=last_seq, rotated=rotated)


def counts_by_type(records):
    """{event_type: count} over loaded records (load_journal output)."""
    counts = {}
    for record in records:
        counts[record["type"]] = counts.get(record["type"], 0) + 1
    return counts

"""Step-indexed train-state checkpoints.

Parity with the reference's ``tools.Checkpoints`` (tools/tf.py:78-173):
files ``<base>-<step>.ckpt`` in a directory, discovery by scanning and
sorting by step, ``can_restore`` / ``restore`` (latest or a given step) /
``save``, auto-restore of the latest at training start (runner.py:514-525).

A last-known-good **pin** (``pin``/``pinned_step``) marks one step as exempt
from ``max_to_keep`` pruning: the guardian (cli/runner.py) pins the newest
snapshot saved while the run was healthy, so rollback always has a clean
restore target even after the cadence wrote ``max_to_keep`` poisoned
snapshots past it.

Snapshots are the full TrainState pytree (params, optimizer state, step, rng)
serialized with ``flax.serialization`` (msgpack); restore deserializes into a
freshly-initialized template state, so shape/dtype mismatches fail loudly.
Writes are atomic (tmp file + rename) so a killed run never leaves a torn
latest checkpoint.

Optional authentication: pass ``authenticator`` (a
``parallel.auth.GradientAuthenticator``) and every snapshot is HMAC-tagged
in a ``.tag`` sidecar and verified on restore — the host-boundary
counterpart of the reference's signed tensor pushes (docs/transport.md).

Optional at-rest encryption: pass ``cipher`` (a
``parallel.crypto.SnapshotCipher``) and snapshot bytes are encrypted before
hitting disk — the framework-side counterpart of the reference's TLS
channels (grpc_channel.patch:70-85) for the state that outlives the run.
With both, the tag covers the CIPHERTEXT (encrypt-then-MAC): restore
rejects tampering before deriving a single keystream byte.

Optional background writes (``background=True``, orbax-style): ``save``
fetches the state to host synchronously — the caller may donate the device
buffers to its very next step dispatch, so the device_get cannot be
deferred — then hands serialization + HMAC + disk I/O + pruning to a
single worker thread and returns.  ``wait()`` joins pending writes and
re-raises any failure; the runner calls it before exiting and the reference
semantics (a completed ``save`` is restorable) hold once it returns.
"""

import os
import re

import flax.serialization
import jax

from . import trace
from ..utils import UserException, info, warning


class Checkpoints:
    def __init__(self, directory, base_name="model", max_to_keep=5, authenticator=None,
                 background=False, allow_legacy_tags=True, cipher=None, custody=None):
        self.directory = directory
        self.base_name = base_name
        self.max_to_keep = int(max_to_keep)
        self.authenticator = authenticator
        self.cipher = cipher
        # Chain of custody (secure/custody.py): when set, every save writes
        # a signed lineage manifest beside the snapshot (run id, GAR spec,
        # data digest, submission tag chain) and every restore VERIFIES it
        # before deserialization — the train -> sign -> serve provenance the
        # serving restore path also checks.  The lineage fields are
        # snapshotted on the save caller's thread (``lineage``), so the
        # background writer signs the chain head as of the save.
        self.custody = custody
        # One-time migration for snapshots tagged before key derivation
        # gained domain separation: when True, a tag minted under the OLD
        # scheme (same secret) is accepted at restore and the snapshot is
        # immediately re-tagged under the current scheme. Operators whose
        # snapshots are all current-scheme can set False to close the
        # downgrade path entirely.
        self.allow_legacy_tags = bool(allow_legacy_tags)
        self._pattern = re.compile(re.escape(base_name) + r"-(\d+)\.ckpt$")
        # Last-known-good pin (guardian rollback): the pinned step is
        # excluded from max_to_keep pruning, so the snapshot the watchdog
        # would roll back to survives however many unhealthy snapshots the
        # cadence writes after it.  Read by the single writer thread and
        # written by the caller thread — a plain attribute is safe (atomic
        # reference assignment; staleness only delays one prune).
        self._pinned = None
        self._pool = None
        self._pending = []
        if background:
            import concurrent.futures

            # One worker: writes (and their prunes) stay strictly ordered.
            self._pool = concurrent.futures.ThreadPoolExecutor(
                max_workers=1, thread_name_prefix="ckpt"
            )
        if directory:
            os.makedirs(directory, exist_ok=True)

    def _path(self, step):
        return os.path.join(self.directory, "%s-%d.ckpt" % (self.base_name, step))

    def steps(self):
        """Sorted list of steps with an on-disk snapshot (tools/tf.py:92-102)."""
        if not self.directory or not os.path.isdir(self.directory):
            return []
        found = []
        for name in os.listdir(self.directory):
            match = self._pattern.match(name)
            if match:
                found.append(int(match.group(1)))
        return sorted(found)

    def can_restore(self, step=None):
        steps = self.steps()
        return bool(steps) if step is None else step in steps

    def pin(self, step):
        """Pin ``step`` as last-known-good: its snapshot survives
        ``max_to_keep`` pruning until a newer pin replaces it.  Pinning a
        new step releases the previous pin (the old snapshot becomes
        ordinary and prunable again)."""
        self._pinned = None if step is None else int(step)

    def pinned_step(self):
        """The pinned step if its snapshot is on disk, else None."""
        pinned = self._pinned
        return pinned if pinned is not None and self.can_restore(pinned) else None

    def discard_after(self, step):
        """Remove every snapshot with step > ``step`` — the abandoned
        timeline after a guardian rollback.  Without this, a later
        auto-restore (this run killed, then relaunched) would resurrect the
        newest — poisoned — snapshot instead of the rolled-back-to one.
        Call ``wait()`` first when background writes may be pending.
        Returns the discarded steps."""
        dropped = [s for s in self.steps() if s > step]
        for old in dropped:
            for path in (self._path(old), self._path(old) + ".tag",
                         self._path(old) + ".manifest.json"):
                try:
                    os.remove(path)
                except OSError:
                    pass
        return dropped

    def restore(self, template_state, step=None):
        """Restore into ``template_state``'s structure; latest step if None."""
        with trace.span("checkpoint.restore", cat="checkpoint"):
            return self._restore(template_state, step)

    def _restore(self, template_state, step=None):
        steps = self.steps()
        if not steps:
            raise UserException("No checkpoint to restore in %r" % (self.directory,))
        if step is None:
            step = steps[-1]
        elif step not in steps:
            raise UserException("No checkpoint for step %d in %r" % (step, self.directory))
        with open(self._path(step), "rb") as fd:
            data = fd.read()
        if self.authenticator is not None:
            tag_path = self._path(step) + ".tag"
            try:
                with open(tag_path, "rb") as fd:
                    tag = fd.read()
            except OSError:
                # Fail-closed (an attacker with file access could simply
                # delete the tag otherwise), but tell the operator the
                # migration path for snapshots saved before tagging was on.
                raise UserException(
                    "Checkpoint %r has no authentication tag. If it predates "
                    "tagging (saved without --session-secret), restore once "
                    "WITHOUT the secret and resume with it — new snapshots "
                    "are tagged; otherwise treat the snapshot as untrusted"
                    % (self._path(step),)
                )
            if not self.authenticator.verify(0, step, data, tag):
                # In-band migration for snapshots tagged before the key
                # derivation gained domain separation: accept the OLD scheme
                # under the SAME secret (still proves knowledge of the
                # secret), warn, and RE-TAG IMMEDIATELY so the downgrade
                # window closes for this snapshot right now — without this an
                # operator would loop between this error and the missing-tag
                # one with no way to re-trust an old snapshot.
                legacy_ok = getattr(self.authenticator, "verify_legacy", None)
                if (
                    self.allow_legacy_tags
                    and legacy_ok is not None
                    and legacy_ok(0, step, data, tag)
                ):
                    fresh = self.authenticator.sign(0, step, data)
                    try:
                        tag_tmp = tag_path + ".tmp"
                        with open(tag_tmp, "wb") as fd:
                            fd.write(fresh)
                        os.replace(tag_tmp, tag_path)
                        retag = "re-tagged under the current scheme"
                    except OSError:
                        # read-only store (archive mount): the verification
                        # already succeeded, so accept; re-tagging just
                        # could not be persisted
                        retag = "re-tagging skipped (directory not writable)"
                    warning(
                        "Checkpoint %r was tagged under the legacy key scheme "
                        "(pre-context-separation); accepted under the same "
                        "session secret, %s" % (self._path(step), retag)
                    )
                else:
                    raise UserException(
                        "Checkpoint %r failed HMAC verification: corrupted, "
                        "forged, or a --session-secret mismatch; treat the "
                        "snapshot as untrusted" % (self._path(step),)
                    )
        if self.custody is not None:
            # Provenance BEFORE deserialization (after the byte-integrity
            # tag): the lineage manifest must sign exactly the on-disk
            # bytes, or the snapshot is refused (secure/custody.py —
            # fail-closed on a missing manifest unless allow_unsigned).
            self.custody.verify(self._path(step), step, data)
        if self.cipher is not None:
            data = self.cipher.decrypt(step, data)
        else:
            from ..parallel.crypto import SnapshotCipher

            if SnapshotCipher.is_encrypted(data):
                # No cipher but the blob is encrypted: fail with the cause,
                # not a baffling msgpack error from keystream-looking bytes.
                raise UserException(
                    "Checkpoint %r is encrypted; pass --encrypt-checkpoints "
                    "with the matching --session-secret to restore it"
                    % (self._path(step),)
                )
        state = flax.serialization.from_bytes(template_state, data)
        info("Restored checkpoint at step %d from %r" % (step, self.directory))
        return state, step

    def save(self, state, step=None):
        """Snapshot ``state``; prunes beyond ``max_to_keep`` oldest-first.

        With ``background=True`` only the host fetch happens here; the rest
        runs on the writer thread and ``wait()`` surfaces its failures."""
        if step is None:
            step = int(jax.device_get(state.step))
        for field in ("carry", "momentum"):
            if getattr(state, field, None) is not None:
                # Not serialized (core/train_state.py) — drop BEFORE device_get
                # or the (n, d) matrix crosses to the host just to be discarded.
                state = state.replace(**{field: None})
        with trace.span("checkpoint.fetch", cat="checkpoint", step=int(step)):
            host_state = jax.device_get(state)
        # lineage snapshot on the CALLER's thread: the manifest must sign
        # the tag-chain head as of this save, not of some later step the
        # background writer drains at
        lineage = self.custody.lineage(step) if self.custody is not None else None
        if self._pool is not None:
            self._pending.append(
                self._pool.submit(self._write, host_state, step, lineage)
            )
            return self._path(step)
        return self._write(host_state, step, lineage)

    def wait(self, shutdown=False):
        """Join ALL pending background writes, then re-raise the first
        failure — a later write is never left unjoined (or its failure
        silently dropped) because an earlier one raised.

        ``shutdown=True`` additionally retires the worker thread: a
        long-lived parent that constructs ``Checkpoints(background=True)``
        repeatedly (test harnesses, notebooks) would otherwise accumulate
        one idle thread per instance until GC.  Final-cleanup callers
        (cli/runner.py) pass it; mid-run cadence flushes don't."""
        pending, self._pending = self._pending, []
        first_error = None
        for future in pending:
            try:
                future.result()
            except Exception as exc:
                if first_error is None:
                    first_error = exc
        if shutdown and self._pool is not None:
            pool, self._pool = self._pool, None
            pool.shutdown(wait=True)
        if first_error is not None:
            raise first_error

    @trace.span("checkpoint.write", cat="checkpoint")
    def _write(self, host_state, step, lineage=None):
        # (span runs on the writer thread under background=True — the
        # tracer is thread-safe and the trace shows the write off the
        # critical path, which is the point of the background writer)
        data = flax.serialization.to_bytes(host_state)
        if self.cipher is not None:
            # BEFORE tagging: encrypt-then-MAC, the tag authenticates
            # exactly the bytes on disk
            data = self.cipher.encrypt(step, data)
        path = self._path(step)
        if self.custody is not None:
            # the manifest signs the FINAL on-disk bytes (post-encryption)
            # and lands before the data rename, like the tag sidecar:
            # discovery scans .ckpt files, so a manifest without data is
            # invisible while data without a manifest fails restore
            self.custody.write(path, step, data, payload=lineage)
        if self.authenticator is not None:
            # Slot 0 = the controller identity; the step binding ties each tag
            # to its snapshot (an attacker with file access can still delete
            # newer pairs to roll back — pin ``step=`` on restore if rollback
            # resistance matters). The tag lands on disk BEFORE the data
            # rename: discovery scans .ckpt files, so a tag without data is
            # invisible, while data without a tag would fail restore.
            tag = self.authenticator.sign(0, step, data)
            tag_tmp = path + ".tag.tmp"
            with open(tag_tmp, "wb") as fd:
                fd.write(tag)
            os.replace(tag_tmp, path + ".tag")
        tmp = path + ".tmp"
        with open(tmp, "wb") as fd:
            fd.write(data)
        os.replace(tmp, path)
        if self.max_to_keep > 0:
            for old in self.steps()[: -self.max_to_keep]:
                if old == self._pinned:
                    continue  # last-known-good survives pruning (see pin)
                os.remove(self._path(old))
                for sidecar in (self._path(old) + ".tag",
                                self._path(old) + ".manifest.json"):
                    if os.path.exists(sidecar):
                        os.remove(sidecar)
        return path

"""The causal plane's reader half: N journals in, ONE verified story out.

Journals (obs/events.py) are per-process truths; a fleet incident spans
processes.  Schema v2's ``cause`` references — ``(instance, run_id, seq)``
edges stamped at every boundary crossing (supervisor ``--cause`` argv
injection, the router's ``X-Causal-Id`` header, same-journal decision ->
actuation links) — let this module put the truths back together:

- :func:`merge_streams` — the deterministic, EDGE-RESPECTING merge.
  Within one instance the journal's own order is law (never violated);
  across instances events interleave by wall clock with ``(t_wall,
  instance)`` tie-breaking, EXCEPT that an event whose ``cause`` cites a
  not-yet-merged record of another stream waits for its cause.  Clocks
  skew across hosts, so an effect CAN carry an earlier ``t_wall`` than
  its cause — the merge emits it after its cause anyway and reports the
  inversion as a measured skew sample for that instance pair.  Skew is
  data, never a crash.
- :func:`audit` — the causal DAG checks behind the postmortem verdict:
  dangling cause references (an edge into nothing), orphan actions (an
  actuation with neither a cause edge nor evidence), incomplete spawn
  chains (a ``supervisor_restart``/``supervisor_retune`` of a journaled
  instance that no later ``run_start`` cites) and rollbacks that fail to
  name their sentinel verdict (``evidence.verdict_id`` — verdicts are
  FILES, not journal events, so the link is by identity, not by edge).
- :func:`run_postmortem` / :func:`render_story` — the shared checker:
  load every journal strictly (a torn tail is destroyed evidence, not a
  writer mid-append), merge, audit, and emit the
  ``aggregathor.obs.postmortem.v1`` report plus a human story.  The
  verdict is binary and the CLI's exit code (``cli/postmortem.py``);
  benchmarks/soak.py and benchmarks/causal_audit.py judge through the
  same functions, so the smoke, the soak and the operator agree.

Everything here is pure over the loaded records — no clocks, no sockets —
so the same journals always replay to the same story.
"""

import os

from . import events as obs_events

#: the postmortem report schema (BENCHMARKS.md schema index)
POSTMORTEM_SCHEMA = "aggregathor.obs.postmortem.v1"

#: action types whose conviction IS their payload — detections at the
#: edge of observability (a timeout window expiring, a signature failing
#: verification): nothing upstream of them exists in any journal to cite,
#: so a missing cause edge is not an orphan for these.
SELF_EVIDENT_ACTIONS = frozenset((
    "topology_level_timeout",
    "topology_corruption_verdict",
))

#: spawn-shaped actions: each must be answered by a later ``run_start``
#: citing it (chain completeness), provided the spawned instance keeps a
#: journal at all — a crash-looper one-liner with no journal is
#: unobservable and cannot fail the verdict.
SPAWN_ACTIONS = frozenset(("supervisor_restart", "supervisor_retune"))


def load_stream(path):
    """Whole-journal load for postmortems: :func:`~.events.load_journal`
    semantics (validation, seq-chain, rotation-aware) but STRICT about the
    tail — the incremental readers defer a line without its newline to the
    writer's next append, a postmortem has no next append.  Unconsumed
    trailing bytes mean the journal was truncated or torn: raises
    ``ValueError`` (destroyed evidence must flip the verdict, not vanish)."""
    with open(path, "rb"):
        pass                    # missing journal is the caller's error entry
    records, cursor = obs_events.tail_journal(path)
    try:
        size = os.path.getsize(path)
    except OSError:
        size = cursor.offset
    if size > cursor.offset:
        raise ValueError(
            "journal %r ends mid-line at offset %d (%d trailing bytes "
            "never got their newline): truncated or torn tail"
            % (path, cursor.offset, size - cursor.offset))
    return records


def _ref_key(cause, own_instance):
    """A cause reference's resolution key; ``instance`` None means the
    citing event's own journal."""
    instance = cause.get("instance")
    return (instance if instance is not None else own_instance,
            cause.get("run_id"), cause["seq"])


def merge_streams(streams):
    """Merge per-instance record lists into one causally ordered timeline.

    ``streams``: ``{instance_name: [records in file order]}``.  Returns
    ``(events, report)``.  Each merged event is a COPY stamped with
    ``instance`` (the owning journal — the fleet payload contract); a
    record whose own ``instance`` field that stamp would shadow (the
    supervisor's acted-on target) keeps it under ``subject``.

    ``report`` carries the cross-instance clock story: per-ordered-pair
    skew samples (an effect merged after a cause that carries a LATER
    wall clock), the count of forced emissions (cause cycles — broken by
    wall clock rather than deadlocking), and ambiguous reference keys
    (seq restarts under one run_id — rotated segments — make a key
    non-unique; references to those resolve to the first occurrence)."""
    names = sorted(streams)
    # --- identity pre-pass: which (instance, run_id, seq) keys exist ---
    first_t_wall = {}
    ambiguous = set()
    for name in names:
        for record in streams[name]:
            key = (name, record.get("run_id"), record["seq"])
            if key in first_t_wall:
                ambiguous.add(key)
            else:
                first_t_wall[key] = record.get("t_wall")
    # --- the k-way edge-respecting merge ------------------------------
    position = {name: 0 for name in names}
    emitted = set()
    merged = []
    skew = {}
    forced = 0

    def order_key(item):
        name, record = item
        return (record.get("t_wall", 0.0), name)

    while True:
        heads = [(name, streams[name][position[name]])
                 for name in names if position[name] < len(streams[name])]
        if not heads:
            break
        eligible = []
        for name, record in heads:
            cause = record.get("cause")
            if cause is None:
                eligible.append((name, record))
                continue
            target = _ref_key(cause, name)
            if (target[0] == name          # same stream: file order is law
                    or target not in first_t_wall   # dangling: audit's job
                    or target in ambiguous          # non-unique: best effort
                    or target in emitted):
                eligible.append((name, record))
        if eligible:
            name, record = min(eligible, key=order_key)
        else:
            # every head waits on a not-yet-merged cause: a reference
            # cycle.  Break it by wall clock — the merge must always
            # terminate, and the audit reports the cycle's dangling half.
            name, record = min(heads, key=order_key)
            forced += 1
        position[name] += 1
        emitted.add((name, record.get("run_id"), record["seq"]))
        out = dict(record, instance=name)
        if "instance" in record and record["instance"] != name:
            out["subject"] = record["instance"]
        merged.append(out)
        # --- skew: effect wall clock earlier than its cause's ---------
        cause = record.get("cause")
        if cause is not None:
            target = _ref_key(cause, name)
            cause_t = first_t_wall.get(target)
            effect_t = record.get("t_wall")
            if (target[0] != name and cause_t is not None
                    and effect_t is not None and effect_t < cause_t):
                pair = "%s->%s" % (target[0], name)
                sample = skew.setdefault(
                    pair, {"samples": 0, "max_seconds": 0.0})
                sample["samples"] += 1
                sample["max_seconds"] = max(
                    sample["max_seconds"], float(cause_t - effect_t))
    report = {
        "skew_pairs": skew,
        "forced_order": forced,
        "ambiguous_refs": [
            {"instance": k[0], "run_id": k[1], "seq": k[2]}
            for k in sorted(ambiguous,
                            key=lambda k: (k[0], k[1] or "", k[2]))],
    }
    return merged, report


def audit(streams):
    """The causal DAG checks over loaded streams.  Returns
    ``(chains, violations, edges_total)`` — ``chains`` the reconstructed
    cross-process stories (spawn chains answered, rollbacks naming their
    verdicts), ``violations`` the failure lists behind the verdict."""
    names = set(streams)
    exists = set()
    for name in names:
        for record in streams[name]:
            exists.add((name, record.get("run_id"), record["seq"]))
    dangling, unresolvable, orphans, incomplete, chains = [], [], [], [], []
    edges = 0
    # run_start citations: which action keys got answered by a spawn
    answered = {}
    for name in names:
        for record in streams[name]:
            if record.get("type") != "run_start":
                continue
            cause = record.get("cause")
            if cause is None:
                continue
            answered[_ref_key(cause, name)] = {
                "instance": name, "run_id": record.get("run_id"),
                "seq": record["seq"]}
    for name in sorted(names):
        for record in streams[name]:
            etype = record.get("type")
            cause = record.get("cause")
            where = {"instance": name, "type": etype,
                     "run_id": record.get("run_id"), "seq": record["seq"]}
            if cause is not None:
                edges += 1
                target = _ref_key(cause, name)
                if target not in exists:
                    entry = dict(where, cause={
                        "instance": target[0], "run_id": target[1],
                        "seq": target[2]})
                    if target[0] in names:
                        dangling.append(entry)
                    else:
                        # the cited journal was not given to this
                        # postmortem: reported, but not a verdict failure
                        # — absence of input is not absence of cause
                        unresolvable.append(entry)
            if etype in obs_events.ACTION_EVENT_TYPES:
                if (cause is None and etype not in SELF_EVIDENT_ACTIONS
                        and not record.get("evidence")):
                    orphans.append(where)
                if etype == "supervisor_rollback":
                    verdict_id = (record.get("evidence") or {}).get(
                        "verdict_id")
                    if not verdict_id:
                        incomplete.append(dict(
                            where, missing="evidence.verdict_id (the "
                            "sentinel verdict this rollback answers)"))
                    else:
                        chains.append({
                            "kind": "verdict_rollback", "action": where,
                            "verdict_id": verdict_id})
                if etype in SPAWN_ACTIONS:
                    subject = record.get("instance")
                    key = (name, record.get("run_id"), record["seq"])
                    spawned = answered.get(key)
                    if spawned is not None:
                        chains.append({
                            "kind": "spawn", "action": dict(
                                where, subject=subject),
                            "run_start": spawned})
                    elif subject in names:
                        # the spawned instance journals — its run_start
                        # MUST cite the action that spawned it
                        incomplete.append(dict(
                            where, subject=subject,
                            missing="a run_start in %r citing this %s"
                                    % (subject, etype)))
                    # a spawn subject with no journal is unobservable:
                    # neither a chain nor a violation
    violations = {
        "dangling_refs": dangling,
        "unresolvable_refs": unresolvable,
        "orphan_actions": orphans,
        "incomplete_chains": incomplete,
    }
    return chains, violations, edges


def run_postmortem(sources, include_timeline=False):
    """The whole checker: ``{instance: journal_path}`` in,
    ``aggregathor.obs.postmortem.v1`` report out.  A journal that fails
    to load (missing, truncated, seq chain broken) becomes a per-instance
    ``load_errors`` entry AND fails the verdict — a postmortem that
    silently drops a stream tells a clean story about a dirty run.

    ``include_timeline`` additionally returns the merged event list under
    a ``timeline`` key (NOT part of the report schema — callers that
    persist the report pop it first; :mod:`..cli.postmortem` feeds it to
    :func:`render_story`)."""
    streams, instances, load_errors = {}, {}, []
    for name in sorted(sources):
        path = sources[name]
        try:
            records = load_stream(path)
        except (OSError, ValueError) as exc:
            instances[name] = {"path": path, "events": 0,
                               "error": "%s: %s" % (type(exc).__name__, exc)}
            load_errors.append({"instance": name, "path": path,
                                "error": str(exc)})
            continue
        streams[name] = records
        instances[name] = {"path": path, "events": len(records),
                           "by_type": obs_events.counts_by_type(records)}
    merged, merge_report = merge_streams(streams)
    chains, violations, edges = audit(streams)
    violations["load_errors"] = load_errors
    failing = [key for key in ("dangling_refs", "orphan_actions",
                               "incomplete_chains", "load_errors")
               if violations[key]]
    extra = {"timeline": merged} if include_timeline else {}
    return dict(extra, **{
        "schema": POSTMORTEM_SCHEMA,
        "instances": instances,
        "events_total": len(merged),
        "edges_total": edges,
        "chains": chains,
        "violations": violations,
        "skew": {"pairs": merge_report["skew_pairs"],
                 "forced_order": merge_report["forced_order"],
                 "ambiguous_refs": merge_report["ambiguous_refs"]},
        "verdict": "FAIL" if failing else "PASS",
        "failing": failing,
    })


def _describe_ref(ref):
    return "%s:%s:%s" % (ref.get("instance") or "?",
                         ref.get("run_id") or "-", ref.get("seq"))


def render_story(report, merged=None):
    """The report as a markdown story (``--story``): verdict first, then
    the reconstructed chains, then every violation with its address — an
    operator reads WHY before WHAT.  Pass the merged event list (the
    ``timeline`` of ``run_postmortem(include_timeline=True)``) to append
    the full fleet timeline, each caused event carrying a
    ``└─ because:`` line naming the event it answers."""
    lines = ["# Fleet postmortem", ""]
    lines.append("**Verdict: %s**" % report["verdict"])
    if report["failing"]:
        lines.append("")
        lines.append("Failing checks: %s" % ", ".join(report["failing"]))
    lines.append("")
    lines.append("## Streams")
    lines.append("")
    lines.append("| instance | events | note |")
    lines.append("|---|---|---|")
    for name in sorted(report["instances"]):
        entry = report["instances"][name]
        lines.append("| %s | %d | %s |" % (
            name, entry.get("events", 0), entry.get("error", "ok")))
    lines.append("")
    lines.append("## Chains (%d edge(s) across %d event(s))"
                 % (report["edges_total"], report["events_total"]))
    lines.append("")
    if not report["chains"]:
        lines.append("No cross-process chains reconstructed.")
    for chain in report["chains"]:
        if chain["kind"] == "spawn":
            action = chain["action"]
            spawned = chain["run_start"]
            lines.append(
                "- **%s** of `%s` (%s) answered by `run_start` %s"
                % (action["type"], action.get("subject"),
                   _describe_ref(action), _describe_ref(spawned)))
        elif chain["kind"] == "verdict_rollback":
            action = chain["action"]
            lines.append(
                "- **supervisor_rollback** (%s) answers sentinel verdict "
                "`%s`" % (_describe_ref(action), chain["verdict_id"]))
    lines.append("")
    lines.append("## Violations")
    lines.append("")
    clean = True
    labels = (
        ("load_errors", "journal failed to load (verdict-failing)"),
        ("dangling_refs", "cause edge into nothing (verdict-failing)"),
        ("orphan_actions",
         "actuation with neither cause nor evidence (verdict-failing)"),
        ("incomplete_chains", "unanswered chain (verdict-failing)"),
        ("unresolvable_refs", "cited journal not given to this postmortem"),
    )
    for key, label in labels:
        entries = report["violations"][key]
        if not entries:
            continue
        clean = False
        lines.append("### %s — %s" % (key, label))
        lines.append("")
        for entry in entries:
            lines.append("- %s" % (entry,))
        lines.append("")
    if clean:
        lines.append("None.")
        lines.append("")
    skew = report["skew"]
    lines.append("## Clock skew")
    lines.append("")
    if skew["pairs"]:
        lines.append("| cause -> effect | inversions | max skew (s) |")
        lines.append("|---|---|---|")
        for pair in sorted(skew["pairs"]):
            sample = skew["pairs"][pair]
            lines.append("| %s | %d | %.6f |" % (
                pair, sample["samples"], sample["max_seconds"]))
    else:
        lines.append("No effect-before-cause wall-clock inversions measured.")
    if skew["forced_order"]:
        lines.append("")
        lines.append("%d event(s) force-merged through a reference cycle."
                     % skew["forced_order"])
    if merged:
        index = {}
        for record in merged:
            index[(record.get("instance"), record.get("run_id"),
                   record["seq"])] = record
        lines.append("")
        lines.append("## Timeline")
        lines.append("")
        for record in merged:
            stamp = record.get("t_wall")
            lines.append("- %s `%s` **%s** seq %d%s" % (
                "t_wall %.6f" % stamp if stamp is not None else "t_wall ?",
                record.get("instance"), record.get("type"), record["seq"],
                " (step %s)" % record["step"]
                if record.get("step") is not None else ""))
            cause = record.get("cause")
            if cause is None:
                continue
            target = _ref_key(cause, record.get("instance"))
            answered = index.get(target)
            lines.append("  - └─ because: `%s` **%s** seq %d" % (
                target[0], answered.get("type") if answered
                else "(not in this postmortem)", target[2]))
    lines.append("")
    return "\n".join(lines)

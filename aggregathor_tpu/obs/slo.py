"""Regression sentinel: baseline documents + PASS/REGRESS verdicts.

Benchmarks (BENCHMARKS.md) answer "how fast is this configuration today";
nothing so far answers "did THIS run regress against what this box used to
do" without a human diffing JSON.  The sentinel closes the loop: a stored
baseline document (schema ``aggregathor.obs.slo.v1``, seeded from a fresh
capture run via ``--slo-capture``) records the throughput-shaped metrics a
run is expected to hold, and at run end the runner compares the live
values and emits a PASS/REGRESS verdict — as an ``slo_verdict`` summary
event, an info line, an exit-independent verdict JSON, and the live
``/status`` payload.

Checked metrics (each with a direction and a relative tolerance):

- ``steps_per_s``              higher is better (the steady-state
  throughput, first/compile dispatch excluded — ``PerfReport``);
- ``gar_seconds_total``        lower is better (the ``--gar-probe``
  cumulative rule cost);
- ``input_overlap_fraction``   higher is better (the input pipeline's
  measured overlap, docs/input_pipeline.md).

A metric absent from the baseline, or unmeasured in the current run
(e.g. ``--gar-probe`` off, device-sampled input with no pipeline), is
SKIPPED and listed as such — a sentinel must not fabricate a regression
from a knob that was simply not enabled.
"""

import json
import os
import platform
import time

from ..utils import UserException

SCHEMA = "aggregathor.obs.slo.v1"

#: default relative tolerance when the baseline document does not carry one
DEFAULT_TOLERANCE = 0.25

#: direction per known metric: "higher" regresses when the current value
#: falls below baseline*(1-tol); "lower" when it rises above
#: baseline*(1+tol).  The serve_* entries are the serving-side SLO judged
#: by benchmarks/serve_load.py (docs/serving.md) — same sentinel, same
#: baseline schema, one more producer.
DIRECTIONS = {
    "steps_per_s": "higher",
    "gar_seconds_total": "lower",
    "input_overlap_fraction": "higher",
    "serve_req_per_s": "higher",
    "serve_p50_ms": "lower",
    "serve_p99_ms": "lower",
}


def collect_current(registry, perf=None):
    """The live values the sentinel judges, pulled from the one metrics
    registry (plus ``PerfReport`` for throughput).  Unmeasured metrics are
    ABSENT from the result, not zero: a zero would read as an infinite
    regression for higher-is-better checks."""
    current = {}
    if perf is not None and perf.nb_steps > 1:
        current["steps_per_s"] = float(perf.steps_per_s_excl_first())
    families = {family.name: family for family in registry.families()}
    gar = families.get("gar_seconds_total")
    if gar is not None and not gar.labelnames and gar.value > 0.0:
        current["gar_seconds_total"] = float(gar.value)
    overlap = families.get("input_overlap_fraction")
    if overlap is not None and not overlap.labelnames:
        current["input_overlap_fraction"] = float(overlap.value)
    return current


def capture(path, current, run_id=None, tolerances=None, notes=None):
    """Write a baseline document from one run's measured values (atomic).
    Returns the document."""
    doc = {
        "schema": SCHEMA,
        "captured_at": time.time(),
        "run_id": run_id,
        "host": {
            "platform": platform.platform(),
            "machine": platform.machine(),
        },
        "metrics": {name: float(value) for name, value in current.items()},
        "tolerances": {
            name: float((tolerances or {}).get(name, DEFAULT_TOLERANCE))
            for name in current
        },
        "directions": {
            name: DIRECTIONS.get(name, "higher") for name in current
        },
    }
    if notes:
        doc["notes"] = str(notes)
    directory = os.path.dirname(path)
    if directory:
        os.makedirs(directory, exist_ok=True)
    tmp = path + ".tmp"
    with open(tmp, "w") as fd:
        json.dump(doc, fd, indent=1)
        fd.write("\n")
    os.replace(tmp, path)
    return doc


class Sentinel:
    """Loads a baseline document and judges a run's current metrics."""

    def __init__(self, baseline):
        """``baseline`` is a document dict or a path to one.  A missing
        file or a wrong schema fails loudly AT LOAD (startup), not at run
        end — a misconfigured sentinel must not surface after an hour of
        training."""
        if isinstance(baseline, str):
            try:
                with open(baseline) as fd:
                    baseline = json.load(fd)
            except (OSError, ValueError) as exc:
                raise UserException(
                    "cannot load SLO baseline %r: %s (seed one with "
                    "--slo-capture on a healthy run)" % (baseline, exc)
                )
        if not isinstance(baseline, dict):
            raise UserException(
                "SLO baseline must be a JSON object, got %s (seed one with "
                "--slo-capture on a healthy run)" % type(baseline).__name__
            )
        if baseline.get("schema") != SCHEMA:
            raise UserException(
                "SLO baseline schema is %r, expected %r"
                % (baseline.get("schema"), SCHEMA)
            )
        if not isinstance(baseline.get("metrics"), dict) or not baseline["metrics"]:
            raise UserException("SLO baseline carries no metrics")
        self.baseline = baseline

    def verdict(self, current, run_id=None):
        """Judge ``current`` (a ``collect_current`` dict) against the
        baseline.  Returns the verdict document: per-metric checks
        (``ok``/``regressed``/``skipped``) and an overall ``"PASS"`` /
        ``"REGRESS"`` — PASS means no checked metric regressed (skipped
        metrics are listed, never counted as passes)."""
        checks = []
        regressed = 0
        for name, base in self.baseline["metrics"].items():
            base = float(base)
            tolerance = float(
                self.baseline.get("tolerances", {}).get(name, DEFAULT_TOLERANCE)
            )
            direction = self.baseline.get("directions", {}).get(
                name, DIRECTIONS.get(name, "higher")
            )
            check = {
                "metric": name,
                "baseline": base,
                "tolerance": tolerance,
                "direction": direction,
            }
            if name not in current:
                check["status"] = "skipped"
                check["current"] = None
            else:
                value = float(current[name])
                check["current"] = value
                if direction == "lower":
                    bound = base * (1.0 + tolerance)
                    ok = value <= bound
                else:
                    bound = base * (1.0 - tolerance)
                    ok = value >= bound
                check["bound"] = bound
                check["status"] = "ok" if ok else "regressed"
                regressed += 0 if ok else 1
            checks.append(check)
        return {
            "schema": SCHEMA + ".verdict",
            "run_id": run_id,
            "judged_at": time.time(),
            "baseline_run_id": self.baseline.get("run_id"),
            "baseline_captured_at": self.baseline.get("captured_at"),
            "verdict": "REGRESS" if regressed else "PASS",
            "regressed": regressed,
            "checks": checks,
        }


def save_verdict(path, verdict):
    """Write a verdict document (atomic)."""
    directory = os.path.dirname(path)
    if directory:
        os.makedirs(directory, exist_ok=True)
    tmp = path + ".tmp"
    with open(tmp, "w") as fd:
        json.dump(verdict, fd, indent=1)
        fd.write("\n")
    os.replace(tmp, path)
    return verdict


def describe_verdict(verdict):
    """One info-line rendering of a verdict document."""
    parts = []
    for check in verdict["checks"]:
        if check["status"] == "skipped":
            parts.append("%s skipped" % check["metric"])
        else:
            parts.append("%s %.4g vs %.4g (%s, tol %.0f%%): %s" % (
                check["metric"], check["current"], check["baseline"],
                check["direction"], check["tolerance"] * 100.0,
                check["status"].upper(),
            ))
    return "SLO %s — %s" % (verdict["verdict"], "; ".join(parts))

"""Live trainer exporter: an in-process HTTP metrics/status endpoint.

Until now the only way to watch a TRAINING run live was tailing
``--metrics-file`` dumps written at summary fires; serve/ had a real
``/metrics`` endpoint but training did not.  ``LiveExporter`` closes that
gap with the smallest possible server (the serve stack's stdlib
``ThreadingHTTPServer`` idiom, minus the batcher): a daemon thread
answering

- ``GET /metrics``  — Prometheus text exposition of the process-wide
  registry (``?format=json`` returns the JSON snapshot instead), exactly
  what serve's endpoint renders — one scrape config covers both phases;
- ``GET /status``   — a small JSON document from the runner's status
  provider: run id, step progress, steps/s, the most recent flight-
  recorder window (obs/flight.py) and the latest SLO sentinel verdict
  (obs/slo.py);
- ``GET /healthz``  — liveness.

The handler threads only render text from the registry (scrape-time gauge
callbacks included) — they never touch the training loop, the engines or
any jitted program, so scraping a live run costs a GIL slice, not a step.
``port=0`` binds an ephemeral port; ``--live-ready-file`` (cli/runner.py)
publishes ``host port`` for scripts, like serve's ready-file handshake.
"""

import json
import threading
import time
import urllib.parse

from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from . import metrics as obs_metrics
from ..utils import info


class _Handler(BaseHTTPRequestHandler):
    server_version = "aggregathor-live/1"
    protocol_version = "HTTP/1.1"

    def log_message(self, fmt, *args):  # scrapes must not spam stderr
        pass

    def _reply(self, code, body, content_type):
        body = body.encode() if isinstance(body, str) else body
        self.send_response(code)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _reply_json(self, code, payload):
        self._reply(code, json.dumps(payload), "application/json")

    def do_GET(self):
        parsed = urllib.parse.urlsplit(self.path)
        server = self.server
        if parsed.path == "/metrics":
            server.note_scrape("metrics")
            fmt = urllib.parse.parse_qs(parsed.query).get("format", [None])[0]
            if fmt == "json":
                self._reply_json(200, server.registry.snapshot())
            elif fmt in (None, "prometheus"):
                self._reply(200, server.registry.render_prometheus(),
                            obs_metrics.PROMETHEUS_CONTENT_TYPE)
            else:
                self._reply_json(
                    400, {"error": "unknown metrics format %r" % fmt})
        elif parsed.path == "/status":
            server.note_scrape("status")
            self._reply_json(200, server.status_payload())
        elif parsed.path == "/healthz":
            server.note_scrape("healthz")
            self._reply_json(200, {"status": "ok", "run_id": server.run_id})
        else:
            self._reply_json(404, {"error": "unknown path %r" % self.path})


class LiveExporter(ThreadingHTTPServer):
    """The training run's scrape endpoint.

    Args:
      registry: the metrics registry to expose (default the process-wide
        ``obs.metrics.REGISTRY``).
      status_provider: zero-arg callable returning the JSON-able ``/status``
        body (the runner closes over its loop state); exceptions degrade to
        an ``{"error": ...}`` payload instead of killing the scrape.
      run_id: stamped on ``/healthz`` and ``/status``.
      port: 0 binds an ephemeral port (read ``server_address[1]``).
    """

    daemon_threads = True

    def __init__(self, registry=None, status_provider=None, run_id=None,
                 host="127.0.0.1", port=0):
        super().__init__((host, int(port)), _Handler)
        self.registry = registry if registry is not None else obs_metrics.REGISTRY
        self.status_provider = status_provider
        self.run_id = run_id
        self.started_at = time.time()
        self._scrapes = self.registry.counter(
            "live_scrapes_total", "Live-exporter requests served",
            labelnames=("endpoint",),
        )
        self._serve_thread = None

    def note_scrape(self, endpoint):
        self._scrapes.labels(endpoint=endpoint).inc()

    def status_payload(self):
        payload = {"run_id": self.run_id, "uptime_s": time.time() - self.started_at}
        if self.status_provider is not None:
            try:
                payload.update(self.status_provider() or {})
            except Exception as exc:  # a scrape must never kill the run
                payload["error"] = str(exc)
        return payload

    def serve_background(self):
        """Run ``serve_forever`` on a daemon thread; returns (host, port)."""
        self._serve_thread = threading.Thread(
            target=self.serve_forever, daemon=True, name="live-exporter"
        )
        self._serve_thread.start()
        host, port = self.server_address[:2]
        info("Live trainer exporter on http://%s:%d (/metrics, /status)"
             % (host, port))
        return host, port

    def shutdown_all(self):
        """Stop the HTTP loop (idempotent) and unregister the scrape
        counter so a successor exporter starts fresh."""
        self.shutdown()
        self.server_close()
        if self._serve_thread is not None:
            self._serve_thread.join(5.0)
            self._serve_thread = None
        self.registry.unregister("live_scrapes_total")

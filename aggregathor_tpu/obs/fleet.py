"""One-scrape fleet federation: N processes' telemetry behind one port.

A production deployment of this codebase is SEVERAL processes — a training
run (``cli/runner.py --live-port``) plus serving processes
(``cli/serve.py``) — each already exporting its own ``/metrics`` +
``/status``.  The ROADMAP's replicated-serving-fleet item needs
cross-process shed/latency aggregation on ONE scrape; this module is that
aggregation point, and the groundwork the serving-fleet PR stands on.

:class:`FleetCollector` polls N child endpoints on a cadence and serves,
from one port:

- ``GET /fleet/metrics``  — every child's last-held exposition merged
  under a per-instance ``instance`` label, PLUS fleet-level sums for
  counter/histogram series under ``instance="_fleet"``, PLUS the
  collector's own meta family (``fleet_instance_up`` / ``_stale`` /
  ``fleet_last_scrape_age_seconds`` / ``fleet_polls_total`` /
  ``fleet_scrape_errors_total``);
- ``GET /fleet/status``   — per-instance up/down, miss counts, scrape age
  and the child's own ``/status`` body;
- ``GET /fleet/journal``  — the instances' causal run journals
  (obs/events.py) merged into one wall-clock-ordered timeline;
- ``GET /healthz``        — collector liveness.

**Down is explicit, never silent.**  An instance that misses
``down_after`` consecutive polls is marked ``down`` and its LAST sample is
HELD under an explicit staleness marker (``fleet_instance_stale{...} 1``)
— so killing a serving process mid-run cannot make the fleet's counter
sums jump backwards (continuity is what makes a fleet counter graphable),
and a scrape error on one child degrades that child only, never the
endpoint.

Everything decision-shaped is injectable (``fetch``, ``clock``), so tests
drive the merge math on synthetic expositions without sockets; the smoke
(``scripts/run_obs_smoke.sh``) then proves the real thing: two live
processes on one scrape, one killed mid-run reading ``down`` with fleet
sums continuous.

Run standalone::

    python -m aggregathor_tpu.obs.fleet --port 9100 \\
        --instance train=127.0.0.1:9000 --instance serve=127.0.0.1:8000 \\
        --journal train=/tmp/run.journal.jsonl
"""

import argparse
import json
import os
import signal
import sys
import threading
import time
import urllib.parse
import urllib.request

from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from . import events as obs_events
from . import metrics as obs_metrics
from ..utils import UserException, info


def _default_fetch(url, timeout):
    with urllib.request.urlopen(url, timeout=timeout) as response:
        return response.read().decode()


class _Instance:
    """One child endpoint's scrape state (collector-internal)."""

    __slots__ = ("name", "url", "journal_path", "metrics", "status",
                 "last_ok_at", "misses", "last_error", "ever_seen")

    def __init__(self, name, url, journal_path=None):
        self.name = name
        self.url = url
        self.journal_path = journal_path
        self.metrics = None      # parse_prometheus output, last success
        self.status = None       # /status JSON body, last success
        self.last_ok_at = None   # collector clock at last success
        self.misses = 0          # consecutive failed polls
        self.last_error = None
        self.ever_seen = False


class FleetCollector:
    """Polls child ``/metrics`` + ``/status`` endpoints; merges + serves.

    Args:
      instances: ``{name: base_url}`` — ``host:port`` is normalized to
        ``http://host:port``.  Names become the ``instance`` label.
      journal_paths: optional ``{name: journal_jsonl_path}`` merged by
        ``/fleet/journal`` (names need not match ``instances`` — a journal
        may belong to a process that exports no metrics).
      down_after: consecutive missed polls before an instance reads
        ``down`` (its last sample is then HELD under the staleness marker,
        never dropped).
      timeout: per-request fetch timeout (seconds).
      fetch: injectable ``fetch(url, timeout) -> text`` (tests).
      clock: injectable monotonic clock (ages, tests).
    """

    def __init__(self, instances, journal_paths=None, down_after=3,
                 timeout=2.0, fetch=None, clock=None):
        if not instances:
            raise UserException("FleetCollector wants at least one instance")
        if int(down_after) < 1:
            raise UserException("down_after must be >= 1 poll")
        self.down_after = int(down_after)
        self.timeout = float(timeout)
        self.fetch = fetch if fetch is not None else _default_fetch
        self.clock = clock if clock is not None else time.monotonic
        self._lock = threading.Lock()
        self._instances = {}
        for name, url in instances.items():
            if "://" not in url:
                url = "http://" + url
            self._instances[str(name)] = _Instance(
                str(name), url.rstrip("/"),
                (journal_paths or {}).get(name),
            )
        for name, path in (journal_paths or {}).items():
            if name not in self._instances:
                self._instances[str(name)] = _Instance(str(name), None, path)
        self.polls_total = 0
        self.errors_total = {name: 0 for name in self._instances}
        self._thread = None
        self._stop = threading.Event()

    # ------------------------------------------------------------------ #
    # polling

    def poll_once(self):
        """Scrape every instance once.  A child's failure degrades THAT
        child (miss counted, last sample held); it never raises."""
        with self._lock:
            self.polls_total += 1
            targets = [i for i in self._instances.values() if i.url is not None]
        for inst in targets:
            try:
                # explicit ?format=prometheus: bare /metrics serves text on
                # every exporter since PR 16, but the explicit form also
                # reads text from pre-16 serve processes mid-rollout
                text = self.fetch(
                    inst.url + "/metrics?format=prometheus", self.timeout
                )
                parsed = obs_metrics.parse_prometheus(text)
                status = json.loads(self.fetch(inst.url + "/status", self.timeout))
            except Exception as exc:
                with self._lock:
                    inst.misses += 1
                    inst.last_error = "%s: %s" % (type(exc).__name__, exc)
                    self.errors_total[inst.name] += 1
                continue
            with self._lock:
                inst.metrics = parsed
                inst.status = status
                inst.last_ok_at = self.clock()
                inst.misses = 0
                inst.last_error = None
                inst.ever_seen = True

    def instance_up(self, name):
        """True while ``name`` has a fresh sample (fewer than
        ``down_after`` consecutive misses since its last success)."""
        with self._lock:
            inst = self._instances[name]
            return inst.ever_seen and inst.misses < self.down_after

    # ------------------------------------------------------------------ #
    # merged readout

    def render_metrics(self):
        """The one-scrape exposition (Prometheus text format 0.0.4)."""
        now = self.clock()
        with self._lock:
            snapshot = [
                (inst.name, inst.url, inst.metrics, inst.last_ok_at,
                 inst.misses, inst.ever_seen)
                for inst in self._instances.values() if inst.url is not None
            ]
            polls = self.polls_total
            errors = dict(self.errors_total)
        lines = []

        def sample(name, labels, value):
            rendered = ",".join(
                '%s="%s"' % (k, obs_metrics.escape_label_value(v))
                for k, v in labels
            )
            lines.append("%s{%s} %s" % (name, rendered, obs_metrics._fmt(value)))

        # collector meta family: up/stale/age per instance + poll counters
        lines.append("# HELP fleet_instance_up 1 while the instance's last "
                     "poll cycle succeeded recently")
        lines.append("# TYPE fleet_instance_up gauge")
        for name, _url, _metrics, _ok_at, misses, seen in snapshot:
            sample("fleet_instance_up", [("instance", name)],
                   1.0 if (seen and misses < self.down_after) else 0.0)
        lines.append("# HELP fleet_instance_stale 1 while a down instance's "
                     "last sample is being HELD (never silently dropped)")
        lines.append("# TYPE fleet_instance_stale gauge")
        for name, _url, metrics, _ok_at, misses, seen in snapshot:
            stale = seen and misses >= self.down_after and metrics is not None
            sample("fleet_instance_stale", [("instance", name)],
                   1.0 if stale else 0.0)
        lines.append("# HELP fleet_last_scrape_age_seconds Seconds since the "
                     "instance's last successful scrape")
        lines.append("# TYPE fleet_last_scrape_age_seconds gauge")
        for name, _url, _metrics, ok_at, _misses, _seen in snapshot:
            sample("fleet_last_scrape_age_seconds", [("instance", name)],
                   float("inf") if ok_at is None else max(0.0, now - ok_at))
        lines.append("# HELP fleet_polls_total Poll cycles run by the collector")
        lines.append("# TYPE fleet_polls_total counter")
        lines.append("fleet_polls_total %s" % obs_metrics._fmt(polls))
        lines.append("# HELP fleet_scrape_errors_total Failed instance scrapes")
        lines.append("# TYPE fleet_scrape_errors_total counter")
        for name in sorted(errors):
            sample("fleet_scrape_errors_total", [("instance", name)],
                   float(errors[name]))

        # child families, merged: per-instance labels on every sample, plus
        # the fleet sum (instance="_fleet") for counter/histogram series —
        # held samples of down instances INCLUDED, so a killed process
        # cannot make a fleet counter jump backwards
        families = {}
        for name, _url, metrics, _ok_at, _misses, _seen in snapshot:
            if metrics is None:
                continue
            for fname, family in metrics.items():
                entry = families.setdefault(
                    fname, {"type": family.get("type"),
                            "help": family.get("help", ""), "rows": []}
                )
                if entry["type"] is None:
                    entry["type"] = family.get("type")
                for sample_name, labels, value in family["samples"]:
                    entry["rows"].append((name, sample_name, labels, value))
        for fname in sorted(families):
            entry = families[fname]
            kind = entry["type"] or "untyped"
            lines.append("# HELP %s %s" % (fname, entry["help"]))
            lines.append("# TYPE %s %s" % (fname, kind))
            sums = {}
            for inst_name, sample_name, labels, value in entry["rows"]:
                ordered = [("instance", inst_name)] + sorted(labels.items())
                sample(sample_name, ordered, value)
                if kind in ("counter", "histogram"):
                    key = (sample_name, tuple(sorted(labels.items())))
                    sums[key] = sums.get(key, 0.0) + value
            for (sample_name, labels), total in sorted(sums.items()):
                sample(sample_name, [("instance", "_fleet")] + list(labels),
                       total)
        return "\n".join(lines) + "\n"

    def status_payload(self):
        """The ``/fleet/status`` JSON body."""
        now = self.clock()
        with self._lock:
            payload = {
                "polls": self.polls_total,
                "down_after": self.down_after,
                "generated_at": time.time(),
                "instances": {},
            }
            for inst in self._instances.values():
                up = inst.ever_seen and inst.misses < self.down_after
                payload["instances"][inst.name] = {
                    "url": inst.url,
                    "up": up,
                    "stale": bool(inst.ever_seen and not up),
                    "misses": inst.misses,
                    # the exact down-judgment inputs a restart decision
                    # needs: misses under its canonical name (the down
                    # threshold is consecutive_misses >= down_after) next
                    # to the freshness age — supervisor/policy.py reads
                    # these, "misses" stays for pre-PR-17 scrapers
                    "consecutive_misses": inst.misses,
                    "last_scrape_age_seconds": (
                        None if inst.last_ok_at is None
                        else max(0.0, now - inst.last_ok_at)
                    ),
                    "last_error": inst.last_error,
                    "journal": inst.journal_path,
                    "status": inst.status,
                }
        return payload

    def journal_payload(self):
        """The ``/fleet/journal`` JSON body: every configured journal
        loaded through the validator (obs/events.py) and merged into one
        causally ordered timeline (obs/causal.py ``merge_streams``: wall
        clock + ``(t_wall, instance)`` tie-break where no ``cause`` edge
        says otherwise, edges respected where one does — an effect never
        precedes its cited cause, and a wall-clock inversion between
        hosts is reported as measured ``skew`` rather than crashed on),
        each event stamped with its instance.  A missing/garbled journal
        degrades to a per-instance error entry — one bad file must not
        hide the others' timeline."""
        from . import causal

        with self._lock:
            sources = [
                (inst.name, inst.journal_path)
                for inst in self._instances.values()
                if inst.journal_path is not None
            ]
        streams, per_instance = {}, {}
        for name, path in sources:
            try:
                records = obs_events.load_journal(path)
            except FileNotFoundError:
                per_instance[name] = {"path": path, "events": 0,
                                      "error": "journal not written yet"}
                continue
            except (OSError, ValueError) as exc:
                # permission denied, path-is-a-directory, garbled bytes —
                # all degrade to a per-instance error entry (one bad file
                # must not hide the others' timeline)
                per_instance[name] = {"path": path, "events": 0,
                                      "error": "%s: %s" % (type(exc).__name__,
                                                           exc)}
                continue
            per_instance[name] = {
                "path": path, "events": len(records),
                "by_type": obs_events.counts_by_type(records),
            }
            streams[name] = records
        merged, merge_report = causal.merge_streams(streams)
        return {
            "schema": obs_events.SCHEMA,
            "instances": per_instance,
            "events": merged,
            "skew": {"pairs": merge_report["skew_pairs"],
                     "forced_order": merge_report["forced_order"]},
        }

    # ------------------------------------------------------------------ #
    # poll loop lifecycle

    def start(self, interval_s=1.0):
        """Poll every ``interval_s`` seconds on a daemon thread (one
        immediate poll first, so the endpoint is populated at ready time)."""
        if interval_s <= 0.0:
            raise UserException("fleet poll interval must be > 0 seconds")
        if self._thread is not None:
            return
        self.poll_once()

        def run():
            while not self._stop.wait(interval_s):
                self.poll_once()

        self._thread = threading.Thread(
            target=run, daemon=True, name="fleet-collector"
        )
        self._thread.start()

    def close(self):
        self._stop.set()
        thread, self._thread = self._thread, None
        if thread is not None:
            thread.join(5.0)


# --------------------------------------------------------------------- #
# the one-port HTTP front


class _Handler(BaseHTTPRequestHandler):
    server_version = "aggregathor-fleet/1"
    protocol_version = "HTTP/1.1"

    def log_message(self, fmt, *args):  # scrapes must not spam stderr
        pass

    def _reply(self, code, body, content_type):
        body = body.encode() if isinstance(body, str) else body
        self.send_response(code)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):
        path = urllib.parse.urlsplit(self.path).path
        collector = self.server.collector
        try:
            if path == "/fleet/metrics":
                self._reply(200, collector.render_metrics(),
                            obs_metrics.PROMETHEUS_CONTENT_TYPE)
            elif path == "/fleet/status":
                self._reply(200, json.dumps(collector.status_payload()),
                            "application/json")
            elif path == "/fleet/journal":
                self._reply(200, json.dumps(collector.journal_payload()),
                            "application/json")
            elif path == "/healthz":
                self._reply(200, json.dumps({"status": "ok"}),
                            "application/json")
            else:
                self._reply(404, json.dumps({"error": "unknown path %r" % path}),
                            "application/json")
        except Exception as exc:  # a scrape must never kill the collector
            self._reply(500, json.dumps(
                {"error": "%s: %s" % (type(exc).__name__, exc)}
            ), "application/json")


class FleetServer(ThreadingHTTPServer):
    """The collector's HTTP face (``serve_background`` / ``shutdown_all``,
    the LiveExporter lifecycle)."""

    daemon_threads = True

    def __init__(self, collector, host="127.0.0.1", port=0):
        super().__init__((host, int(port)), _Handler)
        self.collector = collector
        self._serve_thread = None

    def serve_background(self):
        self._serve_thread = threading.Thread(
            target=self.serve_forever, daemon=True, name="fleet-server"
        )
        self._serve_thread.start()
        host, port = self.server_address[:2]
        info("Fleet collector on http://%s:%d (/fleet/metrics, /fleet/status, "
             "/fleet/journal)" % (host, port))
        return host, port

    def shutdown_all(self):
        self.shutdown()
        self.server_close()
        if self._serve_thread is not None:
            self._serve_thread.join(5.0)
            self._serve_thread = None


# --------------------------------------------------------------------- #
# CLI


def _parse_pairs(specs, what):
    out = {}
    for spec in specs:
        name, sep, value = spec.partition("=")
        if not sep or not name or not value:
            raise UserException(
                "--%s wants NAME=%s, got %r" % (what, what.upper(), spec)
            )
        if name in out:
            raise UserException("--%s %r given twice" % (what, name))
        out[name] = value
    return out


def main(argv=None):
    parser = argparse.ArgumentParser(
        prog="python -m aggregathor_tpu.obs.fleet",
        description="One-scrape fleet federation over N /metrics + /status "
                    "endpoints (docs/observability.md 'The control room')",
    )
    parser.add_argument("--instance", action="append", default=[],
                        metavar="NAME=HOST:PORT",
                        help="child endpoint to federate (repeatable)")
    parser.add_argument("--journal", action="append", default=[],
                        metavar="NAME=PATH",
                        help="causal run journal served by /fleet/journal "
                             "(repeatable; NAME need not be an --instance)")
    parser.add_argument("--host", default="127.0.0.1", help="bind address")
    parser.add_argument("--port", type=int, default=0,
                        help="bind port (0 = ephemeral)")
    parser.add_argument("--poll-interval", type=float, default=1.0,
                        help="seconds between poll cycles")
    parser.add_argument("--down-after", type=int, default=3,
                        help="consecutive missed polls before an instance "
                             "reads down (its last sample is held, marked "
                             "stale)")
    parser.add_argument("--timeout", type=float, default=2.0,
                        help="per-request scrape timeout (seconds)")
    parser.add_argument("--ready-file", default=None, metavar="PATH",
                        help="write 'host port pid' here once bound and the "
                             "first poll cycle ran (harness handshake)")
    args = parser.parse_args(argv)
    instances = _parse_pairs(args.instance, "instance")
    journals = _parse_pairs(args.journal, "journal")
    if not instances:
        parser.error("at least one --instance NAME=HOST:PORT is required")

    collector = FleetCollector(
        instances, journal_paths=journals, down_after=args.down_after,
        timeout=args.timeout,
    )
    server = FleetServer(collector, host=args.host, port=args.port)
    stop = threading.Event()

    def on_signal(signum, frame):
        info("Signal %d: fleet collector shutting down" % signum)
        stop.set()

    previous = {
        signal.SIGINT: signal.signal(signal.SIGINT, on_signal),
        signal.SIGTERM: signal.signal(signal.SIGTERM, on_signal),
    }
    try:
        collector.start(args.poll_interval)
        host, port = server.serve_background()
        if args.ready_file:
            tmp = args.ready_file + ".tmp"
            with open(tmp, "w") as fd:
                fd.write("%s %d %d\n" % (host, port, os.getpid()))
            os.replace(tmp, args.ready_file)
        stop.wait()
    finally:
        for signum, handler in previous.items():
            signal.signal(signum, handler)
        collector.close()
        server.shutdown_all()
    return 0


if __name__ == "__main__":
    sys.exit(main())

"""Flight recorder: bounded per-step telemetry rings written IN-SCAN.

The PR-4 telemetry pillars are host-bound: every dispatch that wants
per-step evidence must pull full metrics to the host (the forensics feed),
and under ``--unroll`` the summary stream only ever sees the LAST sub-step
of each chunk.  The flight recorder is the device-side half: a fixed-size
ring of per-step lanes carried as a non-serialized ``TrainState`` side
buffer and written inside the jitted step body itself (``parallel/
engine.py``, both dataflows), so every scanned step leaves
a row on the accelerator at zero host cost.  The host fetches the whole
ring ONCE at summary cadence (one amortized copy instead of per-dispatch
pulls) and dumps it post-mortem on guardian rollback or crash — exact
per-step evidence for the window that killed the run, like an aircraft
flight recorder.

Hard constraints (asserted by tests/test_flight.py):

- **zero added recompiles** — the ring is state carried through the same
  one compiled program; the compile count with the recorder on equals the
  recorder-off run (1 steady-state executable either way);
- **bit-identical lanes** — every lane stores the SAME traced value the
  metrics dictionary returns, so ring rows are bit-identical to the
  per-dispatch metrics at any ``--unroll``;
- **bounded memory** — capacity ``C`` rows of a handful of scalars plus up
  to three ``(C, n)`` vectors; a 256-row ring at n=8 is a few KB of HBM.

Lanes (each present only when the engine computes the source metric):

====================  ========  ==========================================
lane                  shape     source
====================  ========  ==========================================
``step``              (C,)      in-graph step counter (slot validity tag)
``loss``              (C,)      ``metrics["total_loss"]``
``update_norm``       (C,)      ``metrics["grad_norm"]``
``spike``             (C,)      probe spike score (guardian/probe.py)
``loss_finite``       (C,)      probe finite-loss flag
``worker_nan``        (C, n)    probe post-transport NaN-row flags
``worker_sq_dist``    (C, n)    per-worker squared distance (worker_metrics)
``chaos_regime``      (C,)      active chaos regime index
``secure_rejected``   (C, n)    secure submission verdict lanes
====================  ========  ==========================================

Slot ``step % C`` holds step ``step``'s row; the ``step`` lane (init -1)
makes every slot self-identifying, so a fetched ring needs no host-side
cursor — stale slots (pre-wraparound, or zeroed by a rollback re-init)
are recognized and dropped by :meth:`FlightRecorder.fetch`.

The post-mortem document serializes under schema
``aggregathor.obs.flight.v1`` (:func:`dump_window`); non-finite floats are
encoded as the strings ``"nan"`` / ``"inf"`` / ``"-inf"`` (strict JSON has
no tokens for them, and for a divergence post-mortem the NaN *is* the
evidence — ``null`` would erase its sign and kind).
"""

import json
import os
import time

import jax
import jax.numpy as jnp
import numpy as np

from ..utils import UserException

SCHEMA = "aggregathor.obs.flight.v1"

#: lanes shaped (C,) — name -> (dtype, fill value)
_SCALAR_LANES = {
    "step": (jnp.int32, -1),
    "loss": (jnp.float32, jnp.nan),
    "update_norm": (jnp.float32, jnp.nan),
}
_PROBE_SCALAR_LANES = {
    "spike": (jnp.float32, jnp.nan),
    "loss_finite": (jnp.int32, -1),
}


class FlightRecorder:
    """Static ring configuration + the traced write and host fetch.

    One instance describes the ring LAYOUT (capacity and which lanes) and
    is shared by the engine (``init_buffers``/``record`` run under jit) and
    the host loop (``fetch``).  Lane flags must match what the engine
    actually computes — :meth:`validate_for` is called by both engines'
    constructors and fails loudly on a lane whose source metric the engine
    will not produce.

    Args:
      capacity: ring rows (>= 1).  Rows older than the last ``capacity``
        steps are overwritten; size the ring to at least the summary
        cadence (and ``--unroll``) to fetch every step exactly once.
      nb_workers: n — the width of the per-worker lanes.
      probe: record the health-probe lanes (spike / loss_finite /
        worker_nan); needs the engine's ``health_probe``.
      worker_metrics: record ``worker_sq_dist``; needs ``worker_metrics``.
      chaos: record the regime-index lane; needs a chaos schedule.
      secure: record the submission-verdict lane; needs ``secure``.
    """

    def __init__(self, capacity, nb_workers, probe=True, worker_metrics=False,
                 chaos=False, secure=False):
        self.capacity = int(capacity)
        self.nb_workers = int(nb_workers)
        if self.capacity < 1:
            raise UserException(
                "FlightRecorder wants capacity >= 1 (got %d)" % self.capacity
            )
        if self.nb_workers < 1:
            raise UserException(
                "FlightRecorder wants nb_workers >= 1 (got %d)" % self.nb_workers
            )
        self.probe = bool(probe)
        self.worker_metrics = bool(worker_metrics)
        self.chaos = bool(chaos)
        self.secure = bool(secure)

    # ------------------------------------------------------------------ #
    # engine side (traced)

    def validate_for(self, nb_workers, probe, worker_metrics, chaos, secure):
        """Fail loudly when a configured lane's source metric is absent
        from the engine this recorder is being attached to."""
        if nb_workers != self.nb_workers:
            raise UserException(
                "FlightRecorder was sized for n=%d workers but the engine "
                "has %d" % (self.nb_workers, nb_workers)
            )
        for lane, wanted, have in (
            ("probe", self.probe, probe),
            ("worker_sq_dist", self.worker_metrics, worker_metrics),
            ("chaos_regime", self.chaos, chaos),
            ("secure_rejected", self.secure, secure),
        ):
            if wanted and not have:
                raise UserException(
                    "FlightRecorder records the %r lane but the engine does "
                    "not compute its source metric" % lane
                )

    def lane_shapes(self):
        """{name: (shape, dtype, fill)} for every configured lane."""
        C, n = self.capacity, self.nb_workers
        lanes = {
            name: ((C,), dtype, fill)
            for name, (dtype, fill) in _SCALAR_LANES.items()
        }
        if self.probe:
            lanes.update({
                name: ((C,), dtype, fill)
                for name, (dtype, fill) in _PROBE_SCALAR_LANES.items()
            })
            lanes["worker_nan"] = ((C, n), jnp.int32, -1)
        if self.worker_metrics:
            lanes["worker_sq_dist"] = ((C, n), jnp.float32, jnp.nan)
        if self.chaos:
            lanes["chaos_regime"] = ((C,), jnp.int32, -1)
        if self.secure:
            lanes["secure_rejected"] = ((C, n), jnp.int32, -1)
        return lanes

    def init_buffers(self):
        """Fresh (host-buildable) ring pytree, every slot invalid."""
        return {
            name: jnp.full(shape, fill, dtype)
            for name, (shape, dtype, fill) in self.lane_shapes().items()
        }

    def record(self, buffers, step, metrics):
        """(traced) Write step ``step``'s row into slot ``step % C``.

        Every lane stores the exact traced value the ``metrics`` dict
        carries — the ring IS the metrics stream, ring-buffered — so a
        fetched row is bit-identical to the per-step metrics by
        construction.  Runs inside the jitted step body (both engines);
        all recorded values are replicated there, so the replicated ring
        stays replicated."""
        from ..guardian.probe import PROBE_KEY

        slot = jax.lax.rem(
            jnp.asarray(step, jnp.int32), jnp.int32(self.capacity)
        )
        out = dict(buffers)

        def put(name, value):
            buf = buffers[name]
            out[name] = jax.lax.dynamic_update_index_in_dim(
                buf, jnp.asarray(value).astype(buf.dtype), slot, 0
            )

        put("step", step)
        put("loss", metrics["total_loss"])
        put("update_norm", metrics["grad_norm"])
        if self.probe:
            probe = metrics[PROBE_KEY]
            put("spike", probe["spike"])
            put("loss_finite", probe["loss_finite"])
            put("worker_nan", probe["worker_nan_rows"])
        if self.worker_metrics:
            put("worker_sq_dist", metrics["worker_sq_dist"])
        if self.chaos:
            put("chaos_regime", metrics["chaos_regime"])
        if self.secure:
            put("secure_rejected", metrics["secure"]["rejected"])
        return out

    # ------------------------------------------------------------------ #
    # host side

    def fetch(self, buffers):
        """One fetched ring -> the valid window, ordered by step.

        ``buffers`` is the (device or host) ring pytree; the ONE
        ``jax.device_get`` here is the recorder's whole host cost per
        summary fire.  Returns ``{lane: np.ndarray}`` with rows sorted by
        the ``step`` lane ascending, slots never written (step -1)
        dropped.  The ``step`` lane holds IN-GRAPH step indices: row
        ``s`` describes the step that took the counter from ``s`` to
        ``s + 1`` (the summary stream's "completed step" ``s + 1``)."""
        host = {
            name: np.asarray(value)
            for name, value in jax.device_get(buffers).items()
        }
        steps = host["step"]
        order = np.argsort(steps, kind="stable")
        order = order[steps[order] >= 0]
        return {name: value[order] for name, value in host.items()}


def summarize_window(window, tail=5):
    """Small JSON-able view of a fetched window (the live ``/status``
    payload): step range, row count, and the last ``tail`` rows of the
    scalar lanes."""
    steps = window.get("step")
    if steps is None or steps.size == 0:
        return {"rows": 0}
    out = {
        "rows": int(steps.size),
        "first_step": int(steps[0]),
        "last_step": int(steps[-1]),
    }
    for lane in ("loss", "update_norm", "spike", "chaos_regime"):
        if lane in window:
            out[lane] = [_json_value(v) for v in window[lane][-int(tail):]]
    if "worker_nan" in window:
        out["worker_nan_rows_last"] = [
            int(v) for v in np.asarray(window["worker_nan"][-1]).reshape(-1)
        ]
    return out


def _json_value(value):
    """Strict-JSON scalar: non-finite floats become tagged strings (a
    post-mortem must keep the difference between NaN and ±inf)."""
    if isinstance(value, (np.integer, int)):
        return int(value)
    value = float(value)
    if np.isfinite(value):
        return value
    if np.isnan(value):
        return "nan"
    return "inf" if value > 0 else "-inf"


def dump_window(path, window, run_id=None, reason=None, capacity=None,
                extra=None):
    """Write one fetched window as a post-mortem document (atomic write).

    Schema ``aggregathor.obs.flight.v1``: per-lane row lists in step
    order, non-finite floats encoded per :func:`_json_value`.  Returns the
    document dict."""
    lanes = {}
    for name, values in window.items():
        arr = np.asarray(values)
        if arr.ndim <= 1:
            lanes[name] = [_json_value(v) for v in arr]
        else:
            lanes[name] = [[_json_value(v) for v in row] for row in arr]
    steps = window.get("step")
    doc = {
        "schema": SCHEMA,
        "run_id": run_id,
        "reason": reason,
        "written_at": time.time(),
        "capacity": capacity,
        "rows": int(steps.size) if steps is not None else 0,
        "step_range": (
            [int(steps[0]), int(steps[-1])]
            if steps is not None and steps.size else None
        ),
        "lanes": lanes,
    }
    if extra:
        doc["extra"] = dict(extra)
    directory = os.path.dirname(path)
    if directory:
        os.makedirs(directory, exist_ok=True)
    tmp = path + ".tmp"
    with open(tmp, "w") as fd:
        json.dump(doc, fd, indent=1)
        fd.write("\n")
    os.replace(tmp, path)
    return doc


def load_window(path):
    """Load + schema-check a post-mortem document (tests, smoke)."""
    with open(path) as fd:
        doc = json.load(fd)
    if doc.get("schema") != SCHEMA:
        raise ValueError(
            "expected schema %r, got %r" % (SCHEMA, doc.get("schema"))
        )
    if not isinstance(doc.get("lanes"), dict) or "step" not in doc["lanes"]:
        raise ValueError("flight document wants a lanes dict with a step lane")
    nb = len(doc["lanes"]["step"])
    for name, rows in doc["lanes"].items():
        if len(rows) != nb:
            raise ValueError(
                "lane %r has %d rows, step lane has %d" % (name, len(rows), nb)
            )
    return doc

"""GAR kernel latency benchmark: ms vs gradient dimension, per tier.

The measurement protocol BASELINE.md prescribes: per-rule kernel latency as a
function of the flattened gradient dimension ``d``, alongside the steps/s
bench (bench.py). Tiers:

- ``jnp``     — the default jit/XLA tier (runs on whatever backend is live)
- ``pallas``  — the hand-written TPU kernels (TPU only; silently skipped
                elsewhere)
- ``native``  — the C++ host library via ctypes (CPU threads)

Usage::

    python benchmarks/gar_kernels.py [--n 32] [--f 8] [--dims 65536,1048576]
                                     [--rules krum,bulyan,median] [--reps 20]

Prints one human table and one machine-readable JSON line per (rule, tier, d).
"""

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# Pin the plain rule names to the pure-jnp tier: round 4 made the base
# coordinate rules auto-dispatch to the Pallas kernels on TPU
# (gars/common.py use_pallas_coordinate_tier), which would silently turn
# this script's jnp column into a second Pallas column.  The *-pallas
# registrations override aggregate_block directly and ignore this.
os.environ["GRAFT_GAR_TIER"] = "jnp"


def time_fn(fn, reps):
    """Median per-call ms; EVERY timed repetition individually synced.

    Delegates to the ONE canonical timing protocol in
    ``aggregathor_tpu.gars.scaling.time_aggregate`` (warmup, then per rep:
    ``sync_fetch`` — ``block_until_ready`` + a scalar host fetch — of that
    rep's own output, median over reps).  Under the tunneled TPU backend
    ``jax.block_until_ready`` returns immediately (measured: a d=8M
    aggregation "completed" in 0.03 ms at an impossible 20 TB/s); only a
    host fetch actually waits for the device stream.  The previous protocol
    dispatched ``reps`` unsynced calls and subtracted a single-call time
    (slope): under tunnel latency jitter the slope went NEGATIVE and the
    ``max(..., 0.0)`` clamp wrote whole rows as 0.0 ms (the ``dnc`` rows in
    resume_gar_kernels.json) — it was timing async dispatch, not the
    kernel.  The host fetch subsumes both tiers (a no-op roundtrip on the
    already-synchronous native tier).
    """
    from aggregathor_tpu.gars.scaling import time_aggregate

    return time_aggregate(fn, reps)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--n", type=int, default=32, help="worker count")
    ap.add_argument("--f", type=int, default=8, help="declared Byzantine count")
    ap.add_argument("--dims", default="65536,1048576,8388608", help="comma list of d")
    ap.add_argument(
        "--rules",
        default="average,average-nan,median,averaged-median,krum,bulyan,"
                "trimmed-mean,centered-clip,geometric-median,bucketing,dnc",
    )
    ap.add_argument("--reps", type=int, default=20)
    ap.add_argument("--scale-ns", default=None,
                    help="comma list of worker counts: sweep krum+bulyan at "
                         "--scale-d, reporting COMPILE seconds + kernel ms "
                         "(the reference's C++ selection loop had no n limit, "
                         "op_bulyan/cpu.cpp:134-161; Bulyan's lax.scan form "
                         "must keep compile time flat in t = n - 2f - 2)")
    ap.add_argument("--scale-d", type=int, default=65536)
    ap.add_argument("--sweep-ns", default=None,
                    help="comma list of worker counts (e.g. 8,32,128,512): "
                         "the n-sweep scaling mode — flat krum/bulyan vs the "
                         "composite tree rules (hier, bucketing-over-hier) "
                         "at fixed --sweep-d, emitting one "
                         "aggregathor.gar.scaling.v1 document with the "
                         "sublinear-in-n² verdict (gars/scaling.py, "
                         "docs/gar_scaling.md)")
    ap.add_argument("--sweep-d", type=int, default=65536,
                    help="fixed gradient dimension for --sweep-ns")
    ap.add_argument("--sweep-f", type=int, default=1,
                    help="declared Byzantine count for --sweep-ns (small, so "
                         "every generated composite stays feasible at the "
                         "smallest swept n)")
    ap.add_argument("--sweep-reps", type=int, default=5)
    ap.add_argument("--sweep-out", default=None,
                    help="write the aggregathor.gar.scaling.v1 JSON here")
    ap.add_argument("--platform", default=None, help="force a JAX platform")
    ap.add_argument("--resume-file", default=None,
                    help="JSON path recording completed (rule, tier, d) "
                         "cells: a re-run skips them (and reprints their "
                         "rows) so a scarce TPU up-window resumes the sweep "
                         "instead of restarting it.")
    args = ap.parse_args()

    if args.platform:
        os.environ["JAX_PLATFORMS"] = args.platform
    import jax

    if args.platform:
        jax.config.update("jax_platforms", args.platform)
    import jax.numpy as jnp

    from aggregathor_tpu import gars
    from aggregathor_tpu.ops import native

    from aggregathor_tpu.utils.state import load_json, save_json_atomic

    platform = jax.devices()[0].platform
    on_tpu = platform == "tpu"
    native_ok = native.available()
    rules = args.rules.split(",") if args.rules else []
    dims = [int(d) for d in args.dims.split(",") if d]  # "" = scale-n only
    rows = []
    resume = load_json(args.resume_file) if args.resume_file else {}

    def measured(rule, tier, d, f, thunk):
        """The cell's ms: from the resume cache, or measured via thunk()."""
        key = "%s|%s|%d|%d|%d|%d" % (rule, tier, d, args.n, args.f, args.reps)
        ms = resume.get(key)
        if ms == 0.0:
            # A 0.0 cell is the old unsynced timer's failure signature (its
            # dispatch-loop slope clamped negative), not a measurement:
            # re-measure it with the per-rep-synced protocol.
            ms = None
        if ms is None:
            ms = thunk()
            if args.resume_file:
                resume[key] = ms
                save_json_atomic(args.resume_file, resume)
        rows.append((rule, tier, d, ms, f))

    for d in dims:
        # The d=8.4M fixture is ~1 GB of random floats; build it LAZILY so
        # a fully-cached d costs neither the generation nor the device
        # transfer.  Seeded per-d, so laziness never changes the values.
        fixture = {}

        def g_host(d=d, fixture=fixture):
            if "host" not in fixture:
                fixture["host"] = np.random.default_rng(d).normal(
                    size=(args.n, d)).astype(np.float32)
            return fixture["host"]

        def g_dev(fixture=fixture):
            if "dev" not in fixture:
                fixture["dev"] = jax.device_put(g_host())
            return fixture["dev"]

        for rule in rules:
            # Bulyan's bound is n >= 4f + 3; clamp f so every rule runs at
            # the requested n (the reference would reject such configs too).
            f = min(args.f, (args.n - 3) // 4) if rule.startswith("bulyan") else args.f
            # jit tier
            gar = gars.instantiate(rule, args.n, f)
            agg = jax.jit(gar.aggregate)
            measured(rule, "jnp:" + platform, d, f,
                     lambda: time_fn(lambda: agg(g_dev()), args.reps))

            # pallas tier (TPU only)
            if on_tpu and (rule + "-pallas") in gars.itemize():
                pgar = gars.instantiate(rule + "-pallas", args.n, f)
                pagg = jax.jit(pgar.aggregate)
                measured(rule, "pallas", d, f,
                         lambda: time_fn(lambda: pagg(g_dev()), args.reps))

            # native host tier
            if native_ok and hasattr(native, rule.replace("-", "_")):
                nfn = getattr(native, rule.replace("-", "_"))
                if rule in ("krum", "bulyan", "averaged-median"):
                    call = lambda nfn=nfn, f=f: nfn(g_host(), f)
                else:
                    call = lambda nfn=nfn: nfn(g_host())
                measured(rule, "native", d, f,
                         lambda: time_fn(call, max(3, args.reps // 4)))

    scale_rows = []
    if args.scale_ns:
        d = args.scale_d
        for n in (int(x) for x in args.scale_ns.split(",")):
            f = max(1, (n - 3) // 4)  # the largest f Bulyan admits at n
            g = None  # lazily built: a fully-cached n costs no fixture
            for rule in ("krum", "bulyan"):
                key = "scale|%s|%d|%d|%d" % (rule, n, d, args.reps)
                cached = resume.get(key)
                if cached is not None:
                    compile_s, ms = cached
                else:
                    if g is None:
                        g = jax.device_put(np.random.default_rng(n).normal(
                            size=(n, d)).astype(np.float32))
                    agg = jax.jit(gars.instantiate(rule, n, f).aggregate)
                    # PURE trace+compile time (the flatness claim): AOT
                    # lower+compile, no execution or host fetch mixed in.
                    t0 = time.perf_counter()
                    compiled = agg.lower(g).compile()
                    compile_s = time.perf_counter() - t0
                    ms = time_fn(lambda: compiled(g), max(3, args.reps // 2))
                    if args.resume_file:
                        resume[key] = [compile_s, ms]
                        save_json_atomic(args.resume_file, resume)
                scale_rows.append({
                    "metric": "gar_scale_n", "rule": rule,
                    "tier": "jnp:" + platform, "n": n, "f": f, "d": d,
                    "compile_s": round(compile_s, 2),
                    "value": round(ms, 4), "unit": "ms",
                })

    sweep_doc = None
    if args.sweep_ns:
        from aggregathor_tpu.gars import scaling

        sweep_doc = scaling.run_sweep(
            [int(x) for x in args.sweep_ns.split(",") if x],
            args.sweep_d, f=args.sweep_f, reps=args.sweep_reps,
            progress=lambda line: print("sweep  " + line, flush=True),
        )
        scaling.validate_scaling_doc(sweep_doc)
        print(scaling.render_table(sweep_doc))
        if args.sweep_out:
            scaling.save_doc(args.sweep_out, sweep_doc)
            print("wrote %s" % args.sweep_out)

    print("%-18s %-12s %12s %12s" % ("rule", "tier", "d", "ms"))
    for rule, tier, d, ms, f in rows:
        print("%-18s %-12s %12d %12.3f" % (rule, tier, d, ms))
    for rule, tier, d, ms, f in rows:
        print(
            json.dumps(
                {
                    "metric": "gar_kernel_ms",
                    "rule": rule,
                    "tier": tier,
                    "n": args.n,
                    "f": f,  # effective f (clamped for bulyan's n >= 4f+3)
                    "d": d,
                    "value": round(ms, 4),
                    "unit": "ms",
                }
            )
        )
    for row in scale_rows:
        print(json.dumps(row))
    if sweep_doc is not None:
        print("GRAFT_BENCH_RESULT " + json.dumps(sweep_doc, sort_keys=True))
        return 0 if sweep_doc["verdict"]["ok"] else 1
    return 0


if __name__ == "__main__":
    # TERM must unwind the interpreter so the backend client closes
    # cleanly — the capture watcher escalates TERM-before-KILL.
    from aggregathor_tpu.utils.proc import graceful_sigterm

    graceful_sigterm()
    sys.exit(main())

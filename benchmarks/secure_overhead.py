"""Security tax of authenticated gradient submission: measured, not presumed.

The acceptance bar of the secure submission layer (docs/security.md): at
n=32 workers and d=8192 the per-step sign+verify cost must stay under 15%
of step time on CPU.  This benchmark measures the REAL training dispatch
two ways on the same synthetic (n, d) problem:

- ``baseline``  the plain engine (``secure=False``);
- ``secured``   the same engine with in-graph digests (``secure=True``)
  PLUS the host-side per-step HMAC sign/verify over the digest stacks
  (``SubmissionAuthenticator.process_step`` — exactly what the runner's
  secure feed pays every dispatch).

Both modes block on the step result every iteration (the secured mode must
fetch its digests, so the baseline is synced identically — paired
comparison), and repeats interleave so load drift cannot masquerade as
security tax.  The document also reports the host crypto in isolation
(sign/verify milliseconds per step over the 16-byte digests) and the
FULL-ROW signing cost (HMAC over all n*d gradient bytes — what the
reference's transport paid per push, the honest upper bound the digest
design avoids).

Usage::

    python benchmarks/secure_overhead.py [--n 32] [--d 8192]
        [--steps 40] [--repeats 3] [--bar 15] [--output overhead.json]

Emits a human table plus machine-readable JSON, schema
``aggregathor.secure.overhead.v1`` (registered in BENCHMARKS.md).
"""

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

SCHEMA = "aggregathor.secure.overhead.v1"

MODES = ("baseline", "secured")

#: document keys the schema validator (tests + smoke) asserts
REQUIRED_KEYS = (
    "schema", "platform", "config", "modes", "overhead_pct", "noise_pct",
    "host_crypto", "bar_pct", "verdict",
)


def validate_secure_overhead(doc):
    """Schema check shared by tests/test_secure.py and the smoke script."""
    assert doc.get("schema") == SCHEMA, doc.get("schema")
    for key in REQUIRED_KEYS:
        assert key in doc, "missing key %r" % key
    for mode in MODES:
        row = doc["modes"][mode]
        for key in ("steps_per_s", "median_ms", "steps"):
            assert key in row, (mode, key)
        assert row["steps_per_s"] > 0.0
    crypto = doc["host_crypto"]
    for key in ("sign_ms_per_step", "verify_ms_per_step",
                "full_row_sign_ms_per_step", "full_row_verify_ms_per_step"):
        assert key in crypto and crypto[key] >= 0.0, key
    assert isinstance(doc["verdict"]["pass"], bool)
    return doc


def build_parser():
    parser = argparse.ArgumentParser(
        description="authenticated-submission overhead vs the unsecured baseline"
    )
    parser.add_argument("--n", type=int, default=32, help="worker count")
    parser.add_argument("--d", type=int, default=8192, help="model dimension")
    parser.add_argument("--batch", type=int, default=4, help="per-worker batch rows")
    parser.add_argument("--gar", default="median", help="aggregation rule (gars registry)")
    parser.add_argument("--steps", type=int, default=40, help="timed steps per mode per repeat")
    parser.add_argument("--repeats", type=int, default=3,
                        help="interleaved repeats (paired medians tame drift)")
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--bar", type=float, default=15.0,
                        help="secured-mode overhead bar, percent of step time")
    parser.add_argument("--output", default=None, metavar="JSON")
    parser.add_argument("--platform", default=None, help="force a JAX platform (tpu/cpu)")
    return parser


def main(argv=None):
    args = build_parser().parse_args(argv)
    if args.platform:
        os.environ["JAX_PLATFORMS"] = args.platform

    import jax
    import jax.numpy as jnp

    if args.platform:
        jax.config.update("jax_platforms", args.platform)

    from aggregathor_tpu import gars
    from aggregathor_tpu.core import build_optimizer, build_schedule
    from aggregathor_tpu.parallel import RobustEngine, make_mesh
    from aggregathor_tpu.secure import SubmissionAuthenticator

    n, d = args.n, args.d

    # Synthetic d-dimensional least-squares worker: the gradient is exactly
    # d-dimensional, so the (n, d) submission geometry matches the claim
    # being measured, with no dataset/input-pipeline noise in the loop.
    def loss_fn(params, batch):
        return jnp.mean((params["w"][None, :] - batch) ** 2)

    def init_params(key):
        return {"w": jax.random.normal(key, (d,), jnp.float32)}

    gar = gars.instantiate(args.gar, n, max(1, n // 4))
    tx = build_optimizer("sgd", build_schedule("fixed", ["initial-rate:0.05"]))
    rng = np.random.default_rng(args.seed)
    batch = np.asarray(rng.normal(size=(n, args.batch, d)), np.float32)

    engines, steps, states, batches = {}, {}, {}, {}
    for mode in MODES:
        engines[mode] = RobustEngine(
            make_mesh(nb_workers=1), gar, n, secure=(mode == "secured")
        )
        steps[mode] = engines[mode].build_step(loss_fn, tx)
        states[mode] = engines[mode].init_state(
            init_params(jax.random.PRNGKey(args.seed)), tx, seed=args.seed + 1
        )
        batches[mode] = engines[mode].shard_batch(batch)
        # compile outside the timing
        states[mode], metrics = steps[mode](states[mode], batches[mode])
        jax.block_until_ready(metrics["total_loss"])

    auth = SubmissionAuthenticator(b"benchmark-secret", n)
    sign_s, verify_s = [], []

    def feed(pending, at_step):
        """The runner's secure feed: sign/verify the PREVIOUS dispatch's
        digests while the current one is in flight (cli/runner.py pays the
        crypto one dispatch behind, never blocking the hot path)."""
        sec = {k: np.asarray(jax.device_get(v)) for k, v in pending.items()}
        t1 = time.perf_counter()
        tags = auth.sign_step(at_step, sec["digest_sent"], forged=sec["forged"])
        t2 = time.perf_counter()
        ok = auth.verify_step(at_step, sec["digest_recv"], tags)
        sign_s.append(t2 - t1)
        verify_s.append(time.perf_counter() - t2)
        assert bool(ok.all()), "honest submissions must verify"

    def run(mode, nb_steps, step_base):
        samples = []
        pending = None
        for index in range(nb_steps):
            t0 = time.perf_counter()
            states[mode], metrics = steps[mode](states[mode], batches[mode])
            if mode == "secured":
                if pending is not None:
                    feed(pending, step_base + index - 1)
                pending = metrics["secure"]
            jax.block_until_ready(metrics["total_loss"])
            samples.append(time.perf_counter() - t0)
        if pending is not None:
            feed(pending, step_base + nb_steps - 1)
        return samples

    samples = {mode: [] for mode in MODES}
    repeat_medians = {mode: [] for mode in MODES}
    for repeat in range(args.repeats):
        for mode in MODES:
            chunk = run(mode, args.steps, repeat * args.steps)
            samples[mode] += chunk
            repeat_medians[mode].append(float(np.median(chunk)))
    for mode in MODES:
        assert steps[mode]._cache_size() == 1, (
            "%s retraced: %d compiles" % (mode, steps[mode]._cache_size())
        )

    # Host crypto in isolation: the digest path (what training pays) and the
    # full-row path (signing the raw n*d gradient bytes — reference parity,
    # the upper bound).
    rows = np.asarray(rng.normal(size=(n, d)), np.float32)
    digests = np.asarray(rng.integers(0, 2 ** 32, size=(n, 4)), "<u4")
    reps = 20

    def time_crypto(payload):
        t0 = time.perf_counter()
        for index in range(reps):
            tags = auth.auth.sign_many(index, payload)
        sign_ms = (time.perf_counter() - t0) / reps * 1e3
        t0 = time.perf_counter()
        for index in range(reps):
            auth.auth.verify_many(reps - 1, payload, tags)
        return sign_ms, (time.perf_counter() - t0) / reps * 1e3

    digest_sign_ms, digest_verify_ms = time_crypto(digests)
    full_sign_ms, full_verify_ms = time_crypto(rows)

    def stats(values):
        arr = np.asarray(values, np.float64)
        return {
            "median_ms": round(float(np.median(arr)) * 1e3, 4),
            "p95_ms": round(float(np.percentile(arr, 95)) * 1e3, 4),
            "steps_per_s": round(1.0 / float(np.median(arr)), 3),
            "steps": int(arr.size),
        }

    modes = {mode: stats(values) for mode, values in samples.items()}
    per_repeat = [
        (sec - base) / base * 100.0
        for sec, base in zip(repeat_medians["secured"], repeat_medians["baseline"])
    ]
    overhead_pct = float(np.median(per_repeat))
    base_arr = np.asarray(repeat_medians["baseline"])
    noise_pct = float(
        (base_arr.max() - base_arr.min()) / 2.0 / np.median(base_arr) * 100.0
    )
    # Noise-aware verdict (trace_overhead.py discipline): on a loaded CI
    # core a load spike must not read as security tax — fail only beyond
    # BOTH the bar and the box's own measured noise floor.
    passed = overhead_pct <= max(args.bar, noise_pct)

    doc = {
        "schema": SCHEMA,
        "platform": jax.devices()[0].platform,
        "config": {
            "n": n, "d": d, "batch": args.batch, "gar": args.gar,
            "steps_per_mode": args.steps * args.repeats,
            "repeats": args.repeats, "seed": args.seed,
        },
        "modes": modes,
        "overhead_pct": round(overhead_pct, 3),
        "overhead_pct_per_repeat": [round(v, 3) for v in per_repeat],
        "noise_pct": round(noise_pct, 3),
        "host_crypto": {
            "sign_ms_per_step": round(float(np.median(sign_s)) * 1e3, 4),
            "verify_ms_per_step": round(float(np.median(verify_s)) * 1e3, 4),
            "full_row_sign_ms_per_step": round(full_sign_ms, 4),
            "full_row_verify_ms_per_step": round(full_verify_ms, 4),
            "digest_sign_ms_per_step": round(digest_sign_ms, 4),
            "digest_verify_ms_per_step": round(digest_verify_ms, 4),
        },
        "bar_pct": args.bar,
        "verdict": {"bar_pct": args.bar, "pass": bool(passed)},
    }
    validate_secure_overhead(doc)

    print("%-10s %12s %10s %12s" % ("mode", "median_ms", "p95_ms", "steps/s"))
    for mode in MODES:
        row = modes[mode]
        print("%-10s %12.3f %10.3f %12.2f"
              % (mode, row["median_ms"], row["p95_ms"], row["steps_per_s"]))
    print("security tax: %+.2f%% of step time (bar %.0f%%, box noise ±%.1f%%)"
          % (overhead_pct, args.bar, noise_pct))
    print("host crypto/step: sign %.3f ms, verify %.3f ms over digests "
          "(full-row reference cost: %.2f / %.2f ms at n=%d, d=%d)"
          % (doc["host_crypto"]["sign_ms_per_step"],
             doc["host_crypto"]["verify_ms_per_step"],
             full_sign_ms, full_verify_ms, n, d))
    print("VERDICT: %s" % ("PASS" if passed else "FAIL"))

    if args.output:
        with open(args.output, "w") as fd:
            json.dump(doc, fd, indent=1)
            fd.write("\n")
        print("document -> %s" % args.output)
    return 0 if passed else 1


if __name__ == "__main__":
    sys.exit(main())

"""Deterministic causal-plane audit: a synthetic fleet incident, written
with INJECTED clocks through the real journal writer, replayed through
the real postmortem checker — byte-identical output on every run.

benchmarks/soak.py proves the causal plane against a live fleet, but a
live fleet's journals change with the weather (ports, pids, scheduler
timing), so its postmortem can never be a checked-in artifact.  This
benchmark is the other half of the bargain: the SAME code paths —
``obs.events.Journal`` writing (clock-injected), ``obs.causal`` merging
and auditing — over a scripted incident whose every timestamp is chosen,
so the ``aggregathor.obs.postmortem.v1`` report it emits is reproducible
to the byte.  The checked-in ``POSTMORTEM_r19.json`` at the repo root IS
this benchmark's output; regenerating it must leave ``git diff`` clean.

The scripted incident (4 journals: supervisor, train, serve, router):

1. **spawn chain, with skew**: the supervisor liveness-restarts ``serve``
   (``cause=None`` — the evidence is the ABSENCE of a process); the
   respawned serve appends a resumed segment to its own journal whose
   ``run_start`` cites the ``supervisor_restart`` across the process
   boundary.  Serve's clock runs 0.8 s BEHIND the supervisor's, so the
   effect carries an earlier wall clock than its cause — the merge must
   order it after anyway and report the inversion as measured skew.
2. **retune chain**: the supervisor cites a ``deadline_window`` event it
   tailed from the TRAIN journal as the cause of a ``supervisor_retune``;
   the retuned trainer's resumed-segment ``run_start`` cites the retune.
3. **verdict rollback**: a ``supervisor_rollback`` names its sentinel
   verdict by ``evidence.verdict_id`` (verdicts are files, not events).
4. **router echo**: a ``router_retry`` cites the ``router_backend_down``
   in its own journal; the respawned serve cites the router's re-route
   (the ``X-Causal-Id`` shape) from a third journal.

Then two NEGATIVE legs prove the verdict can actually flip (neither is
part of the checked-in report):

- a TORN serve journal (trailing bytes without their newline) must fail
  the verdict with ``load_errors`` — destroyed evidence, not a smaller
  story;
- the respawned ``run_start`` with its ``cause`` stripped must fail with
  ``incomplete_chains`` — a spawn nobody answers.

Exit status is the overall verdict.  Example::

    python benchmarks/causal_audit.py --out POSTMORTEM_r19.json
"""

import argparse
import json
import os
import sys

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO_ROOT)

SCHEMA = "aggregathor.obs.postmortem.v1"


def validate(doc):
    """Shape check for round-tripping consumers (tests assert this on the
    checked-in POSTMORTEM_r19.json)."""
    if doc.get("schema") != SCHEMA:
        raise ValueError("not a %s document" % SCHEMA)
    for key in ("instances", "events_total", "edges_total", "chains",
                "violations", "skew", "verdict", "failing"):
        if key not in doc:
            raise ValueError("missing %r" % key)
    if doc["verdict"] not in ("PASS", "FAIL"):
        raise ValueError("verdict must be PASS or FAIL: %r" % doc["verdict"])
    for key in ("dangling_refs", "unresolvable_refs", "orphan_actions",
                "incomplete_chains", "load_errors"):
        if key not in doc["violations"]:
            raise ValueError("violations missing %r" % key)
    for key in ("pairs", "forced_order", "ambiguous_refs"):
        if key not in doc["skew"]:
            raise ValueError("skew missing %r" % key)
    return doc


def load(path):
    with open(path) as fd:
        return validate(json.load(fd))


class _Clock:
    """A deterministic clock: advances a fixed tick per reading."""

    def __init__(self, start, tick):
        self.t = float(start)
        self.tick = float(tick)

    def __call__(self):
        value = self.t
        self.t = round(self.t + self.tick, 6)
        return value


def write_fleet(workdir):
    """Script the incident through the REAL journal writer; returns
    ``{instance: path}``."""
    from aggregathor_tpu.obs import events

    paths = {}

    def journal(name, run_id, wall_start):
        path = os.path.join(workdir, "journal_%s.jsonl" % name)
        paths[name] = path
        return events.Journal(path, run_id=run_id,
                              wall_clock=_Clock(wall_start, 0.25),
                              mono_clock=_Clock(0.0, 0.25))

    # serve's wall clock runs 0.8 s behind the supervisor's: the respawn
    # chain below becomes a measured effect-before-cause inversion
    supervisor = journal("supervisor", "audit-supervisor", 1000.0)
    train = journal("train", "audit-train", 1000.1)
    serve = journal("serve", "audit-serve", 999.2)
    router = journal("router", "audit-router", 1000.05)

    supervisor.emit("run_start", role="supervisor",
                    instances=["router", "serve", "train"])
    train.emit("run_start", role="trainer", experiment="digits")
    serve.emit("run_start", role="serve", port=7000)
    router.emit("run_start", role="router", backends=["serve"])

    # --- 1. the serve death: router sees it, supervisor restarts it ----
    down = router.emit("router_backend_down", backend="serve", misses=2)
    router.emit("router_retry", client="client-0", backend="serve",
                cause=events.cause_of(down))     # same-journal edge
    restart = supervisor.emit(
        "supervisor_restart", instance="serve", reason="exit", attempt=1,
        backoff_s=2.0, evidence={"exit_code": -9},
        cause=None)        # liveness: the evidence is an absent process
    # the respawned serve: a resumed segment in the SAME file (append
    # mode, seq restarts at 0) — exactly what a restarted process does
    serve.close()
    serve = journal("serve", "audit-serve", 999.65)   # still 0.8 s behind
    serve.emit("run_start", role="serve", port=7000,
               cause=events.cause_of(restart, "supervisor"))
    reroute = router.emit("router_route", client="client-0",
                          backend="serve", reason="backend_down")
    serve.emit("serve_weight_swap", step=20,     # the X-Causal-Id shape:
               cause=events.cause_of(reroute, "router"))  # cross-journal

    # --- 2. the retune: supervisor cites what it tailed from train -----
    ceiling = train.emit("deadline_window", window_s=0.5, at_ceiling=True)
    retune = supervisor.emit(
        "supervisor_retune", instance="train", rung="step-deadline*10",
        evidence={"trigger": "deadline_ceiling",
                  "events": [{"type": "deadline_window",
                              "seq": ceiling["seq"]}]},
        cause={"instance": "train", "run_id": "audit-train",
               "seq": ceiling["seq"]})
    train.close()
    train = journal("train", "audit-train", 1002.6)
    train.emit("run_start", role="trainer", experiment="digits",
               cause=events.cause_of(retune, "supervisor"))

    # --- 3. the rollback: names its sentinel verdict BY IDENTITY -------
    supervisor.emit(
        "supervisor_rollback", instance="train", restore_step=10,
        discarded_steps=[20], custody_verified=True,
        evidence={"verdict_id": "audit-verdict", "judged_at": 1003.5},
        cause=None)        # verdicts are files, not journal events

    supervisor.emit("run_end", role="supervisor")
    for sink in (supervisor, train, serve, router):
        sink.close()
    return paths


def main(argv=None):
    parser = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    parser.add_argument("--out", default=None,
                        help="write the postmortem report here")
    parser.add_argument("--workdir", default=None,
                        help="journal scratch directory "
                             "(default: a fresh tempdir)")
    args = parser.parse_args(argv)

    import tempfile

    from aggregathor_tpu.obs import causal

    workdir = args.workdir or tempfile.mkdtemp(prefix="causal_audit_")
    os.makedirs(workdir, exist_ok=True)
    paths = write_fleet(workdir)

    report = causal.run_postmortem(paths)
    # the checked-in artifact must not embed the scratch directory
    for entry in report["instances"].values():
        entry["path"] = os.path.basename(entry["path"])
    validate(report)

    failures = []
    if report["verdict"] != "PASS":
        failures.append("verdict %s (failing: %s)"
                        % (report["verdict"], ", ".join(report["failing"])))
    chains = {(c["kind"], c["action"]["type"]) for c in report["chains"]}
    for want in (("spawn", "supervisor_restart"),
                 ("spawn", "supervisor_retune"),
                 ("verdict_rollback", "supervisor_rollback")):
        if want not in chains:
            failures.append("chain %r not reconstructed" % (want,))
    skew = report["skew"]["pairs"].get("supervisor->serve")
    if not skew or skew["max_seconds"] <= 0.0:
        failures.append("the injected supervisor->serve clock skew was "
                        "not measured: %r" % (report["skew"]["pairs"],))

    # --- negative leg A: a torn journal must flip the verdict ----------
    torn_dir = os.path.join(workdir, "torn")
    os.makedirs(torn_dir, exist_ok=True)
    torn_paths = dict(paths)
    torn = os.path.join(torn_dir, "journal_serve.jsonl")
    with open(paths["serve"], "rb") as fd:
        body = fd.read()
    with open(torn, "wb") as fd:
        fd.write(body[:-10])                     # mid-line, no newline
    torn_paths["serve"] = torn
    torn_report = causal.run_postmortem(torn_paths)
    if torn_report["verdict"] != "FAIL" \
            or "load_errors" not in torn_report["failing"]:
        failures.append("torn serve journal did not flip the verdict: %r"
                        % (torn_report["failing"],))

    # --- negative leg B: an unanswered spawn must flip the verdict -----
    mute_dir = os.path.join(workdir, "mute")
    os.makedirs(mute_dir, exist_ok=True)
    mute_paths = dict(paths)
    mute = os.path.join(mute_dir, "journal_serve.jsonl")
    with open(paths["serve"]) as fd, open(mute, "w") as out:
        for line in fd:
            record = json.loads(line)
            if record["type"] == "run_start":
                record.pop("cause", None)        # the respawn forgets
            out.write(json.dumps(record) + "\n")
    mute_paths["serve"] = mute
    mute_report = causal.run_postmortem(mute_paths)
    if mute_report["verdict"] != "FAIL" \
            or "incomplete_chains" not in mute_report["failing"]:
        failures.append("unanswered spawn did not flip the verdict: %r"
                        % (mute_report["failing"],))

    print("causal audit: %d event(s), %d edge(s), %d chain(s); "
          "skew supervisor->serve %.3fs; torn->%s, mute->%s"
          % (report["events_total"], report["edges_total"],
             len(report["chains"]),
             skew["max_seconds"] if skew else float("nan"),
             torn_report["verdict"], mute_report["verdict"]))
    if args.out:
        with open(args.out, "w") as fd:
            json.dump(report, fd, indent=1, sort_keys=True)
            fd.write("\n")
        print("report -> %s" % args.out)
    if failures:
        for failure in failures:
            print("FAIL: %s" % failure)
        return 1
    print("verdict: PASS")
    return 0


if __name__ == "__main__":
    sys.exit(main())

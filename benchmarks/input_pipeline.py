"""Host->device input pipeline: sync vs old-prefetch vs three-stage pipeline.

The bench trajectory (BENCH_r01..r05) showed the trainer INPUT-bound, not
compute-bound, and the old chunk ``DevicePrefetcher`` measurably SLOWER
than synchronous dispatch (2.62 vs 2.74 steps/s on the tunneled TPU): its
one daemon thread serially re-did the same gather + one monolithic
``device_put`` the sync path pays anyway.  This benchmark times the REAL
unrolled trainer (``build_multi_step``, K distinct batches per dispatch)
under the three input strategies the CLI offers (docs/input_pipeline.md):

- ``sync``      gather + transfer ON the timed path, no helper thread —
                the ``--prefetch 0`` baseline;
- ``prefetch``  the retired whole-chunk background thread (kept for
                iterators without ``next_many``): one daemon does
                gather + one monolithic ``device_put`` per chunk;
- ``pipeline``  the three-stage ``ChunkPipeline``: parallel sharded gather
                into ping-pong buffers, S sliced async transfers, jitted
                device-side assemble — with its overlap metrics read back
                from a private ``MetricsRegistry``.

Per mode it reports steps/s and the INPUT-GAP fraction (wall time the
consumer spent acquiring the next device chunk / total wall time — the
slice of the run the device sat idle waiting on input).  For ``pipeline``
the registry's ``input_overlap_fraction`` / ``input_gather_seconds_total``
/ ``input_put_seconds_total`` land in the JSON too, so overlap is measured,
not presumed.

Usage::

    python benchmarks/input_pipeline.py [--experiment cnnet]
        [--nb-workers 8] [--gar multikrum] [--f 2] [--unroll 10]
        [--chunks 6] [--slices 4] [--depth 2] [--output pipeline.json]
        [--bar 1.5] [--strict]

Emits one human table plus machine-readable JSON (schema
``aggregathor.input.pipeline.v1``; registered in BENCHMARKS.md).  The
verdict line states whether the pipeline beat ``--bar`` x sync steps/s and
whether the old prefetcher's <=1.0x regression is gone; ``--strict`` turns
a missed bar into a nonzero exit (CI boxes with one loaded core cannot
always overlap, so the default is report-only).
"""

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

SCHEMA = "aggregathor.input.pipeline.v1"

MODES = ("sync", "prefetch", "pipeline")


def build_parser():
    parser = argparse.ArgumentParser(
        description="host->device input strategies: steps/s + input-gap fraction")
    parser.add_argument("--experiment", default="cnnet", help="experiment name (models registry)")
    parser.add_argument("--experiment-args", nargs="*", default=["batch-size:64", "augment:device"],
                        help="key:value experiment arguments")
    parser.add_argument("--nb-workers", type=int, default=8)
    parser.add_argument("--gar", default="krum", help="aggregation rule (gars registry)")
    parser.add_argument("--f", type=int, default=2, help="declared Byzantine workers")
    parser.add_argument("--unroll", type=int, default=10, help="steps per chunk (K)")
    parser.add_argument("--chunks", type=int, default=6, help="timed chunks per mode")
    parser.add_argument("--slices", type=int, default=4,
                        help="transfer slices per chunk (pipeline mode)")
    parser.add_argument("--depth", type=int, default=2, help="queue depth (threaded modes)")
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--bar", type=float, default=1.5,
                        help="pipeline-vs-sync speedup bar")
    parser.add_argument("--strict", action="store_true",
                        help="exit nonzero when the bar is missed")
    parser.add_argument("--output", default=None, metavar="JSON")
    parser.add_argument("--platform", default=None, help="force a JAX platform (tpu/cpu)")
    return parser


def main(argv=None):
    args = build_parser().parse_args(argv)
    if args.platform:
        os.environ["JAX_PLATFORMS"] = args.platform

    import jax

    if args.platform:
        jax.config.update("jax_platforms", args.platform)

    from aggregathor_tpu import gars, models
    from aggregathor_tpu.core import build_optimizer, build_schedule
    from aggregathor_tpu.models.datasets import (
        ChunkPipeline, DevicePrefetcher, split_chunk)
    from aggregathor_tpu.obs.metrics import MetricsRegistry
    from aggregathor_tpu.parallel import RobustEngine, make_mesh

    n, unroll, chunks = args.nb_workers, args.unroll, args.chunks
    experiment = models.instantiate(args.experiment, args.experiment_args)
    gar = gars.instantiate(args.gar, n, args.f)
    tx = build_optimizer("sgd", build_schedule("fixed", ["initial-rate:0.05"]))
    engine = RobustEngine(
        make_mesh(nb_workers=1), gar, nb_workers=n, nb_real_byz=0,
        batch_transform=experiment.device_transform(),
    )
    multi_fn = engine.build_multi_step(experiment.loss, tx)
    # host copy: the K-step trainer DONATES its state, so a device-resident
    # canonical params tree would be deleted by the first mode's first call
    params = jax.tree_util.tree_map(
        np.asarray, experiment.init(jax.random.PRNGKey(args.seed)))

    def fresh_state():
        return engine.init_state(params, tx, seed=args.seed + 1)

    # Warm up once: compile the K-step trainer and the pipeline's
    # slice-assemble executable so no mode's timed loop pays a compile.
    it = experiment.make_train_iterator(n, seed=args.seed + 2)
    state = fresh_state()
    warm_chunk = engine.shard_batches(it.next_many(unroll))
    state, metrics = multi_fn(state, warm_chunk)
    jax.block_until_ready(metrics["total_loss"])
    parts = [engine.shard_batches(s)
             for s in split_chunk(it.next_many(unroll), args.slices)]
    jax.block_until_ready(engine.assemble_batches(parts))

    results = {}

    def timed_mode(mode):
        """Run ``chunks`` dispatches under ``mode``; per-chunk input wait and
        total wall time give the mode's input-gap fraction.  Every mode
        consumes the SAME sample stream (fresh iterator, same seed), so the
        losses are comparable and pipeline bit-identity shows up as an
        identical final loss."""
        mode_it = experiment.make_train_iterator(n, seed=args.seed + 2)
        mode_state = fresh_state()
        source = None
        registry = None
        if mode == "prefetch":
            def chunk_source():
                for _ in range(chunks):
                    yield mode_it.next_many(unroll)

            source = DevicePrefetcher(chunk_source(), engine.shard_batches,
                                      depth=args.depth)
        elif mode == "pipeline":
            registry = MetricsRegistry()
            source = ChunkPipeline(
                mode_it, unroll, chunks, put=engine.shard_batches,
                assemble=engine.assemble_batches, depth=args.depth,
                slices=args.slices, registry=registry,
            )
        input_s = 0.0
        loss = None
        t_start = time.perf_counter()
        try:
            for _ in range(chunks):
                t0 = time.perf_counter()
                if source is not None:
                    device_chunk = next(source)
                else:
                    device_chunk = engine.shard_batches(mode_it.next_many(unroll))
                input_s += time.perf_counter() - t0
                mode_state, metrics = multi_fn(mode_state, device_chunk)
                loss = metrics["total_loss"]
            loss = float(np.asarray(jax.block_until_ready(loss))[-1])
        finally:
            if source is not None:
                source.close()
        total_s = time.perf_counter() - t_start
        row = {
            "steps_per_s": round(chunks * unroll / total_s, 3),
            "input_gap_fraction": round(input_s / total_s, 4),
            "input_s": round(input_s, 4),
            "total_s": round(total_s, 4),
            "final_loss": round(loss, 6),
            "timed_steps": chunks * unroll,
        }
        if registry is not None:
            snap = registry.snapshot()
            for name, key in (
                ("input_overlap_fraction", "overlap_fraction"),
                ("input_gather_seconds_total", "gather_s"),
                ("input_put_seconds_total", "put_s"),
                ("input_wait_seconds_total", "wait_s"),
                ("input_chunks_total", "chunks_produced"),
            ):
                row[key] = round(float(snap[name]), 4)
        return row

    for mode in MODES:
        results[mode] = timed_mode(mode)

    sync_rate = results["sync"]["steps_per_s"]
    speedup = {
        mode: round(results[mode]["steps_per_s"] / sync_rate, 3)
        for mode in ("prefetch", "pipeline")
    }
    doc = {
        "schema": SCHEMA,
        "experiment": args.experiment,
        "platform": jax.devices()[0].platform,
        "nb_workers": n,
        "gar": args.gar,
        "f": args.f,
        "unroll": unroll,
        "chunks": chunks,
        "slices": args.slices,
        "depth": args.depth,
        "batch_size": experiment.batch_size,
        "modes": results,
        "speedup_vs_sync": speedup,
        "bar": args.bar,
    }
    print("%-10s %12s %12s %12s %12s" % (
        "mode", "steps/s", "input-gap", "final loss", "vs sync"))
    for mode in MODES:
        row = results[mode]
        print("%-10s %12.3f %12.4f %12.6f %12s" % (
            mode, row["steps_per_s"], row["input_gap_fraction"],
            row["final_loss"],
            "%.2fx" % speedup[mode] if mode in speedup else "1.00x"))
    ok = speedup["pipeline"] >= args.bar
    print("verdict: pipeline %.2fx sync (bar %.2fx) %s; old prefetch %.2fx "
          "(regression %s); pipeline overlap fraction %.3f" % (
              speedup["pipeline"], args.bar, "OK" if ok else "MISSED",
              speedup["prefetch"],
              "gone" if speedup["pipeline"] > speedup["prefetch"] else "NOT gone",
              results["pipeline"].get("overlap_fraction", 0.0)))
    if args.output:
        with open(args.output, "w") as fd:
            json.dump(doc, fd, indent=2, sort_keys=True)
            fd.write("\n")
        print("wrote %s" % args.output)
    print("GRAFT_BENCH_RESULT " + json.dumps(doc, sort_keys=True))
    return 0 if (ok or not args.strict) else 1


if __name__ == "__main__":
    sys.exit(main())

"""Serving latency/throughput profile: per bucket size and replica count.

Measures the compiled inference path (``serve/engine.InferenceEngine``)
exactly as the server drives it: padded bucket-shaped batches through the
R-way replicated robust vote.  For every (bucket, replicas) cell it reports
compile time (one-off), p50/p95/p99 per-call latency (obs.perf
.LatencyHistogram over ``--reps`` timed calls) and rows/s throughput —
the capacity-planning numbers behind the batcher's deadline/bucket knobs
(docs/serving.md).

Usage::

    python benchmarks/serve_latency.py [--experiment digits]
        [--buckets 1,8,64] [--replicas 1,3,5] [--gar median] [--reps 30]
        [--output profile.json]

Prints one human table row and one machine-readable JSON line per cell
(schema ``aggregathor.serve.latency-profile.v1``); ``--output`` additionally
writes the whole profile as one JSON document.
"""

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

SCHEMA = "aggregathor.serve.latency-profile.v1"


def build_parser():
    parser = argparse.ArgumentParser(description="serving latency/throughput per bucket x replicas")
    parser.add_argument("--experiment", default="digits", help="experiment name (models registry)")
    parser.add_argument("--experiment-args", nargs="*", default=[], help="key:value experiment arguments")
    parser.add_argument("--buckets", default="1,8,64", help="comma-separated bucket sizes")
    parser.add_argument("--replicas", default="1,3", help="comma-separated replica counts")
    parser.add_argument("--gar", default="median", help="vote rule for R > 1 (gars registry)")
    parser.add_argument("--reps", type=int, default=30, help="timed calls per cell")
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--output", default=None, metavar="JSON", help="write the full profile here")
    parser.add_argument("--platform", default=None, help="force a JAX platform (tpu/cpu)")
    return parser


def main(argv=None):
    args = build_parser().parse_args(argv)
    if args.platform:
        os.environ["JAX_PLATFORMS"] = args.platform

    import jax

    if args.platform:
        jax.config.update("jax_platforms", args.platform)

    from aggregathor_tpu import gars, models
    from aggregathor_tpu.obs import LatencyHistogram
    from aggregathor_tpu.serve import InferenceEngine

    buckets = [int(b) for b in args.buckets.split(",")]
    replica_counts = [int(r) for r in args.replicas.split(",")]
    experiment = models.instantiate(args.experiment, args.experiment_args)
    params = jax.device_get(experiment.init(jax.random.PRNGKey(args.seed)))
    rng = np.random.default_rng(args.seed)

    platform = jax.devices()[0].platform
    cells = []
    print("%-8s %-4s %-8s %14s %10s %10s %10s %12s"
          % ("bucket", "R", "vote", "ladder_comp_s", "p50_ms", "p95_ms", "p99_ms", "rows/s"))
    for nb_replicas in replica_counts:
        vote = (
            gars.instantiate(args.gar, nb_replicas, (nb_replicas - 1) // 2)
            if nb_replicas > 1 else None
        )
        engine = InferenceEngine(
            experiment, [params] * nb_replicas, gar=vote,
            buckets=buckets, seed=args.seed,
        )
        compile_t0 = time.perf_counter()
        engine.warmup()
        compile_s = time.perf_counter() - compile_t0
        for bucket in buckets:
            x = rng.random((bucket,) + engine.sample_shape, np.float32)
            hist = LatencyHistogram()
            engine.predict(x)  # steady-state: warm cache, warm data path
            for _ in range(args.reps):
                t0 = time.perf_counter()
                engine.predict(x)
                hist.record(time.perf_counter() - t0)
            tail = hist.percentiles()
            throughput = bucket / max(tail["p50"], 1e-9)
            cell = {
                "schema": SCHEMA,
                "experiment": args.experiment,
                "platform": platform,
                "bucket": bucket,
                "replicas": nb_replicas,
                "gar": args.gar if nb_replicas > 1 else None,
                # whole-LADDER warmup time for this replica count (one-off,
                # shared by every bucket row of the same R — NOT per bucket)
                "ladder_compile_s": round(compile_s, 4),
                "p50_ms": round(tail["p50"] * 1e3, 4),
                "p95_ms": round(tail["p95"] * 1e3, 4),
                "p99_ms": round(tail["p99"] * 1e3, 4),
                "rows_per_s": round(throughput, 2),
                "reps": args.reps,
            }
            cells.append(cell)
            print("%-8d %-4d %-8s %14.3f %10.3f %10.3f %10.3f %12.1f"
                  % (bucket, nb_replicas, cell["gar"] or "-", compile_s,
                     cell["p50_ms"], cell["p95_ms"], cell["p99_ms"], throughput))
            print(json.dumps(cell))
    if args.output:
        with open(args.output, "w") as fd:
            json.dump({"schema": SCHEMA, "cells": cells}, fd, indent=1)
    return 0


if __name__ == "__main__":
    sys.exit(main())
